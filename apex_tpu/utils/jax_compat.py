"""Version-compat spellings for the small set of SPMD APIs this package
uses that moved between JAX releases.

The package targets the current VMA-typed SPMD API (``jax.shard_map``,
``lax.axis_size``, ``lax.pvary``); on older installs (pre-0.5) those
live elsewhere or don't exist, and every collective component would
fail on the *spelling* rather than the semantics.  Centralizing the
fallbacks here keeps each module importing one name instead of
open-coding try/except at every call site.

- :func:`axis_size` — ``lax.axis_size``, else the classic
  ``psum(1, axis)`` spelling (folds to a constant under SPMD).
- :func:`pvary` — ``lax.pvary``, else identity: pre-VMA shard_map
  gradients already materialize per-rank, which is exactly the state
  the tag requests, so identity preserves the semantics.
- :func:`shard_map` — ``jax.shard_map``, else
  ``jax.experimental.shard_map.shard_map``.  The shim accepts a
  ``check_rep`` kwarg everywhere: on legacy it passes through (legacy
  default True — the checker's efficient-transpose rewrite is what
  makes gradients wrt *replicated* inputs correct there, so it must
  stay on by default); on the VMA API it is stripped (replication is
  carried in types, the knob doesn't exist).  The few call sites whose
  collective pattern the legacy checker cannot infer (it derives
  variance from ``pvary`` annotations that are identity here) pass
  ``check_rep=False`` explicitly — legal because they only
  differentiate wrt *sharded* inputs, where the unrewritten psum
  transpose is already correct.
"""

from __future__ import annotations

import jax
from jax import lax


def axis_size(axis_name):
    """Size of a mapped mesh axis (``lax.axis_size`` compat)."""
    try:
        return lax.axis_size(axis_name)
    except AttributeError:
        return lax.psum(1, axis_name)


pvary = getattr(lax, "pvary", lambda x, axes: x)

try:
    _shard_map_modern = jax.shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=None, **kw):
        del check_rep  # legacy-only knob; VMA types carry replication
        return _shard_map_modern(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=True, **kw):
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_rep,
                                 **kw)

__all__ = ["axis_size", "pvary", "shard_map"]
