"""Tracing / profiling annotations (SURVEY.md §5.1).

The reference sprinkled NVTX ranges at hot spots
(``apex/parallel/sync_batchnorm.py:66,84,129``,
``sync_batchnorm_kernel.py:11-47``) and drove nsight via
``torch.cuda.cudart().cudaProfilerStart/Stop``
(``tests/distributed/DDP/ddp_race_condition_test.py:44,66``) plus a
``--prof`` early-exit loop in the imagenet example
(``examples/imagenet/main_amp.py:63-64,311-334``).

TPU equivalents:

- :func:`nvtx_range` — ``jax.named_scope`` (names the HLO ops, visible in
  XProf's trace viewer and HLO graphs) combined with
  ``jax.profiler.TraceAnnotation`` (names the host-side section);
- :func:`range_push` / :func:`range_pop` — the imperative NVTX API shape;
- :func:`profiler_start` / :func:`profiler_stop` — capture an XProf trace
  to a log directory (view with TensorBoard's profile plugin or
  xprof);
- :func:`annotate` — decorator form for step functions.
"""

from __future__ import annotations

import contextlib
from typing import Callable, List, Optional

import jax


@contextlib.contextmanager
def nvtx_range(name: str):
    """Named region covering both the traced computation (HLO metadata)
    and host time (profiler TraceAnnotation)."""
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


_range_stack: List[contextlib.ExitStack] = []


def range_push(name: str) -> None:
    """Imperative begin (``torch.cuda.nvtx.range_push`` shape)."""
    es = contextlib.ExitStack()
    es.enter_context(nvtx_range(name))
    _range_stack.append(es)


def range_pop() -> None:
    """Imperative end (``torch.cuda.nvtx.range_pop``)."""
    if _range_stack:
        _range_stack.pop().close()


def annotate(name: Optional[str] = None) -> Callable:
    """Decorator: run the function inside a named range."""
    def deco(fn):
        label = name or fn.__name__

        def wrapped(*args, **kwargs):
            with nvtx_range(label):
                return fn(*args, **kwargs)

        wrapped.__name__ = fn.__name__
        wrapped.__doc__ = fn.__doc__
        return wrapped
    return deco


_trace_active = False


def profiler_start(logdir: str = "/tmp/apex_tpu_trace") -> None:
    """Begin an XProf capture (``cudaProfilerStart`` analog)."""
    global _trace_active
    if not _trace_active:
        jax.profiler.start_trace(logdir)
        _trace_active = True


def profiler_stop() -> None:
    """End the capture and flush the trace (``cudaProfilerStop``)."""
    global _trace_active
    if _trace_active:
        jax.profiler.stop_trace()
        _trace_active = False
