from apex_tpu.utils.logging import maybe_print, set_verbosity, warn_or_err
from apex_tpu.utils.profiling import (
    annotate,
    nvtx_range,
    profiler_start,
    profiler_stop,
    range_pop,
    range_push,
)

__all__ = ["maybe_print", "set_verbosity", "warn_or_err",
           "nvtx_range", "range_push", "range_pop", "annotate",
           "profiler_start", "profiler_stop"]
