from apex_tpu.utils.logging import maybe_print, set_verbosity, warn_or_err

__all__ = ["maybe_print", "set_verbosity", "warn_or_err"]
