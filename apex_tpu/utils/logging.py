"""Rank-0-aware, verbosity-gated logging.

Port of the reference's ``apex/amp/_amp_state.py:31-52`` (``maybe_print`` /
``master_print``): under multi-process SPMD only process 0 prints, and
messages are gated on a global verbosity that ``amp.initialize`` sets.
"""

from __future__ import annotations

import sys
import warnings

import jax

_verbosity = 1


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = int(v)


def _is_rank0() -> bool:
    try:
        return jax.process_index() == 0
    except Exception:
        return True


def maybe_print(message: str, rank0_only: bool = True, min_verbosity: int = 1,
                file=None) -> None:
    """Print gated on verbosity and (by default) process index
    (reference ``_amp_state.py:43-52``)."""
    if _verbosity < min_verbosity:
        return
    if rank0_only and not _is_rank0():
        return
    print(message, file=file or sys.stdout)


def warn_or_err(condition: bool, message: str, strict: bool = False) -> None:
    """Warn (or raise under strict mode) on a policy inconsistency
    (reference ``_amp_state.py:54-62`` warn_or_err)."""
    if condition:
        return
    if strict:
        raise RuntimeError(message)
    warnings.warn(message)
