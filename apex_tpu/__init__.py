"""apex_tpu — a TPU-native mixed-precision / fused-kernel / data-parallel training
framework built on JAX, XLA, and Pallas.

This package provides the capabilities of NVIDIA Apex (reference:
``/root/reference`` — ``apex/__init__.py:1-13``) redesigned for TPU:

- :mod:`apex_tpu.amp` — automatic mixed precision with O0–O3-style policies,
  a jit-safe dynamic loss scaler, and fp32 master-weight management
  (reference ``apex/amp``).
- :mod:`apex_tpu.optimizers` — ``FusedAdam``, ``FusedLAMB``, ``FP16Optimizer``
  (reference ``apex/optimizers`` + ``csrc/fused_adam_cuda*``,
  ``csrc/multi_tensor_lamb_stage_{1,2}.cu``).
- :mod:`apex_tpu.normalization` — ``FusedLayerNorm`` (reference
  ``apex/normalization/fused_layer_norm.py`` + ``csrc/layer_norm_cuda*``).
- :mod:`apex_tpu.parallel` — data-parallel gradient reduction over a
  ``jax.sharding.Mesh``, ``Reducer``, ``SyncBatchNorm``, ``LARC``
  (reference ``apex/parallel``).
- :mod:`apex_tpu.multi_tensor_apply` / :mod:`apex_tpu.ops` — fused
  multi-tensor scale / axpby / l2norm over packed parameter pytrees
  (reference ``apex/multi_tensor_apply`` + ``csrc/multi_tensor_*``).
- :mod:`apex_tpu.fp16_utils` — model/dtype conversion helpers, master-param
  utilities, and legacy loss scalers (reference ``apex/fp16_utils``).
- :mod:`apex_tpu.rnn` — scanned-cell RNN stack: LSTM/GRU/ReLU/Tanh/mLSTM,
  stacked, bidirectional, recurrent projections (reference ``apex/RNN``).
- :mod:`apex_tpu.analysis` — static graph lint over lowered/compiled
  programs: donation, sharding, collective-volume, constant-capture, and
  O1-policy passes (no reference analog — a traced/compiled framework
  makes the guarantees checkable instead of structural).
- :mod:`apex_tpu.resilience` — fault tolerance: crash-atomic
  checksum-verified sharded checkpointing, seeded fault injection, and a
  self-healing train loop with watchdog + divergence rewind (the
  reference's resume contract, ``apex/fp16_utils/fp16_optimizer.py:298-359``,
  extended to preemption / corruption / NaN-storm / hung-step inputs).
- :mod:`apex_tpu.serve` — continuous-batching decode serving: fixed-slot
  scheduler, paged block-pool KV cache with per-slot page tables, fused
  on-device sampling epilogue, one compiled step that never retraces
  across admission/retirement (no reference analog — 2019-era apex has
  no inference story at all).
- :mod:`apex_tpu.obs` — unified runtime telemetry: a lag-resolved
  metrics registry (zero host syncs on the step path), structured
  trace spans, and the shared xplane/chrome-trace attribution library
  every profile tool imports.

Unlike the reference, which monkey-patches eager PyTorch, everything here is
functional and jit-compiled: loss-scale state is a pytree carried through the
step function, overflow skipping is a ``jnp.where`` (never a host sync), and
gradient reduction is ``jax.lax.psum`` over mesh axes with XLA doing the
compute/communication overlap that apex's bucketed NCCL streams did by hand.
"""

from apex_tpu import amp
from apex_tpu import analysis
from apex_tpu import checkpoint
from apex_tpu import data
from apex_tpu import fp16_utils
from apex_tpu import multi_tensor_apply
from apex_tpu import normalization
from apex_tpu import obs
from apex_tpu import optimizers
from apex_tpu import parallel
from apex_tpu import resilience
from apex_tpu import rnn
from apex_tpu import serve

#: The reference spells the RNN package ``apex.RNN`` (not auto-imported
#: there; ``apex/__init__.py:1-13``) — keep the capitalized alias so
#: migrating code finds it.
RNN = rnn

__version__ = "0.1.0"

__all__ = [
    "amp",
    "analysis",
    "checkpoint",
    "data",
    "fp16_utils",
    "multi_tensor_apply",
    "normalization",
    "obs",
    "optimizers",
    "parallel",
    "resilience",
    "rnn",
    "serve",
    "RNN",
    "__version__",
]
