"""Bitwise-determinism lint over the lowered StableHLO.

Every headline gate in this repo — serve-vs-solo, spec accept, disagg
chaos, prefix-CoW, fleet regrow — is a BITWISE equality on emitted
tokens, and the two determinism bug classes that have actually bitten
were both found by hand: the XLA:CPU remat ulp-tie that PR 11
root-caused into :func:`~apex_tpu.models.generate.pin_logits` /
:func:`~apex_tpu.models.generate.greedy_argmax`, and the
"shape-lucky" accumulation class whose ``_attn_cached`` b1-vs-b8
suspect survived as the documented kv8 tolerance.  This pass
machine-checks the exactness contract itself, the way the precision
pass machine-checks the paper's mixed-precision contract.

Four per-lane rules over the :mod:`~apex_tpu.analysis.dflow` SSA walk:

- ``det-tie-argmax`` — a floating argmax / top-k / compare-select
  epilogue that is NOT the reassociation-proof ``greedy_argmax`` form.
  jax's native ``jnp.argmax`` outlines to a private function built on a
  *variadic* ``stablehlo.reduce`` whose reducer region tie-breaks with
  a ``FLOAT`` compare + select — the exact shape whose winner can move
  when XLA reassociates the upstream accumulation by one ulp.
  ``greedy_argmax`` lowers to separate max-reduce / EQ-compare /
  min-index-reduce ops (no variadic reduce) and never fires.  A
  tie-break whose float operand derives from a random-bits expansion
  (the gumbel-perturbed categorical draw) is the *legal* key-seeded
  form and is recorded as info evidence instead.
- ``det-multi-materialize`` — one float value consumed by BOTH a
  sampling/compare epilogue and a program output, with no
  ``optimization_barrier`` pinning the producer: XLA may materialize
  the two uses from different rematerializations that differ by an
  ulp, so the emitted token and the returned logits disagree.  This is
  the ``pin_logits`` remat class, detected structurally so it fires on
  any future head, not just gpt.
- ``det-scatter-order`` — a scatter whose update windows are not
  statically provably disjoint: ``unique_indices = true`` proves it,
  and the paged-pool writes' clip+trash routing (indices selected
  against a constant trash block: ``where(mask, idx, TRASH_BLOCK)``)
  is recognized as the legal disjointness convention; anything else is
  an ordering hazard.
- ``det-prng-reuse`` — one ``ui32`` key token reaching two independent
  random-bits expansions (calls into threefry-derived private
  functions): the draws are correlated, and under remat the two
  expansions may not even agree with each other.

Second half, the cross-lane comparator (the spmd-pass treatment
applied to *shapes* instead of ranks): :func:`reduction_signatures`
extracts the canonical reduction signature of every float contraction
/ reduce — the contracted dim sizes, the operand/accumulation dtypes
(``preferred_element_type`` shows up as the result dtype) — and
:func:`compare_signatures` diffs two lanes' signature streams.  A
multiset difference means the two programs accumulate in genuinely
different shapes/dtypes somewhere — ``det-lane-shape-variant``, the
rule that mechanically confirms or clears the ``_attn_cached``
b1-vs-b8 suspect.  Integer reductions are excluded by construction:
integer addition is associative, so its order cannot move a bit.

``tools/det_lint.py`` sweeps the full lane matrix into the committed
``DETLINT_r*.json`` artifact (schema:
:mod:`apex_tpu.analysis.detlint`); ``tools/graph_lint.py --passes
determinism`` runs the per-lane rules standalone (lowering-only).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

from apex_tpu.analysis import dflow
from apex_tpu.analysis.core import PassContext, register_pass
from apex_tpu.analysis.report import Finding

_PASS = "determinism"

#: the rule ids, mirrored stdlib-only in :mod:`apex_tpu.analysis.detlint`
#: (``tests/l0/test_determinism.py`` pins the two lists equal); the
#: first four are per-lane, the last is the cross-lane comparator's.
RULES = ("det-tie-argmax", "det-multi-materialize", "det-scatter-order",
         "det-prng-reuse", "det-lane-shape-variant")

#: per-lane rules (what a single lowering can fire)
LANE_RULES = RULES[:4]

_CALLEE = re.compile(r"@([\w$.-]+)")
#: the threefry2x32 magic constants — any private function whose body
#: materializes them (or a rotation table) is a random-bits expansion
_THREEFRY_MARKS = ("466688986", "dense<[13, 15, 26, 6]>")
_CONTRACT = re.compile(
    r"contracting_dims\s*=\s*\[([0-9, ]*)\]\s*x\s*\[([0-9, ]*)\]")
_APPLIES = re.compile(r"applies\s+stablehlo\.(\w+)")


def _is_float(elem: Optional[str]) -> bool:
    return bool(elem) and (elem.startswith("f") or elem.startswith("bf"))


def _callee(op: dflow.Op) -> Optional[str]:
    m = _CALLEE.search(op.line)
    return m.group(1) if m else None


def _producers(fn: dflow.FuncDef) -> Dict[str, dflow.Op]:
    d: Dict[str, dflow.Op] = {}
    for op in fn.ops:
        for r in (op.results or ((op.result,) if op.result else ())):
            d[r] = op
    return d


def _region_ops(fn: dflow.FuncDef, owner: dflow.Op) -> List[dflow.Op]:
    return [o for o in fn.ops if any(w is owner for w in o.owners)]


def _call_graph(funcs: Dict[str, dflow.FuncDef]) -> Dict[str, set]:
    return {name: {c for op in fn.ops if op.name == "call"
                   for c in [_callee(op)] if c}
            for name, fn in funcs.items()}


def _rng_funcs(funcs: Dict[str, dflow.FuncDef]) -> set:
    """Functions that (transitively) expand random bits: a threefry
    constant or ``rng_bit_generator`` in the body, or a call into one."""
    calls = _call_graph(funcs)
    rng = set()
    for name, fn in funcs.items():
        for op in fn.ops:
            if op.name == "rng_bit_generator" or (
                    op.name == "constant"
                    and any(m in op.line for m in _THREEFRY_MARKS)):
                rng.add(name)
                break
    changed = True
    while changed:
        changed = False
        for name, cs in calls.items():
            if name not in rng and cs & rng:
                rng.add(name)
                changed = True
    return rng


def _tie_sites(fn: dflow.FuncDef) -> List[Tuple[dflow.Op, str]]:
    """Tie-breaking epilogue ops in one function body: the variadic
    float argmax reduce, float top-k, and unstable float sorts."""
    sites = []
    for op in fn.ops:
        if op.name == "reduce" and op.n_results >= 2 and any(
                _is_float(dflow.element_type(t)) for t in op.types):
            region = _region_ops(fn, op)
            if any(o.name == "compare" and "FLOAT" in o.line
                   for o in region) and \
                    any(o.name == "select" for o in region):
                sites.append((op, "variadic argmax reduce"))
        elif op.name == "top_k" and any(
                _is_float(dflow.element_type(t)) for t in op.types):
            sites.append((op, "top_k"))
        elif op.name == "sort" and "is_stable = false" in op.line:
            region = _region_ops(fn, op)
            if any(o.name == "compare" and "FLOAT" in o.line
                   for o in region):
                sites.append((op, "unstable float sort"))
    return sites


def _derives_from_rng(fn: dflow.FuncDef, producers: Dict[str, dflow.Op],
                      token: str, rng: set, depth: int = 6) -> bool:
    """True when ``token``'s value derives from a random-bits expansion
    within ``depth`` producer steps — the legal key-perturbed tie-break
    (gumbel trick) adds the noise right next to the argmax."""
    frontier = {fn.resolve(token)}
    seen = set()
    for _ in range(depth):
        nxt = set()
        for tok in frontier:
            if tok in seen:
                continue
            seen.add(tok)
            op = producers.get(tok)
            if op is None:
                continue
            if op.name == "call" and _callee(op) in rng:
                return True
            for o in op.operands:
                nxt.add(fn.resolve(o))
        frontier = nxt
    return False


class _IndexWalk:
    """Interprocedural backward walk over scatter-index chains.

    jax outlines the clip+trash routing freely — ``jnp.where(mask,
    idx, TRASH_BLOCK)`` can sit in a private ``@_where`` the caller
    only sees as a ``call``, and the scatter itself often sits in an
    outlined update function whose flat indices arrive as function
    arguments.  The guard test must follow both directions: down into
    a callee's returned chain (with the call-site binding so a
    constant passed as an argument is still a constant), and up from
    a function argument to every call site's actual operand.
    """

    def __init__(self, funcs: Dict[str, dflow.FuncDef]):
        self.funcs = funcs
        self.producers = {n: _producers(fn) for n, fn in funcs.items()}
        self.arg_pos = {n: {tok: i for i, (tok, _p) in enumerate(fn.args)}
                        for n, fn in funcs.items()}
        self.call_sites: Dict[str, List[Tuple[str, dflow.Op]]] = {}
        for name, fn in funcs.items():
            for op in fn.ops:
                if op.name == "call":
                    c = _callee(op)
                    if c:
                        self.call_sites.setdefault(c, []).append(
                            (name, op))

    def _const(self, fname: str, token: str, env, steps: int = 4) -> bool:
        """``token`` is (transitively) a constant, through broadcasts /
        reshapes / converts and caller bindings recorded in ``env``."""
        fn = self.funcs[fname]
        tok = fn.resolve(token)
        for _ in range(steps):
            op = self.producers[fname].get(tok)
            if op is None:
                pos = self.arg_pos[fname].get(tok)
                if pos is not None and env is not None:
                    caller, call_op, cenv = env
                    if pos < len(call_op.operands):
                        return self._const(caller,
                                           call_op.operands[pos], cenv,
                                           steps)
                return False
            if op.name == "constant":
                return True
            if op.name in ("broadcast_in_dim", "reshape",
                           "convert") and op.operands:
                tok = fn.resolve(op.operands[0])
                continue
            return False
        return False

    def guarded(self, fname: str, token: str, env=None,
                depth: int = 10, level: int = 2) -> bool:
        """A ``select`` whose taken-or-not branch is a constant — the
        ``where(mask, idx, TRASH_BLOCK)`` clip+trash routing — is
        reachable backward from ``token``."""
        fn = self.funcs[fname]
        frontier = {fn.resolve(token)}
        seen = set()
        args_hit: List[int] = []
        for _ in range(depth):
            nxt = set()
            for tok in frontier:
                if tok in seen:
                    continue
                seen.add(tok)
                op = self.producers[fname].get(tok)
                if op is None:
                    pos = self.arg_pos[fname].get(tok)
                    if pos is not None:
                        if env is not None:
                            caller, call_op, cenv = env
                            if pos < len(call_op.operands) \
                                    and level > 0 and self.guarded(
                                        caller, call_op.operands[pos],
                                        env=cenv, depth=depth,
                                        level=level - 1):
                                return True
                        else:
                            args_hit.append(pos)
                    continue
                if op.name == "select" and any(
                        self._const(fname, b, env)
                        for b in op.operands[1:]):
                    return True
                if op.name == "call" and level > 0:
                    callee = _callee(op)
                    if callee in self.funcs:
                        for ret in self.funcs[callee].returns:
                            if any(self.guarded(callee, rt,
                                                env=(fname, op, env),
                                                depth=depth,
                                                level=level - 1)
                                   for rt in ret.operands):
                                return True
                    continue
                for o in op.operands:
                    nxt.add(fn.resolve(o))
            frontier = nxt
        if args_hit and level > 0 and env is None:
            # the chain left through this function's arguments: the
            # guard must hold at EVERY call site (each call executes
            # the scatter with its own indices)
            sites = self.call_sites.get(fname, [])
            return bool(sites) and all(
                any(pos < len(call_op.operands)
                    and self.guarded(caller, call_op.operands[pos],
                                     depth=depth, level=level - 1)
                    for pos in args_hit)
                for caller, call_op in sites)
        return False


def _token_elem(fn: dflow.FuncDef, producers: Dict[str, dflow.Op],
                token: str) -> Optional[str]:
    tok = fn.resolve(token)
    op = producers.get(tok)
    if op is not None:
        return op.result_elem
    for arg_tok, payload in fn.args:
        if arg_tok == tok:
            return dflow.element_type(payload)
    return None


# ---------------------------------------------------------------------------
# the registered pass
# ---------------------------------------------------------------------------

def determinism_findings(text: str) -> List[Finding]:
    """All per-lane determinism findings for one lowered module."""
    funcs = dflow.parse_module(text)
    mn = dflow.main_func(funcs)
    if mn is None:
        return [Finding(_PASS, "error", "no function found in the "
                        "lowered module", op="det-parse")]
    rng = _rng_funcs(funcs)
    calls = _call_graph(funcs)
    called = set().union(*calls.values()) if calls else set()
    walk = _IndexWalk(funcs)

    findings: List[Finding] = []
    n_epilogue = n_scatter = n_rng_calls = n_barriers = 0

    # tie-prone private functions: outlined argmax/top-k bodies — the
    # finding attributes at the CALL SITE (where the escape analysis
    # can see the operand's provenance), not inside the outlined body
    tie_funcs: Dict[str, str] = {}
    for name, fn in funcs.items():
        sites = _tie_sites(fn)
        if sites and name in called:
            tie_funcs[name] = sites[0][1]

    for name, fn in funcs.items():
        producers = _producers(fn)

        # --- det-tie-argmax -------------------------------------------
        sites: List[Tuple[dflow.Op, str, Optional[str]]] = []
        for op in fn.ops:
            if op.name == "call" and _callee(op) in tie_funcs:
                floats = [t for t, ty in zip(op.operands, op.types)
                          if _is_float(dflow.element_type(ty))]
                sites.append((op, tie_funcs[_callee(op)],
                              floats[0] if floats else (
                                  op.operands[0] if op.operands
                                  else None)))
        if name not in tie_funcs:
            # inline tie sites in a function nobody calls (main): no
            # call site will carry them, flag the op itself
            sites += [(o, k, o.operands[0] if o.operands else None)
                      for o, k in _tie_sites(fn)]
        for site, skind, stok in sites:
                n_epilogue += 1
                if stok is not None and _derives_from_rng(
                        fn, producers, stok, rng):
                    findings.append(Finding(
                        _PASS, "info",
                        f"key-perturbed tie-break ({skind}): operand "
                        f"derives from a random-bits expansion — the "
                        f"legal seeded draw",
                        op="det-tie-argmax", lineno=site.lineno))
                else:
                    findings.append(Finding(
                        _PASS, "error",
                        f"ulp-tie hazard: {skind} over float values "
                        f"not in the reassociation-proof greedy_argmax "
                        f"form — a one-ulp remat/reassociation can "
                        f"move the winner",
                        op="det-tie-argmax", lineno=site.lineno,
                        example=site.line.strip()[:160]))

        # --- det-scatter-order ----------------------------------------
        for op in fn.ops:
            if op.name != "scatter":
                continue
            n_scatter += 1
            if "unique_indices = true" in op.line:
                findings.append(Finding(
                    _PASS, "info", "scatter with unique_indices=true: "
                    "update disjointness proven", op="det-scatter-order",
                    lineno=op.lineno))
            elif len(op.operands) >= 2 and walk.guarded(
                    name, op.operands[1]):
                findings.append(Finding(
                    _PASS, "info", "non-unique scatter with clip+trash "
                    "index routing: masked writes statically land in "
                    "the sacrificial block", op="det-scatter-order",
                    lineno=op.lineno))
            else:
                findings.append(Finding(
                    _PASS, "error",
                    "scatter with statically non-provably-disjoint "
                    "update windows (unique_indices=false, no "
                    "clip+trash index guard): colliding writes commit "
                    "in unspecified order",
                    op="det-scatter-order", lineno=op.lineno,
                    example=op.line.strip()[:160]))

        # --- det-prng-reuse -------------------------------------------
        consumers_by_tok: Dict[str, List[dflow.Op]] = {}
        for op in fn.ops:
            if op.name == "call" and _callee(op) in rng:
                n_rng_calls += 1
                for t in op.operands:
                    consumers_by_tok.setdefault(
                        fn.resolve(t), []).append(op)
        for tok, ops in consumers_by_tok.items():
            if len(ops) < 2:
                continue
            if _token_elem(fn, producers, tok) != "ui32":
                continue  # shared f32 minval/maxval scalars are fine
            findings.append(Finding(
                _PASS, "error",
                f"PRNG key reuse: one key token feeds {len(ops)} "
                f"independent random-bits expansions "
                f"({', '.join(sorted({_callee(o) or '?' for o in ops}))})"
                f" — draws are correlated and remat-unstable",
                op="det-prng-reuse", lineno=ops[0].lineno, count=1,
                example=ops[0].line.strip()[:160]))

        n_barriers += sum(1 for op in fn.ops
                          if op.name == "optimization_barrier")

    # --- det-multi-materialize (program outputs: main only) -----------
    producers = _producers(mn)
    main_tie_ids = {id(o) for o, _k in _tie_sites(mn)}
    epilogue_uses: Dict[str, List[Tuple[dflow.Op, str]]] = {}
    for op in mn.ops:
        why = None
        if op.name == "call":
            c = _callee(op)
            if c in tie_funcs:
                why = f"tie-breaking call @{c}"
            elif c in rng:
                why = f"random-bits call @{c}"
        elif id(op) in main_tie_ids:
            why = "inline tie-break"
        if why:
            for t in op.operands:
                epilogue_uses.setdefault(
                    mn.resolve(t), []).append((op, why))
    ret_tokens = []
    for ret in mn.returns:
        for t in ret.operands:
            tok = mn.resolve(t)
            if tok not in ret_tokens:
                ret_tokens.append(tok)
    for tok in ret_tokens:
        if tok not in epilogue_uses:
            continue
        prod = producers.get(tok)
        if prod is None:
            continue  # a function argument: an input, not a remat
        if not _is_float(prod.result_elem):
            continue
        use_op, why = epilogue_uses[tok][0]
        if prod.name == "optimization_barrier":
            findings.append(Finding(
                _PASS, "info",
                f"barrier-pinned shared value: {why} and a program "
                f"output both read one materialization",
                op="det-multi-materialize", lineno=prod.lineno))
        else:
            findings.append(Finding(
                _PASS, "error",
                f"multi-materialization hazard: value {tok} (from "
                f"{prod.name}) is both a program output and feeds "
                f"{why}, with no optimization_barrier pinning one "
                f"materialization — remat can hand the two uses "
                f"ulp-different copies (the pin_logits class)",
                op="det-multi-materialize", lineno=use_op.lineno,
                example=prod.line.strip()[:160]))

    # evidence counters: the DETLINT 'checked' block re-derives from
    # these, so a lane that linted nothing cannot read as clean-by-vacuum
    findings.append(Finding(_PASS, "info", "argmax/top-k/sort epilogue "
                            "sites examined", op="det-epilogue-sites",
                            count=n_epilogue))
    findings.append(Finding(_PASS, "info", "scatter sites examined",
                            op="det-scatter-sites", count=n_scatter))
    findings.append(Finding(_PASS, "info", "random-bits expansion call "
                            "sites", op="det-rng-calls",
                            count=n_rng_calls))
    findings.append(Finding(_PASS, "info", "optimization_barrier pins",
                            op="det-barriers", count=n_barriers))
    return findings


def determinism_pass(ctx: PassContext, **options) -> List[Finding]:
    return determinism_findings(ctx.stablehlo_text)


register_pass("determinism", determinism_pass)


# ---------------------------------------------------------------------------
# the cross-lane reduction-shape comparator (det-lane-shape-variant)
# ---------------------------------------------------------------------------

def reduction_signatures(text: str) -> List[Tuple[str, Tuple[int, ...],
                                                  Tuple[str, ...]]]:
    """The module's float reduction signature stream, in text order.

    One entry per float contraction/reduce: ``(kind, contracted dim
    sizes, element types)`` where kind is ``"dot"`` or
    ``"reduce:<applied op>"`` (``"reduce:region"`` for generic
    region-bodied reduces) and the element types run operands-then-
    result, so ``preferred_element_type`` accumulation shows up as the
    trailing dtype.  Batch/free dims are deliberately EXCLUDED — b1 vs
    b8 must compare equal when the per-element accumulation order is
    identical; only the contracted extent can move a bit.  Integer-only
    entries are dropped: integer addition is associative, its order
    cannot change the result.
    """
    sigs: List[Tuple[str, Tuple[int, ...], Tuple[str, ...]]] = []
    for fn in dflow.parse_module(text).values():
        for op in fn.ops:
            if op.name == "dot_general":
                m = _CONTRACT.search(op.line)
                if not m or len(op.types) < 2:
                    continue
                lhs = dflow.dims_of(op.types[0])
                contracted = tuple(
                    lhs[int(d)] for d in m.group(1).split(",")
                    if d.strip().isdigit() and int(d) < len(lhs))
                elems = tuple(dflow.element_type(t) for t in op.types)
                if any(_is_float(e) for e in elems):
                    sigs.append(("dot", contracted, elems))
            elif op.name == "reduce":
                am = _APPLIES.search(op.line)
                kind = f"reduce:{am.group(1)}" if am else "reduce:region"
                elems = tuple(dflow.element_type(t) for t in op.types)
                if any(_is_float(e) for e in elems):
                    sigs.append((kind, op.reduce_dims(), elems))
    return sigs


def signature_json(sigs: Sequence[Tuple[str, Tuple[int, ...],
                                        Tuple[str, ...]]]) -> list:
    """JSON-ready form: ``[[kind, [dims...], [elems...]], ...]``."""
    return [[k, list(d), list(e)] for k, d, e in sigs]


def compare_signatures(name_a: str, sigs_a, name_b: str,
                       sigs_b) -> dict:
    """Diff two lanes' signature streams — the
    ``det-lane-shape-variant`` verdict.

    ``"cleared"`` when the multisets match (the two programs perform
    the same float accumulations in the same shapes and dtypes;
    ``positional`` additionally records whether they match in program
    order).  Otherwise ``"variant"`` with one record per signature
    present in only one lane.
    """
    a = [tuple((k, tuple(d), tuple(e))) for k, d, e in sigs_a]
    b = [tuple((k, tuple(d), tuple(e))) for k, d, e in sigs_b]
    counts: Dict[tuple, int] = {}
    for s in a:
        counts[s] = counts.get(s, 0) + 1
    for s in b:
        counts[s] = counts.get(s, 0) - 1
    variants = []
    for sig in sorted(k for k, v in counts.items() if v != 0):
        n = counts[sig]
        variants.append({
            "only_in": name_a if n > 0 else name_b,
            "kind": sig[0], "dims": list(sig[1]), "elems": list(sig[2]),
            "count": abs(n)})
    return {"verdict": "cleared" if not variants else "variant",
            "positional": a == b, "variants": variants,
            "counts": {name_a: len(a), name_b: len(b)}}
