"""PROFILE_DRIFT_r*.json — schema for the committed continuous-profile
drift artifact, and the ONE drift-sentinel rule.

``tools/continuous_profile.py`` writes one of these per round: a
scripted continuous-profiling session over the serve engine — bounded
capture windows parsed through :mod:`apex_tpu.obs.xplane`, bucketed
through the shared compiled-HLO classifiers
(:mod:`apex_tpu.obs.stepclass`), judged online by the
:class:`~apex_tpu.obs.contprof.DriftSentinel` — with TWO lanes: a
**clean** session the sentinel must stay quiet on, and a
**seeded-regression** session (a documented synthetic op-time
inflation of one bucket) the sentinel must catch, naming the drifting
bucket, in exactly ``k`` windows.

The sentinel rule lives HERE, as pure stdlib functions, because the
schema must RE-DERIVE every verdict from the recorded windows — a
quiet verdict over a recorded window sequence that derives a
confirmed drift is a CONTRADICTORY record and schema-invalid, exactly
the SCENARIO/TRACE/TIMELINE discipline.  The online sentinel
(:mod:`apex_tpu.obs.contprof`) imports these functions instead of
carrying a second copy, so the live tripwire and the committed
artifact's validator can never disagree:

- :func:`out_of_band` — one window vs the baseline under the PR-13
  statistical band rule (:data:`DEFAULT_BAND` 0.03 fallback; a
  recorded variance-derived width always wins): a bucket FRACTION is
  out when it moved more than ``band`` in absolute terms (fractions
  near zero make relative bands meaningless), the step WALL is out
  when it sits above ``baseline × (1 + band)`` (slower only — faster
  is not a regression);
- :func:`replay_sentinel` — the K-consecutive confirmation machine: a
  drift is confirmed only after ``k`` consecutive out-of-band windows
  (never a single noisy one), latches until a fully in-band window,
  and names the drifting bucket (the excursion present in all ``k``
  windows with the largest mean |delta|; ties break by name).

Like the other round artifacts this is gate memory:
``tools/gate_hygiene.py`` validates every committed
``PROFILE_DRIFT_r*.json`` here.  Deliberately **stdlib-only** (no
jax): gate_hygiene loads it by file path.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

#: default statistical band width (the PR-13 fallback — the lower
#: edge of the documented chip-day variance); a recorded
#: variance-derived width always overrides it.
DEFAULT_BAND = 0.03

#: the decode bucket vocabulary — MUST equal
#: ``apex_tpu.analysis.decode_profile.BUCKETS`` and
#: ``apex_tpu.obs.stepclass.DECODE_BUCKETS`` (duplicated because
#: gate_hygiene loads each schema module standalone by file path;
#: ``tests/l0/test_contprof.py`` pins the tuples equal).
DECODE_BUCKETS = ("param_read", "kv_read", "kv_write", "attention",
                  "sampling", "host_sync", "other")

#: the pinned train-step vocabulary — MUST equal
#: ``apex_tpu.obs.stepclass.TRAIN_BUCKETS`` (same arrangement).
TRAIN_BUCKETS = ("fwd", "bwd", "optimizer", "collectives", "host_gap",
                 "other")

#: profile kinds and the bucket vocabulary each one buckets into
KINDS = {"decode": DECODE_BUCKETS, "serve-decode": DECODE_BUCKETS,
         "train": TRAIN_BUCKETS}


def _num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool)


# ---------------------------------------------------------------------------
# the sentinel rule (imported by apex_tpu.obs.contprof — one copy)
# ---------------------------------------------------------------------------

def out_of_band(fractions: Dict[str, float],
                step_wall_s: Optional[float],
                baseline: dict, band: float) -> List[dict]:
    """Excursions of one window against the baseline: ``[{"metric",
    "value", "baseline", "delta"}, ...]`` sorted by metric name.  A
    bucket fraction is out when ``|frac − base| > band`` (absolute
    move); the step wall is out when ``wall > base × (1 + band)``
    (``delta`` records the relative excess).  Judged on the RECORDED
    (rounded) numbers, so the validator re-derives exactly what the
    sentinel saw."""
    out: List[dict] = []
    base_fr = baseline.get("fractions") or {}
    for bucket in sorted(set(base_fr) | set(fractions or {})):
        f, bf = (fractions or {}).get(bucket), base_fr.get(bucket)
        if not (_num(f) and _num(bf)):
            continue
        delta = round(float(f) - float(bf), 4)
        if abs(delta) > band:
            out.append({"metric": bucket, "value": f, "baseline": bf,
                        "delta": delta})
    bw = baseline.get("step_wall_s")
    if _num(step_wall_s) and _num(bw) and bw > 0 \
            and step_wall_s > bw * (1.0 + band):
        out.append({"metric": "step_wall", "value": step_wall_s,
                    "baseline": bw,
                    "delta": round(step_wall_s / bw - 1.0, 4)})
    return out


def confirm_bucket(excursion_lists: List[List[dict]]) -> str:
    """The drifting bucket of a confirmed run of out-of-band windows:
    prefer metrics present in EVERY window of the run, rank by mean
    |delta| over the windows where the metric appears, break ties by
    name.  Deterministic — the validator re-derives it."""
    per_metric: Dict[str, List[float]] = {}
    for exc in excursion_lists:
        for e in exc:
            per_metric.setdefault(e["metric"], []).append(
                abs(float(e["delta"])))
    in_all = [m for m, ds in per_metric.items()
              if len(ds) == len(excursion_lists)]
    pool = in_all if in_all else list(per_metric)
    return min(pool,
               key=lambda m: (-sum(per_metric[m]) / len(per_metric[m]),
                              m))


def replay_sentinel(windows: List[dict], baseline: dict, band: float,
                    k: int) -> List[dict]:
    """Run the K-consecutive confirmation machine over recorded
    windows; returns the confirmed drifts ``[{"window", "bucket",
    "windows_out"}, ...]`` the sentinel must have produced.  A drift
    confirms at the ``k``-th consecutive out-of-band window, then
    LATCHES (no re-confirmation) until a fully in-band window resets
    the machine."""
    drifts: List[dict] = []
    run: List[List[dict]] = []
    active = False
    for w in windows:
        exc = out_of_band(w.get("fractions") or {},
                          w.get("step_wall_s"), baseline, band)
        if not exc:
            run = []
            active = False
            continue
        run.append(exc)
        if not active and len(run) >= k:
            drifts.append({"window": w.get("index"),
                           "bucket": confirm_bucket(run[-k:]),
                           "windows_out": len(run)})
            active = True
    return drifts


# ---------------------------------------------------------------------------
# schema validation
# ---------------------------------------------------------------------------

def _check_session(name: str, sess, band: float, k: int,
                   buckets, problems: List[str]) -> None:
    if not isinstance(sess, dict):
        problems.append(f"sessions[{name}] is not an object")
        return
    base = sess.get("baseline")
    if not isinstance(base, dict) or \
            not isinstance(base.get("fractions"), dict) or \
            not isinstance(base.get("source"), str):
        problems.append(f"sessions[{name}].baseline needs a 'source' "
                        f"str and a 'fractions' object")
        return
    bf = base["fractions"]
    unknown = [b for b in bf if b not in buckets]
    if unknown:
        problems.append(
            f"sessions[{name}].baseline carries unknown buckets "
            f"{sorted(unknown)} — one pinned vocabulary per kind")
    s = sum(float(v) for v in bf.values() if _num(v))
    if not 0.9 <= s <= 1.1:
        problems.append(f"sessions[{name}].baseline fractions sum to "
                        f"{s:.4f}, expected ~1")

    windows = sess.get("windows")
    if not isinstance(windows, list) or not windows:
        problems.append(f"sessions[{name}].windows missing/empty — a "
                        f"session with no captures judges nothing")
        return
    last = None
    for i, w in enumerate(windows):
        if not isinstance(w, dict) or \
                not isinstance(w.get("index"), int) or \
                not isinstance(w.get("fractions"), dict):
            problems.append(f"sessions[{name}].windows[{i}] needs an "
                            f"int index and a fractions object")
            return
        if last is not None and w["index"] <= last:
            problems.append(f"sessions[{name}].windows not strictly "
                            f"index-ascending at position {i}")
            return
        last = w["index"]
        wu = [b for b in w["fractions"] if b not in buckets]
        if wu:
            problems.append(
                f"sessions[{name}].windows[{i}] carries unknown "
                f"buckets {sorted(wu)}")
        # -- the recorded excursions must re-derive from the window's
        # own recorded fractions and the stated band (a window marked
        # in-band while its numbers sit out of band is the lie the
        # whole schema exists to reject)
        derived = out_of_band(w["fractions"], w.get("step_wall_s"),
                              base, band)
        stated = w.get("out_of_band")
        if not isinstance(stated, list):
            problems.append(f"sessions[{name}].windows[{i}] missing "
                            f"'out_of_band' list (empty = in-band)")
            continue
        dm = [e["metric"] for e in derived]
        stated_sorted = sorted(
            [e for e in stated if isinstance(e, dict)],
            key=lambda e: str(e.get("metric")))
        sm = [e.get("metric") for e in stated_sorted]
        if dm != sorted_metrics(sm):
            problems.append(
                f"CONTRADICTORY record: sessions[{name}].windows[{i}]"
                f" states out_of_band metrics {sm} but its recorded "
                f"fractions derive {dm} under band {band}")
            continue
        # names agree — the NUMBERS must re-derive too: an excursion
        # naming the right metric but carrying invented value/
        # baseline/delta fields (a dramatized drift, a minimized one)
        # is the same fabrication class
        for d_e, s_e in zip(derived, stated_sorted):
            bad = [f for f in ("value", "baseline", "delta")
                   if not _num(s_e.get(f))
                   or abs(float(s_e[f]) - float(d_e[f])) > 1e-9]
            if bad:
                problems.append(
                    f"CONTRADICTORY record: sessions[{name}]"
                    f".windows[{i}] out_of_band "
                    f"[{d_e['metric']!r}] states "
                    f"{ {f: s_e.get(f) for f in bad} } but "
                    f"re-deriving from the recorded fractions gives "
                    f"{ {f: d_e[f] for f in bad} }")
                break

    # -- verdicts must replay: the K-consecutive machine over the
    # recorded windows IS the ground truth
    derived_drifts = replay_sentinel(windows, base, band, k)
    stated_drifts = sess.get("drifts")
    if not isinstance(stated_drifts, list):
        problems.append(f"sessions[{name}] missing 'drifts' list "
                        f"(empty is fine — absent asserts nothing)")
        stated_drifts = []
    d_pairs = [(d["window"], d["bucket"]) for d in derived_drifts]
    s_pairs = [(d.get("window"), d.get("bucket"))
               for d in stated_drifts if isinstance(d, dict)]
    if d_pairs != s_pairs:
        problems.append(
            f"CONTRADICTORY record: sessions[{name}].drifts states "
            f"{s_pairs} but replaying the sentinel over the recorded "
            f"windows (band {band}, k {k}) derives {d_pairs} — a "
            f"quiet verdict over out-of-band windows (or an invented "
            f"drift) is invalid")
    quiet = sess.get("quiet")
    if not isinstance(quiet, bool):
        problems.append(f"sessions[{name}] missing bool 'quiet'")
    elif quiet != (len(stated_drifts) == 0):
        problems.append(
            f"CONTRADICTORY record: sessions[{name}].quiet={quiet} "
            f"but the session records {len(stated_drifts)} drift(s)")


def sorted_metrics(metrics: List[str]) -> List[str]:
    """Stated excursion metrics, normalized for comparison (the
    derivation emits them sorted by name)."""
    return sorted(m for m in metrics if isinstance(m, str))


def validate_profile_drift(doc) -> List[str]:
    """Problems with one parsed PROFILE_DRIFT document (empty =
    valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("round"), int):
        problems.append("missing/invalid 'round' (int)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    kind = doc.get("kind")
    if kind not in KINDS:
        problems.append(f"missing/unknown 'kind' {kind!r} (one of "
                        f"{sorted(KINDS)})")
        return problems
    buckets = KINDS[kind]

    band_rec = doc.get("band")
    if not isinstance(band_rec, dict) or not _num(band_rec.get("value")) \
            or not 0.0 < band_rec["value"] < 1.0 \
            or not isinstance(band_rec.get("source"), str):
        problems.append("missing/invalid 'band' (object with a "
                        "'value' in (0,1) and a 'source' str)")
        return problems
    band = float(band_rec["value"])
    k = doc.get("k")
    if not (isinstance(k, int) and k >= 1):
        problems.append("missing/invalid 'k' (int >= 1) — the "
                        "consecutive-window confirmation count")
        return problems
    if k < 2:
        problems.append("k must be >= 2: a sentinel confirming on a "
                        "single window alarms on every noisy capture")

    sessions = doc.get("sessions")
    if not isinstance(sessions, dict) or not sessions:
        problems.append("missing/empty 'sessions' map")
        return problems
    for name, sess in sorted(sessions.items()):
        _check_session(name, sess, band, k, buckets, problems)

    # -- the two mandatory lanes + the gate that re-derives from them
    clean = sessions.get("clean")
    seeded = sessions.get("seeded")
    if not isinstance(clean, dict):
        problems.append("missing 'clean' session — the sentinel must "
                        "demonstrably stay quiet on an undisturbed run")
    if not isinstance(seeded, dict):
        problems.append("missing 'seeded' session — the sentinel must "
                        "demonstrably catch a seeded regression")
    else:
        seed = seeded.get("seed")
        if not isinstance(seed, dict) or seed.get("bucket") not in \
                buckets or not _num(seed.get("factor")):
            problems.append("'seeded' session missing 'seed' "
                            "(bucket + factor) — an undocumented "
                            "synthetic regression is indistinguishable "
                            "from a fabricated catch")
        else:
            drifts = seeded.get("drifts") or []
            first = drifts[0] if drifts and isinstance(drifts[0], dict) \
                else {}
            if first.get("bucket") != seed["bucket"]:
                problems.append(
                    f"CONTRADICTORY record: the seeded session "
                    f"inflated bucket {seed['bucket']!r} but the "
                    f"first confirmed drift names "
                    f"{first.get('bucket')!r} — the sentinel must "
                    f"name the bucket that actually drifted")

    gate = doc.get("gate")
    if not isinstance(gate, dict) or \
            not isinstance(gate.get("clean_quiet"), bool) or \
            not isinstance(gate.get("seeded_caught"), bool) or \
            not isinstance(gate.get("ok"), bool):
        problems.append("missing/invalid 'gate' (clean_quiet + "
                        "seeded_caught + ok bools)")
    elif isinstance(clean, dict) and isinstance(seeded, dict):
        d_clean = clean.get("quiet") is True
        d_caught = bool(seeded.get("drifts"))
        if gate["clean_quiet"] != d_clean:
            problems.append(
                f"CONTRADICTORY verdict: gate.clean_quiet="
                f"{gate['clean_quiet']} but the clean session derives "
                f"{d_clean}")
        if gate["seeded_caught"] != d_caught:
            problems.append(
                f"CONTRADICTORY verdict: gate.seeded_caught="
                f"{gate['seeded_caught']} but the seeded session "
                f"derives {d_caught}")
        if gate["ok"] != (d_clean and d_caught):
            problems.append(
                f"CONTRADICTORY verdict: gate.ok={gate['ok']} but the "
                f"sessions derive {d_clean and d_caught}")

    if not (isinstance(doc.get("note"), str) and doc["note"].strip()):
        problems.append("missing/empty 'note' (str)")
    return problems


def validate_profile_drift_file(path: str) -> List[str]:
    """Problems with one PROFILE_DRIFT_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable profile-drift JSON: {e}"]
    return validate_profile_drift(doc)
