"""MEMLINT_r*.json — schema for the committed memory-lint artifact.

``tools/graph_lint.py --emit-json`` writes one of these per round: the
static memory/cost story of every lint lane (per-lane peak HBM bytes,
the donation-aliasing table, cost-model flops/bytes) plus the
multichip dryrun slices' per-device HBM.  Like the incident records,
the artifact is gate memory: ``tools/gate_hygiene.py`` validates every
committed ``MEMLINT_r*.json`` against this schema so the numbers can't
rot into prose nobody machine-checks.

This module is deliberately **stdlib-only** (no jax import):
``gate_hygiene`` loads it directly by file path the same way it loads
``resilience/incidents.py``.

Document shape::

    {
      "round": 1,
      "platform": "cpu",               # backend the lint compiled for
      "budget_bytes": 17179869184,     # device budget the lanes were
                                       # gated against (null = ungated)
      "lanes": {
        "<lane>": {
          "ok": true,                  # no error-severity finding
          "peak_hbm_bytes": 123456,    # per-device static high-water
          "breakdown": {"argument_bytes": ..., "output_bytes": ...,
                        "temp_bytes": ..., "alias_bytes": ...},
          "donation": [{"arg": "...", "bytes": 1, "aliased": true}],
          "cost": {"flops": 1.0, "hbm_bytes": 2.0},
          "findings": {"error": 0, "warning": 0, "info": 5}
        }, ...
      },
      "multichip": {                   # optional: dryrun slice summary
        "n_devices": 8,
        "slices": {"<slice>": {"ok": true,
                               "hbm_bytes_per_device": 4096}}
      }
    }
"""

from __future__ import annotations

import json
from typing import List

#: keys every lane record must carry, with their validators
_LANE_REQUIRED = {
    "ok": lambda v: isinstance(v, bool),
    "peak_hbm_bytes": lambda v: isinstance(v, int) and v >= 0,
    "donation": lambda v: isinstance(v, list),
    "cost": lambda v: isinstance(v, dict),
    "findings": lambda v: isinstance(v, dict),
}


def validate_memlint(doc) -> List[str]:
    """Problems with one parsed MEMLINT document (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("round"), int):
        problems.append("missing/invalid 'round' (int)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    lanes = doc.get("lanes")
    if not isinstance(lanes, dict) or not lanes:
        return problems + ["missing/empty 'lanes' object"]
    for name, lane in lanes.items():
        if not isinstance(lane, dict):
            problems.append(f"lane {name!r} is not an object")
            continue
        for key, check in _LANE_REQUIRED.items():
            if key not in lane:
                problems.append(f"lane {name!r} missing {key!r}")
            elif not check(lane[key]):
                problems.append(f"lane {name!r} has invalid {key!r}: "
                                f"{lane[key]!r}")
        for entry in lane.get("donation") or []:
            if not (isinstance(entry, dict) and "arg" in entry
                    and isinstance(entry.get("aliased"), bool)):
                problems.append(
                    f"lane {name!r} donation entry malformed: "
                    f"{entry!r}")
                break
        cost = lane.get("cost")
        if isinstance(cost, dict) and cost:
            for key in ("flops", "hbm_bytes"):
                if not isinstance(cost.get(key), (int, float)):
                    problems.append(
                        f"lane {name!r} cost missing numeric {key!r}")
    multi = doc.get("multichip")
    if multi is not None:
        if not isinstance(multi, dict) or \
                not isinstance(multi.get("slices"), dict):
            problems.append("'multichip' present but has no 'slices' "
                            "object")
        else:
            for sname, rec in multi["slices"].items():
                if not isinstance(rec, dict) or "ok" not in rec:
                    problems.append(f"multichip slice {sname!r} "
                                    f"malformed")
    return problems


def validate_memlint_file(path: str) -> List[str]:
    """Problems with one MEMLINT_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable memlint JSON: {e}"]
    return validate_memlint(doc)
