"""apex_tpu.analysis — static graph lint over lowered/compiled programs.

The reference apex's core guarantee is structural (O1 patches the whole
``torch`` namespace, DDP owns the gradient buckets); apex_tpu's
equivalent guarantees are *checkable*: the program is text, and the
silent TPU performance/correctness bug classes — dropped buffer
donation doubling HBM, accidental parameter all-gathers after SPMD
partitioning, comm-volume regressions, weight-sized constants baked
into the jaxpr, FP32-list math executing in 16-bit — are all statically
visible in the lowered StableHLO or compiled HLO.

Usage::

    from apex_tpu import analysis

    step = jax.jit(amp.make_train_step(a, loss_fn), donate_argnums=0)
    report = analysis.analyze(step, state, x, y)       # graph passes
    report = report.merged(analysis.analyze(           # + O1 policy
        forward, params, x, passes=("policy",), compile=False))
    if not report.ok:
        raise RuntimeError(report.format())

``tools/graph_lint.py`` runs exactly this over the four in-tree model
families and is wired into the test suite; per-pass details live in the
pass modules (:mod:`~apex_tpu.analysis.donation`,
:mod:`~apex_tpu.analysis.sharding`,
:mod:`~apex_tpu.analysis.collectives`,
:mod:`~apex_tpu.analysis.constants`,
:mod:`~apex_tpu.analysis.policy`).
"""

from apex_tpu.analysis.core import (
    DEFAULT_PASSES,
    PASSES,
    ArgInfo,
    OutInfo,
    PassContext,
    analyze,
    analyze_lowered,
    build_context,
    lower_quiet,
    register_pass,
    run_passes,
)
from apex_tpu.analysis.report import SEVERITIES, Finding, Report

# importing a pass module registers its pass; the import order here is
# the DEFAULT_PASSES execution order plus the opt-in passes (policy on
# forwards; memory/cost/syncs need — or prefer — the compiled
# executable, so the lane drivers request them explicitly)
from apex_tpu.analysis import donation     # noqa: F401  (registers)
from apex_tpu.analysis import sharding     # noqa: F401  (registers)
from apex_tpu.analysis import collectives  # noqa: F401  (registers)
from apex_tpu.analysis import constants    # noqa: F401  (registers)
from apex_tpu.analysis import policy       # noqa: F401  (registers)
from apex_tpu.analysis import memory       # noqa: F401  (registers)
from apex_tpu.analysis import cost         # noqa: F401  (registers)
from apex_tpu.analysis import syncs       # noqa: F401  (registers)
from apex_tpu.analysis import dflow        # noqa: F401  (shared walker)
from apex_tpu.analysis import precision    # noqa: F401  (registers)
from apex_tpu.analysis import export       # noqa: F401  (registers)
from apex_tpu.analysis import spmd         # noqa: F401  (registers)
from apex_tpu.analysis import pallas_lint  # noqa: F401  (registers)
from apex_tpu.analysis import determinism  # noqa: F401  (registers)

from apex_tpu.analysis.collectives import collective_audit, collective_table
from apex_tpu.analysis.spmd import (
    collective_schedule,
    compare_lowerings,
    diff_schedules,
    reshape_pair_findings,
    schedule_fingerprint,
)

__all__ = [
    "analyze", "analyze_lowered", "build_context", "lower_quiet",
    "run_passes", "register_pass",
    "ArgInfo", "OutInfo", "PassContext", "Finding", "Report",
    "PASSES", "DEFAULT_PASSES", "SEVERITIES",
    "collective_audit", "collective_table",
    "collective_schedule", "compare_lowerings", "diff_schedules",
    "reshape_pair_findings", "schedule_fingerprint",
    "donation", "sharding", "collectives", "constants", "policy",
    "memory", "cost", "syncs", "dflow", "precision", "export", "spmd",
    "pallas_lint", "determinism",
]
