"""Constant-capture lint: weights baked into the program.

A jitted function that *closes over* an array instead of taking it as
an argument gets that array burned into the jaxpr as a literal — the
classic hazard when porting eager training loops: the program re-traces
(and the executable re-serializes) whenever the "constant" changes, the
lowered module bloats by the full weight, and donation/sharding can
never apply to it.  A splat (single repeated value, e.g. an all-zeros
init cache) is exempt: XLA stores it as scalar + broadcast, so it costs
nothing and is a normal idiom.

The walk runs on the lowered StableHLO text: captured arrays print as
``stablehlo.constant dense<...>`` (or ``dense_resource<...>``) with the
full tensor type, so weight-sized non-splat literals are directly
visible, with their byte size, before anything compiles.
"""

from __future__ import annotations

import re
from typing import List

from apex_tpu.analysis.core import PassContext, register_pass
from apex_tpu.analysis.report import Finding

#: "weight-sized": 1 MiB of literal data in the program text is a
#: captured parameter, not a mask or an eps table.
DEFAULT_MIN_BYTES = 1 << 20

_CONST_LINE = re.compile(
    r"^\s*%\S+\s*=\s*stablehlo\.constant\s+"
    r"(?P<form>dense(?:_resource)?)<(?P<value>.*)>\s*:\s*"
    r"tensor<(?P<type>[0-9x?]*[a-z][a-z0-9]*)>\s*$")
_ELEM_BYTES = {"i1": 1, "i8": 1, "ui8": 1, "i16": 2, "ui16": 2,
               "f16": 2, "bf16": 2, "i32": 4, "ui32": 4, "f32": 4,
               "i64": 8, "ui64": 8, "f64": 8, "complex": 8}


def _tensor_bytes(type_str: str) -> "tuple[int, str]":
    """(nbytes, dtype) of a ``DxDx...xdtype`` tensor-type body."""
    parts = type_str.split("x")
    dtype = parts[-1]
    n = 1
    for d in parts[:-1]:
        if not d.isdigit():
            return 0, dtype   # dynamic dim — not a baked weight
        n *= int(d)
    return n * _ELEM_BYTES.get(dtype, 4), dtype


def _is_splat(form: str, value: str) -> bool:
    """``dense<3.0>`` is a splat; ``dense<[...]>``/``dense<"0x...">``/
    ``dense_resource<...>`` carry per-element data."""
    return form == "dense" and "[" not in value and '"' not in value


def constant_capture_pass(ctx: PassContext,
                          min_bytes: int = DEFAULT_MIN_BYTES,
                          ) -> List[Finding]:
    """Flag non-splat constants of ``min_bytes`` or more in the lowered
    program — arrays that should almost certainly be arguments."""
    findings: List[Finding] = []
    for lineno, line in enumerate(ctx.stablehlo_text.splitlines(), 1):
        if "stablehlo.constant" not in line:
            continue
        m = _CONST_LINE.match(line)
        if not m:
            continue
        if _is_splat(m.group("form"), m.group("value")):
            continue
        nbytes, dtype = _tensor_bytes(m.group("type"))
        if nbytes < min_bytes:
            continue
        findings.append(Finding(
            "constant-capture", "error",
            f"weight-sized constant tensor<{m.group('type')}> "
            f"({nbytes} bytes) is baked into the program — a closed-over "
            f"array that should be passed as an argument (re-traces on "
            f"every new value; donation/sharding cannot apply)",
            op="constant", dtype=dtype, bytes=nbytes, lineno=lineno,
            example=line.strip()[:120]))
    return findings


register_pass("constant-capture", constant_capture_pass)
