"""SERVE_DISAGG_r*.json — schema for the committed disaggregated-
serving gate artifact.

``tools/serve_disagg.py`` writes one of these per round: the
disaggregated-vs-monolithic offered-load A/B (one prefill mesh slice +
N decode replicas on disjoint slices behind the KV-shipping router,
versus one monolithic engine with the same total slots, fed the SAME
request stream) plus the replica-kill chaos drill.  The headline gate
is the DistServe/Splitwise claim in machine-checked form: at equal
device count, disaggregated decode p99 must not exceed the monolithic
engine's — and a recorded verdict that contradicts its own numbers is
SCHEMA-INVALID, so the artifact can never say "ok" over a lost A/B.

Like the other gate artifacts, this is gate memory:
``tools/gate_hygiene.py`` validates every committed
``SERVE_DISAGG_r*.json`` against this module in tier-1.

This module is deliberately **stdlib-only** (no jax import):
``gate_hygiene`` loads it directly by file path the same way it loads
the other ``apex_tpu/analysis`` schema modules.

Document shape::

    {
      "round": 1,
      "platform": "cpu",
      "config": {"model": "gpt_tiny", "concurrency": 16,
                 "prefill": 64, "new_tokens": 16, "block_size": 4},
      "topology": {                       # device slices, DISJOINT
        "n_devices": 16, "transfer": "ship",
        "prefill_devices": [0],
        "replica_devices": [[1], [2]]
      },
      "mono":   {"num_slots": 16, "tok_s": ..., "p50_ms": ...,
                 "p99_ms": ..., "steps": ..., "retraces": 1},
      "disagg": {"slots_per_replica": 8, "n_replicas": 2,
                 "tok_s": ..., "p50_ms": ..., "p99_ms": ...,
                 "per_replica": [{"steps": ..., "p50_ms": ...,
                                  "p99_ms": ...}, ...],
                 "kv_transfer_bytes": ..., "shipments": ...,
                 "reroutes": 0},
      "chaos":  {                         # the replica-kill drill
        "killed_replica": 0, "rerouted": 2, "bitwise_ok": true
      },
      "gate": {"p99_ok": true, "ok": true},
      "note": "..."
    }
"""

from __future__ import annotations

import json
from typing import List

#: the KV paths the router can run
TRANSFER_MODES = ("ship", "recompute")


def _num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def validate_serve_disagg(doc) -> List[str]:
    """Problems with one parsed SERVE_DISAGG document (empty =
    valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("round"), int):
        problems.append("missing/invalid 'round' (int)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    cfg = doc.get("config")
    if not isinstance(cfg, dict) or not all(
            isinstance(cfg.get(k), int)
            for k in ("concurrency", "prefill", "new_tokens")):
        problems.append("missing/invalid 'config' "
                        "(concurrency/prefill/new_tokens ints)")

    # -- topology: the slices must actually be disjoint ---------------
    topo = doc.get("topology")
    if not isinstance(topo, dict):
        problems.append("missing/invalid 'topology' object")
    else:
        if not isinstance(topo.get("n_devices"), int) \
                or topo["n_devices"] < 2:
            problems.append("topology.n_devices missing or < 2 "
                            "(a fleet needs a prefill AND a decode "
                            "slice)")
        if topo.get("transfer") not in TRANSFER_MODES:
            problems.append(
                f"topology.transfer {topo.get('transfer')!r} not in "
                f"{TRANSFER_MODES}")
        pre = topo.get("prefill_devices")
        reps = topo.get("replica_devices")
        if not (isinstance(pre, list) and pre
                and all(isinstance(d, int) for d in pre)):
            problems.append("topology.prefill_devices must be a "
                            "non-empty int list")
            pre = None
        if not (isinstance(reps, list) and reps
                and all(isinstance(r, list) and r
                        and all(isinstance(d, int) for d in r)
                        for r in reps)):
            problems.append("topology.replica_devices must be a "
                            "non-empty list of non-empty int lists")
            reps = None
        if pre is not None and reps is not None:
            slices = [pre] + list(reps)
            flat = [d for s in slices for d in s]
            if len(flat) != len(set(flat)):
                problems.append(
                    "topology slices OVERLAP — shared devices fake "
                    "the disaggregation (prefill bursts would steal "
                    "decode cycles)")
            if isinstance(topo.get("n_devices"), int) \
                    and len(flat) > topo["n_devices"]:
                problems.append(
                    f"topology claims {len(flat)} sliced devices on "
                    f"an n_devices={topo['n_devices']} platform")

    # -- the two arms -------------------------------------------------
    def check_arm(name):
        arm = doc.get(name)
        if not isinstance(arm, dict):
            problems.append(f"missing/invalid '{name}' object")
            return None
        for k in ("tok_s", "p50_ms", "p99_ms"):
            if not _num(arm.get(k)) or arm[k] < 0:
                problems.append(f"{name}.{k} missing or not a "
                                f"non-negative number: {arm.get(k)!r}")
                return None
        if arm["p99_ms"] < arm["p50_ms"]:
            problems.append(f"{name}: p99 {arm['p99_ms']} under p50 "
                            f"{arm['p50_ms']} — not a percentile pair")
        return arm

    mono = check_arm("mono")
    disagg = check_arm("disagg")
    if disagg is not None:
        for k in ("kv_transfer_bytes", "shipments", "reroutes"):
            if not _num(disagg.get(k)) or disagg[k] < 0:
                problems.append(f"disagg.{k} missing or not a "
                                f"non-negative number: "
                                f"{disagg.get(k)!r}")
        pr = disagg.get("per_replica")
        if not (isinstance(pr, list) and pr
                and all(isinstance(r, dict) for r in pr)):
            problems.append("disagg.per_replica must be a non-empty "
                            "list of per-replica records")
        if isinstance(topo, dict) and _num(disagg.get("shipments")) \
                and topo.get("transfer") == "ship" \
                and disagg["shipments"] > 0 \
                and _num(disagg.get("kv_transfer_bytes")) \
                and disagg["kv_transfer_bytes"] <= 0:
            problems.append(
                "disagg records shipments under transfer='ship' but "
                "zero kv_transfer_bytes — shipped KV moves bytes")

    # -- chaos drill --------------------------------------------------
    chaos = doc.get("chaos")
    if chaos is not None:
        if not isinstance(chaos, dict):
            problems.append("'chaos' present but not an object")
            chaos = None
        else:
            if not isinstance(chaos.get("killed_replica"), int):
                problems.append("chaos.killed_replica missing (int)")
            if not isinstance(chaos.get("rerouted"), int) \
                    or chaos["rerouted"] < 1:
                problems.append(
                    "chaos.rerouted missing or < 1 — a kill that "
                    "rerouted nothing drilled nothing")
            if not isinstance(chaos.get("bitwise_ok"), bool):
                problems.append("chaos.bitwise_ok missing (bool)")

    # -- the gate: verdicts must agree with their own numbers ---------
    gate = doc.get("gate")
    if not isinstance(gate, dict) \
            or not isinstance(gate.get("p99_ok"), bool) \
            or not isinstance(gate.get("ok"), bool):
        problems.append("missing/invalid 'gate' (p99_ok + ok bools)")
    else:
        if mono is not None and disagg is not None:
            derived = disagg["p99_ms"] <= mono["p99_ms"]
            if gate["p99_ok"] != derived:
                problems.append(
                    f"CONTRADICTORY verdict: gate.p99_ok="
                    f"{gate['p99_ok']} but disagg p99 "
                    f"{disagg['p99_ms']} vs mono p99 {mono['p99_ms']} "
                    f"derives {derived}")
        chaos_ok = True if chaos is None \
            else chaos.get("bitwise_ok") is True
        if gate["ok"] != (gate["p99_ok"] and chaos_ok):
            problems.append(
                f"CONTRADICTORY verdict: gate.ok={gate['ok']} but "
                f"p99_ok={gate['p99_ok']} and chaos "
                f"{'absent' if chaos is None else chaos.get('bitwise_ok')} "
                f"derive {gate['p99_ok'] and chaos_ok}")
    return problems


def validate_serve_disagg_file(path: str) -> List[str]:
    """Problems with one SERVE_DISAGG_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable serve-disagg JSON: {e}"]
    return validate_serve_disagg(doc)
