"""BENCH_VARIANCE_r*.json — schema for the committed repeated-timing
variance artifact (the statistics under every floor and band).

``tools/bench_variance.py`` writes one of these per measurement round:
N repeated timings per kernel / bench config, each entry carrying the
sample statistics (``n``, ``values``, ``mean``, ``min``, ``max``,
``std``, ``rel_spread``) that ``bench.derive_floor_bands()`` turns
into statistical gate floors (``floor = mean − k·std``) and
``tools/perf_timeline.py`` turns into per-series band widths.  A
floor justified by this artifact is justified by RECORDED variance,
not anecdote — ROADMAP item 1's "re-derive every floor and band width
from BENCH_VARIANCE.json statistics" made committable.

Contradiction rejection, like every gate schema in this family: an
entry's recorded ``mean``/``min``/``max``/``std``/``rel_spread`` must
AGREE with the ``values`` they summarize (within the tool's stated
rounding) and ``n`` must equal ``len(values)`` — a spread wide enough
to excuse a floor drop cannot be typed in, it has to be derivable
from the recorded samples.  Error entries (``{"error": ...}``) are
legal per-entry records (partial variance evidence beats none after
chip time is spent) but carry no statistical weight.

This module is deliberately **stdlib-only** (no jax import):
``tools/gate_hygiene.py`` loads it directly by file path in tier-1.

Document shape::

    {
      "platform": "tpu",
      "device_kind": "TPU v5e",
      "tiny": false,                # tiny smokes carry no evidence
      "round": 1,
      "entries": {
        "kernel:fused_adam": {
          "metric": "ms_per_step", "n": 5,
          "values": [..], "mean": .., "min": .., "max": ..,
          "std": .., "rel_spread": ..,
          "roofline_frac": {"n": 5, "values": [..], "mean": ..,
                            "min": .., "max": .., "std": ..,
                            "rel_spread": ..},      # optional sub-stat
          "geometry": {...}                          # optional
        },
        "config:gpt_small_o2": {
          "metric": "tok_s", ...,
          "mfu": {...}, "hbm_frac": {...}            # optional
        },
        "kernel:broken_one": {"error": "XlaRuntimeError: ..."}
      }
    }
"""

from __future__ import annotations

import json
import math
from typing import List

#: rounding the tool applies to values/mean/min/max (6 places) and to
#: rel_spread (4) — the agreement tolerance below covers it.
_VALUE_TOL = 2e-6
_SPREAD_TOL = 2e-4

#: nested sub-statistic blocks an entry may carry per metric family
SUB_STATS = ("mfu", "hbm_frac", "roofline_frac")


def _num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def _check_stats(name: str, e: dict, problems: List[str]) -> None:
    """One stats block: n/values present and self-consistent."""
    values = e.get("values")
    n = e.get("n")
    if not isinstance(values, list) or not values or \
            not all(_num(v) for v in values):
        problems.append(f"{name}: missing/empty 'values' list")
        return
    if not isinstance(n, int) or n != len(values):
        problems.append(f"{name}: n={n!r} but values has "
                        f"{len(values)} sample(s)")
    for field in ("mean", "min", "max"):
        if not _num(e.get(field)):
            problems.append(f"{name}: missing '{field}'")
            return
    derived_mean = sum(values) / len(values)
    tol = _VALUE_TOL * max(1.0, abs(derived_mean))
    if abs(e["mean"] - derived_mean) > tol:
        problems.append(
            f"CONTRADICTORY record: {name}.mean={e['mean']} but the "
            f"recorded values derive {round(derived_mean, 6)}")
    if abs(e["min"] - min(values)) > tol or \
            abs(e["max"] - max(values)) > tol:
        problems.append(
            f"CONTRADICTORY record: {name}.min/max disagree with the "
            f"recorded values")
    if not (e["min"] <= e["mean"] + tol and
            e["mean"] <= e["max"] + tol):
        problems.append(f"{name}: min <= mean <= max violated")
    spread = e.get("rel_spread")
    if spread is not None:
        if not _num(spread) or spread < 0:
            problems.append(f"{name}: rel_spread must be a "
                            f"non-negative number")
        elif derived_mean:
            derived = (max(values) - min(values)) / derived_mean
            if abs(spread - derived) > _SPREAD_TOL:
                problems.append(
                    f"CONTRADICTORY record: {name}.rel_spread="
                    f"{spread} but the recorded values derive "
                    f"{round(derived, 4)}")
    std = e.get("std")
    if std is not None:
        if not _num(std) or std < 0:
            problems.append(f"{name}: std must be a non-negative "
                            f"number")
        elif len(values) > 1:
            var = sum((v - derived_mean) ** 2 for v in values) \
                / (len(values) - 1)
            derived_std = math.sqrt(var)
            if abs(std - derived_std) > \
                    _VALUE_TOL * max(1.0, derived_std):
                problems.append(
                    f"CONTRADICTORY record: {name}.std={std} but the "
                    f"recorded values derive {round(derived_std, 6)}")


def validate_variance(doc) -> List[str]:
    """Problems with one parsed BENCH_VARIANCE document (empty =
    valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    if not isinstance(doc.get("tiny"), bool):
        problems.append("missing/invalid 'tiny' (bool — a tiny smoke "
                        "must say so: its spreads are not evidence)")
    entries = doc.get("entries")
    if not isinstance(entries, dict) or not entries:
        problems.append("missing/empty 'entries' map")
        return problems
    for key, e in sorted(entries.items()):
        if not (isinstance(key, str)
                and key.partition(":")[0] in ("kernel", "config")):
            problems.append(f"entry key {key!r} must be "
                            f"'kernel:<name>' or 'config:<name>'")
        if not isinstance(e, dict):
            problems.append(f"entries[{key}] is not an object")
            continue
        if "error" in e:
            if not isinstance(e["error"], str) or not e["error"]:
                problems.append(f"entries[{key}].error must be a "
                                f"non-empty string")
            continue
        _check_stats(f"entries[{key}]", e, problems)
        for sub in SUB_STATS:
            if sub in e:
                if not isinstance(e[sub], dict):
                    problems.append(f"entries[{key}].{sub} is not an "
                                    f"object")
                else:
                    _check_stats(f"entries[{key}].{sub}", e[sub],
                                 problems)
    return problems


def validate_variance_file(path: str) -> List[str]:
    """Problems with one BENCH_VARIANCE_r*.json file (empty =
    valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable variance JSON: {e}"]
    return validate_variance(doc)
