"""PREFIXCACHE_r*.json — the cross-request prefix-sharing gate
artifact and its contradiction-rejecting schema.

The serve engine's prefix cache (``apex_tpu/serve/paged.py`` /
``scheduler.py``) deduplicates KV across requests: content-addressed
blocks are shared by refcount, a full-prompt match forks copy-on-write,
and a hit request skips prefill for the matched span.  The claim worth
committing is an A/B over the SAME shared-system-prompt c16 stream —
sharing on vs sharing off at equal devices and equal requests:

- the sharing arm dispatches FEWER prefill tokens (work actually
  skipped, counted in deterministic tokens, not wall time), and
- the sharing arm admits MORE requests per resident block (the pool
  deduplication — same stream, smaller peak block footprint), and
- every streamed output stays BITWISE equal to solo ``generate()``
  (sharing is a perf optimization, never a fidelity trade).

Contradiction rejection, like every gate schema in this family: the
headline numbers must RE-DERIVE from the per-request spans the
scheduler recorded (``prefix_events``), and the gate verdict must
re-derive from the recorded numbers — a typed-in "ok", a hit rate the
spans refute, or a skipped-token total the spans don't add up to is
schema-invalid.  ``tools/gate_hygiene.py`` loads this module by file
path in tier-1, so the module stays **stdlib-only** (no jax import).

Document shape::

    {
      "round": 1,
      "platform": "cpu",
      "config": {"model": "gpt_tiny", "concurrency": 16,
                 "system_prompt_tokens": 32, "prefill": 64,
                 "new_tokens": 16, "block_size": 4},
      "sharing": {                       # prefix_cache=True arm
        "prefill_chunks": 34,            # fixed-size chunks dispatched
        "prefill_tokens_dispatched": 268,
        "admitted_requests": 16,
        "peak_live_blocks": 40,          # max allocator.live_count
        "admitted_requests_per_block": 0.4,
        "p50_ms": 1.9, "p99_ms": 3.2,    # engine's own histogram
        "retraces": 1,                   # decode executables minted
        "prefix": {
          "probes": 16, "hits": 15, "hit_rate": 0.9375,
          "hit_tokens": 480,             # tokens NOT re-prefilled
          "cow_copies": 1, "shared_blocks_peak": 8,
          "cached_evictions": 0,
          "requests": [                  # the scheduler's own spans
            {"uid": "c0", "prompt_len": 64, "matched": 0,
             "dispatched": 64}, ...]
        }
      },
      "baseline": {                      # prefix_cache=False arm
        "prefill_chunks": 128, "prefill_tokens_dispatched": 748,
        "admitted_requests": 16, "peak_live_blocks": 52,
        "admitted_requests_per_block": 0.307,
        "p50_ms": 1.8, "p99_ms": 3.1, "retraces": 1
      },
      "bitwise_ok": true,                # both arms vs solo generate()
      "gate": {"hit_rate_ok": true, "ab_ok": true,
               "bitwise_ok": true, "ok": true},
      "note": "..."
    }

Span semantics (what the scheduler records per admission):
``matched`` is the prefix length satisfied from the content index
(block-aligned; ``prompt_len`` itself on a full-prompt CoW match) and
``dispatched`` is what prefill actually re-ran — ``prompt_len -
matched``, floored at 1 because a full match still re-dispatches ONE
token through the CoW rewrite.  So ``dispatched == max(prompt_len -
matched, 1)`` per span, ``hit_tokens == Σ (prompt_len - dispatched)``,
and ``hits``/``probes``/``hit_rate`` count the spans directly.

Gate derivations the validator enforces:

- ``gate.hit_rate_ok == (prefix.hit_rate > 0)``;
- ``gate.ab_ok`` == sharing dispatched FEWER prefill tokens AND
  admitted MORE requests per block AND both arms stayed at one decode
  trace (``retraces == 1`` — sharing must not mint executables);
- ``gate.bitwise_ok == bitwise_ok``;
- ``gate.ok == hit_rate_ok and ab_ok and bitwise_ok``.
"""

from __future__ import annotations

import json
from typing import List

#: tolerance for re-derived ratios (hit_rate, requests-per-block)
_TOL = 1e-6


def _num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def _check_arm(name: str, arm, problems: List[str]) -> bool:
    """Structural fields every arm record carries; True when usable."""
    if not isinstance(arm, dict):
        problems.append(f"missing/invalid '{name}' arm (object)")
        return False
    ok = True
    for field in ("prefill_chunks", "prefill_tokens_dispatched",
                  "admitted_requests", "peak_live_blocks", "retraces"):
        if not isinstance(arm.get(field), int) or arm[field] < 0:
            problems.append(f"{name}.{field} missing (int >= 0)")
            ok = False
    for field in ("admitted_requests_per_block", "p50_ms", "p99_ms"):
        if not _num(arm.get(field)) or arm[field] < 0:
            problems.append(f"{name}.{field} missing (number >= 0)")
            ok = False
    if not ok:
        return False
    blocks = max(arm["peak_live_blocks"], 1)
    derived = arm["admitted_requests"] / blocks
    if abs(arm["admitted_requests_per_block"] - derived) > 1e-4:
        problems.append(
            f"CONTRADICTORY record: {name}.admitted_requests_per_block="
            f"{arm['admitted_requests_per_block']} but "
            f"admitted_requests/peak_live_blocks derives "
            f"{round(derived, 6)}")
    return True


def _check_prefix(prefix, problems: List[str]) -> bool:
    """The sharing arm's prefix block: headline counters must re-derive
    from the recorded per-request spans."""
    if not isinstance(prefix, dict):
        problems.append("missing/invalid 'sharing.prefix' (object)")
        return False
    ok = True
    for field in ("probes", "hits", "hit_tokens", "cow_copies",
                  "shared_blocks_peak", "cached_evictions"):
        if not isinstance(prefix.get(field), int) or prefix[field] < 0:
            problems.append(f"sharing.prefix.{field} missing (int >= 0)")
            ok = False
    if not _num(prefix.get("hit_rate")) or \
            not 0.0 <= prefix["hit_rate"] <= 1.0:
        problems.append("sharing.prefix.hit_rate missing (number in "
                        "[0, 1])")
        ok = False
    reqs = prefix.get("requests")
    if not isinstance(reqs, list) or not reqs:
        problems.append("sharing.prefix.requests missing/empty (the "
                        "per-request spans the headline counters must "
                        "re-derive from)")
        ok = False
    if not ok:
        return False

    hits = skipped = matched_total = 0
    for i, r in enumerate(reqs):
        if not isinstance(r, dict) or \
                not isinstance(r.get("uid"), str) or \
                not isinstance(r.get("prompt_len"), int) or \
                not isinstance(r.get("matched"), int) or \
                not isinstance(r.get("dispatched"), int):
            problems.append(
                f"sharing.prefix.requests[{i}] needs uid (str) + "
                f"prompt_len/matched/dispatched (int)")
            return False
        n, m, d = r["prompt_len"], r["matched"], r["dispatched"]
        if not (0 <= m <= n) or d != max(n - m, 1):
            problems.append(
                f"CONTRADICTORY record: sharing.prefix.requests[{i}] "
                f"({r['uid']!r}) states prompt_len={n} matched={m} "
                f"dispatched={d}, but dispatched must equal "
                f"max(prompt_len - matched, 1) — a full match still "
                f"re-dispatches one token through the CoW rewrite")
            return False
        matched_total += m
        if m > 0:
            hits += 1
        skipped += n - d
    if prefix["probes"] != len(reqs):
        problems.append(
            f"CONTRADICTORY record: sharing.prefix.probes="
            f"{prefix['probes']} but {len(reqs)} request span(s) are "
            f"recorded — every admission probes exactly once")
    if prefix["hits"] != hits:
        problems.append(
            f"CONTRADICTORY record: sharing.prefix.hits="
            f"{prefix['hits']} but the recorded spans derive {hits} "
            f"(matched > 0)")
    derived_rate = hits / max(len(reqs), 1)
    if abs(prefix["hit_rate"] - derived_rate) > _TOL:
        problems.append(
            f"CONTRADICTORY record: sharing.prefix.hit_rate="
            f"{prefix['hit_rate']} but the recorded spans derive "
            f"{round(derived_rate, 6)}")
    if prefix["hit_tokens"] != skipped:
        problems.append(
            f"CONTRADICTORY record: sharing.prefix.hit_tokens="
            f"{prefix['hit_tokens']} but the recorded spans derive "
            f"{skipped} skipped prefill tokens "
            f"(Σ prompt_len - dispatched)")
    return True


def validate_prefixcache(doc) -> List[str]:
    """Problems with one parsed PREFIXCACHE document (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("round"), int):
        problems.append("missing/invalid 'round' (int)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")

    cfg = doc.get("config")
    if not isinstance(cfg, dict):
        problems.append("missing/invalid 'config' (object)")
    else:
        for field in ("concurrency", "system_prompt_tokens", "prefill",
                      "new_tokens", "block_size"):
            if not isinstance(cfg.get(field), int) or cfg[field] <= 0:
                problems.append(f"config.{field} missing (int > 0)")
        if not isinstance(cfg.get("model"), str):
            problems.append("config.model missing (str)")

    sharing_ok = _check_arm("sharing", doc.get("sharing"), problems)
    baseline_ok = _check_arm("baseline", doc.get("baseline"), problems)
    prefix_ok = sharing_ok and _check_prefix(
        doc["sharing"].get("prefix"), problems)

    if not isinstance(doc.get("bitwise_ok"), bool):
        problems.append("missing/invalid 'bitwise_ok' (bool)")

    # -- arms must describe the SAME offered stream --------------------
    if sharing_ok and baseline_ok:
        sh, bl = doc["sharing"], doc["baseline"]
        if sh["admitted_requests"] != bl["admitted_requests"]:
            problems.append(
                f"CONTRADICTORY record: arms admitted different "
                f"request counts ({sh['admitted_requests']} vs "
                f"{bl['admitted_requests']}) — the A/B must run the "
                f"same stream")
        if prefix_ok and \
                sh["admitted_requests"] != doc["sharing"]["prefix"][
                    "probes"]:
            problems.append(
                f"CONTRADICTORY record: sharing arm admitted "
                f"{sh['admitted_requests']} request(s) but recorded "
                f"{doc['sharing']['prefix']['probes']} probe span(s)")
        if prefix_ok:
            dispatched = sum(r["dispatched"] for r in
                             doc["sharing"]["prefix"]["requests"])
            if sh["prefill_tokens_dispatched"] != dispatched:
                problems.append(
                    f"CONTRADICTORY record: "
                    f"sharing.prefill_tokens_dispatched="
                    f"{sh['prefill_tokens_dispatched']} but the "
                    f"recorded spans derive {dispatched}")

    gate = doc.get("gate")
    if not isinstance(gate, dict) or not all(
            isinstance(gate.get(k), bool)
            for k in ("hit_rate_ok", "ab_ok", "bitwise_ok", "ok")):
        problems.append("missing/invalid 'gate' (hit_rate_ok + ab_ok + "
                        "bitwise_ok + ok bools)")
        return problems

    # -- the verdict must re-derive from the recorded numbers ----------
    if prefix_ok:
        derived = doc["sharing"]["prefix"]["hit_rate"] > 0.0
        if gate["hit_rate_ok"] != derived:
            problems.append(
                f"CONTRADICTORY verdict: gate.hit_rate_ok="
                f"{gate['hit_rate_ok']} but the recorded hit rate "
                f"derives {derived}")
    if sharing_ok and baseline_ok:
        sh, bl = doc["sharing"], doc["baseline"]
        derived_ab = (
            sh["prefill_tokens_dispatched"]
            < bl["prefill_tokens_dispatched"]
            and sh["admitted_requests_per_block"]
            > bl["admitted_requests_per_block"]
            and sh["retraces"] == 1 and bl["retraces"] == 1)
        if gate["ab_ok"] != derived_ab:
            problems.append(
                f"CONTRADICTORY verdict: gate.ab_ok={gate['ab_ok']} "
                f"but the recorded arms derive {derived_ab} (fewer "
                f"prefill tokens + more requests per block + one "
                f"decode trace each)")
    if isinstance(doc.get("bitwise_ok"), bool) and \
            gate["bitwise_ok"] != doc["bitwise_ok"]:
        problems.append(
            f"CONTRADICTORY verdict: gate.bitwise_ok="
            f"{gate['bitwise_ok']} but the document records "
            f"bitwise_ok={doc['bitwise_ok']}")
    derived_ok = gate["hit_rate_ok"] and gate["ab_ok"] \
        and gate["bitwise_ok"]
    if gate["ok"] != derived_ok:
        problems.append(
            f"CONTRADICTORY verdict: gate.ok={gate['ok']} but its own "
            f"components derive {derived_ok}")
    return problems


def validate_prefixcache_file(path: str) -> List[str]:
    """Problems with one PREFIXCACHE_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable prefixcache JSON: {e}"]
    return validate_prefixcache(doc)
