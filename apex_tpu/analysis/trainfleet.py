"""TRAINFLEET_r*.json — schema for the committed elastic-fleet chaos
drill (``tools/train_fleet.py``).

One document per drill round: a real 2-process DDP + amp-O2 training
run in which a rank was SIGKILLed mid-training, the surviving rank
shrank onto the smaller mesh from the last durable step, the fleet
regrew when the rank returned, and the recovery is **bitwise-audited**
against uninterrupted replays of the same post-restore schedules.

Like the other gate artifacts (MEMLINT, FLEETLINT, SCHED...), the
document is *self-incriminating*: every verdict it stores must
RE-DERIVE from the raw material it also stores, and a contradiction
fails validation (and therefore tier-1, via ``tools/gate_hygiene.py``):

- each recovery's ``steps_lost`` must equal ``interrupted_step -
  restore_step``, the interrupted step must be a recorded ``kill``
  event, the restore step must be the matching generation plan's, and
  the loss must be within ``config.checkpoint_every`` — the durability
  bound the fleet design promises;
- generation membership must *chain*: a ``shrink`` generation's
  members are a strict subset of its predecessor's, a ``regrow``
  generation's a strict superset;
- every ``bitwise`` flag must re-derive from the recorded sha256 state
  digests (drill snapshots/finals vs replay finals);
- ``gate.ok`` must equal the conjunction of the bitwise flags;
- the embedded incidents must each satisfy the incident schema
  (``apex_tpu/resilience/incidents.py``), cover the
  ``fleet-shrink`` / ``fleet-restored`` / ``fleet-regrow`` statuses,
  and their flight-recorder tails must contain the
  ``kill`` / ``shrink_detected`` / ``restore`` / ``regrow_detected``
  events the drill claims were recorded;
- the regrow generation's ``aot`` events must all say
  ``source == "cache"`` — a regrown rank *loads* its step, the elastic
  claim the AOT cache exists to back.

This module is deliberately **stdlib-only** (no jax import):
``gate_hygiene`` loads it directly by file path; the incident
sub-schema is loaded the same way (``resilience/incidents.py`` is
itself stdlib-only).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

#: event kinds the drill's ledger log must contain for the story the
#: artifact tells to be auditable at all
REQUIRED_EVENT_KINDS = ("kill", "shrink_detected", "restore",
                        "regrow_detected", "plan", "gen_complete")

#: incident statuses the drill must have produced (one per transition)
REQUIRED_INCIDENT_STATUSES = ("fleet-shrink", "fleet-restored",
                              "fleet-regrow")

#: per-status flight-recorder kinds that must appear in that
#: incident's embedded tail
_INCIDENT_FLIGHT_KINDS = {
    "fleet-shrink": ("kill", "shrink_detected"),
    "fleet-restored": ("restore",),
    "fleet-regrow": ("regrow_detected",),
}

_BITWISE_FLAGS = ("shrink_matches_uninterrupted",
                  "regrow_matches_uninterrupted",
                  "final_cross_rank_identical")


def _incidents_schema():
    """Load ``resilience/incidents.py`` by file path (mirrors how
    ``gate_hygiene`` loads THIS module — importing the ``apex_tpu``
    package would drag jax into a stdlib-only checker)."""
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "resilience", "incidents.py")
    spec = importlib.util.spec_from_file_location(
        "_trainfleet_incidents", os.path.abspath(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _is_digest(v: Any) -> bool:
    return isinstance(v, str) and len(v) >= 32 and all(
        c in "0123456789abcdef" for c in v)


def _check_config(doc: dict, problems: List[str]) -> Optional[dict]:
    cfg = doc.get("config")
    if not isinstance(cfg, dict):
        problems.append("missing/invalid 'config' object")
        return None
    for key, pred, want in (
            ("num_steps", lambda v: isinstance(v, int) and v > 0,
             "int > 0"),
            ("checkpoint_every", lambda v: isinstance(v, int) and v > 0,
             "int > 0"),
            ("world_size", lambda v: isinstance(v, int) and v >= 2,
             "int >= 2"),
            ("lease_ttl_s", lambda v: isinstance(v, (int, float))
             and v > 0, "number > 0"),
            ("heartbeat_s", lambda v: isinstance(v, (int, float))
             and v > 0, "number > 0")):
        if not pred(cfg.get(key)):
            problems.append(f"config.{key} missing/invalid (want {want}): "
                            f"{cfg.get(key)!r}")
            return None
    return cfg


def _check_generations(doc: dict, cfg: dict, problems: List[str]
                       ) -> Optional[List[dict]]:
    gens = doc.get("generations")
    if not (isinstance(gens, list) and len(gens) >= 3):
        problems.append("'generations' must list >= 3 entries "
                        "(initial, shrink, regrow)")
        return None
    snapshots = doc.get("snapshots") or {}
    for i, g in enumerate(gens):
        if not isinstance(g, dict):
            problems.append(f"generations[{i}] is not an object")
            return None
        if g.get("gen") != i:
            problems.append(f"generations[{i}].gen={g.get('gen')!r} "
                            f"(generations must be dense, in order)")
            return None
        members = g.get("members")
        if not (isinstance(members, list) and members and all(
                isinstance(r, int) for r in members)):
            problems.append(f"generations[{i}].members missing/invalid")
            return None
        if g.get("reason") not in ("initial", "shrink", "regrow",
                                   "reform"):
            problems.append(f"generations[{i}].reason invalid: "
                            f"{g.get('reason')!r}")
            return None
        if i == 0:
            if len(members) != cfg["world_size"]:
                problems.append(
                    f"generation 0 has {len(members)} members but "
                    f"config.world_size={cfg['world_size']}")
        else:
            rs = g.get("restore_step")
            if not isinstance(rs, int):
                problems.append(f"generations[{i}].restore_step must be "
                                f"an int (a replan without a durable "
                                f"step to restore is not a recovery)")
            elif str(rs) not in snapshots:
                problems.append(
                    f"generations[{i}].restore_step={rs} has no recorded "
                    f"snapshot digest (snapshots: "
                    f"{sorted(snapshots)[:8]})")
            prev = set(gens[i - 1]["members"])
            cur = set(members)
            if g["reason"] == "shrink" and not cur < prev:
                problems.append(
                    f"generations[{i}] says 'shrink' but members {sorted(cur)} "
                    f"are not a strict subset of {sorted(prev)}")
            if g["reason"] == "regrow" and not cur > prev:
                problems.append(
                    f"generations[{i}] says 'regrow' but members {sorted(cur)} "
                    f"are not a strict superset of {sorted(prev)}")
    return gens


def _check_recoveries(doc: dict, cfg: dict, gens: List[dict],
                      problems: List[str]) -> None:
    recs = doc.get("recoveries")
    if not (isinstance(recs, list) and recs):
        problems.append("missing/empty 'recoveries' list")
        return
    kill_steps = {e.get("step") for e in doc.get("events", [])
                  if isinstance(e, dict) and e.get("kind") == "kill"}
    if not any(isinstance(r, dict) and r.get("reason") == "shrink"
               for r in recs):
        problems.append("no 'shrink' recovery recorded — the drill's "
                        "whole point")
    for i, r in enumerate(recs):
        if not isinstance(r, dict):
            problems.append(f"recoveries[{i}] is not an object")
            continue
        g = r.get("generation")
        if not (isinstance(g, int) and 0 < g < len(gens)):
            problems.append(f"recoveries[{i}].generation invalid: {g!r}")
            continue
        gen = gens[g]
        if r.get("reason") != gen["reason"]:
            problems.append(
                f"recoveries[{i}].reason={r.get('reason')!r} contradicts "
                f"generations[{g}].reason={gen['reason']!r}")
        if r.get("restore_step") != gen.get("restore_step"):
            problems.append(
                f"recoveries[{i}].restore_step={r.get('restore_step')!r} "
                f"contradicts generations[{g}].restore_step="
                f"{gen.get('restore_step')!r}")
        want_ranks = sorted(set(gens[g - 1]["members"])
                            ^ set(gen["members"]))
        if r.get("ranks") != want_ranks:
            problems.append(
                f"recoveries[{i}].ranks={r.get('ranks')!r} contradicts the "
                f"generation membership delta {want_ranks}")
        if r.get("reason") == "shrink":
            istep = r.get("interrupted_step")
            if istep not in kill_steps:
                problems.append(
                    f"recoveries[{i}].interrupted_step={istep!r} is not a "
                    f"recorded 'kill' event step ({sorted(kill_steps)})")
                continue
            derived = istep - gen["restore_step"]
            if r.get("steps_lost") != derived:
                problems.append(
                    f"recoveries[{i}].steps_lost={r.get('steps_lost')!r} "
                    f"contradicts interrupted_step - restore_step = "
                    f"{derived}")
            if derived < 0 or derived > cfg["checkpoint_every"]:
                problems.append(
                    f"recoveries[{i}]: {derived} steps lost violates the "
                    f"durability bound (0 <= lost <= checkpoint_every="
                    f"{cfg['checkpoint_every']})")


def _check_bitwise(doc: dict, cfg: dict, gens: List[dict],
                   problems: List[str]) -> None:
    snapshots = doc.get("snapshots")
    if not (isinstance(snapshots, dict) and snapshots and all(
            k.isdigit() and _is_digest(v) for k, v in snapshots.items())):
        problems.append("missing/invalid 'snapshots' "
                        "({step: sha256} of committed drill snapshots)")
        return
    finals = doc.get("finals")
    last_members = [str(r) for r in gens[-1]["members"]]
    if not (isinstance(finals, dict)
            and sorted(finals) == sorted(last_members)):
        problems.append(
            f"'finals' must record exactly the last generation's members "
            f"{sorted(last_members)} (got "
            f"{sorted(finals) if isinstance(finals, dict) else finals!r})")
        return
    for r, f in finals.items():
        if not (isinstance(f, dict) and _is_digest(f.get("digest"))
                and f.get("step") == cfg["num_steps"] - 1):
            problems.append(
                f"finals[{r!r}] must carry step={cfg['num_steps'] - 1} "
                f"and a sha256 digest: {f!r}")
            return

    replays = doc.get("replays")
    if not (isinstance(replays, dict) and isinstance(
            replays.get("shrink"), dict) and isinstance(
            replays.get("regrow"), dict)):
        problems.append("missing 'replays' object with 'shrink' and "
                        "'regrow' records")
        return
    shrink_gen = next((g for g in gens if g["reason"] == "shrink"), None)
    regrow_gen = next((g for g in reversed(gens)
                       if g["reason"] == "regrow"), None)
    if shrink_gen is None or regrow_gen is None:
        problems.append("generations record no shrink/regrow pair to "
                        "audit the replays against")
        return
    rs, rg = replays["shrink"], replays["regrow"]
    for name, rep, want_restore, want_final, want_world in (
            ("shrink", rs, shrink_gen["restore_step"],
             regrow_gen["restore_step"], len(shrink_gen["members"])),
            ("regrow", rg, regrow_gen["restore_step"],
             cfg["num_steps"] - 1, len(regrow_gen["members"]))):
        if rep.get("restore_step") != want_restore:
            problems.append(
                f"replays.{name}.restore_step={rep.get('restore_step')!r} "
                f"contradicts the generation plan's {want_restore}")
        if rep.get("final_step") != want_final:
            problems.append(
                f"replays.{name}.final_step={rep.get('final_step')!r} != "
                f"{want_final} (it must cover exactly the schedule the "
                f"drill ran)")
        if rep.get("world") != want_world:
            problems.append(
                f"replays.{name}.world={rep.get('world')!r} != "
                f"{want_world} (the generation's world size)")
        rfin = rep.get("finals")
        if not (isinstance(rfin, dict) and rfin and all(
                isinstance(f, dict) and _is_digest(f.get("digest"))
                for f in rfin.values())):
            problems.append(f"replays.{name}.finals missing/invalid")
            return

    bitwise = doc.get("bitwise")
    if not (isinstance(bitwise, dict) and all(
            isinstance(bitwise.get(k), bool) for k in _BITWISE_FLAGS)):
        problems.append(f"'bitwise' must carry bools {_BITWISE_FLAGS}")
        return
    # -- the re-derivation rules (contradiction rejection) --------------
    shrink_digests = {f["digest"] for f in rs["finals"].values()}
    derived_shrink = (len(shrink_digests) == 1 and shrink_digests ==
                      {snapshots.get(str(regrow_gen["restore_step"]))})
    derived_regrow = (sorted(rg["finals"]) == sorted(finals) and all(
        rg["finals"][r]["digest"] == finals[r]["digest"] for r in finals))
    derived_cross = len({f["digest"] for f in finals.values()}) == 1
    for flag, derived in (
            ("shrink_matches_uninterrupted", derived_shrink),
            ("regrow_matches_uninterrupted", derived_regrow),
            ("final_cross_rank_identical", derived_cross)):
        if bitwise[flag] != derived:
            problems.append(
                f"bitwise.{flag}={bitwise[flag]} contradicts the recorded "
                f"digests (which derive {derived})")

    gate = doc.get("gate")
    if not (isinstance(gate, dict) and isinstance(gate.get("ok"), bool)):
        problems.append("missing/invalid 'gate.ok' (bool)")
        return
    derived_ok = all(bitwise[k] for k in _BITWISE_FLAGS)
    if gate["ok"] != derived_ok:
        problems.append(f"gate.ok={gate['ok']} contradicts the bitwise "
                        f"flags (which derive {derived_ok})")


def _check_events(doc: dict, gens: List[dict],
                  problems: List[str]) -> None:
    events = doc.get("events")
    if not (isinstance(events, list) and events):
        problems.append("missing/empty 'events' list (the ledger log)")
        return
    kinds = {e.get("kind") for e in events if isinstance(e, dict)}
    missing = [k for k in REQUIRED_EVENT_KINDS if k not in kinds]
    if missing:
        problems.append(f"event log never recorded {missing} "
                        f"(kinds seen: {sorted(k for k in kinds if k)})")
    # the regrown generation must have LOADED its step, not compiled it
    last_gen = gens[-1]["gen"]
    aot = [e for e in events if isinstance(e, dict)
           and e.get("kind") == "aot" and e.get("gen") == last_gen]
    if len(aot) < len(gens[-1]["members"]):
        problems.append(
            f"generation {last_gen} has {len(aot)} 'aot' events for "
            f"{len(gens[-1]['members'])} members — a rank's "
            f"load-vs-compile story is unrecorded")
    for e in aot:
        if e.get("source") != "cache":
            problems.append(
                f"generation {last_gen} rank {e.get('rank')} compiled its "
                f"step (aot source={e.get('source')!r}) — a regrown rank "
                f"must LOAD from the AOT cache")


def _check_incidents(doc: dict, problems: List[str]) -> None:
    incs = doc.get("incidents")
    if not (isinstance(incs, list) and incs):
        problems.append("missing/empty 'incidents' list")
        return
    try:
        schema = _incidents_schema()
    except Exception as e:  # noqa: BLE001 - name the load failure
        problems.append(f"cannot load the incident sub-schema: {e!r}")
        return
    by_status: Dict[str, List[dict]] = {}
    for i, rec in enumerate(incs):
        sub = schema.validate_incident(rec)
        if sub:
            problems.append(f"incidents[{i}] invalid: {sub[:2]}")
            continue
        by_status.setdefault(rec["status"], []).append(rec)
    for status in REQUIRED_INCIDENT_STATUSES:
        if status not in by_status:
            problems.append(
                f"no {status!r} incident recorded (statuses present: "
                f"{sorted(by_status)})")
            continue
        want = _INCIDENT_FLIGHT_KINDS[status]
        covered = any(
            set(want) <= {ev.get("kind")
                          for ev in (rec.get("flight") or {})
                          .get("events", []) if isinstance(ev, dict)}
            for rec in by_status[status])
        if not covered:
            problems.append(
                f"no {status!r} incident's flight tail contains the "
                f"{list(want)} events it exists to record")


def validate_trainfleet(doc) -> List[str]:
    """Problems with one parsed TRAINFLEET document (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if doc.get("artifact") != "TRAINFLEET":
        problems.append(f"'artifact' must be 'TRAINFLEET' "
                        f"(got {doc.get('artifact')!r})")
    if not (isinstance(doc.get("round"), int) and doc["round"] >= 1):
        problems.append("missing/invalid 'round' (int >= 1)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    if not isinstance(doc.get("generated_utc"), str):
        problems.append("missing/invalid 'generated_utc' (str)")
    cfg = _check_config(doc, problems)
    if cfg is None:
        return problems
    gens = _check_generations(doc, cfg, problems)
    if gens is None:
        return problems
    _check_events(doc, gens, problems)
    _check_recoveries(doc, cfg, gens, problems)
    _check_bitwise(doc, cfg, gens, problems)
    _check_incidents(doc, problems)
    return problems


def validate_trainfleet_file(path: str) -> List[str]:
    """Problems with one TRAINFLEET_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable trainfleet JSON: {e}"]
    return validate_trainfleet(doc)
