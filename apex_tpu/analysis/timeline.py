"""TIMELINE_r*.json — the longitudinal metric timeline over every
committed gate artifact, and its contradiction-rejecting schema.

Every round-numbered artifact family in this repo (``BENCH_r*.json``,
``KERNELBENCH_r*.json``, ``MEMLINT_r*.json``, ...) validates ONE round
in isolation; nothing looked ACROSS rounds, so the two known tpu-heads
regressions (gpt −3.2% / bert_lamb −3.6% between r04 and r05, VERDICT
r5 weak #1) were found by a human reading JSON diffs.  This module is
the cross-round view:

- an **adapter registry** (:data:`ADAPTERS`, one small adapter per
  schema family, registered like analysis passes) normalizes every
  committed family into rows of ``(family, round, config, metric,
  value)``.  A committed ``*_r*.json`` whose family has NO adapter is
  a **lint error** (:func:`ingest_repo` reports it; the tool exits on
  it), so the timeline can never silently go stale as new families
  land;
- :func:`build_series` folds rows into per-series trajectories
  (``family|config|metric`` → round-ordered points, each optionally
  carrying the commit that introduced its round's artifact);
- :func:`detect_regressions` applies the **statistical band** rule:
  a gated series regresses when its newest value sits below
  ``best_prior × (1 − band)``, where ``band`` is the recorded relative
  spread from the newest committed ``BENCH_VARIANCE_r*.json`` when a
  non-tiny entry exists for that config/kernel, else
  :data:`DEFAULT_BAND` (0.03 — the lower edge of the documented
  ±2–4 % chip-day variance; a per-config variance entry always wins).
  Each regression row names the FIRST round where the series fell
  below the band and (via ``tools/perf_timeline.py``) the suspect
  commits between the two rounds' artifact commits — the gpt/bert
  finding, mechanical.

Contradiction rejection, like every gate schema in this family
(:func:`validate_timeline`):

- a regression-table entry must cite a series whose RECORDED points
  actually cross the band it states (a fabricated regression, or a
  suppressed one, is schema-invalid);
- the coverage table must list every committed family and file (when
  validated against a checkout — ``tools/gate_hygiene.py`` holds the
  NEWEST committed timeline to this bar), so "all families ingested"
  is machine-checked, not claimed;
- ``gate.ok`` must re-derive from the regression table — no
  self-citing headline verdicts (the SCENARIO/TRACE discipline).

This module is deliberately **stdlib-only** (no jax import):
``tools/gate_hygiene.py`` loads it directly by file path in tier-1.
The gated-series set (which configs/kernels carry published floors)
is supplied by the TOOL — ``bench.MFU_FLOORS`` / ``bench.
DECODE_FLOORS`` / ``kernel_bench.KERNEL_FLOORS`` import jax-adjacent
modules, and the schema judges the artifact by its own recorded
numbers, never by re-importing the tables.

Document shape::

    {
      "round": 1,
      "head": "8b1c76c",                 # commit the timeline was built at
      "bands": {"default": 0.03, "source": "BENCH_VARIANCE_r01.json",
                "per_series": {"BENCH|gpt_small_o2|tok_s": 0.043, ...}},
      "series": {
        "BENCH|gpt_small_tpu_heads_o2|tok_s": {
          "family": "BENCH", "config": "gpt_small_tpu_heads_o2",
          "metric": "tok_s", "gated": true,
          "points": [{"round": 3, "value": ..., "commit": "6343e94"},
                     ...]}, ...
      },
      "regressions": [
        {"series": "BENCH|gpt_small_tpu_heads_o2|tok_s", "band": 0.03,
         "best_round": 4, "best_value": 139660.56,
         "drop_round": 5, "drop_value": 135149.42, "from_round": 4,
         "newest_round": 5, "newest_value": 135149.42,
         "drop_frac": 0.0323,
         "suspects": [{"commit": "90d60d2", "subject": "..."}, ...]},
        ...
      ],
      "coverage": {"BENCH": {"files": ["BENCH_r01.json", ...],
                             "rows": 57}, ...},
      "provisional_floors": ["gpt_small_tpu_decode_kv8"],
      "gate": {"regressions": 2, "ok": false},
      "note": "..."
    }
"""

from __future__ import annotations

import json
import os
import re
from typing import Callable, Dict, List, Optional, Tuple

#: default statistical band width for gated series without a recorded
#: per-config/per-kernel variance entry: the lower edge of the
#: documented ±2–4 % chip-day variance.  A non-tiny
#: BENCH_VARIANCE_r*.json entry always overrides it.
DEFAULT_BAND = 0.03

#: ``NAME_rNN[suffix].json`` — the round-numbered artifact naming
#: convention every gate family follows (suffix: the INCIDENT_r02_wedge
#: class).
FAMILY_RE = re.compile(r"^(?P<family>.+)_r(?P<round>\d+)"
                       r"(?P<suffix>.*)\.json$")

Row = Tuple[str, str, float]          # (config, metric, value)
Adapter = Callable[[dict, Dict[Tuple[str, str], float]], List[Row]]

#: the adapter registry: one entry per committed artifact family.
#: ``ingest_repo`` treats a committed family absent from this table as
#: a lint error — register the adapter in the same PR that adds the
#: family, or the timeline refuses to build.
ADAPTERS: Dict[str, Adapter] = {}


def parse_artifact_name(name: str):
    """``(family, round, suffix)`` for a round-numbered artifact file
    name, else ``None``."""
    m = FAMILY_RE.match(os.path.basename(name))
    if not m:
        return None
    return m.group("family"), int(m.group("round")), m.group("suffix")


def series_key(family: str, config: str, metric: str) -> str:
    return f"{family}|{config}|{metric}"


def _num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def adapter(family: str):
    """Register an ingestion adapter: ``fn(doc, prev) -> [(config,
    metric, value), ...]`` where ``prev`` maps ``(config, metric)`` to
    the previous round's value for the same family (how the BENCH
    adapter reconstructs a round whose artifact only recorded
    deltas)."""
    def wrap(fn: Adapter) -> Adapter:
        ADAPTERS[family] = fn
        return fn
    return wrap


def _numeric_items(d) -> List[Tuple[str, float]]:
    if not isinstance(d, dict):
        return []
    return [(k, float(v)) for k, v in sorted(d.items()) if _num(v)]


def _generic(doc, prev) -> List[Row]:
    """Two-level numeric walk: top-level numbers under ``summary``,
    one level of nested dicts under their own key — enough structure
    for the archive families (ONCHIP, MULTICHIP, D64_DECOMPOSE,
    ROOFLINE_RN50, INCIDENT) whose per-round stories are small."""
    rows: List[Row] = []
    if not isinstance(doc, dict):
        return rows
    for k, v in sorted(doc.items()):
        if _num(v):
            rows.append(("summary", k, float(v)))
        elif isinstance(v, dict):
            rows.extend((k, k2, v2) for k2, v2 in _numeric_items(v))
    return rows


# ---------------------------------------------------------------------------
# family adapters
# ---------------------------------------------------------------------------

#: per-config metrics the BENCH adapter lifts out of the configs map
BENCH_METRICS = ("img_s", "tok_s", "seq_s", "mfu", "hfu", "hbm_frac")
#: the rate metrics (one per config) the regression gate rides
RATE_METRICS = ("img_s", "tok_s", "seq_s")

_DELTAS_RE = re.compile(r'"deltas":\s*(\{[^{}]*\})')


def _extract_deltas(tail: str) -> Dict[str, float]:
    """The flat ``"deltas": {...}`` map out of a (possibly truncated)
    BENCH tail — the driver keeps only the last ~2000 chars of stdout,
    which can cut the configs map while the regression deltas survive
    whole."""
    m = _DELTAS_RE.search(tail or "")
    if not m:
        return {}
    try:
        d = json.loads(m.group(1))
    except ValueError:
        return {}
    return {k: float(v) for k, v in d.items() if _num(v)}


@adapter("BENCH")
def _ingest_bench(doc, prev) -> List[Row]:
    """Model-bench rounds: per-config rate/MFU/hbm_frac.  Prefers the
    driver's ``parsed`` block, falls back to a full JSON line in the
    tail, and — for a round whose tail was truncated past recovery
    (BENCH_r05) — RECONSTRUCTS each rate value as ``prev × (1 + delta)``
    from the round's own recorded regression deltas: the artifact
    itself asserts the delta, so the derived point carries exactly the
    information review saw."""
    configs = None
    parsed = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
        else None
    if parsed and isinstance(parsed.get("configs"), dict):
        configs = parsed["configs"]
    if configs is None:
        for line in (doc.get("tail") or "").splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if isinstance(cand, dict) and \
                    isinstance(cand.get("configs"), dict):
                configs = cand["configs"]
                break
    rows: List[Row] = []
    if configs is not None:
        for name, cfg in sorted(configs.items()):
            if not isinstance(cfg, dict):
                continue
            rows.extend((name, metric, float(cfg[metric]))
                        for metric in BENCH_METRICS
                        if _num(cfg.get(metric)))
        return rows
    for name, delta in sorted(_extract_deltas(
            doc.get("tail") or "").items()):
        for metric in RATE_METRICS:
            base = prev.get((name, metric))
            if base is not None:
                rows.append((name, metric,
                             round(base * (1.0 + delta), 4)))
    if rows:
        return rows
    # earliest rounds: headline value only
    if parsed and _num(parsed.get("value")):
        rows.append(("headline", str(parsed.get("unit", "value")),
                     float(parsed["value"])))
    return rows


@adapter("KERNELBENCH")
def _ingest_kernelbench(doc, prev) -> List[Row]:
    rows: List[Row] = []
    for name, k in sorted((doc.get("kernels") or {}).items()):
        if isinstance(k, dict):
            rows.extend((name, metric, float(k[metric]))
                        for metric in ("ms_per_step", "gbps",
                                       "roofline_frac")
                        if _num(k.get(metric)))
    return rows


@adapter("BENCH_VARIANCE")
def _ingest_bench_variance(doc, prev) -> List[Row]:
    rows: List[Row] = []
    for key, e in sorted((doc.get("entries") or {}).items()):
        if not isinstance(e, dict):
            continue
        rows.extend((key, metric, float(e[metric]))
                    for metric in ("mean", "rel_spread", "std")
                    if _num(e.get(metric)))
    return rows


@adapter("MEMLINT")
def _ingest_memlint(doc, prev) -> List[Row]:
    rows: List[Row] = []
    for lane, rec in sorted((doc.get("lanes") or {}).items()):
        if isinstance(rec, dict) and _num(rec.get("peak_hbm_bytes")):
            rows.append((lane, "peak_hbm_bytes",
                         float(rec["peak_hbm_bytes"])))
    return rows


@adapter("PRECLINT")
def _ingest_preclint(doc, prev) -> List[Row]:
    rows: List[Row] = []
    for lane, rec in sorted((doc.get("lanes") or {}).items()):
        rows.extend((lane, k, v) for k, v in _numeric_items(rec))
    return rows


@adapter("FLEETLINT")
def _ingest_fleetlint(doc, prev) -> List[Row]:
    """Cross-rank SPMD lint rounds: per-lane consistency verdict (1.0 =
    every rank compiled the same collective schedule) and the lane's
    collective count, plus the gate's inconsistent-lane total."""
    rows: List[Row] = []
    for lane, rec in sorted((doc.get("lanes") or {}).items()):
        if not isinstance(rec, dict):
            continue
        if isinstance(rec.get("consistent"), bool):
            rows.append((lane, "consistent", float(rec["consistent"])))
        counts = [r["n_collectives"]
                  for r in (rec.get("ranks") or {}).values()
                  if isinstance(r, dict) and _num(r.get("n_collectives"))]
        if counts:
            rows.append((lane, "n_collectives", float(max(counts))))
    gate = doc.get("gate")
    if isinstance(gate, dict) and _num(gate.get("inconsistent_lanes")):
        rows.append(("gate", "inconsistent_lanes",
                     float(gate["inconsistent_lanes"])))
    return rows


@adapter("KERNLINT")
def _ingest_kernlint(doc, prev) -> List[Row]:
    """Pallas kernel-sanitizer rounds: per-kernel clean verdict (1.0 =
    zero unwaived rule findings over the sweep) and total error-finding
    count, plus the gate's clean-kernel fraction — the longitudinal
    record that every hand-written kernel stays race-free, covered,
    and under the VMEM budget."""
    rows: List[Row] = []
    for name, rec in sorted((doc.get("kernels") or {}).items()):
        if not isinstance(rec, dict):
            continue
        if isinstance(rec.get("ok"), bool):
            rows.append((f"kernel:{name}", "lint_clean",
                         float(rec["ok"])))
        findings = rec.get("findings")
        if isinstance(findings, dict):
            total = sum(v for v in findings.values() if _num(v))
            rows.append((f"kernel:{name}", "rule_findings",
                         float(total)))
    gate = doc.get("gate")
    if isinstance(gate, dict) and _num(gate.get("kernels_total")) \
            and gate["kernels_total"] > 0 \
            and _num(gate.get("kernels_clean")):
        rows.append(("gate", "kernels_clean_frac",
                     round(gate["kernels_clean"]
                           / gate["kernels_total"], 4)))
    return rows


@adapter("DETLINT")
def _ingest_detlint(doc, prev) -> List[Row]:
    """Bitwise-determinism lint rounds: per-lane clean verdict (1.0 =
    zero unwaived tie/materialize/scatter/PRNG findings over the
    lowered program) and total error-finding count, per-pair
    comparator verdict (1.0 = reduction-signature streams cleared,
    0.0 = an undocumented lane-shape variant) with its variant-class
    count, plus the gate's clean-lane and cleared-pair fractions —
    the longitudinal record that every gated program stays in the
    reassociation-proof forms and that paired lanes keep identical
    float-reduction shapes."""
    rows: List[Row] = []
    for lane, rec in sorted((doc.get("lanes") or {}).items()):
        if not isinstance(rec, dict):
            continue
        if isinstance(rec.get("ok"), bool):
            rows.append((f"lane:{lane}", "lint_clean", float(rec["ok"])))
        findings = rec.get("findings")
        if isinstance(findings, dict):
            total = sum(v for v in findings.values() if _num(v))
            rows.append((f"lane:{lane}", "rule_findings", float(total)))
    for key, rec in sorted((doc.get("pairs") or {}).items()):
        if not isinstance(rec, dict):
            continue
        if rec.get("verdict") in ("cleared", "variant"):
            rows.append((f"pair:{key}", "cleared",
                         float(rec["verdict"] == "cleared")))
        variants = rec.get("variants")
        if isinstance(variants, list):
            rows.append((f"pair:{key}", "variant_classes",
                         float(len(variants))))
    gate = doc.get("gate")
    if isinstance(gate, dict):
        if _num(gate.get("lanes_total")) and gate["lanes_total"] > 0 \
                and _num(gate.get("lanes_clean")):
            rows.append(("gate", "lanes_clean_frac",
                         round(gate["lanes_clean"]
                               / gate["lanes_total"], 4)))
        if _num(gate.get("pairs_total")) and gate["pairs_total"] > 0 \
                and _num(gate.get("pairs_ok")):
            rows.append(("gate", "pairs_ok_frac",
                         round(gate["pairs_ok"]
                               / gate["pairs_total"], 4)))
    return rows


@adapter("PREFIXCACHE")
def _ingest_prefixcache(doc, prev) -> List[Row]:
    """Prefix-sharing rounds: per-arm deterministic counts (prefill
    tokens dispatched, resident-block footprint) plus the hit-rate
    headline — the longitudinal record of what KV dedup saves."""
    rows: List[Row] = []
    for arm in ("sharing", "baseline"):
        rec = doc.get(arm)
        if not isinstance(rec, dict):
            continue
        rows.extend((arm, k, float(rec[k]))
                    for k in ("prefill_chunks",
                              "prefill_tokens_dispatched",
                              "peak_live_blocks",
                              "admitted_requests_per_block",
                              "tok_s", "p50_ms", "p99_ms")
                    if _num(rec.get(k)))
    sharing = doc.get("sharing")
    prefix = sharing.get("prefix") if isinstance(sharing, dict) else None
    if isinstance(prefix, dict):
        rows.extend(("prefix", k, float(prefix[k]))
                    for k in ("hit_rate", "hit_tokens", "cow_copies",
                              "shared_blocks_peak")
                    if _num(prefix.get(k)))
    return rows


@adapter("TRAINFLEET")
def _ingest_trainfleet(doc, prev) -> List[Row]:
    """Elastic-fleet chaos rounds: the drill's wall clock, generation
    count, per-recovery steps-lost (bounded by the checkpoint
    interval), and the bitwise verdicts as 1.0/0.0 — the longitudinal
    record of what a rank kill costs."""
    rows: List[Row] = []
    if _num(doc.get("wall_s")):
        rows.append(("drill", "wall_s", float(doc["wall_s"])))
    gens = doc.get("generations")
    if isinstance(gens, list):
        rows.append(("drill", "generations", float(len(gens))))
    for rec in (doc.get("recoveries") or []):
        if isinstance(rec, dict) and _num(rec.get("steps_lost")):
            rows.append((str(rec.get("reason", "recovery")),
                         "steps_lost", float(rec["steps_lost"])))
    bitwise = doc.get("bitwise")
    if isinstance(bitwise, dict):
        rows.extend(("bitwise", k, float(v))
                    for k, v in sorted(bitwise.items())
                    if isinstance(v, bool))
    gate = doc.get("gate")
    if isinstance(gate, dict) and isinstance(gate.get("ok"), bool):
        rows.append(("gate", "ok", float(gate["ok"])))
    return rows


@adapter("SCENARIO")
def _ingest_scenario(doc, prev) -> List[Row]:
    rows: List[Row] = []
    for cell, rec in sorted((doc.get("cells") or {}).items()):
        if isinstance(rec, dict):
            rows.extend((cell, metric, float(rec[metric]))
                        for metric in ("tokens_per_step", "p50_ms",
                                       "p99_ms", "tok_s",
                                       "acceptance_rate")
                        if _num(rec.get(metric)))
    return rows


@adapter("SERVE_DISAGG")
def _ingest_serve_disagg(doc, prev) -> List[Row]:
    rows: List[Row] = []
    for arm in ("mono", "disagg"):
        rows.extend((arm, k, v) for k, v in _numeric_items(doc.get(arm)))
    chaos = doc.get("chaos")
    if isinstance(chaos, dict):
        rows.extend(("chaos", k, v) for k, v in _numeric_items(chaos))
    return rows


@adapter("TRACE")
def _ingest_trace(doc, prev) -> List[Row]:
    rows = [("engine", k, v) for k, v in _numeric_items(doc.get("engine"))]
    reqs = doc.get("requests")
    if isinstance(reqs, (list, dict)):
        rows.append(("requests", "count", float(len(reqs))))
    return rows


@adapter("OBS")
def _ingest_obs(doc, prev) -> List[Row]:
    rows: List[Row] = []
    for section in ("overhead", "tracing"):
        rows.extend((section, k, v)
                    for k, v in _numeric_items(doc.get(section)))
    return rows


@adapter("EXPORT")
def _ingest_export(doc, prev) -> List[Row]:
    return [("cold_start", k, v)
            for k, v in _numeric_items(doc.get("cold_start"))]


@adapter("DECODE_PROFILE")
def _ingest_decode_profile(doc, prev) -> List[Row]:
    rows = [("fractions", k, v)
            for k, v in _numeric_items(doc.get("device_time_fractions"))]
    if _num(doc.get("coverage")):
        rows.append(("summary", "coverage", float(doc["coverage"])))
    return rows


@adapter("DECODE_DECOMPOSE")
def _ingest_decode_decompose(doc, prev) -> List[Row]:
    rows = [("fractions", k, v)
            for k, v in _numeric_items(doc.get("device_time_fractions"))]
    rows.extend(("measured", k, v)
                for k, v in _numeric_items(doc.get("measured")))
    if _num(doc.get("coverage")):
        rows.append(("summary", "coverage", float(doc["coverage"])))
    return rows


@adapter("PROFILE_DRIFT")
def _ingest_profile_drift(doc, prev) -> List[Row]:
    """Continuous-profiler drift rounds: per-session window/drift
    counts and the last window's bucket fractions + step wall — the
    longitudinal record of what the live sentinel saw each round."""
    rows: List[Row] = []
    band = doc.get("band")
    if isinstance(band, dict) and _num(band.get("value")):
        rows.append(("summary", "band", float(band["value"])))
    if _num(doc.get("k")):
        rows.append(("summary", "k", float(doc["k"])))
    for name, sess in sorted((doc.get("sessions") or {}).items()):
        if not isinstance(sess, dict):
            continue
        wins = [w for w in (sess.get("windows") or [])
                if isinstance(w, dict)]
        rows.append((name, "windows", float(len(wins))))
        rows.append((name, "drifts",
                     float(len(sess.get("drifts") or []))))
        if wins:
            last = wins[-1]
            rows.extend((f"{name}:last_window", k, v)
                        for k, v in _numeric_items(
                            last.get("fractions")))
            if _num(last.get("step_wall_s")):
                rows.append((f"{name}:last_window", "step_wall_s",
                             float(last["step_wall_s"])))
    return rows


@adapter("CONVERGENCE")
def _ingest_convergence(doc, prev) -> List[Row]:
    # shapes vary by round (legacy r02 single record through the r06
    # lane map) — the generic two-level walk covers all of them
    return _generic(doc, prev)


for _family in ("INCIDENT", "MULTICHIP", "ONCHIP", "D64_DECOMPOSE",
                "ROOFLINE_RN50"):
    ADAPTERS[_family] = _generic

#: families the scanner recognizes but never ingests: a timeline
#: cannot ingest itself (its rounds are validated by this schema, not
#: summarized into it).
SELF_FAMILIES = ("TIMELINE",)


# ---------------------------------------------------------------------------
# ingestion
# ---------------------------------------------------------------------------

def scan_artifacts(repo_dir: str) -> Dict[str, List[Tuple[int, str]]]:
    """``{family: [(round, filename), ...]}`` over every round-numbered
    JSON artifact in ``repo_dir`` (sorted by round; self families
    excluded)."""
    fams: Dict[str, List[Tuple[int, str]]] = {}
    for name in sorted(os.listdir(repo_dir)):
        parsed = parse_artifact_name(name)
        if parsed is None:
            continue
        family, rnd, _ = parsed
        if family in SELF_FAMILIES:
            continue
        fams.setdefault(family, []).append((rnd, name))
    for v in fams.values():
        v.sort()
    return fams


def ingest_repo(repo_dir: str) -> dict:
    """Normalize every committed artifact family into timeline rows.

    Returns ``{"rows": [{family, round, config, metric, value}, ...],
    "coverage": {family: {"files": [...], "rows": N}},
    "unknown": [...], "unreadable": [...]}`` — ``unknown`` (a committed
    family with no registered adapter) is the lint error the caller
    must refuse to build over."""
    rows: List[dict] = []
    coverage: Dict[str, dict] = {}
    unknown: List[str] = []
    unreadable: List[str] = []
    for family, files in sorted(scan_artifacts(repo_dir).items()):
        fn = ADAPTERS.get(family)
        if fn is None:
            unknown.extend(name for _, name in files)
            continue
        cov = coverage.setdefault(family, {"files": [], "rows": 0})
        prev: Dict[Tuple[str, str], float] = {}
        for rnd, name in files:
            try:
                with open(os.path.join(repo_dir, name)) as f:
                    doc = json.load(f)
            except (OSError, ValueError) as e:
                unreadable.append(f"{name}: {e}")
                continue
            try:
                fam_rows = fn(doc, prev)
            except Exception as e:  # noqa: BLE001 - adapter isolation
                unreadable.append(
                    f"{name}: adapter failed: "
                    f"{type(e).__name__}: {e}"[:300])
                continue
            # coverage records only what was ACTUALLY ingested — an
            # unreadable/adapter-failed artifact must stay OUT of the
            # table so the staleness lint (coverage vs checkout)
            # flags it instead of vouching for rows that never landed
            cov["files"].append(name)
            prev = {}
            for config, metric, value in fam_rows:
                rows.append({"family": family, "round": rnd,
                             "config": config, "metric": metric,
                             "value": value})
                prev[(config, metric)] = value
            cov["rows"] += len(fam_rows)
    return {"rows": rows, "coverage": coverage, "unknown": unknown,
            "unreadable": unreadable}


def build_series(rows: List[dict],
                 commits: Optional[Dict[Tuple[str, int], str]] = None,
                 ) -> Dict[str, dict]:
    """Fold ingested rows into per-series trajectories.  ``commits``
    maps ``(family, round)`` to the git commit that introduced that
    round's artifact (resolved by the tool; absent points carry
    ``None``).  A later row for the same (series, round) wins — one
    value per round per series."""
    by_key: Dict[str, dict] = {}
    for row in rows:
        key = series_key(row["family"], row["config"], row["metric"])
        s = by_key.setdefault(key, {
            "family": row["family"], "config": row["config"],
            "metric": row["metric"], "points": {}})
        commit = (commits or {}).get((row["family"], row["round"]))
        s["points"][row["round"]] = {"round": row["round"],
                                     "value": row["value"],
                                     "commit": commit}
    for s in by_key.values():
        s["points"] = [s["points"][r] for r in sorted(s["points"])]
    return by_key


# ---------------------------------------------------------------------------
# the statistical-band regression rule
# ---------------------------------------------------------------------------

def crossing_points(points: List[dict], band: float):
    """``(best, first_drop, newest)`` when the series' newest value
    sits below ``best_prior × (1 − band)``, else ``None`` — the ONE
    rule both :func:`detect_regressions` and the validator apply, so
    the artifact can never state a crossing its own points refute."""
    if len(points) < 2:
        return None
    prior = points[:-1]
    best = max(prior, key=lambda p: p["value"])
    newest = points[-1]
    gate = best["value"] * (1.0 - band)
    if best["value"] <= 0 or newest["value"] >= gate:
        return None
    drop = next(p for p in points
                if p["round"] > best["round"] and p["value"] < gate)
    return best, drop, newest


def detect_regressions(series: Dict[str, dict],
                       gated: List[str],
                       bands: Optional[Dict[str, float]] = None,
                       default_band: float = DEFAULT_BAND,
                       ) -> List[dict]:
    """The regression table: one row per gated series whose newest
    value fell below its statistical band, naming the first round
    where it dropped (``drop_round``) and the round immediately before
    (``from_round``) — the commit range the tool attributes suspects
    over."""
    out: List[dict] = []
    for key in sorted(gated):
        s = series.get(key)
        if s is None:
            continue
        band = float((bands or {}).get(key, default_band))
        hit = crossing_points(s["points"], band)
        if hit is None:
            continue
        best, drop, newest = hit
        from_round = max(p["round"] for p in s["points"]
                         if p["round"] < drop["round"])
        out.append({
            "series": key, "band": round(band, 4),
            "best_round": best["round"], "best_value": best["value"],
            "drop_round": drop["round"], "drop_value": drop["value"],
            "from_round": from_round,
            "newest_round": newest["round"],
            "newest_value": newest["value"],
            "drop_frac": round(1.0 - newest["value"] / best["value"], 4),
        })
    return out


# ---------------------------------------------------------------------------
# schema validation
# ---------------------------------------------------------------------------

def _check_series(key: str, s, problems: List[str]) -> bool:
    if not isinstance(s, dict):
        problems.append(f"series[{key}] is not an object")
        return False
    for field in ("family", "config", "metric"):
        if not isinstance(s.get(field), str):
            problems.append(f"series[{key}].{field} missing (str)")
            return False
    if key != series_key(s["family"], s["config"], s["metric"]):
        problems.append(
            f"series[{key}]: key does not match its own "
            f"family|config|metric fields")
    pts = s.get("points")
    if not isinstance(pts, list) or not pts:
        problems.append(f"series[{key}].points missing/empty")
        return False
    last_round = None
    for i, p in enumerate(pts):
        if not isinstance(p, dict) or \
                not isinstance(p.get("round"), int) or \
                not _num(p.get("value")):
            problems.append(f"series[{key}].points[{i}] needs an int "
                            f"round and a numeric value")
            return False
        if last_round is not None and p["round"] <= last_round:
            problems.append(f"series[{key}].points not strictly "
                            f"round-ascending at index {i}")
            return False
        last_round = p["round"]
    return True


def _check_regression(i: int, row, series: dict, problems: List[str]):
    if not isinstance(row, dict):
        problems.append(f"regressions[{i}] is not an object")
        return
    key = row.get("series")
    s = series.get(key) if isinstance(series, dict) else None
    if not isinstance(s, dict) or not isinstance(s.get("points"), list):
        problems.append(f"regressions[{i}] cites unknown series "
                        f"{key!r}")
        return
    band = row.get("band")
    if not _num(band) or not 0.0 < band < 1.0:
        problems.append(f"regressions[{i}].band missing/out of (0,1): "
                        f"{band!r}")
        return
    for field in ("best_round", "drop_round", "from_round",
                  "newest_round"):
        if not isinstance(row.get(field), int):
            problems.append(f"regressions[{i}].{field} missing (int)")
            return
    for field in ("best_value", "drop_value", "newest_value",
                  "drop_frac"):
        if not _num(row.get(field)):
            problems.append(f"regressions[{i}].{field} missing "
                            f"(number)")
            return
    # -- the crossing must be real in the cited series' own points ----
    hit = crossing_points(s["points"], float(band))
    if hit is None:
        problems.append(
            f"CONTRADICTORY record: regressions[{i}] cites series "
            f"{key!r} whose recorded points never cross the stated "
            f"band {band}")
        return
    best, drop, newest = hit
    derived_from = max(p["round"] for p in s["points"]
                       if p["round"] < drop["round"])
    stated = (row["best_round"], row["drop_round"],
              row["from_round"], row["newest_round"])
    derived = (best["round"], drop["round"], derived_from,
               newest["round"])
    if stated != derived:
        problems.append(
            f"CONTRADICTORY record: regressions[{i}] states "
            f"(best, drop, from, newest) rounds {stated} but the "
            f"cited series derives {derived} — from_round defines "
            f"the suspect-commit range and must be the round "
            f"immediately before the drop")
    for field, point in (("best_value", best), ("drop_value", drop),
                         ("newest_value", newest)):
        if abs(row[field] - point["value"]) > 1e-9 * max(
                1.0, abs(point["value"])):
            problems.append(
                f"CONTRADICTORY record: regressions[{i}].{field}="
                f"{row[field]} but the cited series records "
                f"{point['value']} at that round")
    derived_frac = round(1.0 - newest["value"] / best["value"], 4)
    if abs(row["drop_frac"] - derived_frac) > 5e-4:
        problems.append(
            f"CONTRADICTORY record: regressions[{i}].drop_frac="
            f"{row['drop_frac']} but the cited values derive "
            f"{derived_frac}")


def _check_coverage(doc, repo_dir: Optional[str],
                    problems: List[str]) -> None:
    coverage = doc.get("coverage")
    if not isinstance(coverage, dict) or not coverage:
        problems.append("missing/empty 'coverage' table (proving every "
                        "family was ingested is the artifact's job)")
        return
    for family, rec in coverage.items():
        if not isinstance(rec, dict) or \
                not isinstance(rec.get("files"), list) or \
                not isinstance(rec.get("rows"), int):
            problems.append(f"coverage[{family}] needs a files list "
                            f"and a rows int")
    if repo_dir is None:
        return
    # validated against a checkout: EVERY committed round-numbered
    # artifact (self families aside) must be listed, or the timeline
    # went stale — the staleness lint gate_hygiene holds the newest
    # committed round to
    try:
        names = sorted(os.listdir(repo_dir))
    except OSError:
        return
    for name in names:
        parsed = parse_artifact_name(name)
        if parsed is None or parsed[0] in SELF_FAMILIES:
            continue
        family = parsed[0]
        rec = coverage.get(family)
        files = rec.get("files") if isinstance(rec, dict) else None
        if not isinstance(files, list) or name not in files:
            problems.append(
                f"STALE timeline: committed artifact {name} (family "
                f"{family}) is not in the coverage table — re-run "
                f"tools/perf_timeline.py and commit the refreshed "
                f"round")


def validate_timeline(doc, repo_dir: Optional[str] = None) -> List[str]:
    """Problems with one parsed TIMELINE document (empty = valid).
    ``repo_dir`` arms the coverage-completeness check against a
    checkout's committed artifacts (the staleness lint); ``None``
    validates internal consistency only."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("round"), int):
        problems.append("missing/invalid 'round' (int)")

    bands = doc.get("bands")
    if not isinstance(bands, dict) or not _num(bands.get("default")) \
            or not 0.0 < bands["default"] < 1.0:
        problems.append("missing/invalid 'bands' (object with a "
                        "'default' width in (0,1))")

    series = doc.get("series")
    if not isinstance(series, dict) or not series:
        problems.append("missing/empty 'series' map")
        series = {}
    valid_series = {k: s for k, s in series.items()
                    if _check_series(k, s, problems)}

    regressions = doc.get("regressions")
    if not isinstance(regressions, list):
        problems.append("missing 'regressions' list (empty is fine — "
                        "absent is a gate that asserts nothing)")
        regressions = []
    for i, row in enumerate(regressions):
        _check_regression(i, row, valid_series, problems)

    # -- no suppressed regressions: every GATED series that crosses
    # its recorded band must have a table row (the converse of the
    # fabrication check — a timeline cannot go green by dropping rows)
    if isinstance(bands, dict) and _num(bands.get("default")):
        per = bands.get("per_series") \
            if isinstance(bands.get("per_series"), dict) else {}
        cited = {row.get("series") for row in regressions
                 if isinstance(row, dict)}
        for key, s in valid_series.items():
            if s.get("gated") is not True or key in cited:
                continue
            band = per.get(key, bands["default"])
            if _num(band) and 0.0 < band < 1.0 and \
                    crossing_points(s["points"], float(band)):
                problems.append(
                    f"CONTRADICTORY record: gated series {key!r} "
                    f"crosses its band {band} but has no regression "
                    f"row — suppressed regression")

    _check_coverage(doc, repo_dir, problems)

    gate = doc.get("gate")
    if not isinstance(gate, dict) or \
            not isinstance(gate.get("regressions"), int) or \
            not isinstance(gate.get("ok"), bool):
        problems.append("missing/invalid 'gate' (regressions int + "
                        "ok bool)")
    else:
        if gate["regressions"] != len(regressions):
            problems.append(
                f"CONTRADICTORY verdict: gate.regressions="
                f"{gate['regressions']} but the regression table has "
                f"{len(regressions)} row(s)")
        if gate["ok"] != (len(regressions) == 0):
            problems.append(
                f"CONTRADICTORY verdict: gate.ok={gate['ok']} but the "
                f"regression table derives {len(regressions) == 0}")
    return problems


def validate_timeline_file(path: str,
                           repo_dir: Optional[str] = None) -> List[str]:
    """Problems with one TIMELINE_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable timeline JSON: {e}"]
    return validate_timeline(doc, repo_dir=repo_dir)
