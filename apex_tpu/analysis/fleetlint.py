"""FLEETLINT_r*.json — schema for the committed cross-rank SPMD lint.

``tools/graph_lint.py --lanes fleet --emit-json FLEETLINT_rN.json``
writes one of these per round: the DDP O1/O2 train steps lowered once
per rank on the virtual mesh, plus the 8→4 shrink / 4→8 regrow reshape
pair, each lane's per-rank collective-schedule fingerprints and a
``consistent`` verdict (:mod:`apex_tpu.analysis.spmd`).  Like MEMLINT
and PRECLINT, the artifact is gate memory: ``tools/gate_hygiene.py``
validates every committed ``FLEETLINT_r*.json`` against this schema so
"the fleet's collective schedules agree" can't rot into prose nobody
machine-checks.

This module is deliberately **stdlib-only** (no jax import):
``gate_hygiene`` loads it directly by file path the same way it loads
``analysis/memlint.py`` and ``analysis/preclint.py``.

Document shape::

    {
      "round": 1,
      "platform": "cpu",
      "n_ranks": 8,                # ranks per-rank lanes were lowered for
      "lanes": {
        "<lane>": {                # e.g. "ddp_o1_train", "reshape_8to4"
          "compare": "schedule",   # full identity | "opcodes" (reshape
                                   #   pairs: groups/bytes legally change)
          "consistent": true,      # MUST re-derive from the hashes below
          "ranks": {
            "<label>": {           # "0".."7", or "mesh8"/"mesh4"
              "schedule_hash": "...",   # sha256 of the canonical schedule
              "opcode_hash": "...",     # sha256 of the (kind,variant) seq
              "n_collectives": 3
            }, ...
          },
          "findings": {"error": 0, "warning": 0, "info": 1},
          "mismatches": [          # non-empty IFF not consistent
            {"ranks": ["0", "7"], "index": 2,
             "a": "all-reduce(bf16, 32B, ...)",   # first diverging op,
             "b": "all-reduce(f32, 64B, ...)"}    #   both spellings
          ]
        }, ...
      },
      "gate": {"ok": true, "inconsistent_lanes": 0}   # re-derived
    }
"""

from __future__ import annotations

import json
from typing import List

_COMPARE_KEY = {"schedule": "schedule_hash", "opcodes": "opcode_hash"}

_RANK_REQUIRED = {
    "schedule_hash": lambda v: isinstance(v, str) and len(v) >= 12,
    "opcode_hash": lambda v: isinstance(v, str) and len(v) >= 12,
    "n_collectives": lambda v: isinstance(v, int) and v >= 0,
}


def _validate_lane(name: str, lane: dict, problems: List[str]) -> None:
    compare = lane.get("compare")
    if compare not in _COMPARE_KEY:
        problems.append(f"lane {name!r} has invalid 'compare': "
                        f"{compare!r} (want 'schedule' or 'opcodes')")
        return
    if not isinstance(lane.get("consistent"), bool):
        problems.append(f"lane {name!r} missing/invalid 'consistent' "
                        f"(bool)")
        return
    ranks = lane.get("ranks")
    if not isinstance(ranks, dict) or len(ranks) < 2:
        problems.append(f"lane {name!r} needs a 'ranks' object with >= 2 "
                        f"entries (a one-sided comparison proves nothing)")
        return
    for label, rec in ranks.items():
        if not isinstance(rec, dict):
            problems.append(f"lane {name!r} rank {label!r} is not an "
                            f"object")
            return
        for key, check in _RANK_REQUIRED.items():
            if not check(rec.get(key)):
                problems.append(f"lane {name!r} rank {label!r} has "
                                f"missing/invalid {key!r}: "
                                f"{rec.get(key)!r}")
                return
    fnd = lane.get("findings")
    if fnd is not None and not (isinstance(fnd, dict) and all(
            isinstance(n, int) and n >= 0 for n in fnd.values())):
        problems.append(f"lane {name!r} has invalid 'findings': {fnd!r}")

    # the contradiction rule: the verdict must re-derive from the
    # recorded per-rank hashes under the lane's own comparison mode
    key = _COMPARE_KEY[compare]
    derived = len({rec[key] for rec in ranks.values()}) == 1
    if lane["consistent"] != derived:
        problems.append(
            f"lane {name!r}: consistent={lane['consistent']} contradicts "
            f"the recorded per-rank {key} values (which "
            f"{'agree' if derived else 'disagree'})")

    mismatches = lane.get("mismatches")
    if not isinstance(mismatches, list):
        problems.append(f"lane {name!r} missing 'mismatches' (list)")
        return
    if derived and mismatches:
        problems.append(f"lane {name!r}: mismatch rows recorded on a "
                        f"hash-consistent lane")
    if not derived and not mismatches:
        problems.append(f"lane {name!r}: hashes disagree but no mismatch "
                        f"row names the first diverging op")
    for i, row in enumerate(mismatches):
        if not isinstance(row, dict):
            problems.append(f"lane {name!r} mismatch[{i}] is not an "
                            f"object")
            continue
        pair = row.get("ranks")
        if not (isinstance(pair, list) and len(pair) == 2 and all(
                isinstance(x, str) and x in ranks for x in pair)):
            problems.append(f"lane {name!r} mismatch[{i}] 'ranks' must "
                            f"name two recorded rank labels: {pair!r}")
        if not (isinstance(row.get("index"), int) and row["index"] >= 0):
            problems.append(f"lane {name!r} mismatch[{i}] missing "
                            f"'index' (int >= 0)")
        for side in ("a", "b"):
            v = row.get(side)
            if not (isinstance(v, str) and v.strip()):
                problems.append(f"lane {name!r} mismatch[{i}] must spell "
                                f"the diverging op on side {side!r}")


def validate_fleetlint(doc) -> List[str]:
    """Problems with one parsed FLEETLINT document (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("round"), int):
        problems.append("missing/invalid 'round' (int)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    if not (isinstance(doc.get("n_ranks"), int) and doc["n_ranks"] >= 2):
        problems.append("missing/invalid 'n_ranks' (int >= 2)")
    lanes = doc.get("lanes")
    if not isinstance(lanes, dict) or not lanes:
        return problems + ["missing/empty 'lanes' object"]
    for name, lane in lanes.items():
        if not isinstance(lane, dict):
            problems.append(f"lane {name!r} is not an object")
            continue
        _validate_lane(name, lane, problems)

    gate = doc.get("gate")
    if not isinstance(gate, dict):
        problems.append("missing 'gate' object")
        return problems
    bad = sorted(name for name, lane in lanes.items()
                 if isinstance(lane, dict)
                 and lane.get("consistent") is False)
    if not isinstance(gate.get("ok"), bool):
        problems.append("gate missing/invalid 'ok' (bool)")
    elif gate["ok"] != (not bad):
        problems.append(f"gate.ok={gate['ok']} contradicts the lanes "
                        f"(inconsistent: {bad or 'none'})")
    if not isinstance(gate.get("inconsistent_lanes"), int):
        problems.append("gate missing/invalid 'inconsistent_lanes' (int)")
    elif gate["inconsistent_lanes"] != len(bad):
        problems.append(
            f"gate.inconsistent_lanes={gate['inconsistent_lanes']} "
            f"contradicts the lanes (counted {len(bad)})")
    return problems


def validate_fleetlint_file(path: str) -> List[str]:
    """Problems with one FLEETLINT_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable fleetlint JSON: {e}"]
    return validate_fleetlint(doc)
