"""KERNLINT_r*.json — schema for the committed Pallas-sanitizer sweep.

``tools/kernel_lint.py --out KERNLINT_rN.json`` writes one of these
per round: every hand-written Pallas kernel (adam, lamb stage-1/2,
layer_norm fwd/bwd, multi_tensor, flash_attention, the experimental
kernels) traced across the geometry ladder and adversarial ragged
shapes, run through all six :mod:`apex_tpu.analysis.pallas_lint`
rules, with per-kernel per-rule finding counts and a verdict.  Like
MEMLINT/PRECLINT/FLEETLINT, the artifact is gate memory:
``tools/gate_hygiene.py`` validates every committed ``KERNLINT_r*.json``
against this schema so "the kernels are race-free, covered, and under
budget" can't rot into prose nobody machine-checks.

This module is deliberately **stdlib-only** (no jax import):
``gate_hygiene`` loads it directly by file path the same way it loads
``analysis/memlint.py`` and ``analysis/fleetlint.py``.

Document shape::

    {
      "round": 1,
      "platform": "cpu",
      "budget_mb": 16.0,           # the VMEM working-set ceiling used
      "rules": ["pallas-parallel-race", ...],   # the full rule list
      "kernels": {
        "<kernel>": {              # e.g. "fused_adam", "layer_norm"
          "ok": true,              # MUST re-derive from the counts below
          "configs": 4,            # (shape, dtype, knob) points swept
          "calls": 6,              # pallas_call sites linted (>= configs)
          "findings": {            # per-rule ERROR counts over the sweep
            "pallas-vmem-overflow": 0, ...      # keys subset of "rules"
          },
          "waivers": {             # optional: rule -> documented reason;
            "<rule>": "why"        #   a waived rule needs findings > 0
          },                       #   (a waiver with none is stale)
          "error": "..."           # optional: sweep crashed; forces
        }, ...                     #   ok=false
      },
      "gate": {"ok": true, "kernels_clean": 9,
               "kernels_total": 9}               # re-derived
    }

The contradiction rules: a kernel's ``ok`` must equal "zero unwaived
finding counts and no error" — a clean verdict sitting on recorded
findings is invalid, as is a waiver citing a rule that never fired;
``gate.ok``/``kernels_clean``/``kernels_total`` must re-derive from the
per-kernel verdicts.
"""

from __future__ import annotations

import json
from typing import List

#: the six pallas_lint rule ids (mirrored here so the validator stays
#: stdlib-only; ``tests/l0/test_pallas_lint.py`` pins the two lists
#: equal so they cannot drift)
RULES = ("pallas-parallel-race", "pallas-alias-race",
         "pallas-oob-unmasked", "pallas-uncovered-output",
         "pallas-vmem-overflow", "pallas-seq-accum-parallel")


def _validate_kernel(name: str, rec: dict, rules: tuple,
                     problems: List[str]) -> None:
    if not isinstance(rec.get("ok"), bool):
        problems.append(f"kernel {name!r} missing/invalid 'ok' (bool)")
        return
    for key in ("configs", "calls"):
        if not (isinstance(rec.get(key), int) and rec[key] >= 0):
            problems.append(f"kernel {name!r} missing/invalid {key!r} "
                            f"(int >= 0)")
            return
    findings = rec.get("findings")
    if not isinstance(findings, dict):
        problems.append(f"kernel {name!r} missing 'findings' object")
        return
    for rule, count in findings.items():
        if rule not in rules:
            problems.append(f"kernel {name!r} records unknown rule "
                            f"{rule!r} (schema knows {sorted(rules)})")
        if not (isinstance(count, int) and count >= 0):
            problems.append(f"kernel {name!r} finding count for "
                            f"{rule!r} is not an int >= 0: {count!r}")
            return
    waivers = rec.get("waivers", {})
    if not isinstance(waivers, dict):
        problems.append(f"kernel {name!r} has invalid 'waivers' "
                        f"(object of rule -> reason)")
        return
    for rule, reason in waivers.items():
        if rule not in rules:
            problems.append(f"kernel {name!r} waives unknown rule "
                            f"{rule!r}")
        if not (isinstance(reason, str) and reason.strip()):
            problems.append(f"kernel {name!r} waiver for {rule!r} "
                            f"needs a non-empty reason")
        if findings.get(rule, 0) == 0:
            problems.append(f"kernel {name!r} waives {rule!r} which "
                            f"recorded no findings (stale waiver)")
    error = rec.get("error")
    if error is not None and not (isinstance(error, str)
                                  and error.strip()):
        problems.append(f"kernel {name!r} has invalid 'error' "
                        f"(non-empty str)")

    # the contradiction rule: the verdict must re-derive from the
    # recorded evidence — unwaived counts and the error field
    unwaived = sum(c for rule, c in findings.items()
                   if isinstance(c, int) and rule not in waivers)
    derived = unwaived == 0 and error is None
    if rec["ok"] != derived:
        if error is not None:
            why = f"a recorded sweep error ({error[:60]!r})"
        elif unwaived:
            why = f"{unwaived} unwaived finding(s)"
        else:
            why = "zero unwaived findings and no error"
        problems.append(f"kernel {name!r}: ok={rec['ok']} contradicts "
                        f"{why}")
    if rec["calls"] < rec["configs"] and error is None:
        problems.append(f"kernel {name!r}: {rec['calls']} linted "
                        f"call(s) over {rec['configs']} config(s) — "
                        f"some configs produced no pallas_call and no "
                        f"'error' explains it")


def validate_kernlint(doc) -> List[str]:
    """Problems with one parsed KERNLINT document (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("round"), int):
        problems.append("missing/invalid 'round' (int)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    budget = doc.get("budget_mb")
    if not (isinstance(budget, (int, float)) and budget > 0):
        problems.append("missing/invalid 'budget_mb' (number > 0)")
    rules = doc.get("rules")
    if not (isinstance(rules, list) and rules
            and all(isinstance(r, str) for r in rules)):
        problems.append("missing/invalid 'rules' (non-empty list of "
                        "rule-id strings)")
        rules = list(RULES)
    kernels = doc.get("kernels")
    if not isinstance(kernels, dict) or not kernels:
        return problems + ["missing/empty 'kernels' object"]
    for name, rec in kernels.items():
        if not isinstance(rec, dict):
            problems.append(f"kernel {name!r} is not an object")
            continue
        _validate_kernel(name, rec, tuple(rules), problems)

    gate = doc.get("gate")
    if not isinstance(gate, dict):
        problems.append("missing 'gate' object")
        return problems
    clean = sum(1 for rec in kernels.values()
                if isinstance(rec, dict) and rec.get("ok") is True)
    total = len(kernels)
    if not isinstance(gate.get("ok"), bool):
        problems.append("gate missing/invalid 'ok' (bool)")
    elif gate["ok"] != (clean == total):
        problems.append(f"gate.ok={gate['ok']} contradicts the kernel "
                        f"verdicts ({clean}/{total} clean)")
    for key, want in (("kernels_clean", clean),
                      ("kernels_total", total)):
        if not isinstance(gate.get(key), int):
            problems.append(f"gate missing/invalid {key!r} (int)")
        elif gate[key] != want:
            problems.append(f"gate.{key}={gate[key]} contradicts the "
                            f"kernel records (counted {want})")
    return problems


def validate_kernlint_file(path: str) -> List[str]:
    """Problems with one KERNLINT_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable kernlint JSON: {e}"]
    return validate_kernlint(doc)
