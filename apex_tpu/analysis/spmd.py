"""Cross-rank SPMD consistency lint and collective-deadlock detector.

The whole distributed story — flat-bucket allreduce DDP, SyncBatchNorm's
cross-replica stats, the elastic fleet that shrinks on preemption and
regrows on recovery — rests on one unstated invariant: *every rank
executes the same collective schedule*.  One rank compiling a different
collective order (the fork's signSGD hack was exactly a one-rank
payload divergence) hangs the whole fleet with no diagnostic: rank 7
sits in an all-reduce nobody else entered.  Every previous pass in this
package audits ONE lowering; this module compares lowerings across
ranks, meshes and reshape transitions, and turns the hang into a named,
gateable finding.

Three layers:

- :func:`collective_schedule` — the program-order sequence of
  collective ops in a lowering (pre-optimization StableHLO or compiled
  HLO), each entry carrying opcode, channel wiring (``channel_id``,
  ``replica_groups``, ``use_global_device_ids``), payload dtypes/bytes
  and the enclosing control-flow region from the :mod:`.dflow` SSA
  walker.  :func:`schedule_fingerprint` hashes it canonically — the
  digest the runtime preflight all-gathers
  (:func:`apex_tpu.parallel.multiproc.spmd_preflight`).
- :func:`diff_schedules` / :func:`compare_lowerings` — structural diff
  of N schedules emitting ``spmd-schedule-mismatch`` (different op
  sequence: the static deadlock), ``spmd-group-mismatch`` (same
  sequence, different replica_groups / channel wiring / region
  placement) and ``spmd-bytes-mismatch`` (payload disagreement — the
  signSGD class: a bucket that travels sign-compressed or at a
  different width on one rank).  Every mismatch finding names the
  first diverging op in BOTH spellings.
- the registered ``spmd-consistency`` pass — on a single lowering it
  runs the *deadlock-shape* check: a collective under a rank-divergent
  predicate (inside an ``if``/``case``/``while`` whose condition
  depends on ``partition_id``/``replica_id``-derived values) is
  ``spmd-conditional-collective``, the one divergence visible without
  a peer to diff against.  With ``peers=`` it additionally diffs the
  context's schedule against each peer lowering.

:func:`reshape_pair_findings` is the elastic-fleet corollary: across a
mesh reshape (the DurableCheckpointManager 8→4 shrink / 4→8 regrow
lanes) byte-identical schedules are *impossible* (group sizes change),
but the opcode sequence must survive — a shrink that adds or reorders
collectives would deadlock the regrown fleet mid-rewind.
"""

from __future__ import annotations

import hashlib
import json
import re
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from apex_tpu.analysis.collectives import (_COLLECTIVE_RE, _SHAPE_RE,
                                           canon_groups, collective_attrs,
                                           shape_bytes)
from apex_tpu.analysis.core import PassContext, register_pass
from apex_tpu.analysis.dflow import (dims_of, element_type, parse_module)
from apex_tpu.analysis.report import Finding

#: StableHLO collective opcodes (short form) -> HLO dash spelling
_STABLEHLO_COLLECTIVES = {
    "all_reduce": "all-reduce",
    "all_gather": "all-gather",
    "reduce_scatter": "reduce-scatter",
    "collective_permute": "collective-permute",
    "all_to_all": "all-to-all",
    "collective_broadcast": "collective-broadcast",
}
#: ops whose result is rank-identifying — the taint sources for the
#: conditional-collective (static deadlock) check
_RANK_SOURCES = ("partition_id", "replica_id")
#: control-flow owners whose predicate choosing a branch/iteration can
#: make a nested collective rank-divergent
_BRANCH_OWNERS = ("if", "case", "while")

_SH_CHANNEL_RE = re.compile(r"channel_handle\s*=.*?handle\s*=\s*(\d+)")
_SH_GROUPS_RE = re.compile(r"replica_groups\s*=\s*dense<([^>]*)>")
_SH_ELEM_BYTES = {"i1": 1, "f8E4M3FN": 1, "f8E4M3B11FNUZ": 1, "f8E5M2": 1}
#: compiled-HLO computation header: ``%region_0.4 (...) -> ... {`` /
#: ``ENTRY %main.10 (...) -> ... {``
_HLO_COMP_RE = re.compile(
    r"^\s*(?P<entry>ENTRY\s+)?%(?P<name>[\w.$-]+)\s*\(.*\)\s*->.*\{")


def _sh_elem_bytes(elem: str) -> int:
    """Byte width of a StableHLO element type (``f32``, ``bf16``,
    ``i64``, ``i1``, ...)."""
    if elem in _SH_ELEM_BYTES:
        return _SH_ELEM_BYTES[elem]
    m = re.search(r"(\d+)$", elem)
    return max(1, int(m.group(1)) // 8) if m else 4


def _entry(kind: str, variant: str, attrs: Mapping[str, Any],
           dtypes: Sequence[str], nbytes: int, lineno: int,
           region: Optional[str]) -> Dict[str, Any]:
    return {"kind": kind, "variant": variant,
            "channel_id": attrs.get("channel_id"),
            "replica_groups": attrs.get("replica_groups"),
            "use_global_device_ids":
                bool(attrs.get("use_global_device_ids")),
            "dtypes": list(dtypes), "bytes": int(nbytes),
            "lineno": lineno, "region": region}


def _schedule_from_hlo(hlo_text: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    comp: Optional[str] = None
    for lineno, line in enumerate(hlo_text.splitlines(), 1):
        cm = _HLO_COMP_RE.match(line)
        if cm:
            comp = None if cm.group("entry") else cm.group("name")
            continue
        m = _COLLECTIVE_RE.search(line)
        if not m or m.group("variant") == "-done":
            continue
        kind = m.group("kind")
        shapes = _SHAPE_RE.findall(m.group("shape"))
        elems = [shape_bytes(dt, dims) for dt, dims in shapes]
        if m.group("variant") == "-start":
            pick = min if kind == "reduce-scatter" else max
            nbytes = pick(elems, default=0)
            idx = elems.index(nbytes) if elems else 0
            dtypes = [shapes[idx][0]] if shapes else []
        else:
            nbytes = sum(elems)
            dtypes = [dt for dt, _dims in shapes]
        out.append(_entry(
            kind, "async" if m.group("variant") == "-start" else "sync",
            collective_attrs(line), dtypes, nbytes, lineno, comp))
    return out


def _schedule_from_funcs(funcs) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for func in funcs.values():
        for op in func.ops:
            kind = _STABLEHLO_COLLECTIVES.get(op.name)
            if kind is None:
                continue
            cm = _SH_CHANNEL_RE.search(op.line)
            gm = _SH_GROUPS_RE.search(op.line)
            attrs = {
                "channel_id": int(cm.group(1)) if cm else None,
                "replica_groups": canon_groups(gm.group(1)) if gm else None,
                "use_global_device_ids":
                    "use_global_device_ids" in op.line,
            }
            # result-role payloads: with a full (operands) -> (results)
            # signature the trailing n_results payloads are the results;
            # otherwise fall back to the last payload
            types = op.types
            if len(types) >= 2 * op.n_results:
                results = types[-op.n_results:]
            else:
                results = types[-1:]
            dtypes = [element_type(t) for t in results]
            nbytes = sum(
                int(_sh_elem_bytes(element_type(t))) *
                max(1, _prod(dims_of(t))) for t in results)
            region = "/".join(
                dict.fromkeys(o.name for o in op.owners
                              if o.name in _BRANCH_OWNERS)) or None
            out.append(_entry(kind, "sync", attrs, dtypes, nbytes,
                              op.lineno, region))
    out.sort(key=lambda e: e["lineno"])
    return out


def _prod(dims: Tuple[int, ...]) -> int:
    n = 1
    for d in dims:
        n *= d
    return n


def _module_text(program: Any) -> str:
    """Accept a lowering (``.as_text()``), module text, or an already
    parsed schedule passthrough marker (callers pass lists through
    :func:`_as_schedule`)."""
    as_text = getattr(program, "as_text", None)
    if callable(as_text):
        return as_text()
    if isinstance(program, str):
        return program
    raise TypeError(
        f"expected a lowering or module text, got {type(program).__name__}")


def _as_schedule(program: Any) -> List[Dict[str, Any]]:
    if isinstance(program, list):
        return program
    return collective_schedule(_module_text(program))


def collective_schedule(text: str) -> List[Dict[str, Any]]:
    """Program-order collective schedule of a lowering.

    Accepts pre-optimization StableHLO (``lowered.as_text()``) or
    compiled HLO; each entry is ``{kind, variant, channel_id,
    replica_groups, use_global_device_ids, dtypes, bytes, lineno,
    region}`` where ``region`` names the enclosing control-flow
    construct(s) (``"while"``, ``"if"``, a non-entry HLO computation)
    or is ``None`` at top level.  ``-done`` halves of async HLO pairs
    are skipped so sync and async spellings of the same logical
    collective yield one entry each."""
    if "stablehlo." in text:
        return _schedule_from_funcs(parse_module(text))
    return _schedule_from_hlo(text)


#: entry keys that define schedule identity across ranks (``lineno`` is
#: text layout, not semantics)
_IDENTITY_KEYS = ("kind", "variant", "channel_id", "replica_groups",
                  "use_global_device_ids", "dtypes", "bytes", "region")
#: the wiring subset — same op sequence, different plumbing
_WIRING_KEYS = ("channel_id", "replica_groups", "use_global_device_ids",
                "variant", "region")
#: the payload subset — the signSGD class
_PAYLOAD_KEYS = ("dtypes", "bytes")


def serialize_schedule(schedule: Sequence[Mapping[str, Any]]) -> str:
    """Canonical JSON of a schedule's identity (stable across ranks
    whose programs are equal; ``lineno`` excluded)."""
    return json.dumps(
        [{k: e.get(k) for k in _IDENTITY_KEYS} for e in schedule],
        sort_keys=True, separators=(",", ":"))


def schedule_fingerprint(schedule: Sequence[Mapping[str, Any]],
                         opcodes_only: bool = False) -> str:
    """sha256 hex digest of the canonical schedule — the value ranks
    exchange in the preflight barrier.  ``opcodes_only=True`` hashes
    just the ``(kind, variant)`` sequence, the invariant that must
    survive a mesh reshape."""
    if opcodes_only:
        payload = json.dumps([[e.get("kind"), e.get("variant")]
                              for e in schedule],
                             separators=(",", ":"))
    else:
        payload = serialize_schedule(schedule)
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()


def format_entry(entry: Optional[Mapping[str, Any]]) -> str:
    """One collective entry as a human-readable spelling —
    ``all-reduce(f32, 32B, groups={{0,...,7}}, channel=1, global-ids)``
    — or ``<end of schedule>`` for a missing entry (length mismatch)."""
    if entry is None:
        return "<end of schedule>"
    parts = [",".join(entry.get("dtypes") or ["?"]),
             f"{entry.get('bytes', 0)}B"]
    if entry.get("replica_groups") is not None:
        parts.append(f"groups={entry['replica_groups']}")
    if entry.get("channel_id") is not None:
        parts.append(f"channel={entry['channel_id']}")
    if entry.get("use_global_device_ids"):
        parts.append("global-ids")
    if entry.get("variant") == "async":
        parts.append("async")
    if entry.get("region"):
        parts.append(f"in {entry['region']}")
    return f"{entry.get('kind', '?')}({', '.join(parts)})"


def first_divergence(a: Sequence[Mapping[str, Any]],
                     b: Sequence[Mapping[str, Any]],
                     keys: Sequence[str] = _IDENTITY_KEYS,
                     ) -> Optional[Tuple[int, str, str]]:
    """First position where two schedules disagree on ``keys``:
    ``(index, spelling_a, spelling_b)``, or ``None`` when equal."""
    for i in range(max(len(a), len(b))):
        ea = a[i] if i < len(a) else None
        eb = b[i] if i < len(b) else None
        if ea is None or eb is None or \
                any(ea.get(k) != eb.get(k) for k in keys):
            return i, format_entry(ea), format_entry(eb)
    return None


def diff_schedules(label_a: str, sched_a: Sequence[Mapping[str, Any]],
                   label_b: str, sched_b: Sequence[Mapping[str, Any]],
                   ) -> List[Finding]:
    """Structural diff of two collective schedules.

    Tiered: a different opcode *sequence* is ``spmd-schedule-mismatch``
    (the static deadlock — one rank enters a collective the other never
    issues); same sequence but different channel wiring / groups /
    region placement is ``spmd-group-mismatch`` (ranks rendezvous on
    mismatched channels); same wiring but different payload dtypes or
    bytes is ``spmd-bytes-mismatch`` (the signSGD class — the bucket
    travels at a different width on one rank).  Each finding names the
    first diverging op in both spellings."""
    kinds = ("kind",)
    d = first_divergence(sched_a, sched_b, kinds)
    if d is not None:
        i, sa, sb = d
        return [Finding(
            "spmd-consistency", "error",
            f"collective schedules diverge at op #{i}: "
            f"{label_a} issues {sa} but {label_b} issues {sb} "
            f"({len(sched_a)} vs {len(sched_b)} collectives) — "
            f"a fleet mixing these lowerings deadlocks here",
            op="spmd-schedule-mismatch", count=i,
            example=f"{label_a}: {sa} | {label_b}: {sb}")]
    findings: List[Finding] = []
    d = first_divergence(sched_a, sched_b, _WIRING_KEYS)
    if d is not None:
        i, sa, sb = d
        findings.append(Finding(
            "spmd-consistency", "error",
            f"same collective sequence but wiring diverges at op #{i}: "
            f"{label_a} issues {sa} but {label_b} issues {sb} "
            f"(replica_groups / channel / region disagree)",
            op="spmd-group-mismatch", count=i,
            example=f"{label_a}: {sa} | {label_b}: {sb}"))
        return findings
    d = first_divergence(sched_a, sched_b, _PAYLOAD_KEYS)
    if d is not None:
        i, sa, sb = d
        findings.append(Finding(
            "spmd-consistency", "error",
            f"same collective sequence but payload diverges at op #{i}: "
            f"{label_a} sends {sa} but {label_b} sends {sb} "
            f"(the signSGD class: one rank's bucket travels at a "
            f"different width)",
            op="spmd-bytes-mismatch", count=i,
            example=f"{label_a}: {sa} | {label_b}: {sb}"))
    return findings


def compare_lowerings(programs: Mapping[str, Any]) -> List[Finding]:
    """Diff N lowerings (one per rank / mesh): ``{label: lowering |
    module text | schedule list}``.  Every label is compared against
    the first (reference) label; findings are the union."""
    items = list(programs.items())
    if len(items) < 2:
        return []
    ref_label, ref_prog = items[0]
    ref_sched = _as_schedule(ref_prog)
    findings: List[Finding] = []
    for label, prog in items[1:]:
        findings.extend(diff_schedules(
            ref_label, ref_sched, label, _as_schedule(prog)))
    return findings


def reshape_pair_findings(label_a: str, prog_a: Any,
                          label_b: str, prog_b: Any) -> List[Finding]:
    """Reshape-compatibility check for an elastic shrink/regrow pair
    (e.g. the 8-device and 4-device train-step lowerings around a
    DurableCheckpointManager mesh change).  Across a reshape the group
    sizes and bytes legitimately differ; what must survive is the
    *opcode sequence* — emitted as ``spmd-schedule-mismatch`` when it
    doesn't, an ``info`` confirmation when it does."""
    sa, sb = _as_schedule(prog_a), _as_schedule(prog_b)
    d = first_divergence(sa, sb, ("kind", "variant"))
    if d is not None:
        i, spell_a, spell_b = d
        return [Finding(
            "spmd-consistency", "error",
            f"reshape pair {label_a}->{label_b} changes the collective "
            f"sequence at op #{i}: {spell_a} vs {spell_b} — a fleet "
            f"rewound across this reshape deadlocks on its first step",
            op="spmd-schedule-mismatch", count=i,
            example=f"{label_a}: {spell_a} | {label_b}: {spell_b}")]
    return [Finding(
        "spmd-consistency", "info",
        f"reshape pair {label_a}->{label_b} opcode-consistent "
        f"({len(sa)} collectives, opcode fingerprint "
        f"{schedule_fingerprint(sa, opcodes_only=True)[:12]})",
        op="reshape-pair", count=len(sa))]


def conditional_collective_findings(stablehlo_text: str) -> List[Finding]:
    """The static deadlock shape: a collective nested in a control-flow
    region whose predicate depends on rank identity.

    Forward taint from ``partition_id`` / ``replica_id`` results over
    the SSA graph (single pass, while-header aliases resolved — the
    same conservative stance as the precision walker); a collective
    whose enclosing ``if``/``case`` predicate operand — or ANY carried
    operand of an enclosing ``while`` (its condition region reads the
    carried values, so this is conservative) — resolves into the taint
    set diverges per rank: some ranks enter the collective, others
    never do, and the fleet hangs."""
    return _conditional_findings(parse_module(stablehlo_text))


def _conditional_findings(funcs) -> List[Finding]:
    findings: List[Finding] = []
    for func in funcs.values():
        tainted: set = set()
        for op in func.ops:
            hit = op.name in _RANK_SOURCES or any(
                func.resolve(t) in tainted for t in op.operands)
            if hit and op.result is not None:
                tainted.add(op.result)
        if not tainted:
            continue
        for op in func.ops:
            kind = _STABLEHLO_COLLECTIVES.get(op.name)
            if kind is None or not op.owners:
                continue
            for owner in op.owners:
                if owner.name not in _BRANCH_OWNERS:
                    continue
                preds = owner.operands if owner.name == "while" \
                    else owner.operands[:1]
                if any(func.resolve(t) in tainted for t in preds):
                    findings.append(Finding(
                        "spmd-consistency", "error",
                        f"{kind} at line {op.lineno} executes under a "
                        f"rank-divergent predicate: the enclosing "
                        f"{owner.name} (line {owner.lineno}) is "
                        f"conditioned on partition/replica identity — "
                        f"ranks taking different branches deadlock "
                        f"the collective",
                        op="spmd-conditional-collective",
                        lineno=op.lineno, example=op.line.strip()[:160]))
                    break
    return findings


def spmd_pass(ctx: PassContext,
              peers: Optional[Mapping[str, Any]] = None) -> List[Finding]:
    """The registered ``spmd-consistency`` pass.

    On a single lowering: the conditional-collective (static deadlock)
    check plus an ``info`` schedule summary carrying the fingerprint
    the preflight would exchange.  With ``peers`` (``{label: lowering |
    text | schedule}``) the context's schedule is additionally diffed
    against each peer."""
    funcs = ctx.memo("dflow",                 # shared with the precision
                     lambda: parse_module(ctx.stablehlo_text))  # pass
    findings = _conditional_findings(funcs)
    sched = ctx.memo("spmd_schedule",
                     lambda: _schedule_from_funcs(funcs))
    findings.append(Finding(
        "spmd-consistency", "info",
        f"collective schedule: {len(sched)} op(s), fingerprint "
        f"{schedule_fingerprint(sched)[:12]}",
        op="schedule", count=len(sched)))
    for label, prog in (peers or {}).items():
        findings.extend(diff_schedules(
            "this", sched, label, _as_schedule(prog)))
    return findings


register_pass("spmd-consistency", spmd_pass)
