"""Lint-gated AOT export: the executable cache the lint gate builds.

Every laned entry point is already lowered and compiled once per
graph-lint run, and the :class:`~apex_tpu.analysis.PassContext` holds
the compiled executable — which until now was thrown away after the
verdict.  This module turns ``analyze()``'s machinery into the build
step of a deployable artifact: after a lane passes its pass matrix,
the compiled executable is AOT-serialized (PJRT executable
serialization via :mod:`jax.experimental.serialize_executable`, the
compiled-program half of the ``jax.export`` story) into a
content-addressed cache, and serve/train startup probes that cache
instead of paying XLA compilation on every cold replica.

Cache-key derivation
--------------------

An entry is keyed by the sha256 of the canonical JSON of
:func:`key_parts`:

- ``module_sha256`` — sha256 of the lowered StableHLO module text
  (the program the user asked for, before XLA's backend passes);
- ``mesh`` — the device topology the program was lowered against
  (``platform[n]``, from the lowering's device assignment);
- ``policy`` — the resolved :class:`apex_tpu.amp.policy.Properties`
  descriptor (opt level, cast dtype, loss-scale mode, fp8 fields);
- ``jax`` / ``jaxlib`` / ``backend`` — the versions that produced the
  executable (a PJRT executable is not portable across them).

Any drift in any part — a one-op program change, a different mesh, a
policy override, a jax upgrade — is a different key, hence a cache
MISS and a fresh compile: stale executables are unreachable by
construction, never "probably compatible".

The lint-gate invariant
-----------------------

An executable can only ENTER the cache clean: :func:`write_entry`
refuses any :class:`~apex_tpu.analysis.Report` carrying an error
finding, and refuses a report whose pass list does not include
``export-compat`` (serializability is part of clean).  The gating
Report is embedded in the per-entry manifest, so an entry can only
LEAVE the cache clean too: :func:`load_entry` re-verifies the
manifest (recomputed key, executable sha256, lint verdict) and skips
— with a warning, never trusting — any entry that is truncated,
bit-flipped, key-inconsistent, or gated by a failing report.

The ``export-compat`` pass
--------------------------

Registered like every other lint pass; statically rejects lanes whose
executables cannot be serialized into a deployable artifact:

==========================  =============================================
finding id (``op``)         rejects
==========================  =============================================
``export-host-callback``    io/pure/debug callbacks, infeed/outfeed: the
                            serialized executable cannot carry the
                            Python callable / host coupling
``export-platform-call``    a ``stablehlo.custom_call`` outside the
                            portable allowlist — backend-library calls
                            resolve against the producing process, not
                            the artifact
``export-static-capture``   a numeric example argument bound statically
                            at trace time: one cache entry per VALUE
                            (a step counter would mint an unbounded
                            entry stream and every replica still misses)
``export-baked-constant``   a weight-sized constant baked into the
                            module: the artifact weighs the checkpoint
                            and the key churns on every new value
==========================  =============================================

Fallback semantics
------------------

:func:`probe` is the startup path (:class:`apex_tpu.serve.ServeEngine`
and ``amp.make_train_step(aot_cache=...)`` ride it): lower once, key,
try the cache; on a verified hit return the deserialized executable,
on a miss (or a corrupted/stale entry, which is skipped with a
warning) fall back to ``lowered.compile()`` and — when
``export_on_miss`` — relint and populate the cache for the next
replica.  The fallback is always a full fresh compile: a bad cache
can cost cold-start time, never correctness.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import re
import shutil
import time
import warnings
from pathlib import Path
from typing import Any, List, Mapping, Optional, Sequence, Tuple

import jax

from apex_tpu.analysis.core import (
    PassContext,
    _args_info,
    _out_info,
    _static_scalars,
    lower_quiet,
    register_pass,
    run_passes,
)
from apex_tpu.analysis.report import Finding, Report
from apex_tpu.analysis.constants import (
    DEFAULT_MIN_BYTES as _CONST_MIN_BYTES,
    constant_capture_pass,
)
from apex_tpu.analysis.syncs import (
    _CALLBACK_TARGETS,
    _INFEED_RE,
    _OUTFEED_RE,
)

#: env knob naming the fleet-wide cache directory.
#: ``tools/aot_export.py`` and :class:`apex_tpu.serve.ServeEngine`
#: fall back to it when no explicit directory is given (one env var
#: enables the whole serving fleet); ``make_train_step(aot_cache=...)``
#: stays EXPLICIT — the cache changes its return contract from a
#: plain jittable to a self-jitting step, which must never flip on an
#: ambient env var.
CACHE_ENV = "APEX_TPU_AOT_CACHE"

#: the full gate matrix an exported lane must pass — ``precision`` is
#: dropped by :func:`probe` when no resolved policy is available (the
#: pass's contract needs one), ``export-compat`` is never droppable.
EXPORT_GATE_PASSES = ("donation", "sharding", "collectives",
                      "constant-capture", "memory", "cost", "syncs",
                      "precision", "export-compat")

#: custom-call targets that serialize portably: sharding annotations
#: are partitioning metadata the artifact's own platform consumes, not
#: references into the producing process.  Everything else — LAPACK
#: wrappers on CPU, cuDNN/cuBLAS handles on GPU, ad-hoc FFI targets —
#: resolves against libraries of the process that compiled it and is
#: refused (``export-platform-call``).
PORTABLE_CUSTOM_CALLS = frozenset({
    "Sharding", "SPMDFullToShardShape", "SPMDShardToFullShape",
    "annotate_device_placement",
})

_EXECUTABLE = "executable.bin"
_MANIFEST = "manifest.json"

_CC_TARGET = re.compile(r"stablehlo\.custom_call\s+@([\w.]+)")


class ExportRefused(Exception):
    """The lint gate refused this executable from the cache.

    ``finding_id`` is the documented id of the first refusing finding
    (an ``export-compat`` op code, or ``lint-error`` when a non-export
    pass gated) — what tools record in the artifact's ``refused``
    field."""

    def __init__(self, finding_id: str, message: str,
                 report: Optional[Report] = None):
        super().__init__(message)
        self.finding_id = finding_id
        self.report = report


# ---------------------------------------------------------------------------
# cache-key derivation
# ---------------------------------------------------------------------------

def module_sha256(stablehlo_text: str) -> str:
    """sha256 of the lowered StableHLO module text — the content half
    of the content address."""
    return hashlib.sha256(stablehlo_text.encode("utf-8")).hexdigest()


def policy_descriptor(policy: Any) -> str:
    """Canonical string of a resolved ``amp.policy.Properties`` (or
    ``"none"``): every field, sorted, dtypes stringified — two
    policies that resolve differently can never share a key."""
    if policy is None:
        return "none"
    if dataclasses.is_dataclass(policy):
        fields = dataclasses.asdict(policy)
    elif hasattr(policy, "_asdict"):
        fields = policy._asdict()
    else:
        return repr(policy)
    return json.dumps(fields, sort_keys=True, default=str)


def mesh_descriptor(lowered: Any = None) -> str:
    """``platform[n]`` of the topology the program was lowered
    against, from the lowering's device assignment when readable
    (best-effort: the process default backend otherwise)."""
    platform = jax.default_backend()
    n = None
    if lowered is not None:
        try:
            da = lowered._lowering.compile_args["device_assignment"]
            n = len(da)
            platform = da[0].platform
        except (AttributeError, KeyError, TypeError, IndexError):
            n = None
    if n is None:
        n = jax.local_device_count()
    return f"{platform}[{n}]"


def runtime_versions() -> dict:
    """The version triple a PJRT executable is pinned to."""
    import jaxlib
    try:
        backend = jax.extend.backend.get_backend()
        backend_v = f"{backend.platform}:{backend.platform_version}"
    except Exception:  # noqa: BLE001 - descriptor stays best-effort
        backend_v = jax.default_backend()
    return {"jax": jax.__version__,
            "jaxlib": getattr(jaxlib, "__version__", "unknown"),
            "backend": backend_v}


def key_parts(stablehlo_text: str, mesh: Optional[str] = None,
              policy: Any = None,
              versions: Optional[Mapping[str, str]] = None) -> dict:
    """The key's preimage: every fact an executable's validity depends
    on.  ``mesh`` defaults to the process topology (pass
    :func:`mesh_descriptor` of the lowering for exactness)."""
    parts = {"module_sha256": module_sha256(stablehlo_text),
             "mesh": mesh if mesh is not None else mesh_descriptor(),
             "policy": policy if isinstance(policy, str)
             else policy_descriptor(policy)}
    parts.update(versions if versions is not None else runtime_versions())
    return parts


def cache_key(parts: Mapping[str, Any]) -> str:
    """sha256 over the canonical JSON of :func:`key_parts`."""
    return hashlib.sha256(
        json.dumps(dict(parts), sort_keys=True).encode("utf-8")
    ).hexdigest()


# ---------------------------------------------------------------------------
# the export-compat pass
# ---------------------------------------------------------------------------

def export_compat_pass(ctx: PassContext,
                       min_const_bytes: int = _CONST_MIN_BYTES,
                       ) -> List[Finding]:
    """Statically reject non-serializable lanes (see the module
    docstring's finding-id table)."""
    findings: List[Finding] = []
    for lineno, line in enumerate(ctx.stablehlo_text.splitlines(), 1):
        if "stablehlo.custom_call" not in line:
            if _INFEED_RE.search(line) or _OUTFEED_RE.search(line):
                findings.append(Finding(
                    "export-compat", "error",
                    "infeed/outfeed inside the program — a serialized "
                    "executable cannot carry the host feeding coupling",
                    op="export-host-callback", lineno=lineno,
                    example=line.strip()[:160]))
            continue
        m = _CC_TARGET.search(line)
        if not m:
            continue
        target = m.group(1)
        if target in _CALLBACK_TARGETS:
            findings.append(Finding(
                "export-compat", "error",
                f"host callback custom_call @{target} — the Python "
                f"callable lives in THIS process; a deserialized "
                f"executable would call into a dangling reference.  "
                f"Strip the callback (or keep this lane compile-only)",
                op="export-host-callback", lineno=lineno,
                example=line.strip()[:160]))
        elif target not in PORTABLE_CUSTOM_CALLS:
            findings.append(Finding(
                "export-compat", "error",
                f"platform-dependent custom_call @{target} — resolves "
                f"against the producing process's backend libraries, "
                f"not the serialized artifact; not exportable",
                op="export-platform-call", lineno=lineno,
                example=line.strip()[:160]))
    for label, typename, value in ctx.static_scalars:
        findings.append(Finding(
            "export-compat", "error",
            f"example argument {label}={value} ({typename}) was bound "
            f"STATICALLY at trace time — the executable is specialized "
            f"per value, so the cache would mint one entry per value "
            f"and every replica still misses; make it a dynamic "
            f"argument (shape-determining statics belong in the lane "
            f"definition, not the call site)",
            op="export-static-capture"))
    for f in constant_capture_pass(ctx, min_bytes=min_const_bytes):
        findings.append(Finding(
            "export-compat", "error",
            f"weight-sized constant baked into the module "
            f"({f.bytes} bytes) — the cache artifact would embed the "
            f"checkpoint and the content key would churn on every new "
            f"value; pass it as an argument",
            op="export-baked-constant", dtype=f.dtype, bytes=f.bytes,
            lineno=f.lineno, example=f.example))
    return findings


register_pass("export-compat", export_compat_pass)


# ---------------------------------------------------------------------------
# cache entries
# ---------------------------------------------------------------------------

def _entry_dir(cache_dir, key: str) -> Path:
    return Path(cache_dir) / key


def serialize_compiled(compiled) -> bytes:
    """One blob for one ``jax.stages.Compiled``: the PJRT executable
    serialization plus the arg/out pytree structure it is called
    through (``jax.experimental.serialize_executable`` returns them
    separately; the cache stores the whole calling convention)."""
    from jax.experimental import serialize_executable as se
    return pickle.dumps(se.serialize(compiled))


def deserialize_compiled(blob: bytes, backend=None):
    from jax.experimental import serialize_executable as se
    serialized, in_tree, out_tree = pickle.loads(blob)
    return se.deserialize_and_load(serialized, in_tree, out_tree,
                                   backend=backend)


def write_entry(cache_dir, key: str, parts: Mapping[str, Any],
                compiled, report: Report, lane: Optional[str] = None,
                extra: Optional[Mapping[str, Any]] = None) -> dict:
    """Serialize ``compiled`` into the cache under ``key`` — ONLY if
    ``report`` gates it clean (no error finding, ``export-compat``
    among the passes that ran).  Returns the manifest.  The write is
    atomic at the entry level (tmp dir + rename), so a concurrent
    reader sees either no entry or a complete one."""
    if "export-compat" not in report.passes:
        raise ExportRefused(
            "export-compat-not-run",
            "the export-compat pass did not run — serializability is "
            "part of the gate, not optional", report)
    if not report.ok:
        # an export-compat id names the hazard most precisely (the
        # syncs pass flags the same io_callback as a host sync, but
        # the EXPORT story is serializability)
        first = next((f for f in report.errors
                      if f.pass_name == "export-compat"),
                     report.errors[0])
        fid = first.op if first.pass_name == "export-compat" \
            else "lint-error"
        raise ExportRefused(
            fid,
            f"lint gate refused the executable: [{first.pass_name}] "
            f"{first.message}", report)
    blob = serialize_compiled(compiled)
    manifest = {
        "key": key,
        "key_parts": dict(parts),
        "sha256": hashlib.sha256(blob).hexdigest(),
        "size": len(blob),
        "lane": lane,
        "lint": report.to_dict(),
        "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
    }
    if extra:
        manifest.update(extra)
    dest = _entry_dir(cache_dir, key)
    if dest.exists():
        # same key == same content: keep an INTACT existing entry
        # rather than replace it under a concurrent reader's feet —
        # but a torn or corrupt one (unreadable manifest, sha
        # mismatch, dirty embedded verdict: exactly what made the
        # caller miss) must be healed, or the poisoned entry would
        # force every future replica through a fresh compile forever
        if _entry_intact(dest, key):
            with open(dest / _MANIFEST) as f:
                return json.load(f)
        shutil.rmtree(dest, ignore_errors=True)
    tmp = dest.parent / f".tmp-{key[:16]}-{os.getpid()}"
    if tmp.exists():
        shutil.rmtree(tmp)
    tmp.mkdir(parents=True)
    try:
        (tmp / _EXECUTABLE).write_bytes(blob)
        with open(tmp / _MANIFEST, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
            f.write("\n")
        try:
            os.rename(tmp, dest)
        except OSError:
            if not dest.exists():   # not a lost same-key race: real IO
                raise
            # a concurrent writer landed the same content first —
            # their complete entry serves every replica equally well
    finally:
        if tmp.exists():
            shutil.rmtree(tmp, ignore_errors=True)
    return manifest


def _entry_intact(d: Path, key: str) -> bool:
    """Cheap integrity check of an existing entry (no
    deserialization): readable manifest whose key matches, executable
    bytes matching the manifest's sha256, clean embedded verdict."""
    try:
        with open(d / _MANIFEST) as f:
            manifest = json.load(f)
        blob = (d / _EXECUTABLE).read_bytes()
    except (OSError, ValueError):
        return False
    return (isinstance(manifest, dict)
            and manifest.get("key") == key
            and hashlib.sha256(blob).hexdigest() == manifest.get("sha256")
            and isinstance(manifest.get("lint"), dict)
            and manifest["lint"].get("ok") is True)


def _skip(key: str, why: str) -> None:
    warnings.warn(f"aot cache entry {key[:16]}… skipped ({why}) — "
                  f"falling back to a fresh compile; the entry is "
                  f"never trusted", RuntimeWarning, stacklevel=3)


def load_entry(cache_dir, key: str, backend=None
               ) -> "Optional[Tuple[Any, dict]]":
    """``(compiled, manifest)`` on a VERIFIED hit, ``None`` on a miss.

    A present-but-unverifiable entry — unreadable or key-inconsistent
    manifest, sha256 mismatch (truncated/bit-flipped blob), a gating
    report that is not clean, an undeserializable executable — is
    skipped with a :class:`RuntimeWarning`, never trusted."""
    d = _entry_dir(cache_dir, key)
    if not d.is_dir():
        return None                      # plain miss: no entry at all
    try:
        with open(d / _MANIFEST) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        _skip(key, f"unreadable manifest: {e}")
        return None
    if not isinstance(manifest, dict) or manifest.get("key") != key:
        _skip(key, "manifest key mismatch")
        return None
    parts = manifest.get("key_parts")
    if not isinstance(parts, dict) or cache_key(parts) != key:
        _skip(key, "key_parts do not hash to the entry key")
        return None
    lint = manifest.get("lint")
    if not isinstance(lint, dict) or lint.get("ok") is not True:
        _skip(key, "gating lint report absent or not clean")
        return None
    try:
        blob = (d / _EXECUTABLE).read_bytes()
    except OSError as e:
        _skip(key, f"unreadable executable: {e}")
        return None
    if hashlib.sha256(blob).hexdigest() != manifest.get("sha256"):
        _skip(key, "executable sha256 mismatch (truncated or "
                    "bit-flipped)")
        return None
    try:
        compiled = deserialize_compiled(blob, backend=backend)
    except Exception as e:  # noqa: BLE001 - corrupt blobs must not crash startup
        _skip(key, f"deserialization failed: {type(e).__name__}: {e}")
        return None
    return compiled, manifest


def list_entries(cache_dir) -> "List[dict]":
    """Manifests of every complete entry (unreadable ones skipped)."""
    out = []
    root = Path(cache_dir)
    if not root.is_dir():
        return out
    for d in sorted(root.iterdir()):
        mf = d / _MANIFEST
        if not mf.is_file():
            continue
        try:
            with open(mf) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict):
            out.append(doc)
    return out


# ---------------------------------------------------------------------------
# the startup probe
# ---------------------------------------------------------------------------

def gate_passes_for(policy: Any) -> Tuple[str, ...]:
    """:data:`EXPORT_GATE_PASSES`, minus ``precision`` when no
    resolved policy is available (the pass's contract needs one);
    ``export-compat`` always stays."""
    if policy is None:
        return tuple(p for p in EXPORT_GATE_PASSES if p != "precision")
    return EXPORT_GATE_PASSES


def probe(jitted, *args, cache_dir, policy=None, mesh: Optional[str] = None,
          lane: Optional[str] = None, export_on_miss: bool = False,
          gate_passes: Optional[Sequence[str]] = None,
          options: Optional[Mapping] = None, **kwargs):
    """``(compiled, info)``: the cold-start path.

    Lowers ``jitted`` on the example args (ONE lowering, exactly like
    ``analyze()``), derives the cache key, and tries ``cache_dir``:

    - verified HIT → the deserialized executable,
      ``info = {"source": "cache", "load_s": ...}``;
    - MISS (or a skipped corrupt/stale entry) → ``lowered.compile()``,
      ``info = {"source": "compile", "compile_s": ...}``; with
      ``export_on_miss`` the fresh executable is relinted under
      :func:`gate_passes_for` and — only if clean — exported, so the
      first replica builds the entry every later replica loads
      (``info["exported"]`` / ``info["refused"]`` record the gate's
      verdict).

    ``cache_dir=None`` degrades to plain compile (the fallback is
    always a full fresh compile — a bad cache can cost cold-start
    time, never correctness)."""
    lowered = lower_quiet(jitted, *args, **kwargs)
    text = lowered.as_text()
    parts = key_parts(text, mesh=mesh if mesh is not None
                      else mesh_descriptor(lowered), policy=policy)
    key = cache_key(parts)
    info: dict = {"key": key, "lane": lane}
    if cache_dir:
        t0 = time.perf_counter()
        hit = load_entry(cache_dir, key)
        if hit is not None:
            compiled, manifest = hit
            info.update(source="cache",
                        load_s=time.perf_counter() - t0,
                        manifest_lane=manifest.get("lane"))
            return compiled, info
    t0 = time.perf_counter()
    compiled = lowered.compile()
    info.update(source="compile", compile_s=time.perf_counter() - t0)
    if cache_dir and export_on_miss:
        ctx = PassContext(
            stablehlo_text=text, hlo_text=compiled.as_text(),
            args=_args_info(lowered), outputs=_out_info(lowered),
            compiled=compiled, policy=policy,
            # the export-static-capture rule reads these: a jit that
            # bound an example scalar statically is specialized per
            # VALUE and must be refused, exactly as analyze() sees it
            static_scalars=_static_scalars(args, kwargs,
                                           lowered.args_info))
        report = run_passes(
            ctx, passes=tuple(gate_passes) if gate_passes is not None
            else gate_passes_for(policy), options=options)
        try:
            write_entry(cache_dir, key, parts, compiled, report,
                        lane=lane)
            info["exported"] = True
        except ExportRefused as e:
            info["exported"] = False
            info["refused"] = e.finding_id
        except OSError as e:   # read-only cache dir: never fail startup
            info["exported"] = False
            info["refused"] = f"io-error: {e}"
    return compiled, info
