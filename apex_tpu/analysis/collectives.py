"""Collective-volume lint over compiled HLO.

This absorbs (and extends) the dryrun's ``_collective_audit`` from
``__graft_entry__.py``: count collective ops and their output bytes in
compiled HLO — the per-step communication volume the sharding implies,
observable from a CPU dryrun alone, no pod needed.  ``__graft_entry__``
keeps a thin import-alias for compatibility; the parser lives here so
the same numbers feed the dryrun slice records, the ``collectives``
lint pass, and its byte-budget gate.

Extensions over the original audit:

- **sync-vs-async spellings** are tallied separately
  (:func:`collective_table`): an op emitted as ``<kind>-start`` /
  ``<kind>-done`` is scheduled for overlap by XLA's latency-hiding
  scheduler, a plain (sync) spelling blocks — a step that was expected
  to overlap its gradient all-reduce but compiles to the sync spelling
  is a schedule regression the byte counts alone can't see.
- **byte budgets** (:func:`collectives_pass`): per-kind and/or total
  ceilings; exceeding one is an ``error`` finding, so comm-volume
  regressions fail a lint gate exactly like MFU regressions fail the
  bench gate.
"""

from __future__ import annotations

import re
from typing import Dict, List, Mapping, Optional

from apex_tpu.analysis.core import PassContext, register_pass
from apex_tpu.analysis.report import Finding

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
                "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
                "f64": 8, "c64": 8, "c128": 16}
#: Tuple shapes may embed TPU tiled layouts with parentheses
#: (``{0:T(256)}``), so the tuple alternative tolerates one nesting level.
_COLLECTIVE_RE = re.compile(
    r"=\s+(?P<shape>\((?:[^()]|\([^()]*\))*\)|\S+)\s+"
    r"(?P<kind>all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all|collective-broadcast|ragged-all-to-all)"
    r"(?P<variant>-start|-done)?\(")
_SHAPE_RE = re.compile(
    r"(pred|s8|u8|s16|u16|f16|bf16|s32|u32|f32|s64|u64|f64|c64|c128)"
    r"\[([0-9,]*)\]")
#: attribute spellings on a compiled-HLO collective line
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")
_GROUPS_RE = re.compile(
    r"replica_groups=(\{(?:[^{}]|\{[^{}]*\})*\}"       # {{0,1},{2,3}} / {}
    r"|\[[0-9,]*\](?:<=\[[0-9,]*\])?)")                # iota [2,4]<=[8]
_GLOBAL_IDS_RE = re.compile(r"use_global_device_ids=true")


def canon_groups(spelling: str) -> str:
    """Canonical ``{{0,1},{2,3}}`` form of a replica_groups attribute,
    accepting the compiled-HLO braces form, the StableHLO
    ``dense<[[0, 1], [2, 3]]>`` form, and the iota form (kept verbatim,
    whitespace-stripped)."""
    s = re.sub(r"\s", "", spelling)
    if "<=" in s:                    # iota spelling has no literal groups
        return s
    inner = re.findall(r"[\[{]([0-9,]*)[\]}]", s)
    return "{" + ",".join("{" + g.strip(",") + "}" for g in inner) + "}"


def collective_attrs(line: str) -> dict:
    """``{channel_id, replica_groups, use_global_device_ids}`` parsed
    from one compiled-HLO collective line (``None``/``False`` when the
    attribute is absent)."""
    cm = _CHANNEL_RE.search(line)
    gm = _GROUPS_RE.search(line)
    return {
        "channel_id": int(cm.group(1)) if cm else None,
        "replica_groups": canon_groups(gm.group(1)) if gm else None,
        "use_global_device_ids": bool(_GLOBAL_IDS_RE.search(line)),
    }


def shape_bytes(dtype: str, dims: str) -> int:
    """Bytes of one ``dtype[dims]`` HLO shape token (``dims`` as the
    comma-separated digits inside the brackets)."""
    n = 1
    for d in filter(None, dims.split(",")):
        n *= int(d)
    return n * _DTYPE_BYTES[dtype]


def collective_table(hlo_text: str) -> Dict[str, dict]:
    """Per-kind ``{count, bytes, sync, async}`` for the collectives in
    compiled HLO text.

    ``-done`` halves of async pairs are skipped, and a ``-start``'s
    tuple shape (operand alias + result + context words) counts only the
    element playing the result role — the largest, except reduce-scatter
    whose result is the *smallest* element — so the same logical
    collective audits identical bytes whether XLA emits the sync or
    async spelling (the spelling itself is recorded in ``sync``/
    ``async``).

    Channel wiring is recorded too: ``channels`` (sorted distinct
    ``channel_id`` values), ``replica_groups`` (distinct canonical
    spellings, first-seen order) and ``global_ids`` (ops carrying
    ``use_global_device_ids=true``) — the attributes the SPMD
    consistency pass diffs across ranks."""
    table: Dict[str, dict] = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        if m.group("variant") == "-done":
            continue
        kind = m.group("kind")
        elems = [shape_bytes(dt, dims)
                 for dt, dims in _SHAPE_RE.findall(m.group("shape"))]
        if m.group("variant") == "-start":
            pick = min if kind == "reduce-scatter" else max
            nbytes = pick(elems, default=0)
        else:
            nbytes = sum(elems)   # sync tuple results are all real buffers
        start = hlo_text.rfind("\n", 0, m.start()) + 1
        end = hlo_text.find("\n", m.end())
        attrs = collective_attrs(
            hlo_text[start:end if end != -1 else len(hlo_text)])
        slot = table.setdefault(kind, {"count": 0, "bytes": 0,
                                       "sync": 0, "async": 0,
                                       "channels": [], "replica_groups": [],
                                       "global_ids": 0})
        slot["count"] += 1
        slot["bytes"] += nbytes
        slot["async" if m.group("variant") == "-start" else "sync"] += 1
        if attrs["channel_id"] is not None \
                and attrs["channel_id"] not in slot["channels"]:
            slot["channels"] = sorted(slot["channels"] + [attrs["channel_id"]])
        if attrs["replica_groups"] is not None \
                and attrs["replica_groups"] not in slot["replica_groups"]:
            slot["replica_groups"].append(attrs["replica_groups"])
        slot["global_ids"] += int(attrs["use_global_device_ids"])
    return table


def collective_audit(hlo_text: str) -> Dict[str, dict]:
    """The original dryrun audit shape: per-kind ``{count, bytes}`` only
    (``__graft_entry__._collective_audit`` compatibility — slice records
    and their consumers pin this exact dict)."""
    return {kind: {"count": rec["count"], "bytes": rec["bytes"]}
            for kind, rec in collective_table(hlo_text).items()}


def collectives_pass(ctx: PassContext,
                     budget: Optional[Mapping[str, int]] = None,
                     ) -> List[Finding]:
    """Collective count/bytes per kind, gated against ``budget``.

    ``budget`` maps a collective kind (``"all-reduce"``, ...) or the
    key ``"total"`` to a maximum byte count; exceeding one is an
    ``error``.  ``{"total": 0}`` asserts the program has no collectives
    at all — the right budget for a single-chip step."""
    if ctx.hlo_text is None:
        return [Finding("collectives", "info",
                        "skipped: program was not compiled "
                        "(analyze(..., compile=True) to audit "
                        "collectives)")]
    table = collective_table(ctx.hlo_text)
    findings = [
        Finding("collectives", "info",
                f"{kind}: {rec['count']} op(s), {rec['bytes']} bytes "
                f"({rec['async']} async / {rec['sync']} sync)",
                op=kind, bytes=rec["bytes"], count=rec["count"])
        for kind, rec in sorted(table.items())]
    budget = dict(budget or {})
    total_cap = budget.pop("total", None)
    for kind, cap in budget.items():
        got = table.get(kind, {}).get("bytes", 0)
        if got > cap:
            findings.append(Finding(
                "collectives", "error",
                f"{kind} volume {got} bytes exceeds budget {cap}",
                op=kind, bytes=got))
    if total_cap is not None:
        total = sum(rec["bytes"] for rec in table.values())
        if total > total_cap:
            findings.append(Finding(
                "collectives", "error",
                f"total collective volume {total} bytes exceeds budget "
                f"{total_cap}", op="total", bytes=total))
    return findings


register_pass("collectives", collectives_pass)
