"""Precision-flow lint: the mixed-precision contract, machine-checked.

The paper's central promise is that O0–O3 are *numerically safe by
policy*: matmuls may run in 16-bit but accumulate in fp32, long
reductions and norm statistics stay fp32, master weights and optimizer
moments stay fp32 under O2, and the dynamic loss scale multiplies the
loss BEFORE the backward and is divided out BEFORE the update
(Micikevicius et al., 2018; Kalamkar et al., 2019).  Until this pass,
none of that was verified statically — a silently wrong cast surfaced
only as a golden-digest drift or a diverged run.  Every invariant below
is checked op-by-op on the lowered StableHLO with the resolved
:class:`~apex_tpu.amp.policy.Properties` in the
:class:`~apex_tpu.analysis.PassContext`.

Finding ids (the ``op`` field of each :class:`Finding`):

``half-accum-matmul`` (error)
    A dot/conv whose accumulation is *forced* below fp32: f32 operands
    with a 16-bit result (an explicit ``preferred_element_type``
    downcast — the accumulator itself is narrowed), or f16 operands
    accumulating into f16 (the fp16 hazard the paper's §3.3 exists
    for).  ``bf16 x bf16 -> bf16`` with DEFAULT precision is CLEAN by
    design: the MXU always accumulates bf16 dots in fp32 and rounds
    once on output, so the lowered result dtype understates the
    accumulator — flagging it would fail every correct O1/O2 program.
    Info (not error) under O3, the documented "speed of light, unsafe"
    level.
``low-precision-reduce`` (error)
    An add/multiply reduction accumulating in a 16-bit dtype over
    ``reduce_threshold`` or more elements per output (default 1024).
    Short 16-bit reduce-adds (a batch-4 bias gradient) lose at most a
    few ulps and the AD-generated backward legitimately emits them in
    the wire dtype; LONG accumulations are where bf16's 8-bit mantissa
    actually destroys information (Kalamkar §3: error grows with n).
    The threshold is what keeps the real lanes clean while a seeded
    4096-element bf16 reduce fires.  Info under O3.
``double-round`` (warning)
    A ``convert`` f32→16-bit whose every consumer immediately converts
    back to f32: the value lost mantissa for nothing (a pointless
    f32→bf16→f32 round-trip on the value path).
``master-weight-dtype`` (error)
    With master weights resolved on (O2), a floating ``master_params``
    or ``opt_state`` input leaf that is not f32 — the optimizer would
    integrate updates in 16-bit, the exact failure mode fp32 masters
    exist to prevent.
``comm-dtype`` (error when configured, warning otherwise)
    A gradient collective (``all_reduce`` / ``reduce_scatter``) whose
    element type is not the policy's communication dtype
    (``comm_dtype=`` option); unconfigured, any collective outside
    {f32, policy half dtype} is flagged as a warning.
``unscaled-grad-use`` (error)
    A value on the loss-scale taint path — multiplied by the scale
    (directly or as an AD cotangent seed) and never divided back —
    reaching a program output.  This is the loss-scale placement
    contract: scale dominates the backward, unscale dominates
    clip/update; a scaled gradient flowing into the returned state (or
    a clip factor computed from scaled grads) fires here.
``loss-scale-unused`` (warning)
    A live loss-scale input that never multiplies anything: the program
    unscales (or skips) gradients that were never scaled.
``loss-scale-unchecked`` (info)
    The lowered argument list could not be matched to the kept example
    args (numbering ambiguous), so loss-scale placement was NOT checked
    — the degradation is surfaced, never silent.
``fp8-same-step-scale`` (error)
    A quantize (``convert`` to an f8 type) whose scale chain is derived
    from an amax (max-reduce) computed **in the same program** from
    live data.  The fp8 contract is *delayed* scaling (Micikevicius et
    al., 2022 §4): the scale must enter as a program INPUT (the carried
    ``DelayedScalingState``), both because a same-step amax serializes
    the quantize behind a full reduction of the tensor it quantizes,
    and because it silently changes the numbers the history-based
    recipe was validated on.  (int8 KV quantization is exempt by
    construction — its converts target ``i8``, and its per-write
    dynamic scale is the documented format.)
``fp8-amax-unrecorded`` (error)
    Under an fp8 policy, a program that quantizes to f8 but whose
    amax-history update never reaches an output: either no max-reduce
    exists at all, or none of its results flow into the returned state
    — the delayed scale would free-run on stale statistics forever
    (the state-threading bug class the O4 lanes exist to catch).
``fp8-double-quantize`` (error)
    A ``convert`` to f8 whose operand derives (through pure
    value-chain ops — converts, rescales, reshapes) from a value that
    was ALREADY f8: a dequantize-requantize round trip rounds twice
    and composes two scales where the format budgets mantissa for one.
    Contractions break the chain — a dot of f8 operands produces new
    data whose own quantization is legitimate (per-op gradient
    rounding across layers is the documented backward recipe, not a
    double quantize).
``precision-summary`` (info)
    Per-lane counters (scale applications, unscales, dots/reduces/
    converts/collectives/f8-quantizes checked) — the PRECLINT
    artifact's evidence that the pass actually looked.

Scale tracking is a five-class forward dataflow over
:mod:`apex_tpu.analysis.dflow`'s SSA view — ``N`` plain value, ``C``
constant-derived, ``S`` scale-derived, ``I`` reciprocal-scale-derived,
``T`` scaled ("tainted") — with ``multiply(N, S) -> T`` recording a
scale application and ``multiply(T, I)`` / ``divide(T, S) -> N``
recording an unscale.  Predicates (``compare``/``is_finite``) drop
taint: the overflow check READS scaled gradients by design.  Values
entering private functions are conservatively tainted-if-any-operand-
tainted; an unscale hidden inside a callee is invisible (documented
limitation — the in-tree scaler unscales inline in ``main``).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

from apex_tpu.analysis.core import PassContext, register_pass
from apex_tpu.analysis.dflow import (FuncDef, Op, base_token, dims_of,
                                     element_type, main_func, parse_module)
from apex_tpu.analysis.report import Finding

_HALF = ("bf16", "f16")
_FLOAT_PREFIXES = ("f", "bf")

#: value classes of the loss-scale dataflow
N, C, S, I, T = "n", "c", "s", "i", "t"

_STRUCTURAL = frozenset((
    "convert", "broadcast_in_dim", "broadcast", "reshape", "transpose",
    "negate", "slice", "dynamic_slice", "dynamic_update_slice",
    "concatenate", "pad", "reverse", "abs", "exponential", "log",
    "sqrt", "rsqrt", "tanh", "logistic", "add", "subtract", "maximum",
    "minimum", "select", "clamp", "power", "get_tuple_element",
    "optimization_barrier", "copy", "tuple", "real", "imag",
))
_PREDICATES = frozenset((
    "compare", "is_finite", "and", "or", "not", "xor", "iota",
    "floor", "ceil", "round_nearest_even", "sign",
))
_LOSSY_REDUCERS = ("stablehlo.add", "stablehlo.multiply")
_GRAD_COLLECTIVES = ("all_reduce", "reduce_scatter")

#: ops a scale/value chain flows through for the fp8 provenance walks
#: (structural moves + the rescale arithmetic of quantize/dequantize);
#: contractions and transcendentals deliberately BREAK the chain
_FP8_CHAIN = frozenset((
    "multiply", "divide", "broadcast_in_dim", "broadcast", "reshape",
    "convert", "clamp", "select", "transpose", "negate", "maximum",
    "minimum", "concatenate", "slice", "dynamic_slice", "copy",
    # jnp.clip lowers as a private @clip call: the quantize's clamp is
    # a call on some jax versions, and a provenance walk that a call
    # boundary could launder would miss every real bug
    "call",
))


def _is_f8(elem: Optional[str]) -> bool:
    """True for the fp8 element spellings (``f8E4M3FN``, ``f8E5M2``,
    ``f8E4M3``, ...)."""
    return bool(elem) and elem.startswith("f8")


def _max_reduce_results(fn, def_map, abs_only: bool = False) -> set:
    """Result tokens of every max-reduce in ``fn`` — the amax
    computations of both quantization recipes.  ``abs_only`` keeps
    only reduces whose input is an ``abs`` result (``max(|x|)``, the
    amax spelling): the reachability check must not be satisfied by
    softmax's numerical-stability max-reduce — every transformer has
    one flowing into the loss, which would mask a dropped
    history-roll entirely."""
    out = set()
    for op in fn.ops:
        if op.name != "reduce" or op.result is None:
            continue
        is_max = "stablehlo.maximum" in op.line
        if not is_max:
            for ret in op.region_returns:
                d = def_map.get(base_token(ret[0])) if ret else None
                if d is not None and d.name == "maximum":
                    is_max = True
        if not is_max:
            continue
        if abs_only:
            src = def_map.get(fn.resolve(op.operands[0])) \
                if op.operands else None
            if src is None or src.name != "abs":
                continue
        out.add(op.result)
    return out


def _propagate(fn, roots: set, through=None) -> set:
    """Forward closure of ``roots`` over ``fn``'s ops: a result joins
    when any operand (while-aliases resolved) is in the set.
    ``through=None`` propagates through every op; a frozenset restricts
    to those op names."""
    derived = set(roots)
    for _ in range(4):                      # while-carried chains
        changed = False
        for op in fn.ops:
            if op.result is None or op.result in derived:
                continue
            if through is not None and op.name not in through:
                continue
            if any(fn.resolve(t) in derived for t in op.operands):
                derived.add(op.result)
                changed = True
        if not changed:
            break
    return derived


def _half_name(policy) -> str:
    """Policy half dtype -> StableHLO element spelling ("bf16"/"f16")."""
    try:
        import numpy as np  # ml_dtypes registers bfloat16 with numpy
        name = np.dtype(policy.half_dtype).name
    except Exception:  # noqa: BLE001 - unresolvable dtype: assume bf16
        name = "bfloat16"
    return {"bfloat16": "bf16", "float16": "f16"}.get(name, "bf16")


def _is_float(elem: Optional[str]) -> bool:
    return bool(elem) and elem.startswith(_FLOAT_PREFIXES) \
        and elem not in ("f8",)


def _use_master_weights(policy) -> bool:
    """The policy's resolved master-weight switch — delegated to
    :attr:`apex_tpu.amp.policy.Properties.use_master_weights` (the one
    shared resolution, so lint and runtime can't drift); a foreign
    policy object without the property falls back to the same rule."""
    umw = getattr(policy, "use_master_weights", None)
    if isinstance(umw, bool):
        return umw
    if getattr(policy, "master_weights", None) is not None:
        return bool(policy.master_weights)
    cast = getattr(policy, "cast_model_dtype", None)
    if cast is None:
        return False
    try:
        import jax.numpy as jnp
        return cast != jnp.float32
    except Exception:  # noqa: BLE001
        return True


# ---------------------------------------------------------------------------
# scale-placement dataflow
# ---------------------------------------------------------------------------

def _join(classes) -> str:
    """S-dominant join: once a value is scale-proportional it stays so
    through structural/arithmetic composition until something multiplies
    it into data (-> T) or cancels it (-> C/N)."""
    cs = set(classes)
    if T in cs:
        return T
    if S in cs:
        return S
    if I in cs:
        return I
    if cs and cs <= {C}:
        return C
    return N


class _ScaleFlow:
    """One forward propagation of the five value classes over a func.

    The *scale application* event — the moment the pure scale chain
    first multiplies actual data — is recognized in every spelling the
    lowerings produce: ``multiply(N, S)``, ``divide(N, I)``, a dot/conv
    with an S operand against data, and an S value entering a private
    call together with plain float data (AD routes the cotangent seed
    through ``take_along_axis``/``log_softmax`` helpers)."""

    def __init__(self, func: FuncDef, scale_tokens):
        self.func = func
        self.classes: Dict[str, str] = {}
        self.applied = 0           # scale-application sites
        self.unscaled = 0          # multiply(T, I) / divide(T, S) sites
        self.first_taint: Dict[str, Op] = {}
        for tok, _t in func.args:
            self.classes[tok] = S if tok in scale_tokens else N

    def cls(self, token: str) -> str:
        tok = self.func.resolve(token)
        full = token if "#" in token else tok
        return self.classes.get(full, self.classes.get(tok, N))

    def _transfer(self, op: Op) -> str:
        ops_cls = [self.cls(t) for t in op.operands]
        cs = set(ops_cls)
        if op.name in ("constant", "iota"):
            return C
        if op.name in _PREDICATES:
            return N
        if op.name == "multiply":
            if T in cs and I in cs:
                self.unscaled += 1
                return N          # the unscale
            if T in cs:
                return T
            if S in cs and N in cs:
                self.applied += 1
                return T          # the scale application
            if I in cs and N in cs:
                return N
            if S in cs and I in cs:
                return C
            return _join(ops_cls)
        if op.name == "divide" and len(ops_cls) >= 2:
            num, den = ops_cls[0], ops_cls[-1]
            if num == T and den == S:
                self.unscaled += 1
                return N          # unscale spelled as a divide
            if T in (num, den):
                return T
            if den == S:
                return I if num == C else N
            if den == I:
                if num == N:
                    self.applied += 1
                    return T      # x / (1/scale) == x * scale
                return S if num == C else N
            if num == S:
                return S          # scale / count: still scale-magnitude
            if num == I:
                return I if den == C else N
            return C if (num, den) == (C, C) else N
        if op.name in ("dot_general", "dot", "convolution"):
            if T in cs:
                return T
            if S in cs and N in cs:
                self.applied += 1
                return T          # cotangent seed contracts with data
            return _join(ops_cls)
        if op.name == "call":
            if T in cs:
                return T
            if S in cs:
                # S mixing with float DATA inside a callee is a scale
                # application; S alongside only predicates/indices/
                # other scale values (the scaler's _where helpers)
                # stays a pure scale chain
                elems = op.operand_elems()
                data_floats = any(
                    c == N and k < len(elems) and _is_float(elems[k])
                    for k, c in enumerate(ops_cls))
                if data_floats:
                    self.applied += 1
                    return T
                return S
            return _join(ops_cls)
        if op.name in ("reduce",) or op.name in _STRUCTURAL:
            return _join(ops_cls)
        if op.name in ("while", "case", "if"):
            return _join(ops_cls)  # refined per-index by the sweep
        return T if T in ops_cls else N

    def _set(self, op: Op, cls) -> bool:
        """Assign (possibly per-index) classes; True when changed."""
        changed = False
        keys = [op.result]
        if op.n_results > 1:
            keys += [f"{op.result}#{k}" for k in range(op.n_results)]
        if isinstance(cls, str):
            cls = {k: cls for k in keys}
        for k in keys:
            v = cls.get(k, cls.get(op.result, N))
            if self.classes.get(k) != v:
                self.classes[k] = v
                changed = True
                if v == T and op.result not in self.first_taint:
                    self.first_taint[op.result] = op
        return changed

    def run(self, max_sweeps: int = 8) -> None:
        for sweep in range(max_sweeps):
            changed = False
            self.applied = 0
            self.unscaled = 0
            for op in self.func.ops:
                if op.result is None:
                    continue
                if op.name in ("while", "case", "if") and op.region_returns:
                    per = {}
                    for k in range(op.n_results):
                        cands = []
                        if op.name == "while" and k < len(op.operands):
                            cands.append(self.cls(op.operands[k]))
                        for ret in op.region_returns:
                            if len(ret) == op.n_results:
                                cands.append(self.cls(ret[k]))
                        key = f"{op.result}#{k}" if op.n_results > 1 \
                            else op.result
                        per[key] = _join(cands) if cands else N
                    per[op.result] = _join(per.values())
                    changed |= self._set(op, per)
                else:
                    changed |= self._set(op, self._transfer(op))
            if not changed:
                break


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

def precision_report(ctx: PassContext, policy: Any = None,
                     reduce_threshold: int = 1024,
                     double_round_min_elems: int = 256,
                     comm_dtype: Optional[str] = None,
                     ) -> Tuple[List[Finding], Dict[str, Any]]:
    """Run every precision check; returns ``(findings, stats)``.

    ``policy`` overrides ``ctx.policy``; with neither, the dtype checks
    run with bf16 defaults and the policy-gated checks (master-weight,
    O3 demotion) degrade conservatively.
    """
    policy = policy if policy is not None else getattr(ctx, "policy", None)
    half = _half_name(policy) if policy is not None else "bf16"
    opt_level = getattr(policy, "opt_level", None)
    enabled = getattr(policy, "enabled", True)
    #: O3 opted out of the safety contract: dtype findings demote to
    #: info.  O4 is the OPPOSITE of an opt-out — fp8 only works at all
    #: because the full contract (masters, dynamic scale, delayed
    #: scaling) is enforced — so it lints strict like O0–O2.
    strict = opt_level in (None, "O0", "O1", "O2", "O4")
    findings: List[Finding] = []
    stats = {"dots": 0, "reduces": 0, "converts": 0, "collectives": 0,
             "scale_args": 0, "scale_applied": 0, "unscaled": 0,
             "fp8_quantizes": 0}

    funcs = ctx.memo("dflow",
                     lambda: parse_module(ctx.stablehlo_text))
    main = main_func(funcs)
    if main is None:
        return [Finding("precision", "info",
                        "no function found in the lowered module; "
                        "precision pass saw nothing", op="precision-summary")
                ], stats

    def_map: Dict[str, Op] = {}
    for fn in funcs.values():
        for op in fn.ops:
            if op.result is not None:
                def_map.setdefault(op.result, op)

    # -- per-op dtype checks over every function ------------------------
    for fn in funcs.values():
        # returned values (func returns + every region's returns) are
        # real uses the consumer table doesn't record: a 16-bit value
        # leaving the function/region was not converted "for nothing"
        returned = {base_token(t) for ret in fn.returns
                    for t in ret.operands}
        for o in fn.ops:
            for rr in o.region_returns:
                returned.update(base_token(t) for t in rr)
        for op in fn.ops:
            if op.name in ("dot_general", "dot", "convolution"):
                elems = [e for e in op.operand_elems() if _is_float(e)]
                re_ = op.result_elem
                if not elems or not _is_float(re_):
                    continue
                stats["dots"] += 1
                if re_ in _HALF and any(e == "f32" for e in elems):
                    findings.append(Finding(
                        "precision", "error" if strict else "info",
                        f"{op.name} accumulates f32 operands into {re_} "
                        f"(preferred_element_type narrows the "
                        f"accumulator below the operands)",
                        op="half-accum-matmul", dtype=re_,
                        lineno=op.lineno, example=op.line.strip()[:200]))
                elif re_ == "f16" and all(e == "f16" for e in elems):
                    findings.append(Finding(
                        "precision", "error" if strict else "info",
                        f"{op.name} accumulates in f16 — fp16 dots must "
                        f"request f32 accumulation "
                        f"(preferred_element_type=float32); bf16 is "
                        f"exempt only because the MXU accumulates it in "
                        f"f32 by hardware contract",
                        op="half-accum-matmul", dtype="f16",
                        lineno=op.lineno, example=op.line.strip()[:200]))
            elif op.name == "reduce":
                acc = op.result_elem
                if not _is_float(acc):
                    continue
                lossy = any(r in op.line for r in _LOSSY_REDUCERS)
                if not lossy and "applies" not in op.line:
                    # generic-form reduce: the reducer region's returned
                    # value names the combining op
                    for ret in op.region_returns:
                        d = def_map.get(base_token(ret[0])) if ret else None
                        if d is not None and d.name in ("add", "multiply"):
                            lossy = True
                if not lossy:
                    continue
                stats["reduces"] += 1
                n = op.reduced_elems()
                if acc in _HALF and n >= reduce_threshold:
                    findings.append(Finding(
                        "precision", "error" if strict else "info",
                        f"reduce accumulates {n} elements per output in "
                        f"{acc}; accumulations this long must run in "
                        f"f32 (jnp.sum/mean upcast automatically — raw "
                        f"lax.reduce does not)",
                        op="low-precision-reduce", dtype=acc,
                        count=n, lineno=op.lineno,
                        example=op.line.strip()[:200]))
            elif op.name == "convert":
                in_e = op.operand_elems()[:1]
                re_ = op.result_elem
                if in_e and in_e[0] == "f32" and re_ in _HALF \
                        and op.result is not None:
                    stats["converts"] += 1
                    elems = int(math.prod(dims_of(op.result_type))) \
                        if op.result_type else 0
                    users = fn.consumers.get(op.result, [])
                    if strict and elems >= double_round_min_elems \
                            and op.result not in returned \
                            and users and all(
                            u.name == "convert" and u.result_elem == "f32"
                            for u in users):
                        findings.append(Finding(
                            "precision", "warning",
                            f"f32→{re_}→f32 double-round over {elems} "
                            f"elements: the {re_} value is only ever "
                            f"converted straight back (mantissa lost "
                            f"for nothing)",
                            op="double-round", dtype=re_, count=elems,
                            lineno=op.lineno,
                            example=op.line.strip()[:200]))
            elif op.name in _GRAD_COLLECTIVES:
                elem = op.result_elem
                if not _is_float(elem):
                    continue
                stats["collectives"] += 1
                # O3's opt-out demotes comm-dtype like every other
                # dtype finding (the documented contract)
                if comm_dtype is not None:
                    if elem != comm_dtype:
                        findings.append(Finding(
                            "precision", "error" if strict else "info",
                            f"gradient {op.name} runs at {elem}; the "
                            f"policy's communication dtype is "
                            f"{comm_dtype}",
                            op="comm-dtype", dtype=elem,
                            lineno=op.lineno,
                            example=op.line.strip()[:200]))
                elif elem not in ("f32", half):
                    findings.append(Finding(
                        "precision", "warning" if strict else "info",
                        f"gradient {op.name} runs at {elem} — neither "
                        f"f32 nor the policy half dtype ({half}); pass "
                        f"comm_dtype= to pin the contract",
                        op="comm-dtype", dtype=elem, lineno=op.lineno,
                        example=op.line.strip()[:200]))

    # -- the fp8 contract (delayed scaling + no-double-quantize) ---------
    fp8_policy = bool(getattr(policy, "fp8", False)) and enabled
    any_f8 = False
    for fn in funcs.values():
        f8_converts = [op for op in fn.ops
                       if op.name == "convert" and _is_f8(op.result_elem)
                       and op.result is not None]
        if not f8_converts:
            continue
        any_f8 = True
        stats["fp8_quantizes"] += len(f8_converts)
        amax_roots = _max_reduce_results(fn, def_map)
        # scale chains seeded by in-program amaxes (the same-step bug)
        amax_derived = _propagate(fn, amax_roots, through=_FP8_CHAIN)
        # value chains seeded by already-f8 values (double quantize)
        f8_vals = {op.result for op in f8_converts}
        f8_derived = _propagate(fn, f8_vals, through=_FP8_CHAIN)
        for op in f8_converts:
            src = fn.resolve(op.operands[0]) if op.operands else None
            if src in amax_derived:
                findings.append(Finding(
                    "precision", "error" if strict else "info",
                    f"f8 quantize at line {op.lineno} consumes a scale "
                    f"derived from a SAME-STEP amax (max-reduce in this "
                    f"program): the fp8 contract is DELAYED scaling — "
                    f"the scale must be a carried input "
                    f"(DelayedScalingState), derived from past steps' "
                    f"amax history",
                    op="fp8-same-step-scale", dtype=op.result_elem,
                    lineno=op.lineno, example=op.line.strip()[:200]))
            if src in f8_derived and src not in f8_vals:
                findings.append(Finding(
                    "precision", "error" if strict else "info",
                    f"f8 quantize at line {op.lineno} re-quantizes a "
                    f"value that was already f8 (dequantize→requantize "
                    f"round trip: two roundings, two scales composed "
                    f"where the format budgets mantissa for one)",
                    op="fp8-double-quantize", dtype=op.result_elem,
                    lineno=op.lineno, example=op.line.strip()[:200]))
    if fp8_policy and any_f8:
        # amax-history update reachability: under the fp8 policy, some
        # AMAX (max over |x| — abs_only, so softmax's stability max
        # can't satisfy the check) must flow into a program output
        # (the recorded history / re-derived scale of the carried
        # state)
        amax_roots = _max_reduce_results(main, def_map, abs_only=True)
        if amax_roots:
            touched = _propagate(main, amax_roots, through=None)
            returned_tokens = {main.resolve(t) for ret in main.returns
                               for t in ret.operands}
            recorded = bool(returned_tokens & touched)
        else:
            recorded = False
        if not recorded:
            findings.append(Finding(
                "precision", "error" if strict else "info",
                "this fp8 program never records an amax into the "
                "carried state: no max-reduce result reaches a program "
                "output, so the delayed scale would free-run on stale "
                "statistics (the amax-history roll must flow into the "
                "returned Fp8TrainState)",
                op="fp8-amax-unrecorded"))

    # -- master-weight / moment dtypes (argument table) ------------------
    if policy is not None and enabled and _use_master_weights(policy):
        for a in ctx.args:
            # matches both NamedTuple (".master_params") and plain-dict
            # ("['master_params']") state spellings
            leaf_kind = None
            if "master_params" in a.path:
                leaf_kind = "master weight"
            elif "opt_state" in a.path:
                leaf_kind = "optimizer moment"
            if leaf_kind is None:
                continue
            if a.dtype.startswith(("float", "bfloat")) \
                    and a.dtype != "float32":
                findings.append(Finding(
                    "precision", "error",
                    f"{leaf_kind} {a.path} is {a.dtype}; with master "
                    f"weights on ({opt_level}) it must be float32 — a "
                    f"16-bit master integrates updates below the "
                    f"representable step size",
                    op="master-weight-dtype", dtype=a.dtype,
                    bytes=a.nbytes))

    # -- loss-scale placement -------------------------------------------
    scale_tokens = set()
    kept = ctx.kept_args
    if kept and len(main.args) == len(kept):
        for k, a in enumerate(kept):
            if "loss_scale" in a.path and a.dtype == "float32":
                scale_tokens.add(main.args[k][0])
    elif any("loss_scale" in a.path for a in ctx.args):
        findings.append(Finding(
            "precision", "info",
            f"argument numbering ambiguous ({len(main.args)} lowered "
            f"args vs {len(kept)} kept) — loss-scale placement not "
            f"checked", op="loss-scale-unchecked"))
    stats["scale_args"] = len(scale_tokens)

    if scale_tokens:
        flow = _ScaleFlow(main, scale_tokens)
        flow.run()
        stats["scale_applied"] = flow.applied
        stats["unscaled"] = flow.unscaled
        if flow.applied == 0:
            findings.append(Finding(
                "precision", "warning",
                "a live loss-scale input never multiplies the loss or "
                "backward — gradients are unscaled (or skipped) "
                "without ever having been scaled",
                op="loss-scale-unused"))
        info = main.result_info
        for ret in main.returns:
            for i, tok in enumerate(ret.operands):
                if flow.cls(tok) == T:
                    path = info[i] if i < len(info) else f"output {i}"
                    seed = flow.first_taint.get(
                        main.resolve(tok))
                    findings.append(Finding(
                        "precision", "error",
                        f"output {path} is still loss-scaled: the "
                        f"value was multiplied by the scale and never "
                        f"divided back before leaving the program "
                        f"(unscale must dominate every update/output "
                        f"use of the gradients)",
                        op="unscaled-grad-use",
                        lineno=seed.lineno if seed else None))

    findings.append(Finding(
        "precision", "info",
        f"checked {stats['dots']} matmul/conv, {stats['reduces']} lossy "
        f"reduce(s), {stats['converts']} f32→16 convert(s), "
        f"{stats['collectives']} gradient collective(s), "
        f"{stats['fp8_quantizes']} f8 quantize(s); loss scale: "
        f"{stats['scale_args']} input(s), {stats['scale_applied']} "
        f"application(s), {stats['unscaled']} unscale(s)",
        op="precision-summary"))
    return findings, stats


def precision_pass(ctx: PassContext, **options) -> List[Finding]:
    """Registry entry: :func:`precision_report` without the stats."""
    findings, _stats = precision_report(ctx, **options)
    return findings


register_pass("precision", precision_pass)
