"""O1 policy lint — the TPU-native answer to the reference's
whole-namespace patch guarantee (the fifth graph-lint pass).

The reference's O1 patches the entire ``torch`` namespace
(``apex/amp/amp.py:68-177``), so *any* model is policy-covered by
construction.  apex_tpu's policy layer (:mod:`apex_tpu.amp.ops`) covers
code that routes through it — a user model calling raw ``jnp``/``lax``
silently escapes the cast lists.  This pass closes that gap the way a
traced/compiled framework can: walk the LOWERED program and flag
FP32-list-category work (transcendentals, norm statistics, raw
accumulation reductions — ``amp/lists.py`` ``FP32_OPS``) executing in a
16-bit dtype.

The walk runs on the pre-optimization StableHLO text
(``jax.jit(fn).lower(*args).as_text()``): that is the program the user
*asked for*, identical across backends — post-optimization HLO can
legally rewrite 16-bit math to fp32 internally (the CPU backend does),
which would hide violations on the platform tests run on.

Audit the FORWARD function (the loss/model apply), not the AD-generated
train step: the policy lists govern ops the user writes, and a backward
pass legitimately accumulates broadcast/bias gradients in the wire
dtype — auditing it would drown the report in expected reduce-adds.
For that reason ``policy`` is not in ``DEFAULT_PASSES`` — request it
explicitly on the forward (``tools/graph_lint.py`` does).

Deliberately NOT flagged, mirroring the reference lists:

- ``tanh`` / ``logistic`` / ``erf`` — half-safe activations (gelu,
  sigmoid); the reference leaves activations in autocast dtype.
- ``maximum``-reductions (softmax's max pass is exact in any dtype).
- 16-bit reduces that jnp already upcasts (``jnp.mean``/``sum`` and
  ``jax.nn.softmax`` accumulate in fp32 and convert back — the audit
  sees those as fp32 reduces and stays quiet).

Two informational (non-failing) counters round out the picture:
``fp32_matmul_count`` (dot/conv running in fp32 inside an O1 program =
missed half-cast opportunities — a perf smell, not a correctness bug)
and ``custom_call_count`` (Pallas kernels are opaque to the walk; the
in-tree kernels compute their statistics in fp32 by construction, see
``ops/pallas/flash_attention.py``).

:func:`audit_text` / :func:`audit` / :func:`format_report` keep the
original report-dict shape — ``apex_tpu.amp.audit`` re-exports them as
the compatibility surface existing callers pin.
"""

from __future__ import annotations

import re
from typing import Any, Callable, List

import jax

from apex_tpu.analysis.core import PassContext, register_pass
from apex_tpu.analysis.report import Finding

#: 16-bit element types a violation can execute in.
_HALF_DTYPES = ("bf16", "f16")

#: StableHLO opcode -> FP32_OPS-category label (amp/lists.py).  These are
#: the numerically-sensitive pointwise ops the reference keeps in fp32
#: (``torch_overrides.py:29-56``).
BLACKLIST_POINTWISE = {
    "exponential": "exp/softmax",
    "exponential_minus_one": "expm1",
    "log": "log/log_softmax",
    "log_plus_one": "log1p",
    "power": "pow",
    "sqrt": "norm-stats",
    "rsqrt": "norm-stats",
    "cosine": "trig",
    "sine": "trig",
    "tan": "trig",
    "acos": "trig",
    "asin": "trig",
    "atan": "trig",
    "cosh": "trig",
    "sinh": "trig",
}

#: reduce computations whose 16-bit accumulation loses precision
#: (sum/prod/mean family); max/min/and/or are exact in any dtype.
_LOSSY_REDUCE_FNS = ("stablehlo.add", "stablehlo.multiply")

_TENSOR_ELEM = re.compile(r"tensor<(?:[0-9?]+x)*([a-z0-9]+)>")
_OP_LINE = re.compile(r"=\s+(?:stablehlo|chlo)\.([a-z_0-9]+)")


def _elem_types(text: str):
    return _TENSOR_ELEM.findall(text)


def _result_elem_type(line: str):
    """Element type of the op's result: the LAST tensor<> token on the
    line (StableHLO prints ``: type`` or ``: (operands) -> result``)."""
    types = _elem_types(line)
    return types[-1] if types else None


def audit_text(stablehlo_text: str) -> dict:
    """Walk StableHLO text; return the policy-audit report dict."""
    violations: dict[tuple, dict] = {}
    fp32_matmuls = 0
    custom_calls = 0

    def flag_reduce(dtype, lineno, line):
        key = ("reduce", dtype)
        rec = violations.setdefault(key, {
            "op": "reduce", "dtype": dtype,
            "category": "16-bit accumulation",
            "count": 0, "first_line": lineno,
            "example": line.strip()[:200]})
        rec["count"] += 1

    # a generic-form reduce (multi-result / custom reducer) prints its
    # header WITHOUT an ``applies`` clause; the adds live in a
    # ``reducer(...) { ... stablehlo.return }`` region on the following
    # lines.  Track the open region's header so a lossy op inside it is
    # attributed to the reduce, not missed.
    open_reduce = None  # (operand dtype, header lineno, header line)

    for lineno, line in enumerate(stablehlo_text.splitlines(), 1):
        m = _OP_LINE.search(line)
        if not m:
            if open_reduce and "stablehlo.return" in line:
                open_reduce = None
            continue
        op = m.group(1)
        if open_reduce is not None:
            if op in ("add", "multiply"):
                flag_reduce(open_reduce[0], open_reduce[1], open_reduce[2])
                open_reduce = None
                continue
            if op == "return":
                open_reduce = None
                continue
        if op in BLACKLIST_POINTWISE:
            dtype = _result_elem_type(line)
            if dtype in _HALF_DTYPES:
                key = (op, dtype)
                rec = violations.setdefault(key, {
                    "op": op, "dtype": dtype,
                    "category": BLACKLIST_POINTWISE[op],
                    "count": 0, "first_line": lineno,
                    "example": line.strip()[:200]})
                rec["count"] += 1
        elif op == "reduce":
            # operand dtype = FIRST tensor token (the reduce input);
            # jnp's own upcasts make this f32, raw lax.reduce won't
            types = _elem_types(line)
            half_in = bool(types) and types[0] in _HALF_DTYPES
            if any(fn in line for fn in _LOSSY_REDUCE_FNS):
                if half_in:
                    flag_reduce(types[0], lineno, line)
            elif "applies" not in line and half_in:
                open_reduce = (types[0], lineno, line)
        elif op in ("dot_general", "dot", "convolution"):
            if _result_elem_type(line) == "f32":
                fp32_matmuls += 1
        elif op == "custom_call":
            custom_calls += 1
    out = sorted(violations.values(),
                 key=lambda r: (-r["count"], r["op"]))
    return {"ok": not out, "violations": out,
            "fp32_matmul_count": fp32_matmuls,
            "custom_call_count": custom_calls}


def audit(fn: Callable[..., Any], *args, **kwargs) -> dict:
    """Lower ``fn`` on ``args``/``kwargs`` and policy-audit the result.

    ``fn`` should be the O1 forward (model apply / loss function) — see
    the module docstring for why not the full train step.  Accepts an
    already-jitted function too (``jax.jit`` of a jitted fn is free)."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    return audit_text(lowered.as_text())


def format_report(report: dict) -> str:
    """Human-readable rendering of :func:`audit`'s dict."""
    lines = []
    if report["ok"]:
        lines.append("policy audit: OK — no FP32-list op executes in "
                     "16-bit")
    else:
        lines.append("policy audit: FAIL — FP32-list work executing in "
                     "16-bit:")
        for v in report["violations"]:
            lines.append(
                f"  {v['op']} [{v['category']}] in {v['dtype']} "
                f"x{v['count']} (first at line {v['first_line']}): "
                f"{v['example']}")
    lines.append(f"  info: {report['fp32_matmul_count']} fp32 "
                 "matmul/conv ops (missed half casts if this is O1), "
                 f"{report['custom_call_count']} opaque custom calls "
                 "(in-tree Pallas kernels keep stats in fp32)")
    return "\n".join(lines)


def policy_pass(ctx: PassContext) -> List[Finding]:
    """The legacy audit as a lint pass: each violation class becomes an
    ``error`` finding; the matmul/custom-call counters become ``info``."""
    rep = audit_text(ctx.stablehlo_text)
    findings = [
        Finding("policy", "error",
                f"FP32-list op {v['op']} [{v['category']}] executes in "
                f"{v['dtype']}",
                op=v["op"], dtype=v["dtype"], count=v["count"],
                lineno=v["first_line"], example=v["example"])
        for v in rep["violations"]]
    findings.append(Finding(
        "policy", "info",
        f"{rep['fp32_matmul_count']} fp32 matmul/conv op(s) (missed "
        f"half casts if this is O1), {rep['custom_call_count']} opaque "
        f"custom call(s)"))
    return findings


register_pass("policy", policy_pass)
