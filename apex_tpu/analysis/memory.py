"""Compiled-HLO memory lint: static per-device HBM budgets.

Mixed precision and fused optimizers are memory-bandwidth *and*
memory-capacity plays: the point of donating the optimizer state is
halving its HBM footprint, and the point of FSDP/pipeline sharding is
fitting a step on a 16 GiB v5e at all.  Whether either actually
happened is statically visible in the compiled executable —
``Compiled.memory_analysis()`` is XLA's own buffer-assignment summary
(argument + output + temp + aliased bytes, per device), and the
``input_output_alias`` header says which donations the compiler
honored.  This pass turns both into gateable findings so a lane fails
lint on the host *before* it OOMs on chip.

Finding codes (``op`` field):

=====================  ==================================================
``peak-hbm``           info: the per-device peak (argument + output +
                       temp − aliased) with the full breakdown
``hbm-budget``         error: peak exceeds ``budget_bytes`` (v5e 16 GiB
                       default when a budget is requested)
``donation-dropped``   error: a donated input the executable did NOT
                       alias — the buffer is live twice (the request
                       was checked by the ``donation`` pass; this is
                       the *compiled outcome*)
``donation-alias``     info: the per-argument donation-aliasing table
``large-buffer``       info: the largest argument/output buffers, the
                       attribution for an over-budget peak
=====================  ==================================================

The numbers come from the executable, not the HLO text: sharded
programs report PER-DEVICE bytes (an FSDP-sharded 1 GiB parameter tree
on 8 devices shows ~128 MiB/device), which is exactly the quantity a
device budget constrains.
"""

from __future__ import annotations

from typing import Any, List, Optional

from apex_tpu.analysis.core import PassContext, register_pass
from apex_tpu.analysis.donation import aliased_parameter_set, kept_index_map
from apex_tpu.analysis.report import Finding

#: v5e per-chip HBM — the default ``budget_bytes`` when a budget is
#: requested without a number (``tools/graph_lint.py --memory-budget``).
V5E_HBM_BYTES = 16 * (1 << 30)


def memory_stats(compiled) -> "Optional[dict]":
    """XLA's per-device memory summary of a compiled executable as a
    plain dict, or ``None`` when the backend doesn't implement it.

    ``peak_hbm_bytes`` is the static high-water estimate: arguments,
    outputs and temps are all live across the step, minus the aliased
    (donated-and-honored) bytes counted once instead of twice."""
    try:
        st = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - backend-optional API
        return None
    if st is None:
        return None
    try:
        out = {
            "argument_bytes": int(st.argument_size_in_bytes),
            "output_bytes": int(st.output_size_in_bytes),
            "temp_bytes": int(st.temp_size_in_bytes),
            "alias_bytes": int(st.alias_size_in_bytes),
            "generated_code_bytes": int(st.generated_code_size_in_bytes),
        }
    except AttributeError:
        return None
    out["peak_hbm_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                             + out["temp_bytes"] - out["alias_bytes"])
    return out


def context_memory_stats(ctx: PassContext) -> "Optional[dict]":
    """:func:`memory_stats` of the context's executable, memoized —
    the memory pass and graph_lint's lane record share one XLA
    memory-analysis run per lowering."""
    return ctx.memo("memory_stats",
                    lambda: memory_stats(ctx.compiled))


def donation_table(ctx: PassContext) -> "Optional[List[dict]]":
    """Per-donated-argument aliasing outcome from the compiled
    executable: ``[{arg, dtype, bytes, aliased}]`` (empty when nothing
    was donated or the program wasn't compiled, ``None`` when the
    kept-argument numbering is ambiguous on this jax version — see
    :func:`~apex_tpu.analysis.donation.kept_index_map`; guessing would
    report honored donations as dropped).  ``bytes`` is the GLOBAL
    logical buffer size from the traced signature.  Memoized on the
    context — the memory pass and graph_lint's lane record both read
    it from one lowering."""
    def compute():
        if ctx.hlo_text is None:
            return []
        donated = [a for a in ctx.kept_args if a.donated]
        if not donated:
            return []
        kept_pos = kept_index_map(ctx)
        if kept_pos is None:
            return None
        aliased = aliased_parameter_set(ctx)
        return [{"arg": a.path or f"arg{a.index}", "dtype": a.dtype,
                 "bytes": a.nbytes,
                 "aliased": kept_pos[a.index] in aliased}
                for a in donated]
    return ctx.memo("donation_table", compute)


def _fmt_bytes(n: int) -> str:
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"


def memory_pass(ctx: PassContext,
                budget_bytes: Optional[int] = None,
                top_k: int = 5) -> List[Finding]:
    """Peak-HBM, budget, and donation-outcome lint over the compiled
    executable (see module docstring for the finding codes).

    ``budget_bytes`` arms the device-budget gate — pass the target
    chip's HBM (:data:`V5E_HBM_BYTES` is the v5e default an FSDP or
    pipeline lane should assert).  Without it the pass only measures.
    """
    if ctx.compiled is None:
        # same lint-nothing escalation as the stats-None branch
        # below: an ARMED budget that cannot run is a warning
        return [Finding(
            "memory", "warning" if budget_bytes is not None else "info",
            "skipped: program was not compiled "
            "(analyze(..., compile=True) to measure peak HBM)"
            + (" — the requested budget gate asserted NOTHING"
               if budget_bytes is not None else ""))]
    findings: List[Finding] = []

    stats = context_memory_stats(ctx)
    if stats is None:
        # with a budget ARMED this is a warning, not an info: the
        # caller asked for an assertion that never executed (same
        # lint-nothing-must-not-pass class as a typo'd lane list)
        findings.append(Finding(
            "memory", "warning" if budget_bytes is not None else "info",
            "this backend exposes no memory_analysis(); peak-HBM "
            "budget not checkable here"
            + (" — the requested budget gate asserted NOTHING"
               if budget_bytes is not None else "")))
    else:
        peak = stats["peak_hbm_bytes"]
        findings.append(Finding(
            "memory", "info",
            f"per-device peak HBM {_fmt_bytes(peak)} (arguments "
            f"{_fmt_bytes(stats['argument_bytes'])} + outputs "
            f"{_fmt_bytes(stats['output_bytes'])} + temps "
            f"{_fmt_bytes(stats['temp_bytes'])} − aliased "
            f"{_fmt_bytes(stats['alias_bytes'])})",
            op="peak-hbm", bytes=peak))
        if budget_bytes is not None and peak > budget_bytes:
            findings.append(Finding(
                "memory", "error",
                f"per-device peak HBM {_fmt_bytes(peak)} exceeds the "
                f"device budget {_fmt_bytes(budget_bytes)} — this lane "
                f"OOMs on chip; shard or donate more state (temps "
                f"{_fmt_bytes(stats['temp_bytes'])}, un-aliased "
                f"arguments "
                f"{_fmt_bytes(stats['argument_bytes'] - stats['alias_bytes'])})",
                op="hbm-budget", bytes=peak))

    table = donation_table(ctx)
    if table is None:
        findings.append(Finding(
            "memory", "info",
            "donation outcomes unverifiable: kept-argument numbering "
            "is ambiguous on this jax version (see the donation "
            "pass)", op="donation-alias"))
    elif table:
        dropped = [t for t in table if not t["aliased"]]
        findings.append(Finding(
            "memory", "info",
            f"donation-aliasing table: {len(table) - len(dropped)}/"
            f"{len(table)} donated input(s) aliased by the compiler",
            op="donation-alias", count=len(table)))
        for t in dropped:
            findings.append(Finding(
                "memory", "error",
                f"donated input {t['arg']} was NOT aliased by the "
                f"compiled executable — {_fmt_bytes(t['bytes'])} of "
                f"state is live twice per step",
                op="donation-dropped", dtype=t["dtype"],
                bytes=t["bytes"]))

    # attribution: the largest live argument/output buffers (global
    # logical sizes — the names a user can act on)
    named = ([("argument", a.path or f"arg{a.index}", a.dtype, a.nbytes)
              for a in ctx.kept_args]
             + [("output", o.path or f"out{o.index}", o.dtype, o.nbytes)
                for o in ctx.outputs])
    named.sort(key=lambda t: -t[3])
    for role, path, dtype, nbytes in named[:top_k]:
        if nbytes <= 0:
            continue
        findings.append(Finding(
            "memory", "info",
            f"largest live buffers: {role} {path} holds "
            f"{_fmt_bytes(nbytes)}",
            op="large-buffer", dtype=dtype, bytes=nbytes))
    return findings


def per_device_stats(compiled) -> "Optional[dict]":
    """Convenience for artifact writers (``__graft_entry__`` slice
    records, ``tools/graph_lint.py --emit-json``): the
    :func:`memory_stats` dict of a compiled executable, or ``None``
    when the backend doesn't report memory."""
    return memory_stats(compiled)


register_pass("memory", memory_pass)
