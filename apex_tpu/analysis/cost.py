"""XLA cost-model lint: static flops / HBM traffic and the roofline
expectation they imply.

``Compiled.cost_analysis()`` is XLA's own per-executable estimate of
floating-point work and bytes accessed — the *static* half of a
roofline: from ``flops`` and ``bytes`` alone the arithmetic intensity
and the best-case utilization of a given chip follow, before anything
runs.  This pass records those numbers per lane, and the artifact
audit (:func:`audit_floor_artifacts`) cross-checks the committed
bench-gate floors against the same physics: a published floor that
sits ABOVE the cost-model ceiling, or a measured number above it, is a
lint error — the gate was calibrated against an impossible bar, and
every future round would either trip it or (worse) trust it.

Finding codes (``op`` field):

=====================  ==================================================
``flops``              info: cost-model flops of the executable
``hbm-bytes``          info: cost-model bytes accessed
``roofline``           info: intensity + static ceiling utilization
                       (needs ``peak_flops`` / ``peak_hbm_bytes_per_s``)
``floor-above-ceiling``  error: a committed floor exceeds the physical
                       ceiling (roofline fraction / MFU > 1)
``measured-above-ceiling``  error: a committed measurement exceeds the
                       ceiling (bandwidth above HBM peak, MFU above 1,
                       HFU below MFU)
=====================  ==================================================
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional

from apex_tpu.analysis.core import PassContext, register_pass
from apex_tpu.analysis.report import Finding

#: measured numbers get this much slack over a hard ceiling before the
#: audit calls them impossible: timer jitter and bytes-model rounding
#: are real, sustained >5% over physics is not.
MEASURE_TOLERANCE = 0.05


def cost_table(compiled) -> Optional[Dict[str, float]]:
    """``{"flops", "hbm_bytes"}`` from XLA's cost model, or ``None``
    when the backend doesn't report one.  ``cost_analysis()`` returns a
    dict on some backends and a one-element list of dicts on others;
    both shapes are absorbed here."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - backend-optional API
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    nbytes = ca.get("bytes accessed")
    if flops is None and nbytes is None:
        return None
    return {"flops": float(flops or 0.0),
            "hbm_bytes": float(nbytes or 0.0)}


def context_cost_table(ctx: PassContext) -> Optional[Dict[str, float]]:
    """:func:`cost_table` of the context's executable, memoized — the
    cost pass and graph_lint's lane record share one HloCostAnalysis
    run per lowering."""
    return ctx.memo("cost_table", lambda: cost_table(ctx.compiled))


def roofline_expectation(flops: float, hbm_bytes: float,
                         peak_flops: float,
                         peak_hbm_bytes_per_s: float) -> dict:
    """The static roofline of a program on a chip: arithmetic
    intensity, the binding resource, and the ceiling utilization — the
    highest MFU any measurement of this program can honestly reach.
    A committed MFU floor for the lane must sit at or under
    ``ceiling_util``."""
    intensity = flops / hbm_bytes if hbm_bytes else float("inf")
    bw_bound_flops_per_s = intensity * peak_hbm_bytes_per_s
    ceiling = min(peak_flops, bw_bound_flops_per_s)
    return {
        "intensity_flops_per_byte": intensity,
        "bound": "compute" if bw_bound_flops_per_s >= peak_flops
                 else "bandwidth",
        "ceiling_flops_per_s": ceiling,
        "ceiling_util": ceiling / peak_flops if peak_flops else 0.0,
    }


def cost_pass(ctx: PassContext,
              peak_flops: Optional[float] = None,
              peak_hbm_bytes_per_s: Optional[float] = None,
              ) -> List[Finding]:
    """Record the executable's cost-model flops/bytes; with chip peaks
    supplied, derive the static roofline expectation (see module
    docstring)."""
    if ctx.compiled is None:
        return [Finding("cost", "info",
                        "skipped: program was not compiled "
                        "(analyze(..., compile=True) to read the "
                        "cost model)")]
    table = context_cost_table(ctx)
    if table is None:
        return [Finding("cost", "info",
                        "this backend exposes no cost_analysis(); "
                        "static roofline not derivable here")]
    findings = [
        Finding("cost", "info",
                f"cost model: {table['flops']:.4g} flops per step",
                op="flops", count=1, bytes=None),
        Finding("cost", "info",
                f"cost model: {table['hbm_bytes']:.4g} bytes accessed "
                f"per step", op="hbm-bytes",
                bytes=int(table["hbm_bytes"])),
    ]
    if peak_flops and peak_hbm_bytes_per_s:
        exp = roofline_expectation(table["flops"], table["hbm_bytes"],
                                   peak_flops, peak_hbm_bytes_per_s)
        findings.append(Finding(
            "cost", "info",
            f"static roofline: intensity "
            f"{exp['intensity_flops_per_byte']:.2f} flop/byte, "
            f"{exp['bound']}-bound, ceiling utilization "
            f"{exp['ceiling_util']:.3f} — any committed MFU floor for "
            f"this lane must sit under that",
            op="roofline"))
    return findings


# ---------------------------------------------------------------------------
# committed-artifact calibration audit


def _rounds_desc(search_dir: str, pattern: str) -> List[str]:
    rounds = []
    for path in glob.glob(os.path.join(search_dir, pattern)):
        m = re.search(r"_r(\d+)\.json$", path)
        if m:
            rounds.append((int(m.group(1)), path))
    return [p for _, p in sorted(rounds, reverse=True)]


def _load(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def audit_kernel_artifact(doc: dict, name: str,
                          floors: Optional[Dict[str, float]] = None,
                          ) -> List[Finding]:
    """Physics audit of one KERNELBENCH document: measured bandwidth
    must sit under the recorded HBM peak, roofline fractions under 1,
    and any published per-kernel floor under the ceiling too."""
    findings: List[Finding] = []
    peak_gbps = doc.get("hbm_gbps_peak")
    for kname, rec in (doc.get("kernels") or {}).items():
        if not isinstance(rec, dict):
            continue
        gbps, frac = rec.get("gbps"), rec.get("roofline_frac")
        if peak_gbps and gbps and gbps > peak_gbps * (1 + MEASURE_TOLERANCE):
            findings.append(Finding(
                "cost", "error",
                f"{name}: kernel {kname} records {gbps} GB/s, above "
                f"the {peak_gbps} GB/s HBM peak — the bytes model or "
                f"the peak table is miscalibrated",
                op="measured-above-ceiling"))
        if frac and frac > 1 + MEASURE_TOLERANCE:
            findings.append(Finding(
                "cost", "error",
                f"{name}: kernel {kname} records roofline fraction "
                f"{frac} > 1 — impossible; the gate memory is "
                f"miscalibrated", op="measured-above-ceiling"))
    for kname, floor in (floors or {}).items():
        if floor > 1.0:
            findings.append(Finding(
                "cost", "error",
                f"published roofline-fraction floor {floor} for kernel "
                f"{kname} exceeds the cost-model ceiling (1.0) — no "
                f"measurement can ever pass it",
                op="floor-above-ceiling"))
    return findings


def audit_bench_artifact(doc: dict, name: str,
                         mfu_floors: Optional[Dict[str, float]] = None,
                         ) -> List[Finding]:
    """Physics audit of one BENCH document: measured MFU ≤ 1, HFU ≥
    MFU (hardware flops include rematerialization, never less than
    model flops), and published MFU floors under the ceiling."""
    findings: List[Finding] = []
    configs = (doc.get("configs")
               or (doc.get("parsed") or {}).get("configs") or {})
    for cname, rec in configs.items():
        if not isinstance(rec, dict):
            continue
        mfu, hfu = rec.get("mfu"), rec.get("hfu")
        if mfu and mfu > 1 + MEASURE_TOLERANCE:
            findings.append(Finding(
                "cost", "error",
                f"{name}: config {cname} records MFU {mfu} > 1 — "
                f"impossible; flops model miscalibrated",
                op="measured-above-ceiling"))
        # hfu is not None, not truthiness: a recorded hfu of exactly
        # 0.0 (broken hardware-flops counter) is the very case this
        # audit exists for
        if mfu and hfu is not None and hfu < mfu * (1 - MEASURE_TOLERANCE):
            findings.append(Finding(
                "cost", "error",
                f"{name}: config {cname} records HFU {hfu} below MFU "
                f"{mfu} — hardware flops can never undercut model "
                f"flops; one of the two counters is wrong",
                op="measured-above-ceiling"))
    for cname, floor in (mfu_floors or {}).items():
        if floor > 1.0:
            findings.append(Finding(
                "cost", "error",
                f"published MFU floor {floor} for config {cname} "
                f"exceeds the ceiling (1.0)",
                op="floor-above-ceiling"))
    return findings


def audit_floor_artifacts(search_dir: str,
                          kernel_floors: Optional[Dict[str, float]] = None,
                          mfu_floors: Optional[Dict[str, float]] = None,
                          ) -> List[Finding]:
    """Cross-check the newest committed ``KERNELBENCH_r*.json`` and
    ``BENCH_r*.json`` against the cost-model ceilings (see the module
    docstring).  Measurements in the artifacts are always audited;
    the published FLOOR tables are audited only when passed in —
    this module deliberately never imports ``bench``/``tools``, so
    callers supply their own tables (``bench.check_floor_calibration``
    and ``tools/graph_lint.py`` both do)."""
    findings: List[Finding] = []
    # the floor tables are artifact-INDEPENDENT physics: a published
    # floor above the ceiling must fail even when no artifact file
    # loads (a corrupt newest round must never launder an impossible
    # floor through a clean verdict)
    findings += audit_kernel_artifact({}, "published floors",
                                      floors=kernel_floors)
    findings += audit_bench_artifact({}, "published floors",
                                     mfu_floors=mfu_floors)
    kpath = next(iter(_rounds_desc(search_dir, "KERNELBENCH_r*.json")),
                 None)
    if kpath:
        doc = _load(kpath)
        if doc is not None:
            findings += audit_kernel_artifact(doc,
                                              os.path.basename(kpath))
        else:
            findings.append(Finding(
                "cost", "warning",
                f"{os.path.basename(kpath)} is unreadable — kernel "
                f"measurements NOT audited this round",
                op="roofline"))
    # newest BENCH round whose measured configs survived the artifact
    # wrapper (older rounds keep the parsed block; a truncated tail
    # records nothing auditable)
    bpath = None
    bench_rounds = _rounds_desc(search_dir, "BENCH_r*.json")
    for cand in bench_rounds:
        doc = _load(cand)
        if doc is None:
            continue
        if (doc.get("configs")
                or (doc.get("parsed") or {}).get("configs")):
            findings += audit_bench_artifact(doc,
                                             os.path.basename(cand))
            bpath = cand
            break
    if bench_rounds and bpath is None:
        findings.append(Finding(
            "cost", "warning",
            f"no readable BENCH_r*.json with measured configs (newest "
            f"{os.path.basename(bench_rounds[0])}) — MFU measurements "
            f"NOT audited this round", op="roofline"))
    if not findings:
        findings.append(Finding(
            "cost", "info",
            f"gate calibration audit: committed floors and "
            f"measurements sit under the cost-model ceilings "
            f"({os.path.basename(kpath) if kpath else 'no KERNELBENCH'}"
            f", {os.path.basename(bpath) if bpath else 'no BENCH'})",
            op="roofline"))
    return findings


register_pass("cost", cost_pass)
