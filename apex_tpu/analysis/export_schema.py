"""EXPORT_r*.json — schema for the committed AOT-export artifact.

``tools/aot_export.py`` writes one of these per round: the export
pipeline's acceptance evidence — per-lane cache keys, the lint
verdicts that gated each executable into (or out of) the
content-addressed cache, load-vs-compile wall clock, and the
round-trip bitwise check.  Like MEMLINT/PRECLINT/OBS records the
artifact is gate memory: ``tools/gate_hygiene.py`` validates every
committed ``EXPORT_r*.json`` against this schema, and the schema
ENFORCES the export invariants — an exported lane must carry a clean
gating lint verdict and a passing bitwise round trip (a contradictory
verdict is schema-invalid, not just wrong), a refused lane must name
the documented finding id that refused it, and the serve cold-start
block's ``ok`` must agree with its own numbers against the
``load_ratio <= COLD_START_RATIO_MAX`` gate ``bench.py`` reads from
this artifact (bench and the artifact can never disagree: bench
SOURCES the number here).

This module is deliberately **stdlib-only** (no jax import):
``gate_hygiene`` loads it directly by file path the same way it loads
``analysis/memlint.py``.

Document shape::

    {
      "round": 1,
      "platform": "cpu",
      "versions": {"jax": "0.4.37", ...},
      "cache": {"dir": ".aot_cache", "entries": 3},
      "lanes": {
        "mlp_o1_train": {
          "export_ok": true,
          "cache_key": "<64 hex>", "module_sha256": "<64 hex>",
          "lint": {"ok": true, "passes": [...], "counts": {...}},
          "compile_s": 0.31, "load_s": 0.01, "load_ratio": 0.04,
          "bitwise_equal": true},
        "seeded_io_callback": {
          "export_ok": false,
          "refused": "export-host-callback",
          "lint": {"ok": false, ...}},
        ...
      },
      "cold_start": {"lane": "serve_step", "compile_s": ..., "load_s": ...,
                     "load_ratio": ..., "budget": 0.5, "ok": true}
    }
"""

from __future__ import annotations

import json
import re
from typing import List

#: the absolute cold-start gate: loading the serve lane from the cache
#: must cost at most this fraction of compiling it on the same host —
#: otherwise the cache is decoration, not a cold-start fix.
COLD_START_RATIO_MAX = 0.5

_HEX64 = re.compile(r"^[0-9a-f]{64}$")


def _check_lint(lane: str, lint, problems: List[str]) -> "bool | None":
    """Validate a lane's embedded lint block; returns its ok flag."""
    if not isinstance(lint, dict) or not isinstance(lint.get("ok"), bool):
        problems.append(f"lane {lane!r}: missing/invalid 'lint' block "
                        f"with boolean 'ok'")
        return None
    if not isinstance(lint.get("counts"), dict):
        problems.append(f"lane {lane!r}: lint block missing 'counts'")
    return lint["ok"]


def validate_export(doc) -> List[str]:
    """Problems with one parsed EXPORT document (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("round"), int):
        problems.append("missing/invalid 'round' (int)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    versions = doc.get("versions")
    if not isinstance(versions, dict) or \
            not isinstance(versions.get("jax"), str):
        problems.append("missing/invalid 'versions' (object with a "
                        "'jax' version string)")

    lanes = doc.get("lanes")
    if not isinstance(lanes, dict) or not lanes:
        problems.append("missing/empty 'lanes' object")
        lanes = {}
    for lane, rec in lanes.items():
        if not isinstance(rec, dict):
            problems.append(f"lane {lane!r}: not an object")
            continue
        export_ok = rec.get("export_ok")
        if not isinstance(export_ok, bool):
            problems.append(f"lane {lane!r}: missing boolean "
                            f"'export_ok'")
            continue
        lint_ok = _check_lint(lane, rec.get("lint"), problems)
        if export_ok:
            for k in ("cache_key", "module_sha256"):
                if not (isinstance(rec.get(k), str)
                        and _HEX64.match(rec[k])):
                    problems.append(f"lane {lane!r}: missing/invalid "
                                    f"{k!r} (64-char sha256 hex)")
            if lint_ok is False:
                problems.append(
                    f"lane {lane!r}: contradictory verdict — "
                    f"export_ok with a FAILING gating lint report (an "
                    f"executable can only enter the cache clean)")
            if not (isinstance(rec.get("compile_s"), (int, float))
                    and rec["compile_s"] > 0):
                problems.append(f"lane {lane!r}: missing positive "
                                f"'compile_s'")
            if not (isinstance(rec.get("load_s"), (int, float))
                    and rec["load_s"] >= 0):
                problems.append(f"lane {lane!r}: missing "
                                f"non-negative 'load_s'")
            if rec.get("bitwise_equal") is not True:
                problems.append(
                    f"lane {lane!r}: contradictory verdict — "
                    f"export_ok without a passing bitwise round trip "
                    f"(reloaded outputs must equal the fresh "
                    f"compile's, bit for bit)")
        else:
            if not (isinstance(rec.get("refused"), str)
                    and rec["refused"]):
                problems.append(
                    f"lane {lane!r}: refused lane must name the "
                    f"documented finding id in 'refused'")
            if lint_ok is True and rec.get("refused") not in (
                    "export-compat-not-run",):
                problems.append(
                    f"lane {lane!r}: contradictory verdict — refused "
                    f"with a CLEAN gating lint report")

    cs = doc.get("cold_start")
    if not isinstance(cs, dict):
        problems.append("missing/invalid 'cold_start' object (the "
                        "serve-lane compile-vs-load numbers bench.py "
                        "sources)")
    else:
        lane = cs.get("lane")
        if not isinstance(lane, str) or not lane:
            problems.append("cold_start: missing 'lane'")
        elif lane not in lanes:
            problems.append(f"cold_start: lane {lane!r} not among the "
                            f"document's lanes")
        for k in ("compile_s", "load_s", "load_ratio", "budget"):
            if not isinstance(cs.get(k), (int, float)):
                problems.append(f"cold_start: missing numeric {k!r}")
        if not isinstance(cs.get("ok"), bool):
            problems.append("cold_start: missing boolean 'ok'")
        elif all(isinstance(cs.get(k), (int, float))
                 for k in ("load_ratio", "budget")):
            implied = cs["load_ratio"] <= cs["budget"]
            if cs["ok"] is not implied:
                problems.append(
                    "cold_start: contradictory verdict — 'ok' "
                    "disagrees with load_ratio vs budget")
    return problems


def validate_export_file(path: str) -> List[str]:
    """Schema problems of one EXPORT_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable export JSON: {e}"]
    return validate_export(doc)
