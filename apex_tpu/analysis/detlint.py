"""DETLINT_r*.json — schema for the committed determinism-lint sweep.

``tools/det_lint.py --out DETLINT_rN.json`` writes one of these per
round: every gated program lane (the solo/batched/kv8 decode steps,
the serve decode/prefill/verify steps) lowered and run through the
four per-lane :mod:`apex_tpu.analysis.determinism` rules, plus the
cross-lane reduction-shape comparator pairs with their recorded
signature streams and verdicts.  Like MEMLINT/PRECLINT/FLEETLINT/
KERNLINT, the artifact is gate memory: ``tools/gate_hygiene.py``
validates every committed ``DETLINT_r*.json`` against this schema so
"every gated program is bitwise-deterministic, and b1/b8 accumulate
identically" can't rot into prose nobody machine-checks.

This module is deliberately **stdlib-only** (no jax import):
``gate_hygiene`` loads it directly by file path the same way it loads
``analysis/kernlint.py``.

Document shape::

    {
      "round": 1,
      "platform": "cpu",
      "rules": ["det-tie-argmax", ...],      # the full rule list
      "lanes": {
        "<lane>": {                # e.g. "decode_b1", "serve_step"
          "ok": true,              # MUST re-derive from the counts below
          "findings": {            # per-rule ERROR counts
            "det-tie-argmax": 0, ...      # keys: the per-lane rules
          },
          "checked": {             # evidence the pass looked at anything
            "epilogue_sites": 1, "scatter_sites": 3,
            "rng_calls": 3, "barriers": 1
          },
          "waivers": {             # optional: rule -> documented reason;
            "<rule>": "why"        #   a waived rule needs findings > 0
          },
          "error": "..."           # optional: lane failed to lower;
        }, ...                     #   forces ok=false
      },
      "pairs": {                   # the det-lane-shape-variant verdicts
        "decode_b1|decode_b8": {
          "lanes": ["decode_b1", "decode_b8"],
          "signatures": {          # full ordered signature streams
            "decode_b1": [["dot", [16], ["bf16","bf16","f32"]], ...],
            "decode_b8": [...]
          },
          "verdict": "cleared",    # MUST re-derive from the signatures
          "positional": true,      # streams identical in program order
          "variants": [],          # MUST equal the multiset diff
          "expected": false,       # variant only: documented tolerance?
          "reason": "..."          # required when expected=true
        }, ...
      },
      "gate": {"ok": true, "lanes_clean": 7, "lanes_total": 7,
               "pairs_ok": 3, "pairs_total": 3}       # re-derived
    }

The contradiction rules: a lane's ``ok`` must equal "zero unwaived
finding counts and no error"; a ``checked`` block that counted nothing
anywhere needs an ``error`` explaining it (a lane that linted nothing
is not clean, it is unexamined); a pair's ``verdict``/``variants``/
``positional`` must re-derive from the recorded signature streams — a
"cleared" verdict sitting on divergent signatures is invalid, as is a
recorded variant list that disagrees with the recomputed multiset
diff; a "variant" verdict needs an explicit ``expected`` bool, and
``expected: true`` needs a non-empty ``reason`` (the documented
tolerance class, e.g. the kv8 dequant path); ``gate.*`` must re-derive
from the lane and pair verdicts, where a pair is ok when cleared or an
expected (reasoned) variant.
"""

from __future__ import annotations

import json
from typing import Dict, List, Tuple

#: the determinism rule ids (mirrored here so the validator stays
#: stdlib-only; ``tests/l0/test_determinism.py`` pins the two lists
#: equal so they cannot drift).  The first four are per-lane; the last
#: is the cross-lane comparator's and never appears in lane findings.
RULES = ("det-tie-argmax", "det-multi-materialize", "det-scatter-order",
         "det-prng-reuse", "det-lane-shape-variant")

#: the rules a single lane's findings block may record
LANE_RULES = RULES[:4]

#: the comparator's rule id (pair-scoped, not lane-scoped)
PAIR_RULE = RULES[4]


def _canon_sig(entry) -> Tuple:
    return (entry[0], tuple(entry[1]), tuple(entry[2]))


def _sig_ok(entry) -> bool:
    return (isinstance(entry, list) and len(entry) == 3
            and isinstance(entry[0], str)
            and isinstance(entry[1], list)
            and all(isinstance(d, int) for d in entry[1])
            and isinstance(entry[2], list)
            and all(isinstance(e, str) for e in entry[2]))


def _diff_signatures(a: list, b: list, name_a: str,
                     name_b: str) -> List[dict]:
    """The multiset difference, in the wire shape ``variants`` uses —
    the same arithmetic :func:`apex_tpu.analysis.determinism.
    compare_signatures` performs, reimplemented here so the validator
    needs no jax."""
    counts: Dict[Tuple, int] = {}
    for e in a:
        counts[_canon_sig(e)] = counts.get(_canon_sig(e), 0) + 1
    for e in b:
        counts[_canon_sig(e)] = counts.get(_canon_sig(e), 0) - 1
    out = []
    for sig in sorted(k for k, v in counts.items() if v != 0):
        n = counts[sig]
        out.append({"only_in": name_a if n > 0 else name_b,
                    "kind": sig[0], "dims": list(sig[1]),
                    "elems": list(sig[2]), "count": abs(n)})
    return out


def _validate_lane(name: str, rec: dict, rules: tuple,
                   problems: List[str]) -> None:
    if not isinstance(rec.get("ok"), bool):
        problems.append(f"lane {name!r} missing/invalid 'ok' (bool)")
        return
    findings = rec.get("findings")
    if not isinstance(findings, dict):
        problems.append(f"lane {name!r} missing 'findings' object")
        return
    for rule, count in findings.items():
        if rule not in rules or rule == PAIR_RULE:
            problems.append(f"lane {name!r} records rule {rule!r} "
                            f"(lane findings take the per-lane rules, "
                            f"not {PAIR_RULE!r} or unknowns)")
        if not (isinstance(count, int) and count >= 0):
            problems.append(f"lane {name!r} finding count for {rule!r} "
                            f"is not an int >= 0: {count!r}")
            return
    checked = rec.get("checked")
    if not (isinstance(checked, dict) and checked and all(
            isinstance(k, str) and isinstance(v, int) and v >= 0
            for k, v in checked.items())):
        problems.append(f"lane {name!r} missing/invalid 'checked' "
                        f"(object of site-class -> int >= 0)")
        return
    error = rec.get("error")
    if error is not None and not (isinstance(error, str)
                                  and error.strip()):
        problems.append(f"lane {name!r} has invalid 'error' "
                        f"(non-empty str)")
    waivers = rec.get("waivers", {})
    if not isinstance(waivers, dict):
        problems.append(f"lane {name!r} has invalid 'waivers' "
                        f"(object of rule -> reason)")
        return
    for rule, reason in waivers.items():
        if rule not in rules:
            problems.append(f"lane {name!r} waives unknown rule "
                            f"{rule!r}")
        if not (isinstance(reason, str) and reason.strip()):
            problems.append(f"lane {name!r} waiver for {rule!r} needs "
                            f"a non-empty reason")
        if findings.get(rule, 0) == 0:
            problems.append(f"lane {name!r} waives {rule!r} which "
                            f"recorded no findings (stale waiver)")

    # the contradiction rules: the verdict must re-derive from the
    # recorded evidence, and a lane that examined nothing is not clean
    unwaived = sum(c for rule, c in findings.items()
                   if isinstance(c, int) and rule not in waivers)
    derived = unwaived == 0 and error is None
    if rec["ok"] != derived:
        if error is not None:
            why = f"a recorded lane error ({error[:60]!r})"
        elif unwaived:
            why = f"{unwaived} unwaived finding(s)"
        else:
            why = "zero unwaived findings and no error"
        problems.append(f"lane {name!r}: ok={rec['ok']} contradicts "
                        f"{why}")
    if error is None and not any(checked.values()):
        problems.append(f"lane {name!r}: every 'checked' counter is "
                        f"zero and no 'error' explains it — a lane "
                        f"that examined nothing must not read as clean")


def _validate_pair(key: str, rec: dict, problems: List[str]) -> None:
    lanes = rec.get("lanes")
    if not (isinstance(lanes, list) and len(lanes) == 2
            and all(isinstance(x, str) for x in lanes)):
        problems.append(f"pair {key!r} missing/invalid 'lanes' "
                        f"(two lane names)")
        return
    if key != "|".join(lanes):
        problems.append(f"pair {key!r} key disagrees with its lanes "
                        f"{lanes}")
    sigs = rec.get("signatures")
    if not (isinstance(sigs, dict)
            and all(x in sigs for x in lanes)):
        problems.append(f"pair {key!r} missing 'signatures' for both "
                        f"lanes (the verdict must carry its evidence)")
        return
    for lane in lanes:
        if not (isinstance(sigs[lane], list)
                and all(_sig_ok(e) for e in sigs[lane])):
            problems.append(f"pair {key!r} signatures for {lane!r} are "
                            f"not [kind, [dims], [elems]] entries")
            return
    verdict = rec.get("verdict")
    if verdict not in ("cleared", "variant"):
        problems.append(f"pair {key!r} verdict {verdict!r} not in "
                        f"('cleared', 'variant')")
        return
    a, b = lanes
    derived = _diff_signatures(sigs[a], sigs[b], a, b)
    recorded = rec.get("variants")
    if not isinstance(recorded, list):
        problems.append(f"pair {key!r} missing 'variants' list")
        return
    def _vkey(v):
        return (v.get("only_in"), v.get("kind"), tuple(v.get("dims", [])),
                tuple(v.get("elems", [])), v.get("count"))
    if sorted(map(_vkey, recorded)) != sorted(map(_vkey, derived)):
        problems.append(f"pair {key!r}: recorded variants disagree "
                        f"with the multiset diff of the recorded "
                        f"signatures ({len(recorded)} recorded vs "
                        f"{len(derived)} derived)")
    want = "cleared" if not derived else "variant"
    if verdict != want:
        problems.append(f"pair {key!r}: verdict {verdict!r} "
                        f"contradicts the recorded signatures "
                        f"(diff says {want!r})")
    positional = rec.get("positional")
    if not isinstance(positional, bool):
        problems.append(f"pair {key!r} missing/invalid 'positional' "
                        f"(bool)")
    else:
        pos_want = [_canon_sig(e) for e in sigs[a]] == \
            [_canon_sig(e) for e in sigs[b]]
        if positional != pos_want:
            problems.append(f"pair {key!r}: positional={positional} "
                            f"contradicts the recorded signature "
                            f"streams")
    if verdict == "variant":
        expected = rec.get("expected")
        if not isinstance(expected, bool):
            problems.append(f"pair {key!r}: a variant verdict needs an "
                            f"explicit 'expected' bool")
        elif expected and not (isinstance(rec.get("reason"), str)
                               and rec["reason"].strip()):
            problems.append(f"pair {key!r}: expected=true needs a "
                            f"non-empty 'reason' (the documented "
                            f"tolerance class)")


def pair_ok(rec: dict) -> bool:
    """A pair passes the gate when cleared, or a documented (expected,
    reasoned) variant."""
    if rec.get("verdict") == "cleared":
        return True
    return rec.get("verdict") == "variant" \
        and rec.get("expected") is True \
        and isinstance(rec.get("reason"), str) and bool(
            rec["reason"].strip())


def validate_detlint(doc) -> List[str]:
    """Problems with one parsed DETLINT document (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("round"), int):
        problems.append("missing/invalid 'round' (int)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    rules = doc.get("rules")
    if not (isinstance(rules, list) and rules
            and all(isinstance(r, str) for r in rules)):
        problems.append("missing/invalid 'rules' (non-empty list of "
                        "rule-id strings)")
        rules = list(RULES)
    lanes = doc.get("lanes")
    if not isinstance(lanes, dict) or not lanes:
        return problems + ["missing/empty 'lanes' object"]
    for name, rec in lanes.items():
        if not isinstance(rec, dict):
            problems.append(f"lane {name!r} is not an object")
            continue
        _validate_lane(name, rec, tuple(rules), problems)

    pairs = doc.get("pairs")
    if not isinstance(pairs, dict) or not pairs:
        problems.append("missing/empty 'pairs' object (the comparator "
                        "verdicts are half the artifact's point)")
        pairs = {}
    for key, rec in pairs.items():
        if not isinstance(rec, dict):
            problems.append(f"pair {key!r} is not an object")
            continue
        _validate_pair(key, rec, problems)

    gate = doc.get("gate")
    if not isinstance(gate, dict):
        problems.append("missing 'gate' object")
        return problems
    clean = sum(1 for rec in lanes.values()
                if isinstance(rec, dict) and rec.get("ok") is True)
    p_ok = sum(1 for rec in pairs.values()
               if isinstance(rec, dict) and pair_ok(rec))
    want = {"lanes_clean": clean, "lanes_total": len(lanes),
            "pairs_ok": p_ok, "pairs_total": len(pairs)}
    for key, val in want.items():
        if not isinstance(gate.get(key), int):
            problems.append(f"gate missing/invalid {key!r} (int)")
        elif gate[key] != val:
            problems.append(f"gate.{key}={gate[key]} contradicts the "
                            f"records (counted {val})")
    if not isinstance(gate.get("ok"), bool):
        problems.append("gate missing/invalid 'ok' (bool)")
    elif gate["ok"] != (clean == len(lanes) and p_ok == len(pairs)):
        problems.append(f"gate.ok={gate['ok']} contradicts the lane/"
                        f"pair verdicts ({clean}/{len(lanes)} lanes "
                        f"clean, {p_ok}/{len(pairs)} pairs ok)")
    return problems


def validate_detlint_file(path: str) -> List[str]:
    """Problems with one DETLINT_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable detlint JSON: {e}"]
    return validate_detlint(doc)
