"""Donation lint: did ``donate_argnums`` actually produce aliasing?

Buffer donation is apex_tpu's answer to the reference's in-place
optimizer updates: a train step that donates its state updates weights
and moments in place, halving peak HBM for the state.  The failure mode
is *silent* — a donated argument XLA cannot alias (shape/dtype matches
no output, or the value is still live) simply isn't donated; the step
runs correctly but every "in-place" buffer is doubled.  JAX emits a
one-time Python warning at lowering, which CI logs swallow.

This pass turns that into a structured, gateable finding.  Ground truth
preference order:

1. the **compiled executable**'s ``input_output_alias`` table (what the
   runtime will actually alias);
2. the lowered StableHLO ``tf.aliasing_output`` argument attributes
   (lowering-time aliasing decisions) when the program wasn't compiled.

A donated argument absent from both is a dropped donation, reported
with its buffer size — the wasted HBM bytes.
"""

from __future__ import annotations

import re
from typing import List, Set

from apex_tpu.analysis.core import PassContext, register_pass
from apex_tpu.analysis.report import Finding

#: ``{out_index}: (param_number, {param_index}, may-alias)`` entries of
#: the HLO module header's input_output_alias table.
_HLO_ALIAS_ENTRY = re.compile(r"\{[0-9, ]*\}:\s*\((\d+)")
_MAIN_SIG = re.compile(r"func\.func (?:public )?@main\((?P<args>.*?)\)"
                       r"\s*->", re.DOTALL)
_ARG_MARK = re.compile(r"%arg(\d+):")


def _alias_blob(hlo_text: str) -> str:
    """The brace-balanced ``input_output_alias={...}`` header blob."""
    key = "input_output_alias={"
    start = hlo_text.find(key)
    if start < 0:
        return ""
    i, depth = start + len(key), 1
    while i < len(hlo_text) and depth:
        depth += {"{": 1, "}": -1}.get(hlo_text[i], 0)
        i += 1
    return hlo_text[start + len(key):i - 1]


def aliased_parameters(hlo_text: str) -> Set[int]:
    """Entry-parameter numbers the compiled executable aliases to an
    output (the numbering matches the flat argument order)."""
    return {int(m.group(1))
            for m in _HLO_ALIAS_ENTRY.finditer(_alias_blob(hlo_text))}


def _main_arg_attrs(stablehlo_text: str):
    """Per-arg attribute text of the lowered ``main`` signature, keyed
    by ``%argN`` index.  Membership-scans the whole slice between one
    ``%argN:`` marker and the next instead of parsing the attr dict —
    attr values may embed braces inside quoted strings (e.g.
    ``mhlo.sharding = "{devices=[8,1]<=[8]}"``), which no flat regex
    over ``{...}`` survives."""
    m = _MAIN_SIG.search(stablehlo_text)
    if not m:
        return {}
    args_text = m.group("args")
    marks = list(_ARG_MARK.finditer(args_text))
    return {int(mk.group(1)):
            args_text[mk.end():marks[i + 1].start()
                      if i + 1 < len(marks) else len(args_text)]
            for i, mk in enumerate(marks)}


def aliased_args_stablehlo(stablehlo_text: str) -> Set[int]:
    """Arg indices carrying ``tf.aliasing_output`` in the lowered
    module's ``main`` signature (lowering-time aliasing)."""
    return {i for i, attrs in _main_arg_attrs(stablehlo_text).items()
            if "tf.aliasing_output" in attrs}


def donor_args_stablehlo(stablehlo_text: str) -> Set[int]:
    """Arg indices marked ``jax.buffer_donor``: donation declared but
    not resolved to a specific output at lowering — the compiler may
    still alias them, so lowering-only evidence is inconclusive."""
    return {i for i, attrs in _main_arg_attrs(stablehlo_text).items()
            if "jax.buffer_donor" in attrs}


def kept_index_map(ctx: PassContext) -> "dict | None":
    """``{flat arg index -> kept text/parameter position}`` when the
    lowered signature confirms the kept-arg inference, ``None`` when
    the numbering is ambiguous (the kept set comes from a private jax
    attribute; a shifted numbering would let any alias-table consumer
    report honored donations as dropped — every consumer must refuse
    to guess, exactly as this pass does).  Memoized on the context:
    the donation, memory, and syncs passes plus the graph_lint lane
    record all consume it from one lowering."""
    def compute():
        kept = ctx.kept_args
        sig_args = _main_arg_attrs(ctx.stablehlo_text)
        if sig_args and len(sig_args) != len(kept):
            return None
        return {a.index: k for k, a in enumerate(kept)}
    return ctx.memo("kept_index_map", compute)


def aliased_parameter_set(ctx: PassContext) -> Set[int]:
    """:func:`aliased_parameters` of the context's compiled HLO,
    memoized — the alias blob is scanned once per lowering however
    many passes read it."""
    return ctx.memo("aliased_parameters",
                    lambda: aliased_parameters(ctx.hlo_text))


def donation_pass(ctx: PassContext, min_bytes: int = 0) -> List[Finding]:
    """Flag donated arguments that produced no input-output alias.

    ``min_bytes`` ignores dropped donations smaller than the threshold
    (a dropped scalar step-counter donation wastes nothing worth
    failing a gate over) — the default flags everything."""
    donated = [a for a in ctx.args if a.donated]
    if not donated:
        return []
    if ctx.hlo_text is not None:
        # the compiled executable is authoritative either way: a module
        # with NO input_output_alias table honored zero donations, so
        # every donated arg is dropped — falling back to lowering-time
        # markers here would downgrade dropped sharded donations
        # (jax.buffer_donor) to inconclusive
        aliased = aliased_parameter_set(ctx)
        unresolved: Set[int] = set()
        evidence = "compiled executable input_output_alias"
    else:
        aliased = aliased_args_stablehlo(ctx.stablehlo_text)
        # ``jax.buffer_donor`` args (e.g. sharded donations) defer the
        # aliasing decision to the compiler: lowering-time evidence is
        # inconclusive, so they must not count as dropped
        unresolved = donor_args_stablehlo(ctx.stablehlo_text)
        evidence = "lowered tf.aliasing_output attributes"
    # alias tables number KEPT parameters only — pruned unused args
    # vanish from the text, shifting everything after them.  The kept
    # set comes from a private jax attribute (core._args_info); cross-
    # check it against the lowered signature's actual arg count and
    # refuse to guess on mismatch — a shifted numbering would report
    # honored donations as dropped (same guard as sharding's index_ok).
    kept_pos = kept_index_map(ctx)
    if kept_pos is None:
        kept = ctx.kept_args
        sig_args = _main_arg_attrs(ctx.stablehlo_text)
        return [Finding(
            "donation", "info",
            f"cannot verify {len(donated)} donation(s): the lowered "
            f"signature has {len(sig_args)} argument(s) but "
            f"{len(kept)} were inferred kept — argument numbering is "
            f"ambiguous on this jax version",
            count=len(donated))]
    findings: List[Finding] = []
    dropped_bytes = 0
    for a in donated:
        if not a.kept:
            findings.append(Finding(
                "donation", "warning",
                f"donated argument {a.index} ({a.path or 'arg'}) is "
                f"unused by the program and was pruned at lowering — "
                f"the donation is vacuous (dead argument?)",
                op=a.path or f"arg{a.index}", dtype=a.dtype,
                bytes=a.nbytes))
            continue
        if kept_pos[a.index] in aliased or a.nbytes < min_bytes:
            continue
        if kept_pos[a.index] in unresolved:
            findings.append(Finding(
                "donation", "info",
                f"donated argument {a.index} ({a.path or 'arg'}) is a "
                f"jax.buffer_donor — aliasing is decided at compile "
                f"time; analyze with compile=True to verify it",
                op=a.path or f"arg{a.index}", dtype=a.dtype,
                bytes=a.nbytes))
            continue
        dropped_bytes += a.nbytes
        findings.append(Finding(
            "donation", "error",
            f"donated argument {a.index} ({a.path or 'arg'}: "
            f"{a.dtype}{list(a.shape)}) was silently dropped — no "
            f"input-output alias in the {evidence}; the buffer is "
            f"duplicated instead of reused",
            op=a.path or f"arg{a.index}", dtype=a.dtype, bytes=a.nbytes))
    n_dropped = sum(1 for f in findings if f.severity == "error")
    if n_dropped:
        findings.append(Finding(
            "donation", "info",
            f"{n_dropped} dropped donation(s) waste {dropped_bytes} "
            f"bytes of HBM per live step",
            bytes=dropped_bytes, count=n_dropped))
    return findings


register_pass("donation", donation_pass)
