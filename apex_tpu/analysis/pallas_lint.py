"""Pallas kernel sanitizer: grid races, index-map OOB/coverage, VMEM
budget, and sequential-contract proofs for every hand-written kernel.

Every other analysis pass stops at StableHLO, where a ``pallas_call``
is an opaque custom call — yet the hand-written kernels are exactly
where this repo has shipped real bugs (the shape-lucky "bitwise"
ragged trees, the never-overwritten draft-cache hole, the documented
``donate=`` in-place skip-``cond`` caveat).  This pass opens the box:
it extracts every ``pallas_call`` from the *jaxpr* (grid, BlockSpecs,
index maps, ``dimension_semantics``, scratch shapes, input/output
aliasing), evaluates each index map **concretely over the full grid**
to build per-operand block-footprint sets, and proves four rule
families:

``pallas-parallel-race`` (error)
    Two grid points that differ in a ``parallel`` dimension write the
    same output block (write-write race: parallel iterations execute
    in unspecified order, possibly on different cores), or — for an
    aliased input/output pair — one parallel iteration reads a block
    another parallel iteration writes (read-after-write carried
    across parallel iterations).
``pallas-alias-race`` (error)
    A donated/aliased input-output pair whose footprints diverge at
    some grid point (the read walks a block an earlier step already
    overwrote in place), or whose output ref is ONLY ever stored
    conditionally (``pl.when``): the skipped-store path leaves the
    block holding the donated input's bytes — the torn-alias class
    behind the documented ``donate=`` skip-``cond`` caveat.
``pallas-oob-unmasked`` (error)
    A block origin that escapes the (padded) array entirely.  Mosaic
    masks the *overhang* of the last partial block — the legal
    ragged-tail idiom — but an origin at or past the array end reads
    or writes memory no mask covers.
``pallas-uncovered-output`` (error)
    An output tile no grid point ever writes (the draft-cache-hole
    class): the union of evaluated output footprints must cover the
    full ceil-division tiling of every output.
``pallas-vmem-overflow`` (error)
    The per-grid-step working set — double-buffered grid-varying
    operand blocks, single-buffered grid-invariant blocks, plus VMEM
    scratch, all dtype-sized — exceeds the VMEM ceiling.  The ceiling
    is ``2 x geometry.vmem_budget()`` (the ``APEX_TPU_VMEM_BUDGET_MB``
    knob names the *streaming half* of VMEM; the checker counts each
    stream's double-buffer partner explicitly, so the ceiling is the
    whole 2x budget = ~16 MiB at defaults).  This turns the geometry
    ladder's promise into a verified invariant for every (shape,
    dtype, knob) a bench config or autotune table can select.
``pallas-seq-accum-parallel`` (error)
    An output ref the kernel *reads* (an accumulator — the
    layer-norm-backward dγ/dβ digest contract) that is revisited
    across a ``parallel`` dimension: carried accumulator state
    requires sequential (``arbitrary``) semantics on the carrying
    dimension.

Registered as the ``pallas-kernel`` pass (reads
``PassContext.closed_jaxpr``; :func:`~apex_tpu.analysis.analyze`
captures the jaxpr automatically when the pass is requested).  The
standalone API needs no lowering at all::

    from apex_tpu.analysis import pallas_lint
    report = pallas_lint.lint_fn(kernel_wrapper, *example_args)
    assert report.ok, report.format()

``tools/kernel_lint.py`` sweeps every shipped kernel across the
geometry ladder and adversarial ragged shapes with exactly this API
and commits the verdict as ``KERNLINT_r*.json``
(:mod:`apex_tpu.analysis.kernlint` is the stdlib-only schema
``tools/gate_hygiene.py`` validates in tier-1);
``tools/graph_lint.py --passes pallas`` runs the pass over the
optimizer-bearing train lanes.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.analysis.core import register_pass
from apex_tpu.analysis.report import Finding, Report, make_report

PASS_NAME = "pallas-kernel"

#: the six rule ids, in severity-of-consequence order (all errors)
RULES = ("pallas-parallel-race", "pallas-alias-race",
         "pallas-oob-unmasked", "pallas-uncovered-output",
         "pallas-vmem-overflow", "pallas-seq-accum-parallel")

#: full-enumeration cap: grids larger than this are probed on their
#: boundary slices instead (first/middle/last two indices per axis) and
#: the coverage rule — which needs exhaustiveness — reports itself
#: skipped rather than asserting over a subsample
MAX_GRID_POINTS = 65536


# ---------------------------------------------------------------------------
# extraction: pallas_call eqns out of a (nested) jaxpr
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Operand:
    """One block-mapped operand (inputs first, then outputs)."""

    index: int            # position in grid_mapping.block_mappings
    role: str             # "in" | "out"
    name: str             # BlockSpec origin (e.g. "p_ref", "outputs[0]")
    block_shape: Tuple[int, ...]
    array_shape: Tuple[int, ...]
    dtype: str
    itemsize: int
    smem: bool
    index_map: Any        # ClosedJaxpr over the grid indices


@dataclasses.dataclass
class Scratch:
    """One scratch operand (persists across grid steps, per core)."""

    shape: Tuple[int, ...]
    dtype: str
    nbytes: int
    smem: bool


@dataclasses.dataclass
class KernelCall:
    """Everything the sanitizer reads from one ``pallas_call`` eqn."""

    name: str
    grid: Tuple[Any, ...]
    semantics: Tuple[str, ...]      # per-dim, "parallel"/"arbitrary"
    operands: List[Operand]
    num_inputs: int
    num_outputs: int
    scratch: List[Scratch]
    aliases: Tuple[Tuple[int, int], ...]   # (input idx, output idx)
    body: Any                       # the kernel body jaxpr
    num_index_operands: int


def _sub_jaxprs(value):
    """Jaxpr objects reachable from one eqn param value."""
    for item in (value if isinstance(value, (list, tuple)) else [value]):
        inner = getattr(item, "jaxpr", None)
        if inner is not None and hasattr(inner, "eqns"):
            yield inner
        elif hasattr(item, "eqns"):
            yield item


def _walk_eqns(jaxpr, out: list) -> None:
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            out.append(eqn)
            continue           # a pallas body cannot nest another call
        for value in eqn.params.values():
            for sub in _sub_jaxprs(value):
                _walk_eqns(sub, out)


def _itemsize(dtype) -> int:
    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:
        return int(getattr(dtype, "itemsize", 4))


def _is_smem(aval) -> bool:
    return "smem" in str(aval).lower()


def _block_dims(block_shape) -> Tuple[int, ...]:
    """Block extents as ints — squeezed (``Mapped``) dims are size 1."""
    return tuple(int(b) if isinstance(b, int) else 1 for b in block_shape)


def describe_call(eqn) -> KernelCall:
    """Normalize one ``pallas_call`` eqn into a :class:`KernelCall`."""
    params = eqn.params
    gm = params["grid_mapping"]
    grid = tuple(gm.grid)
    nsi = params.get("name_and_src_info")
    name = getattr(nsi, "name", None) or "pallas_call"

    sem_raw = None
    cp = params.get("compiler_params") or {}
    mosaic = cp.get("mosaic") if isinstance(cp, dict) else None
    if mosaic is not None:
        sem_raw = (mosaic.get("dimension_semantics")
                   if isinstance(mosaic, dict)
                   else getattr(mosaic, "dimension_semantics", None))
    sem = tuple(str(s) for s in sem_raw) if sem_raw else ()
    # undeclared dims default to "arbitrary" (sequential) — Mosaic's own
    # default, and the conservative one for the race rules
    sem = sem + ("arbitrary",) * (len(grid) - len(sem))

    operands: List[Operand] = []
    n_in = int(gm.num_inputs)
    for i, bm in enumerate(gm.block_mappings):
        sd = bm.array_shape_dtype
        operands.append(Operand(
            index=i, role="in" if i < n_in else "out",
            name=str(getattr(bm, "origin", "") or f"operand{i}"),
            block_shape=_block_dims(bm.block_shape),
            array_shape=tuple(int(d) for d in sd.shape),
            dtype=str(sd.dtype), itemsize=_itemsize(sd.dtype),
            smem=_is_smem(getattr(bm, "transformed_block_aval", "")),
            index_map=bm.index_map_jaxpr))

    body = params["jaxpr"]
    n_idx = int(gm.num_index_operands)
    scratch: List[Scratch] = []
    for var in body.invars[n_idx + len(gm.block_mappings):]:
        aval = var.aval
        shape = tuple(int(d) for d in getattr(aval, "shape", ()))
        dtype = getattr(aval, "dtype", np.float32)
        scratch.append(Scratch(
            shape=shape, dtype=str(dtype),
            nbytes=int(math.prod(shape)) * _itemsize(dtype),
            smem=_is_smem(aval)))

    aliases = tuple((int(a), int(b))
                    for a, b in params.get("input_output_aliases", ()))
    return KernelCall(
        name=name, grid=grid, semantics=sem, operands=operands,
        num_inputs=n_in, num_outputs=int(gm.num_outputs),
        scratch=scratch, aliases=aliases, body=body,
        num_index_operands=n_idx)


def extract_pallas_calls(closed_jaxpr) -> List[KernelCall]:
    """Every ``pallas_call`` in a (closed) jaxpr, however deeply nested
    under pjit/cond/scan/custom-vjp wrappers."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    eqns: list = []
    _walk_eqns(jaxpr, eqns)
    return [describe_call(e) for e in eqns]


# ---------------------------------------------------------------------------
# concrete index-map evaluation over the grid
# ---------------------------------------------------------------------------

def _grid_points(grid: Sequence[int]) -> Tuple[np.ndarray, bool]:
    """``(points, exhaustive)`` — all grid index tuples when the grid is
    small enough, else the boundary-slice subsample (every combination
    of {0, 1, mid, n-2, n-1} per axis)."""
    if not grid:
        return np.zeros((1, 0), np.int64), True
    total = math.prod(int(g) for g in grid)
    if total <= MAX_GRID_POINTS:
        axes = [range(int(g)) for g in grid]
        return np.array(list(itertools.product(*axes)),
                        np.int64).reshape(total, len(grid)), True
    axes = []
    for g in grid:
        g = int(g)
        axes.append(sorted({0, min(1, g - 1), g // 2,
                            max(g - 2, 0), g - 1}))
    pts = np.array(list(itertools.product(*axes)), np.int64)
    return pts, False


def _eval_index_map(index_map, pts: np.ndarray) -> np.ndarray:
    """Evaluate one BlockSpec index-map ClosedJaxpr at every grid point:
    ``(N, n_grid_dims) -> (N, n_block_dims)`` of block indices."""
    import jax
    import jax.numpy as jnp
    from jax import core as jax_core

    def one(*idx):
        return tuple(jax_core.eval_jaxpr(index_map.jaxpr,
                                         index_map.consts, *idx))

    if pts.shape[1] == 0:
        res = one()
        return np.asarray([[int(r) for r in res]], np.int64)
    try:
        cols = [jnp.asarray(pts[:, d], jnp.int32)
                for d in range(pts.shape[1])]
        outs = jax.vmap(one)(*cols)
        return np.stack([np.asarray(o, np.int64) for o in outs], axis=1)
    except Exception:  # noqa: BLE001 - fall back to per-point eval
        rows = []
        for row in pts:
            res = one(*[jnp.int32(int(x)) for x in row])
            rows.append([int(r) for r in res])
        return np.asarray(rows, np.int64)


# ---------------------------------------------------------------------------
# kernel-body ref usage (reads / writes / conditional writes per operand)
# ---------------------------------------------------------------------------

def _ref_usage(call: KernelCall) -> Dict[int, Dict[str, int]]:
    """``{operand index: {"reads": n, "writes": n, "cond_writes": n}}``
    over the kernel body (scratch operands keyed past the block-mapped
    ones).  ``pl.when`` lowers to ``cond``, so stores under it count as
    conditional; loop bodies (scan/while/fori) count as unconditional —
    the torn-alias rule targets *skippable* stores, not repeated ones."""
    usage: Dict[int, Dict[str, int]] = {}

    def rec(idx: int) -> Dict[str, int]:
        return usage.setdefault(idx, {"reads": 0, "writes": 0,
                                      "cond_writes": 0})

    def look(refmap, v) -> Optional[int]:
        try:                     # Literal invars are unhashable
            return refmap.get(v)
        except TypeError:
            return None

    def remap(refmap, sub_vars, outer_vars) -> Dict[Any, int]:
        out = {}
        for sv, ov in zip(sub_vars, outer_vars):
            idx = look(refmap, ov)
            if idx is not None:
                out[sv] = idx
        return out

    def walk(jaxpr, refmap: Dict[Any, int], in_cond: bool) -> None:
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "get":
                idx = look(refmap, eqn.invars[0])
                if idx is not None:
                    rec(idx)["reads"] += 1
                continue
            if prim in ("swap", "addupdate"):
                idx = look(refmap, eqn.invars[0])
                if idx is not None:
                    u = rec(idx)
                    if prim == "addupdate":
                        u["reads"] += 1
                    u["cond_writes" if in_cond else "writes"] += 1
                continue
            if prim == "cond":
                branches = eqn.params.get("branches", ())
                for br in branches:
                    sub = getattr(br, "jaxpr", br)
                    walk(sub, remap(refmap, sub.invars, eqn.invars[1:]),
                         True)
                continue
            if prim == "while":
                cn = int(eqn.params.get("cond_nconsts", 0))
                bn = int(eqn.params.get("body_nconsts", 0))
                carry = eqn.invars[cn + bn:]
                for key, consts in (("cond_jaxpr", eqn.invars[:cn]),
                                    ("body_jaxpr",
                                     eqn.invars[cn:cn + bn])):
                    cj = eqn.params.get(key)
                    if cj is None:
                        continue
                    sub = getattr(cj, "jaxpr", cj)
                    walk(sub, remap(refmap, sub.invars,
                                    list(consts) + list(carry)),
                         in_cond)
                continue
            # generic descent (pjit, scan, custom_* ...): positional
            # alignment when the sub-jaxpr's invars match 1:1
            for value in eqn.params.values():
                for sub in _sub_jaxprs(value):
                    if len(sub.invars) != len(eqn.invars):
                        continue
                    walk(sub, remap(refmap, sub.invars, eqn.invars),
                         in_cond)

    refmap = {}
    start = call.num_index_operands
    for j, var in enumerate(call.body.invars[start:]):
        refmap[var] = j          # 0..nin+nout-1 block-mapped, then scratch
    walk(call.body, refmap, False)
    return usage


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------

def vmem_ceiling() -> int:
    """The VMEM working-set ceiling in bytes: twice the streaming
    budget (``APEX_TPU_VMEM_BUDGET_MB`` names the *half* reserved for
    one copy of the streams; the checker counts every stream's
    double-buffer partner explicitly, so the ceiling is the full 2x
    budget — ~16 MiB, the physical VMEM, at defaults)."""
    from apex_tpu.ops.pallas.geometry import vmem_budget
    return 2 * vmem_budget()


def _cdiv(a: int, b: int) -> int:
    return -(-a // b) if b else 0


def _varies_along(pts: np.ndarray, blocks: List[tuple], d: int) -> bool:
    groups: Dict[tuple, tuple] = {}
    for p, b in zip(map(tuple, pts), blocks):
        key = p[:d] + p[d + 1:]
        prev = groups.setdefault(key, b)
        if prev != b:
            return True
    return False


def _fmt_bytes(n: int) -> str:
    return f"{n / (1 << 20):.2f} MiB" if n >= 1 << 20 else f"{n} B"


def lint_call(call: KernelCall,
              budget_bytes: Optional[int] = None) -> List[Finding]:
    """All six rule families over one extracted ``pallas_call``."""
    findings: List[Finding] = []
    f = findings.append

    if not all(isinstance(g, int) or hasattr(g, "__index__")
               for g in call.grid):
        f(Finding(PASS_NAME, "warning",
                  f"{call.name}: grid {call.grid} is not concrete — "
                  f"footprints unevaluable, rules skipped",
                  op="pallas-unevaluable"))
        return findings
    grid = tuple(int(g) for g in call.grid)
    pts, exhaustive = _grid_points(grid)
    par_dims = [d for d in range(len(grid))
                if call.semantics[d] == "parallel" and grid[d] > 1]

    # -- footprints: per operand, the evaluated block index per point --
    blocks: Dict[int, List[tuple]] = {}
    for op in call.operands:
        try:
            arr = _eval_index_map(op.index_map, pts)
        except Exception as e:  # noqa: BLE001 - per-operand isolation
            f(Finding(PASS_NAME, "warning",
                      f"{call.name}: index map of {op.name} failed to "
                      f"evaluate ({type(e).__name__}: {e}) — rules "
                      f"skipped for this operand",
                      op="pallas-unevaluable"))
            continue
        blocks[op.index] = [tuple(int(x) for x in row) for row in arr]

    usage = _ref_usage(call)

    # -- (b1) OOB: a block origin at/past the array end has no mask ----
    for op in call.operands:
        bl = blocks.get(op.index)
        if bl is None:
            continue
        for pt, b in zip(map(tuple, pts), bl):
            bad = [d for d in range(len(b))
                   if b[d] < 0
                   or (op.array_shape[d] > 0
                       and b[d] * op.block_shape[d] >= op.array_shape[d])]
            if bad:
                d = bad[0]
                f(Finding(
                    PASS_NAME, "error",
                    f"{call.name}: {op.role}put {op.name} block index "
                    f"{b} at grid point {pt} puts dim {d} origin "
                    f"{b[d] * op.block_shape[d]} outside the array "
                    f"{op.array_shape} — Mosaic masks only the "
                    f"overhang of the last in-bounds block; this "
                    f"block is fully out of bounds",
                    op="pallas-oob-unmasked", dtype=op.dtype,
                    example=f"grid={grid} block={op.block_shape}"))
                break

    # -- (b2) coverage: every output tile must be written by some point
    for op in call.operands:
        if op.role != "out":
            continue
        bl = blocks.get(op.index)
        if bl is None:
            continue
        if not exhaustive:
            f(Finding(PASS_NAME, "info",
                      f"{call.name}: grid {grid} exceeds "
                      f"{MAX_GRID_POINTS} points — output coverage of "
                      f"{op.name} checked on boundary slices only",
                      op="pallas-coverage-sampled"))
            continue
        tiles = [_cdiv(op.array_shape[d], op.block_shape[d])
                 for d in range(len(op.block_shape))]
        if math.prod(tiles) > MAX_GRID_POINTS:
            f(Finding(PASS_NAME, "info",
                      f"{call.name}: {op.name} tiling {tiles} too "
                      f"large to enumerate — coverage unchecked",
                      op="pallas-coverage-sampled"))
            continue
        missing = set(itertools.product(*[range(t) for t in tiles])) \
            - set(bl)
        if missing:
            ex = sorted(missing)[0]
            f(Finding(
                PASS_NAME, "error",
                f"{call.name}: output {op.name} tile {ex} (of "
                f"{len(missing)} uncovered tile(s) in the "
                f"{tiles} tiling) is never written by any grid "
                f"point — it ships whatever HBM held before the "
                f"kernel ran",
                op="pallas-uncovered-output", dtype=op.dtype,
                count=len(missing),
                example=f"grid={grid} block={op.block_shape} "
                        f"array={op.array_shape}"))

    # -- (a1)+(d): races and carried accumulators across parallel dims
    for op in call.operands:
        if op.role != "out":
            continue
        bl = blocks.get(op.index)
        if bl is None or not par_dims:
            continue
        u = usage.get(op.index, {})
        reads = u.get("reads", 0) > 0
        seen: Dict[tuple, tuple] = {}
        hit = None
        for pt, b in zip(map(tuple, pts), bl):
            parc = tuple(pt[d] for d in par_dims)
            prev = seen.setdefault(b, parc)
            if prev != parc:
                hit = (b, prev, parc)
                break
        if hit is None:
            continue
        b, p1, p2 = hit
        par_names = [f"dim {d}" for d in par_dims]
        if reads:
            f(Finding(
                PASS_NAME, "error",
                f"{call.name}: output {op.name} carries accumulator "
                f"state (the kernel reads it) but is revisited at "
                f"block {b} by grid points whose parallel "
                f"coordinates differ ({p1} vs {p2} on "
                f"{'/'.join(par_names)}) — accumulation order needs "
                f"sequential ('arbitrary') semantics on the carrying "
                f"dimension",
                op="pallas-seq-accum-parallel", dtype=op.dtype,
                example=f"grid={grid} semantics={call.semantics}"))
        else:
            f(Finding(
                PASS_NAME, "error",
                f"{call.name}: output {op.name} block {b} is written "
                f"by grid points with different parallel coordinates "
                f"({p1} vs {p2} on {'/'.join(par_names)}) — "
                f"write-write race: parallel iterations execute in "
                f"unspecified order",
                op="pallas-parallel-race", dtype=op.dtype,
                example=f"grid={grid} semantics={call.semantics}"))

    # -- (a2) aliased input/output pairs ------------------------------
    for ain, aout in call.aliases:
        out_idx = call.num_inputs + aout
        if ain >= len(call.operands) or out_idx >= len(call.operands):
            continue
        in_op, out_op = call.operands[ain], call.operands[out_idx]
        bi, bo = blocks.get(ain), blocks.get(out_idx)
        if bi is None or bo is None:
            continue
        mismatch = next((i for i, (a, b) in enumerate(zip(bi, bo))
                         if a != b), None)
        if mismatch is not None:
            pt = tuple(pts[mismatch])
            f(Finding(
                PASS_NAME, "error",
                f"{call.name}: aliased pair ({in_op.name} -> "
                f"{out_op.name}) walks different blocks at grid point "
                f"{pt} (read {bi[mismatch]}, write {bo[mismatch]}) — "
                f"the in-place read can observe a block an earlier "
                f"step already overwrote",
                op="pallas-alias-race", dtype=in_op.dtype,
                example=f"grid={grid}"))
        u = usage.get(out_idx, {})
        if u.get("writes", 0) == 0 and u.get("cond_writes", 0) > 0:
            f(Finding(
                PASS_NAME, "error",
                f"{call.name}: aliased output {out_op.name} (donated "
                f"from {in_op.name}) is only ever stored under a "
                f"condition (pl.when) — the skipped-store path "
                f"leaves the block holding the donated input's "
                f"bytes, the torn-alias class behind the donate= "
                f"skip-cond caveat",
                op="pallas-alias-race", dtype=out_op.dtype,
                example=f"cond_writes={u.get('cond_writes', 0)}"))
        if par_dims:
            # RAW carried across parallel iterations: a parallel
            # sibling's write lands in a block this point reads
            writers = {b: tuple(pt[d] for d in par_dims)
                       for pt, b in zip(map(tuple, pts), bo)}
            for pt, b in zip(map(tuple, pts), bi):
                parc = tuple(pt[d] for d in par_dims)
                w = writers.get(b)
                if w is not None and w != parc:
                    f(Finding(
                        PASS_NAME, "error",
                        f"{call.name}: aliased read {in_op.name} at "
                        f"grid point {pt} touches block {b}, which a "
                        f"grid point with different parallel "
                        f"coordinates ({w}) writes in place — "
                        f"read-after-write carried across parallel "
                        f"iterations",
                        op="pallas-parallel-race", dtype=in_op.dtype,
                        example=f"grid={grid} "
                                f"semantics={call.semantics}"))
                    break

    # -- (c) VMEM working set vs the budget ceiling -------------------
    working = 0
    detail = []
    for op in call.operands:
        if op.smem:
            continue
        nbytes = int(math.prod(op.block_shape)) * op.itemsize
        bl = blocks.get(op.index)
        varying = bl is not None and any(
            _varies_along(pts, bl, d) for d in range(len(grid)))
        mult = 2 if varying else 1
        working += mult * nbytes
        detail.append(f"{op.name} {mult}x{_fmt_bytes(nbytes)}")
    for i, scr in enumerate(call.scratch):
        if scr.smem:
            continue
        working += scr.nbytes
        detail.append(f"scratch[{i}] {_fmt_bytes(scr.nbytes)}")
    ceiling = int(budget_bytes) if budget_bytes is not None \
        else vmem_ceiling()
    if working > ceiling:
        f(Finding(
            PASS_NAME, "error",
            f"{call.name}: per-grid-step VMEM working set "
            f"{_fmt_bytes(working)} exceeds the ceiling "
            f"{_fmt_bytes(ceiling)} (2x the "
            f"APEX_TPU_VMEM_BUDGET_MB streaming budget) — "
            f"{'; '.join(detail)}",
            op="pallas-vmem-overflow", bytes=working))

    f(Finding(
        PASS_NAME, "info",
        f"{call.name}: grid={grid} semantics={call.semantics} "
        f"operands={len(call.operands)} scratch={len(call.scratch)} "
        f"aliases={len(call.aliases)} working set "
        f"{_fmt_bytes(working)} / {_fmt_bytes(ceiling)}",
        op="pallas-call", bytes=working))
    return findings


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def lint_jaxpr(closed_jaxpr,
               budget_bytes: Optional[int] = None) -> List[Finding]:
    """All rule findings over every ``pallas_call`` in a jaxpr."""
    calls = extract_pallas_calls(closed_jaxpr)
    if not calls:
        return [Finding(PASS_NAME, "info",
                        "no pallas_call in this program (0 kernels "
                        "checked)", op="pallas-call", count=0)]
    findings: List[Finding] = []
    for call in calls:
        findings.extend(lint_call(call, budget_bytes=budget_bytes))
    return findings


def lint_fn(fn, *args, budget_bytes: Optional[int] = None,
            **kwargs) -> Report:
    """The standalone API: trace ``fn`` on example args (no lowering,
    no compilation) and run every rule over the pallas_calls found.

    ``fn`` may be jitted or plain; kernels traced with
    ``interpret=True`` (the off-TPU path) lint identically — the
    jaxpr-level ``pallas_call`` carries the same grid/BlockSpec
    metadata either way.
    """
    import jax
    closed = jax.make_jaxpr(lambda *a, **k: fn(*a, **k))(*args, **kwargs)
    return make_report(lint_jaxpr(closed, budget_bytes=budget_bytes),
                       (PASS_NAME,))


def pallas_kernel_pass(ctx, budget_bytes: Optional[int] = None,
                       **_opts) -> List[Finding]:
    """The registered pass: reads the jaxpr captured on the context
    (:func:`~apex_tpu.analysis.analyze` records it whenever this pass
    is requested); degrades to an info finding when absent — StableHLO
    alone has already erased the BlockSpec structure."""
    closed = getattr(ctx, "closed_jaxpr", None)
    if closed is None:
        return [Finding(
            PASS_NAME, "info",
            "skipped: no jaxpr captured on this context — request the "
            "pass through analyze() (which traces the jaxpr alongside "
            "the lowering) or use pallas_lint.lint_fn directly")]
    return lint_jaxpr(closed, budget_bytes=budget_bytes)


register_pass(PASS_NAME, pallas_kernel_pass)
