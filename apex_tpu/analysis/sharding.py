"""Sharding lint: replicated weights and parameter-sized all-gathers.

After SPMD partitioning, two bug classes are invisible at runtime but
obvious in the compiled program:

- a large array the user *meant* to shard (FSDP masters, TP weights)
  arriving fully **replicated** — every device holds the whole buffer,
  multiplying HBM by the axis size;
- a **parameter-sized all-gather** inside the train step — the classic
  signature of a weight that lost its sharding mid-graph and is being
  re-materialized whole on every device, every step.

Both are read off the compiled HLO: entry parameters carry explicit
``sharding={...}`` annotations under SPMD, and all-gathers carry their
output shapes.  Single-program modules (``num_partitions=1``, no device
assignments) produce no findings — there is nothing to shard.

Intent escalation: pass ``intended={arg-path-substring: PartitionSpec}``
(see :func:`apex_tpu.parallel.mesh.intended_specs` for building it from
a sharding/array pytree) and a replicated array whose path matches a
sharded intent becomes an ``error`` instead of a ``warning`` — the
program contradicts its declared plan.
"""

from __future__ import annotations

import re
from typing import List, Mapping, Optional, Tuple

from apex_tpu.analysis.collectives import (_COLLECTIVE_RE, _SHAPE_RE,
                                           shape_bytes)
from apex_tpu.analysis.core import PassContext, register_pass
from apex_tpu.analysis.report import Finding

#: 1 MiB: smaller fully-replicated arrays (biases, norm scales, scalars)
#: are replicated by every sane sharding; "large" means weight-sized.
DEFAULT_MIN_BYTES = 1 << 20

_NUM_PARTITIONS = re.compile(r"num_partitions=(\d+)")
_DEVICE_COUNT = re.compile(r"<=\[(\d+)\]")
_PARAM_LINE = re.compile(
    r"^\s*%\S+\s*=\s*(?P<shape>\w+\[[0-9,]*\])\S*\s+"
    r"parameter\((?P<num>\d+)\)(?P<rest>.*)$")


def num_partitions(hlo_text: str) -> int:
    """Device count the module is partitioned over (1 = nothing to
    lint).  The module header's ``num_partitions`` is authoritative;
    sharding device-assignment spellings are the fallback."""
    m = _NUM_PARTITIONS.search(hlo_text[:hlo_text.find("\n")])
    if m:
        return int(m.group(1))
    return max((int(d) for d in _DEVICE_COUNT.findall(hlo_text)),
               default=1)


def entry_parameters(hlo_text: str) -> List[Tuple[int, str, str, int, str]]:
    """(param_number, dtype, dims, nbytes, rest-of-line) for the ENTRY
    computation's parameters — fusion/reducer computations have their
    own ``parameter(N)`` lines that must not be confused with program
    inputs."""
    start = hlo_text.find("\nENTRY ")
    if start < 0:
        return []
    out = []
    for line in hlo_text[start + 1:].splitlines()[1:]:
        if line.startswith("}"):
            break
        m = _PARAM_LINE.match(line)
        if not m:
            continue
        sm = _SHAPE_RE.match(m.group("shape"))
        if not sm:
            continue
        dt, dims = sm.group(1), sm.group(2)
        out.append((int(m.group("num")), dt, dims,
                    shape_bytes(dt, dims), m.group("rest")))
    return out


def _is_replicated(param_rest: str) -> bool:
    # under SPMD every entry param is annotated; a missing annotation
    # means propagation chose for it — treat as replicated (the
    # conservative reading for a lint that flags replication)
    return "sharding={devices=" not in param_rest


def _spec_is_sharded(spec) -> bool:
    try:
        return any(e is not None for e in tuple(spec))
    except TypeError:
        return bool(spec)


def sharding_pass(ctx: PassContext,
                  min_bytes: int = DEFAULT_MIN_BYTES,
                  intended: Optional[Mapping[str, object]] = None,
                  ) -> List[Finding]:
    """Flag large replicated entry parameters and parameter-sized
    all-gathers in a multi-device compiled program.

    ``min_bytes``: replication/gather size that counts as "large".
    ``intended``: ``{arg-path-substring: PartitionSpec}`` — a matching
    replicated arg escalates to ``error``."""
    if ctx.hlo_text is None:
        return [Finding("sharding", "info",
                        "skipped: program was not compiled "
                        "(analyze(..., compile=True) to audit "
                        "sharding)")]
    world = num_partitions(ctx.hlo_text)
    if world <= 1:
        return []
    findings: List[Finding] = []
    intended = dict(intended or {})
    params = entry_parameters(ctx.hlo_text)
    # entry params number KEPT args only (pruned unused args vanish)
    kept = ctx.kept_args
    index_ok = len(params) == len(kept)
    for num, dt, dims, nbytes, rest in params:
        if nbytes < min_bytes or not _is_replicated(rest):
            continue
        arg = kept[num] if index_ok and num < len(kept) else None
        path = arg.path if arg else f"param{num}"
        spec = next((s for k, s in intended.items() if k in path), None)
        wants_shard = spec is not None and _spec_is_sharded(spec)
        sev = "error" if wants_shard else "warning"
        why = (f" but intent declares PartitionSpec {tuple(spec)}"
               if wants_shard else "")
        findings.append(Finding(
            "sharding", sev,
            f"large array {path} ({dt}[{dims}], {nbytes} bytes) is "
            f"fully replicated over {world} devices{why}",
            op=path, dtype=dt, bytes=nbytes))
    # the shared collective regex handles BOTH spellings: sync
    # ``f32[...] all-gather(`` and async tuple-shaped
    # ``(f32[...], f32[...]) all-gather-start(`` (XLA's latency-hiding
    # scheduler prefers the async form for exactly the large transfers
    # this check is about); the result buffer is the largest element.
    for m in _COLLECTIVE_RE.finditer(ctx.hlo_text):
        if m.group("kind") != "all-gather" or m.group("variant") == "-done":
            continue
        elems = _SHAPE_RE.findall(m.group("shape"))
        if not elems:
            continue
        dt, dims = max(elems, key=lambda e: shape_bytes(*e))
        nbytes = shape_bytes(dt, dims)
        if nbytes < min_bytes:
            continue
        findings.append(Finding(
            "sharding", "warning",
            f"parameter-sized all-gather materializes {dt}[{dims}] "
            f"({nbytes} bytes) on every device each step — a weight "
            f"losing its sharding mid-graph looks exactly like this",
            op="all-gather", dtype=dt, bytes=nbytes))
    return findings


register_pass("sharding", sharding_pass)
