"""DECODE_DECOMPOSE_r*.json — schema for the committed decode-step
decomposition artifact.

``tools/decode_decompose.py`` writes one of these per round: a
D64-style device-time bucketing of the b8 decode step — where every
byte of the step's HBM traffic goes (params vs KV read vs KV write vs
attention compute vs sampling vs host sync), derived from a complete
walk of the lowered StableHLO with explicit per-op conventions, and
reconciled against the committed measured decode rate.  VERDICT r5 #6:
b8 runs at 0.43 of the analytic HBM decode ceiling and nothing
explains the gap — this artifact is the explanation's machine-checked
form, and the serve engine's KV layout/dtype choices cite it.

Like MEMLINT/PRECLINT/INCIDENT records, the artifact is gate memory:
``tools/gate_hygiene.py`` validates every committed
``DECODE_DECOMPOSE_r*.json`` against this schema, and the schema
ENFORCES the acceptance bar — the named (non-``other``) buckets must
account for at least :data:`MIN_COVERAGE` of the walked step traffic,
so the decomposition can never rot into a document whose "explanation"
is mostly an unexplained remainder.

This module is deliberately **stdlib-only** (no jax import):
``gate_hygiene`` loads it directly by file path the same way it loads
``analysis/memlint.py`` and ``analysis/preclint.py``.

Document shape::

    {
      "round": 1,
      "platform": "cpu",              # backend the walk lowered for
      "config": {"batch": 8, "prefill": 2048, "new_tokens": 256,
                 "model": "gpt_small_tpu"},
      "method": "stablehlo-walk",     # how the buckets were derived
      "step_bytes": {                 # bytes/step, walk conventions
        "total": 2.1e9,
        "buckets": {"param_read": ..., "kv_read": ..., "kv_write": ...,
                    "attention": ..., "sampling": ..., "host_sync": 0,
                    "other": ...}
      },
      "device_time_fractions": {      # buckets / total (sum ~ 1)
        "param_read": 0.12, ...
      },
      "coverage": 0.97,               # 1 - other fraction, >= 0.9
      "measured": {...},              # committed-rate reconciliation
      "gap_attribution": {...},       # residual vs static candidates
      "note": "..."
    }
"""

from __future__ import annotations

import json
from typing import List

#: every bucket the decomposition must account (``other`` is the
#: explicit remainder; ``host_sync`` is a count-backed bucket that must
#: be 0 bytes for a device-resident token loop)
BUCKETS = ("param_read", "kv_read", "kv_write", "attention",
           "sampling", "host_sync", "other")

#: the acceptance bar: named buckets must cover >= 90% of the step
MIN_COVERAGE = 0.9


def validate_decompose(doc) -> List[str]:
    """Problems with one parsed DECODE_DECOMPOSE document (empty =
    valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("round"), int):
        problems.append("missing/invalid 'round' (int)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    cfg = doc.get("config")
    if not isinstance(cfg, dict) or not all(
            isinstance(cfg.get(k), int)
            for k in ("batch", "prefill", "new_tokens")):
        problems.append("missing/invalid 'config' "
                        "(batch/prefill/new_tokens ints)")
    sb = doc.get("step_bytes")
    buckets = None
    if not isinstance(sb, dict) or not isinstance(sb.get("total"),
                                                  (int, float)):
        problems.append("missing/invalid 'step_bytes' (total + buckets)")
    else:
        buckets = sb.get("buckets")
        if not isinstance(buckets, dict):
            problems.append("'step_bytes' missing 'buckets' object")
            buckets = None
    if buckets is not None:
        for k in BUCKETS:
            v = buckets.get(k)
            if not isinstance(v, (int, float)) or v < 0:
                problems.append(f"bucket {k!r} missing or not a "
                                f"non-negative number: {v!r}")
        total = sb["total"]
        if total > 0:
            s = sum(v for k, v in buckets.items()
                    if isinstance(v, (int, float)))
            if not 0.98 <= s / total <= 1.02:
                problems.append(
                    f"buckets sum to {s:.4g}, not the stated total "
                    f"{total:.4g} — the decomposition must be complete")
    fr = doc.get("device_time_fractions")
    if not isinstance(fr, dict) or not all(
            isinstance(fr.get(k), (int, float)) for k in BUCKETS):
        problems.append("missing/invalid 'device_time_fractions' "
                        "(every bucket)")
        fr = None
    cov = doc.get("coverage")
    if not isinstance(cov, (int, float)):
        problems.append("missing/invalid 'coverage' (number)")
    else:
        if cov < MIN_COVERAGE:
            problems.append(
                f"coverage {cov} under the {MIN_COVERAGE} acceptance "
                f"bar — the named buckets fail to account for the "
                f"step")
        if fr is not None:
            derived = 1.0 - float(fr.get("other", 0.0))
            if abs(cov - derived) > 0.02:
                problems.append(
                    f"coverage {cov} inconsistent with fractions "
                    f"(1 - other = {derived:.4f})")
    if fr is not None:
        s = sum(float(fr[k]) for k in BUCKETS)
        if not 0.95 <= s <= 1.05:
            problems.append(f"device_time_fractions sum to {s:.4f}, "
                            f"expected ~1")
    meas = doc.get("measured")
    if meas is not None and not isinstance(meas, dict):
        problems.append("'measured' present but not an object")
    return problems


def validate_decompose_file(path: str) -> List[str]:
    """Problems with one DECODE_DECOMPOSE_r*.json file (empty =
    valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable decode-decompose JSON: {e}"]
    return validate_decompose(doc)
