"""Host-sync / retrace lint over the lowered step.

A TPU train step is only as fast as its *quietest* iteration: one
hidden host round-trip (an ``io_callback`` buried in a metrics helper,
an infeed/outfeed pair, a ``jax.debug.print`` left enabled) serializes
every step against the Python thread, and one retrace hazard (a
``static_argnums`` step counter, a Python-literal scalar whose dtype
drifts) recompiles the program mid-run.  Both classes are statically
visible: callbacks lower to ``custom_call @xla_python_cpu_callback``
(and friends) in the StableHLO/HLO text, infeed/outfeed are first-class
ops, and the traced signature records which example arguments were
bound statically or traced weak-typed.

Finding codes (``op`` field):

======================  =================================================
``host-callback``       error: ``io_callback`` / ``host_callback`` /
                        infeed / outfeed on the step path — a host
                        sync every iteration
``pure-callback``       warning: ``pure_callback`` — no ordering
                        effect, but the value still round-trips
                        through the host
``debug-callback``      warning: ``jax.debug.print``/``callback`` —
                        fine while debugging, a step-path sync when it
                        ships
``static-scalar``       warning: a numeric example argument was bound
                        STATICALLY at trace time — every new value
                        recompiles the step (step counters and loss
                        scales must be dynamic; shape-determining
                        statics are legitimate and can be ignored)
``weak-scalar``         info: a 0-d argument traced from a Python
                        literal (weak-typed) — passing a typed array
                        for it later is a different signature and
                        retraces
``inplace-read-race``   info: donated-and-aliased buffers are updated
                        in place; host reads of the INPUT array after
                        dispatch race the step (the hazard
                        ``resilience.durable``'s async save snapshots
                        around)
======================  =================================================

The callback classification prefers the compiled HLO metadata
(``op_name="...io_callback..."``) and falls back to StableHLO
attributes (``has_side_effect`` + result arity) when the program
wasn't compiled.
"""

from __future__ import annotations

import re
from typing import List

from apex_tpu.analysis.core import PassContext, register_pass
from apex_tpu.analysis.donation import aliased_parameter_set, kept_index_map
from apex_tpu.analysis.report import Finding

#: custom-call targets that round-trip through the host. The python
#: callback targets cover io/pure/debug callbacks on every backend
#: (cpu/gpu/tpu spellings); the ffi variants are the jax>=0.5 names.
_CALLBACK_TARGETS = (
    "xla_python_cpu_callback", "xla_python_gpu_callback",
    "xla_ffi_python_cpu_callback", "xla_ffi_python_gpu_callback",
    "xla_python_tpu_callback", "tpu_host_callback",
)

_STABLEHLO_CC = re.compile(
    r"stablehlo\.custom_call\s+@(?P<target>[\w.]+)\s*\((?P<operands>[^)]*)\)"
    r"\s*(?P<attrs>\{.*?\})?\s*:\s*(?P<sig>.*)$")
_HLO_CC = re.compile(
    r'custom-call\(.*?custom_call_target="(?P<target>[^"]+)"')
_HLO_OPNAME = re.compile(r'op_name="(?P<opname>[^"]*)"')
_INFEED_RE = re.compile(
    r"(?:stablehlo\.infeed|\binfeed(?:-token)?\()")
_OUTFEED_RE = re.compile(
    r"(?:stablehlo\.outfeed|\boutfeed(?:-token)?\()")


def _classify_stablehlo(line: str) -> str:
    """io / debug / pure from StableHLO attributes: an effectful call
    with results is io_callback, effectful without results is a debug
    print/callback, effect-free is pure_callback."""
    effectful = "has_side_effect = true" in line
    returns_values = not re.search(r"->\s*tuple<\s*>\s*$", line.strip())
    if effectful and returns_values:
        return "io"
    if effectful:
        return "debug"
    return "pure"


def _callback_findings(ctx: PassContext) -> List[Finding]:
    found = []  # (kind, lineno, example)
    if ctx.hlo_text is not None:
        for lineno, line in enumerate(ctx.hlo_text.splitlines(), 1):
            if "custom-call" not in line:
                continue
            m = _HLO_CC.search(line)
            if not m or m.group("target") not in _CALLBACK_TARGETS:
                continue
            nm = _HLO_OPNAME.search(line)
            opname = nm.group("opname") if nm else ""
            if "io_callback" in opname or "host_callback" in opname:
                kind = "io"
            elif "debug" in opname:
                kind = "debug"
            elif "pure_callback" in opname:
                kind = "pure"
            else:
                kind = "io"   # unknown host round-trip: assume the worst
            found.append((kind, lineno, line.strip()[:160]))
    else:
        for lineno, line in enumerate(ctx.stablehlo_text.splitlines(), 1):
            if "stablehlo.custom_call" not in line:
                continue
            m = _STABLEHLO_CC.search(line)
            if not m or m.group("target") not in _CALLBACK_TARGETS:
                continue
            found.append((_classify_stablehlo(line), lineno,
                          line.strip()[:160]))

    sev = {"io": "error", "debug": "warning", "pure": "warning"}
    label = {"io": "host-callback", "debug": "debug-callback",
             "pure": "pure-callback"}
    msg = {
        "io": "io_callback/host_callback on the step path — the step "
              "synchronizes with the Python thread every iteration",
        "debug": "debug callback (jax.debug.print?) on the step path — "
                 "a host sync when it ships; strip it from production "
                 "steps",
        "pure": "pure_callback on the step path — the value "
                "round-trips through the host even without ordering "
                "effects",
    }
    out = []
    for kind, lineno, example in found:
        out.append(Finding("syncs", sev[kind], msg[kind],
                           op=label[kind], lineno=lineno,
                           example=example))
    return out


def _feed_findings(ctx: PassContext) -> List[Finding]:
    text = ctx.hlo_text if ctx.hlo_text is not None \
        else ctx.stablehlo_text
    out = []
    for pattern, what in ((_INFEED_RE, "infeed"), (_OUTFEED_RE,
                                                   "outfeed")):
        hits = [i for i, line in enumerate(text.splitlines(), 1)
                if pattern.search(line)]
        if hits:
            out.append(Finding(
                "syncs", "error",
                f"{what} op(s) inside the step — host-driven data "
                f"feeding serializes the step against the host; use "
                f"device-resident prefetch instead",
                op="host-callback", count=len(hits), lineno=hits[0]))
    return out


def _retrace_findings(ctx: PassContext) -> List[Finding]:
    out = []
    for label, typename, value in ctx.static_scalars:
        if label == "ambiguous":
            # the traced signature cannot say WHICH argument was
            # static — info, not warning: a false warning would tell
            # the user to fix an already-dynamic argument
            out.append(Finding(
                "syncs", "info",
                f"{value} — the traced signature cannot say which was "
                f"bound statically; if one of the numeric candidates "
                f"varies per step (step counter, loss scale) it "
                f"recompiles on every new value and must be dynamic",
                op="static-scalar"))
            continue
        out.append(Finding(
            "syncs", "warning",
            f"example argument {label}={value} ({typename}) was bound "
            f"STATICALLY at trace time — every new value recompiles "
            f"the step.  Step counters / loss scales must be dynamic "
            f"args; shape-determining statics (sequence lengths, "
            f"layer counts) are fine",
            op="static-scalar"))
    for a in ctx.kept_args:
        if a.weak_type and a.shape == ():
            out.append(Finding(
                "syncs", "info",
                f"scalar argument {a.path or a.index} traced from a "
                f"Python literal (weak-typed {a.dtype}) — a typed "
                f"array for the same argument is a different "
                f"signature and retraces; pin it with "
                f"jnp.asarray(v, dtype) if the producer varies",
                op="weak-scalar", dtype=a.dtype))
    return out


def _inplace_race_findings(ctx: PassContext) -> List[Finding]:
    if ctx.hlo_text is None:
        return []
    donated = [a for a in ctx.kept_args if a.donated]
    if not donated:
        return []
    kept_pos = kept_index_map(ctx)
    if kept_pos is None:   # ambiguous numbering: don't guess (see
        return []          # donation.kept_index_map)
    aliased = aliased_parameter_set(ctx)
    inplace = [a for a in donated if kept_pos[a.index] in aliased]
    if not inplace:
        return []
    total = sum(a.nbytes for a in inplace)
    return [Finding(
        "syncs", "info",
        f"{len(inplace)} donated input(s) update in place "
        f"({total} bytes): host reads of the INPUT arrays after "
        f"dispatch race the step's in-place write — snapshot (or "
        f"jax.block_until_ready) before any async consumer reads "
        f"them, as resilience.durable's async save does",
        op="inplace-read-race", bytes=total, count=len(inplace))]


def syncs_pass(ctx: PassContext) -> List[Finding]:
    """Host-sync, retrace-hazard, and in-place-read-race lint (see the
    module docstring for the finding codes)."""
    return (_callback_findings(ctx) + _feed_findings(ctx)
            + _retrace_findings(ctx) + _inplace_race_findings(ctx))


register_pass("syncs", syncs_pass)
