"""TRACE_r*.json — schema for the committed request-trace artifact.

``tools/trace_report.py`` runs the disaggregated c16 chaos drill with
request tracing on (:mod:`apex_tpu.obs.reqtrace`) and commits the
resulting lifecycle document: every request's event list and span
tree, the fleet engines' own token-counter deltas, the chaos block
naming the killed replica, and a gate verdict.  Like every other gate
artifact the document is **contradiction-rejecting** — a trace that
disagrees with itself is schema-INVALID, so the committed artifact
cannot rot into a story nobody re-derived:

- **span trees must nest** — every non-root span's interval must sit
  inside its parent's, parents must precede children, and there is
  exactly one root;
- **token accounting must close** — each request's ``tokens`` must
  equal the sum of its token-carrying events, and the fleet total must
  equal the engines' own ``serve_tokens_total`` deltas (the trace and
  the metrics registry are two witnesses of the same stream; when they
  disagree, one of them is lying);
- **every reroute must name a killed replica** — a ``reroute`` event
  citing a replica the chaos block never killed (or a chaos block
  whose rerouted uids carry no reroute events) is a fabricated
  recovery story;
- **the gate must agree with its own numbers** — ``gate.tokens_ok``
  is re-derived from the accounting above and ``gate.ok`` must be
  exactly ``bitwise_ok and tokens_ok``.

Event vocabulary and lifecycle shape are pinned to
:data:`apex_tpu.obs.reqtrace.EVENT_KINDS` (duplicated here because
this module must stay **stdlib-only** — ``tools/gate_hygiene.py``
loads it directly by file path, never paying the jax import; a test
asserts the two tuples are equal).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

#: pinned copy of apex_tpu.obs.reqtrace.EVENT_KINDS (stdlib-only rule;
#: equality asserted by tests/l0/test_reqtrace.py)
EVENT_KINDS = (
    "enqueue", "admit", "prefill_chunk", "kv_ship", "kv_install",
    "decode_step", "spec_draft", "spec_verify", "preempt", "reroute",
    "retire",
)

#: event kinds whose ``tokens`` fields sum to the request's accounting
TOKEN_KINDS = ("admit", "decode_step", "spec_verify")


def _num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _int(v: Any) -> bool:
    return isinstance(v, int) and not isinstance(v, bool)


def _validate_events(uid: str, events: Any) -> List[str]:
    problems: List[str] = []
    if not isinstance(events, list) or not events:
        return [f"requests[{uid}]: 'events' must be a non-empty list"]
    last_seq, last_ts = None, None
    for i, ev in enumerate(events):
        tag = f"requests[{uid}].events[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{tag}: must be an object")
            continue
        if ev.get("kind") not in EVENT_KINDS:
            problems.append(
                f"{tag}: kind {ev.get('kind')!r} outside the "
                f"vocabulary {EVENT_KINDS}")
        if not (isinstance(ev.get("where"), str)
                and ev["where"].strip()):
            problems.append(f"{tag}: missing non-empty str 'where'")
        seq, ts = ev.get("seq"), ev.get("ts")
        if not _int(seq):
            problems.append(f"{tag}: missing int 'seq'")
        elif last_seq is not None and seq <= last_seq:
            problems.append(
                f"{tag}: seq {seq} does not increase past {last_seq}")
        else:
            last_seq = seq
        if not _num(ts):
            problems.append(f"{tag}: missing numeric 'ts'")
        elif last_ts is not None and ts < last_ts:
            problems.append(
                f"{tag}: ts {ts} precedes its predecessor {last_ts}")
        else:
            last_ts = ts
        if "tokens" in ev and not (_int(ev["tokens"])
                                   and ev["tokens"] >= 0):
            problems.append(f"{tag}: 'tokens' must be an int >= 0")
    if problems:
        return problems
    if events[0]["kind"] != "enqueue":
        problems.append(
            f"requests[{uid}]: lifecycle must begin with 'enqueue', "
            f"got {events[0]['kind']!r}")
    retires = [i for i, e in enumerate(events) if e["kind"] == "retire"]
    if len(retires) != 1 or retires[0] != len(events) - 1:
        problems.append(
            f"requests[{uid}]: lifecycle must end with exactly one "
            f"'retire' (found at {retires})")
    return problems


def _validate_spans(uid: str, spans: Any) -> List[str]:
    """Span-tree nesting: one root, parents precede children, child
    intervals inside parent intervals."""
    problems: List[str] = []
    if not isinstance(spans, list) or not spans:
        return [f"requests[{uid}]: 'spans' must be a non-empty list"]
    roots = 0
    for i, sp in enumerate(spans):
        tag = f"requests[{uid}].spans[{i}]"
        if not isinstance(sp, dict):
            problems.append(f"{tag}: must be an object")
            continue
        if not (isinstance(sp.get("name"), str) and sp["name"].strip()):
            problems.append(f"{tag}: missing non-empty str 'name'")
        t0, t1 = sp.get("t0"), sp.get("t1")
        if not (_num(t0) and _num(t1) and t0 <= t1):
            problems.append(f"{tag}: needs numeric t0 <= t1, got "
                            f"({t0!r}, {t1!r})")
            continue
        parent = sp.get("parent")
        if not _int(parent):
            problems.append(f"{tag}: missing int 'parent'")
            continue
        if parent == -1:
            roots += 1
            continue
        if not 0 <= parent < i:
            problems.append(
                f"{tag}: parent {parent} must index an EARLIER span")
            continue
        pa = spans[parent]
        if isinstance(pa, dict) and _num(pa.get("t0")) \
                and _num(pa.get("t1")) \
                and not (pa["t0"] <= t0 and t1 <= pa["t1"]):
            problems.append(
                f"{tag}: CONTRADICTION — span [{t0}, {t1}] does not "
                f"nest inside its parent [{pa['t0']}, {pa['t1']}]; "
                f"span trees must nest")
    if roots != 1:
        problems.append(
            f"requests[{uid}]: spans must carry exactly one root "
            f"(parent == -1), found {roots}")
    return problems


def validate_trace(doc: Any) -> List[str]:
    """Problems with one parsed TRACE document (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not _int(doc.get("round")):
        problems.append("missing/invalid 'round' (int)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    if not isinstance(doc.get("config"), dict):
        problems.append("missing/invalid 'config' object")

    reqs = doc.get("requests")
    if not isinstance(reqs, dict) or not reqs:
        problems.append("missing/empty 'requests' object")
        return problems

    token_total = 0
    reroute_uids = set()
    reroute_from: Dict[str, List[int]] = {}
    for uid, rec in reqs.items():
        if not isinstance(rec, dict):
            problems.append(f"requests[{uid}]: must be an object")
            continue
        if not (isinstance(rec.get("trace_id"), str)
                and rec["trace_id"].strip()):
            problems.append(
                f"requests[{uid}]: missing non-empty 'trace_id'")
        ev_problems = _validate_events(uid, rec.get("events"))
        problems.extend(ev_problems)
        problems.extend(_validate_spans(uid, rec.get("spans")))
        if ev_problems:
            continue
        events = rec["events"]
        ev_tokens = sum(int(e.get("tokens", 0)) for e in events)
        if not (_int(rec.get("tokens")) and rec["tokens"] >= 0):
            problems.append(
                f"requests[{uid}]: missing int 'tokens' >= 0")
        elif rec["tokens"] != ev_tokens:
            problems.append(
                f"requests[{uid}]: CONTRADICTION — recorded tokens "
                f"{rec['tokens']} != {ev_tokens} summed over the "
                f"request's own token-carrying events")
        token_total += ev_tokens
        for e in events:
            if e["kind"] == "reroute":
                reroute_uids.add(uid)
                if _int(e.get("from_replica")):
                    reroute_from.setdefault(uid, []).append(
                        e["from_replica"])
                else:
                    problems.append(
                        f"requests[{uid}]: reroute event missing int "
                        f"'from_replica' — every reroute must name "
                        f"the replica that died")

    # -- engine-counter cross-check (the trace's second witness) -------
    eng = doc.get("engine")
    if not isinstance(eng, dict):
        problems.append("missing/invalid 'engine' object")
    else:
        per = eng.get("serve_tokens_total")
        delta = eng.get("delta_total")
        if not (isinstance(per, dict) and per
                and all(_num(v) for v in per.values())):
            problems.append(
                "engine missing non-empty numeric "
                "'serve_tokens_total' per-engine table")
        if not _int(delta):
            problems.append("engine missing int 'delta_total'")
        else:
            if isinstance(per, dict) and per \
                    and all(_num(v) for v in per.values()) \
                    and delta != round(sum(per.values())):
                problems.append(
                    f"engine: CONTRADICTION — delta_total {delta} != "
                    f"{round(sum(per.values()))} summed over its own "
                    f"per-engine table")
            if delta != token_total:
                problems.append(
                    f"CONTRADICTION — the trace accounts "
                    f"{token_total} decode tokens but the engines' "
                    f"serve_tokens_total delta is {delta}; the trace "
                    f"and the registry are two witnesses of one "
                    f"stream and must agree")

    # -- chaos / reroute consistency -----------------------------------
    chaos = doc.get("chaos")
    if reroute_uids and not isinstance(chaos, dict):
        problems.append(
            "requests carry reroute events but the document has no "
            "'chaos' block naming what was killed")
    if isinstance(chaos, dict):
        killed = chaos.get("killed")
        if not (isinstance(killed, list)
                and all(_int(k) for k in killed)):
            problems.append("chaos.killed must be a list of replica "
                            "ints")
            killed = []
        for uid, sources in reroute_from.items():
            for src in sources:
                if src not in killed:
                    problems.append(
                        f"requests[{uid}]: CONTRADICTION — reroute "
                        f"names replica {src}, which chaos.killed "
                        f"{killed} never lost")
        listed = chaos.get("rerouted")
        if not (isinstance(listed, list)
                and all(isinstance(u, str) for u in listed)):
            problems.append("chaos.rerouted must be a list of uids")
        elif set(listed) != reroute_uids:
            problems.append(
                f"CONTRADICTION — chaos.rerouted {sorted(listed)} != "
                f"uids with reroute events {sorted(reroute_uids)}")

    # -- gate: must agree with its own numbers -------------------------
    gate = doc.get("gate")
    if not isinstance(gate, dict):
        problems.append("missing/invalid 'gate' object")
    else:
        for key in ("bitwise_ok", "tokens_ok", "ok"):
            if not isinstance(gate.get(key), bool):
                problems.append(f"gate missing bool {key!r}")
        if isinstance(gate.get("tokens_ok"), bool) \
                and isinstance(eng, dict) and _int(eng.get("delta_total")):
            derived = eng["delta_total"] == token_total
            if gate["tokens_ok"] != derived:
                problems.append(
                    f"gate.tokens_ok {gate['tokens_ok']} contradicts "
                    f"the re-derived accounting verdict {derived}")
        if all(isinstance(gate.get(k), bool)
               for k in ("bitwise_ok", "tokens_ok", "ok")) \
                and gate["ok"] != (gate["bitwise_ok"]
                                   and gate["tokens_ok"]):
            problems.append(
                "gate.ok must be exactly bitwise_ok and tokens_ok — "
                "a verdict contradicting its own components is "
                "schema-invalid")
    return problems


def validate_trace_file(path: str) -> List[str]:
    """Problems with one TRACE_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable trace JSON: {e}"]
    return validate_trace(doc)
