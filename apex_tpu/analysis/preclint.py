"""PRECLINT_r*.json — schema for the committed precision-lint artifact.

``tools/graph_lint.py --emit-json PRECLINT_rN.json`` writes one of
these per round: the precision verdict of every lint lane — all four
model families at every opt level O0–O3 plus the decode lanes — as
produced by the precision pass (:mod:`apex_tpu.analysis.precision`).
Like MEMLINT and the incident records, the artifact is gate memory:
``tools/gate_hygiene.py`` validates every committed ``PRECLINT_r*.json``
against this schema so the precision story can't rot into prose nobody
machine-checks.

This module is deliberately **stdlib-only** (no jax import):
``gate_hygiene`` loads it directly by file path the same way it loads
``analysis/memlint.py`` and ``resilience/incidents.py``.

Document shape::

    {
      "round": 1,
      "platform": "cpu",            # backend the lanes lowered for
      "half_dtype": "bfloat16",     # the policies' 16-bit dtype
      "lanes": {
        "<lane>": {                 # e.g. "mlp_o1_train", "decode_b1"
          "ok": true,               # no error-severity finding
          "findings": {"error": 0, "warning": 0, "info": 1},
          "checked": {              # the pass's evidence counters
            "dots": 5, "reduces": 9, "converts": 6,
            "collectives": 0, "scale_args": 1,
            "scale_applied": 1, "unscaled": 4
          }
        }, ...
      }
    }
"""

from __future__ import annotations

import json
from typing import List

#: counters every lane's ``checked`` table must carry
_CHECKED_KEYS = ("dots", "reduces", "converts", "collectives",
                 "scale_args", "scale_applied", "unscaled")

_LANE_REQUIRED = {
    "ok": lambda v: isinstance(v, bool),
    "findings": lambda v: isinstance(v, dict) and all(
        isinstance(n, int) and n >= 0 for n in v.values()),
    "checked": lambda v: isinstance(v, dict) and all(
        isinstance(v.get(k), int) and v[k] >= 0 for k in _CHECKED_KEYS),
}


def validate_preclint(doc) -> List[str]:
    """Problems with one parsed PRECLINT document (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("round"), int):
        problems.append("missing/invalid 'round' (int)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    if not isinstance(doc.get("half_dtype"), str):
        problems.append("missing/invalid 'half_dtype' (str)")
    lanes = doc.get("lanes")
    if not isinstance(lanes, dict) or not lanes:
        return problems + ["missing/empty 'lanes' object"]
    for name, lane in lanes.items():
        if not isinstance(lane, dict):
            problems.append(f"lane {name!r} is not an object")
            continue
        for key, check in _LANE_REQUIRED.items():
            if key not in lane:
                problems.append(f"lane {name!r} missing {key!r}")
            elif not check(lane[key]):
                problems.append(f"lane {name!r} has invalid {key!r}: "
                                f"{lane[key]!r}")
        # a lane claiming ok while recording error findings (or vice
        # versa) is internally inconsistent — the verdict must be
        # derivable from the document alone
        if isinstance(lane.get("findings"), dict) and \
                isinstance(lane.get("ok"), bool):
            has_errors = lane["findings"].get("error", 0) > 0
            if lane["ok"] == has_errors:
                problems.append(
                    f"lane {name!r}: ok={lane['ok']} contradicts "
                    f"findings {lane['findings']}")
    return problems


def validate_preclint_file(path: str) -> List[str]:
    """Problems with one PRECLINT_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable preclint JSON: {e}"]
    return validate_preclint(doc)
