"""Dtype-dataflow walker over pre-optimization StableHLO text.

The precision pass (:mod:`apex_tpu.analysis.precision`) needs more than
the per-line opcode scan the policy audit uses: it must follow VALUES —
"the loss-scale argument, broadcast and negated, multiplies the
backward cotangent; the gradients it taints are cleared by a multiply
with the reciprocal before they reach the optimizer update".  This
module is the shared SSA machinery for that: a pragmatic, line-based
parser of the lowered module into per-function op lists with

- result / operand value tokens (``%33``, ``%33#17``, ``%iterArg_4``),
- every ``tensor<...>`` type payload on the line, in order,
- region tracking: ``while``/``case``/generic-``reduce`` bodies are
  attributed to their owning op, ``stablehlo.return`` operand lists are
  collected per owner (per-branch for ``case``), and ``while`` header
  bindings (``%iterArg_k = %value``) are recorded as aliases,
- per-function use counts (who consumes each value).

It is a FORWARD, single-pass view: loop-carried dataflow is resolved
through the header bindings only (no fixed point), and values passed
into private functions are opaque — a caller-visible class can enter a
``call`` but cannot be transformed inside it.  That is conservative in
the direction the precision pass needs (taint can only be cleared by
ops the walker actually sees; see ``precision.py`` for the rules), and
it keeps the walk O(lines) on the multi-thousand-line lowerings the
lanes produce.

The parse is deliberately text-anchored (the same stance as
``analysis/policy.py``): pre-optimization StableHLO is the program the
user asked for, printed identically across backends, so the walker's
findings cannot be hidden by a backend that legalizes 16-bit math to
fp32 internally (XLA:CPU does).
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Dict, List, Optional, Tuple

_TENSOR = re.compile(r"tensor<([^<>]*)>")
_FUNC = re.compile(
    r"func\.func\s+(?:public\s+|private\s+)?@([\w$.-]+)\s*\((.*)$")
_ARG = re.compile(r"(%\w+):\s*tensor<([^<>]*)>")
_RESULT_INFO = re.compile(r'jax\.result_info\s*=\s*"([^"]*)"')
_OP = re.compile(
    r"^\s*(?:(%\w+(?:\s*,\s*%\w+)*)(?::(\d+))?\s*=\s*)?"
    r"\"?((?:stablehlo|chlo|mhlo|func)\.[\w]+|call|return)\b\"?")
_VALUE = re.compile(r"%[\w]+(?:#\d+)?")
_BIND = re.compile(r"(%\w+)\s*=\s*(%[\w]+(?:#\d+)?)")
_DIMS = re.compile(r"across dimensions = \[([0-9, ]*)\]")


def element_type(payload: str) -> str:
    """``"4x32xbf16"`` -> ``"bf16"``; ``"f32"`` -> ``"f32"``."""
    return payload.split("x")[-1].strip()


def dims_of(payload: str) -> Tuple[int, ...]:
    """Leading integer dims of a tensor payload (``?`` dims skipped)."""
    out = []
    for part in payload.split("x")[:-1]:
        try:
            out.append(int(part))
        except ValueError:
            pass
    return tuple(out)


def base_token(token: str) -> str:
    """``"%33#17"`` -> ``"%33"``."""
    return token.split("#", 1)[0]


@dataclasses.dataclass
class Op:
    """One operation line of a function body."""

    lineno: int
    line: str
    name: str                        # short opcode ("dot_general", ...)
    result: Optional[str]            # base result token ("%33")
    n_results: int
    operands: Tuple[str, ...]        # value tokens as written
    types: Tuple[str, ...]           # tensor<> payloads, line order
    depth: int                       # region nesting inside the body
    #: ``stablehlo.return`` operand lists of regions this op owns
    region_returns: List[Tuple[str, ...]] = dataclasses.field(
        default_factory=list)
    #: enclosing region-owner ops, outermost first (``while``/``case``/
    #: ``if``/``reduce``/... bodies this op's line sits inside)
    owners: Tuple["Op", ...] = ()
    #: every result token: ``("%33",)`` for the common case, the named
    #: list for ``%values, %indices = chlo.top_k(...)``-style prints
    #: (consumers reference the names directly, not ``%33#k``)
    results: Tuple[str, ...] = ()

    @property
    def result_type(self) -> Optional[str]:
        return self.types[-1] if self.types else None

    @property
    def result_elem(self) -> Optional[str]:
        t = self.result_type
        return element_type(t) if t else None

    def operand_elems(self) -> Tuple[str, ...]:
        """Element types of the value operands: with a full signature on
        the line the leading payloads are the operand types; a
        single-payload (elementwise) line means operands and result all
        share it."""
        if len(self.types) >= 2:
            return tuple(element_type(t) for t in self.types[:-1])
        if self.types:
            return (element_type(self.types[0]),) * max(len(self.operands), 1)
        return ()

    def reduce_dims(self) -> Tuple[int, ...]:
        m = _DIMS.search(self.line)
        if not m or not self.types:
            return ()
        shape = dims_of(self.types[0])
        out = []
        for tok in m.group(1).split(","):
            tok = tok.strip()
            if tok.isdigit() and int(tok) < len(shape):
                out.append(shape[int(tok)])
        return tuple(out)

    def reduced_elems(self) -> int:
        """Number of elements folded into each output element."""
        d = self.reduce_dims()
        return int(math.prod(d)) if d else 1


@dataclasses.dataclass
class FuncDef:
    """One ``func.func`` of the lowered module."""

    name: str
    lineno: int
    args: List[Tuple[str, str]]          # (token, tensor payload)
    result_info: List[str]               # jax.result_info strings
    ops: List[Op] = dataclasses.field(default_factory=list)
    returns: List[Op] = dataclasses.field(default_factory=list)
    #: ``%iterArg_k`` -> bound value token (while header bindings)
    aliases: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: base token -> number of operand uses across the body
    use_count: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: base token -> ops consuming it
    consumers: Dict[str, List[Op]] = dataclasses.field(default_factory=dict)

    def resolve(self, token: str) -> str:
        """Follow while-header aliases to the bound value's base token."""
        seen = set()
        tok = base_token(token)
        while tok in self.aliases and tok not in seen:
            seen.add(tok)
            tok = base_token(self.aliases[tok])
        return tok


#: ops whose single line opens a region body on the following lines
_REGION_HINTS = ("while", "case", "if", "reduce", "sort", "scatter",
                 "reduce_window", "map")


def parse_module(text: str) -> Dict[str, FuncDef]:
    """Parse the lowered module text into ``{func_name: FuncDef}``."""
    funcs: Dict[str, FuncDef] = {}
    cur: Optional[FuncDef] = None
    depth = 0                      # brace depth inside the current func
    region_stack: List[Tuple[Op, int]] = []
    last_op: Optional[Op] = None

    for lineno, line in enumerate(text.splitlines(), 1):
        if cur is None:
            fm = _FUNC.search(line)
            if fm:
                cur = FuncDef(
                    name=fm.group(1), lineno=lineno,
                    args=_ARG.findall(line),
                    result_info=_RESULT_INFO.findall(line))
                funcs[cur.name] = cur
                depth = 1
                region_stack = []
                last_op = None
            continue

        opens = line.count("{")
        closes = line.count("}")
        om = _OP.search(line)
        op = None
        if om:
            result_toks = tuple(_VALUE.findall(om.group(1))) \
                if om.group(1) else ()
            result = result_toks[0] if result_toks else None
            n_results = int(om.group(2)) if om.group(2) \
                else max(len(result_toks), 1)
            name = om.group(3).split(".")[-1]
            tail = line
            if result is not None:
                tail = line.split("=", 1)[1]
            binds = [] if result is None else _BIND.findall(tail)
            if name == "while" and binds:
                operands = tuple(v for _k, v in binds)
                for k, v in binds:
                    cur.aliases[k] = v
            else:
                # strip the attribute/type tail: tokens to the left of
                # the first " : " are the value operands (type payloads
                # never contain %, but dims attrs follow operands)
                operands = tuple(_VALUE.findall(tail.split(" : ")[0]))
            op = Op(lineno=lineno, line=line, name=name, result=result,
                    n_results=n_results, operands=operands,
                    types=tuple(_TENSOR.findall(line)), depth=depth - 1,
                    owners=tuple(o for o, _d in region_stack),
                    results=result_toks)
            if name == "return":
                if depth == 1 and "stablehlo" not in om.group(3):
                    cur.returns.append(op)
                elif region_stack:
                    region_stack[-1][0].region_returns.append(operands)
            else:
                cur.ops.append(op)
                for tok in operands:
                    b = base_token(tok)
                    cur.use_count[b] = cur.use_count.get(b, 0) + 1
                    cur.consumers.setdefault(b, []).append(op)
                last_op = op

        if opens > closes:
            owner = op if (op is not None and op.name in _REGION_HINTS) \
                else last_op
            if owner is not None:
                for _ in range(opens - closes):
                    region_stack.append((owner, depth))
        depth += opens - closes
        while region_stack and depth <= region_stack[-1][1]:
            owner, _d = region_stack.pop()
            # region-bodied ops (all_reduce, multi-line case) print the
            # real type signature on the closing "}) : (...) -> ..."
            # line — override the attr-dict noise captured from the
            # header so dtype checks see the op's element types
            if re.match(r"^\s*\}+\)*\s*:", line):
                tail_types = _TENSOR.findall(line)
                if tail_types:
                    owner.types = tuple(tail_types)
        if depth <= 0:
            cur = None
    return funcs


def main_func(funcs: Dict[str, FuncDef]) -> Optional[FuncDef]:
    if "main" in funcs:
        return funcs["main"]
    return next(iter(funcs.values()), None)
