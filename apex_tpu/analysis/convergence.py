"""CONVERGENCE_r*.json — schema for the committed convergence artifacts.

``tools/convergence_run.py`` writes one per round: the loss-curve /
recovery / decode-fidelity evidence the ROADMAP's convergence story
rests on.  Like the PRECLINT/MEMLINT/INCIDENT artifacts, these are gate
memory — ``tools/gate_hygiene.py`` validates every committed
``CONVERGENCE_r*.json`` against this schema so the convergence story
can't rot into numbers nobody machine-checks.

This module is deliberately **stdlib-only** (no jax import):
``gate_hygiene`` loads it directly by file path.

Two document shapes are valid (both exist in-tree):

- the legacy single-record shape (round 2: one imagenet record with a
  top-level ``ok`` bool and ``platform``);
- the multi-record shape (round 3+): ``platform``, ``all_ok`` (bool),
  and one dict per lane (``gpt_pysrc``, ``o4_mnist``,
  ``int8_kv_decode``, ...), each carrying its own ``ok`` bool — except
  ``anchors``, the external-baseline record that has no pass/fail of
  its own.  ``all_ok`` must equal the conjunction of the lanes' ``ok``
  flags (the verdict must be derivable from the document alone).
"""

from __future__ import annotations

import json
from typing import List

#: record keys that are metadata, not pass/fail lanes
_NON_LANE_KEYS = ("anchors",)


def validate_convergence(doc) -> List[str]:
    """Problems with one parsed CONVERGENCE document (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    if isinstance(doc.get("ok"), bool) and "all_ok" not in doc:
        # legacy single-record shape: the document IS the lane
        return problems
    if not isinstance(doc.get("all_ok"), bool):
        return problems + [
            "missing/invalid 'all_ok' (bool; or legacy top-level 'ok')"]
    lanes = {k: v for k, v in doc.items()
             if isinstance(v, dict) and k not in _NON_LANE_KEYS}
    if not lanes:
        return problems + ["no lane records (dict values)"]
    oks = []
    for name, lane in lanes.items():
        if not isinstance(lane.get("ok"), bool):
            problems.append(f"lane {name!r} missing 'ok' (bool)")
        else:
            oks.append(lane["ok"])
    if oks and not problems and doc["all_ok"] != all(oks):
        problems.append(
            f"all_ok={doc['all_ok']} contradicts the lanes' ok flags "
            f"(conjunction is {all(oks)})")
    return problems


def validate_convergence_file(path: str) -> List[str]:
    """Problems with one CONVERGENCE_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable convergence JSON: {e}"]
    return validate_convergence(doc)
