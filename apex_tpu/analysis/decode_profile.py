"""DECODE_PROFILE_r*.json — schema for the committed decode-step
*profile* artifact (the measured counterpart of DECODE_DECOMPOSE).

``tools/profile_decode.py`` writes one of these per round: an xplane
capture of the exact b8 decode bench program, with measured device
time bucketed — via :mod:`apex_tpu.obs.xplane` and a classifier built
from the compiled HLO — into the SAME named buckets the static walk
(``tools/decode_decompose.py`` / ``DECODE_DECOMPOSE_r01.json``) uses.
Matching bucket tables are the whole point: the static walk *predicts*
where the step's time goes (kv_read 0.69, the 709 MB slice-copy
residual); the profile *measures* it, and the two documents reconcile
bucket-by-bucket.  The r01 artifact is the CPU-xplane smoke proving
the capture→bucket pipeline; the on-chip capture that confirms or
refutes the slice-copy attribution is the next driver round's run of
the same tool.

Like the other round artifacts this is gate memory:
``tools/gate_hygiene.py`` validates every committed
``DECODE_PROFILE_r*.json`` here.  Deliberately **stdlib-only** (no
jax): gate_hygiene loads it by file path.

Document shape::

    {
      "round": 1,
      "platform": "cpu",               # backend of the capture
      "config": {"batch": 8, "prefill": 2048, "new_tokens": 256,
                 "model": "gpt_small_tpu"},
      "method": "xplane-capture",
      "capture": {"iters": 2, "total_ps": ..., "matched_frac": 0.97,
                  "source": "xplane-host"},
      "device_time_ps": {"param_read": ..., ..., "other": ...},
      "device_time_fractions": {...},  # sum ~ 1
      "coverage": 0.95,                # 1 - other fraction
      "decompose_ref": {...},          # optional: the walk's fractions
      "verdict": "...",
      "note": "..."
    }
"""

from __future__ import annotations

import json
from typing import List

#: the named buckets — MUST equal
#: ``apex_tpu.analysis.decode_decompose.BUCKETS`` (duplicated here
#: because gate_hygiene loads each schema module standalone by file
#: path; ``tests/l0/test_obs.py`` pins the two tuples equal)
BUCKETS = ("param_read", "kv_read", "kv_write", "attention",
           "sampling", "host_sync", "other")


def validate_profile(doc) -> List[str]:
    """Problems with one parsed DECODE_PROFILE document (empty =
    valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("round"), int):
        problems.append("missing/invalid 'round' (int)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    cfg = doc.get("config")
    if not isinstance(cfg, dict) or not all(
            isinstance(cfg.get(k), int)
            for k in ("batch", "prefill", "new_tokens")):
        problems.append("missing/invalid 'config' "
                        "(batch/prefill/new_tokens ints)")
    if not isinstance(doc.get("method"), str):
        problems.append("missing/invalid 'method' (str)")

    cap = doc.get("capture")
    if not isinstance(cap, dict):
        problems.append("missing/invalid 'capture' object")
    else:
        if not (isinstance(cap.get("iters"), int) and cap["iters"] >= 1):
            problems.append("capture missing positive int 'iters'")
        total = cap.get("total_ps")
        if not (isinstance(total, int) and total > 0):
            problems.append("capture missing positive 'total_ps' — an "
                            "empty capture explains nothing")
        if not isinstance(cap.get("source"), str):
            problems.append("capture missing 'source' (str)")

    ps = doc.get("device_time_ps")
    if not isinstance(ps, dict):
        problems.append("missing/invalid 'device_time_ps' object")
    else:
        for k in BUCKETS:
            v = ps.get(k)
            if not isinstance(v, int) or v < 0:
                problems.append(f"device_time_ps bucket {k!r} missing "
                                f"or not a non-negative int: {v!r}")
        extra = set(ps) - set(BUCKETS)
        if extra:
            problems.append(
                f"device_time_ps carries unknown buckets {sorted(extra)}"
                f" — the profile and the static walk must share one "
                f"bucket vocabulary")

    fr = doc.get("device_time_fractions")
    if not isinstance(fr, dict) or not all(
            isinstance(fr.get(k), (int, float)) for k in BUCKETS):
        problems.append("missing/invalid 'device_time_fractions' "
                        "(every bucket)")
        fr = None
    else:
        s = sum(float(fr[k]) for k in BUCKETS)
        if not 0.95 <= s <= 1.05:
            problems.append(f"device_time_fractions sum to {s:.4f}, "
                            f"expected ~1")

    cov = doc.get("coverage")
    if not isinstance(cov, (int, float)):
        problems.append("missing/invalid 'coverage' (number)")
    elif fr is not None:
        derived = 1.0 - float(fr.get("other", 0.0))
        if abs(cov - derived) > 0.02:
            problems.append(f"coverage {cov} inconsistent with "
                            f"fractions (1 - other = {derived:.4f})")

    if not (isinstance(doc.get("verdict"), str)
            and doc["verdict"].strip()):
        problems.append("missing/empty 'verdict' (str) — the profile "
                        "must state what it confirms or refutes")
    return problems


def validate_profile_file(path: str) -> List[str]:
    """Problems with one DECODE_PROFILE_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable decode-profile JSON: {e}"]
    return validate_profile(doc)
