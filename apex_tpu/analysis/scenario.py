"""SCENARIO_r*.json — schema for the committed serve scenario-matrix
gate artifact.

``tools/serve_scenarios.py`` writes one of these per round: the serve
engine driven through a MATRIX of scenarios — mixed context lengths,
burst vs steady arrivals, per-slot sampling knobs, slot churn /
preemption, the int8 KV cache on/off, speculative decoding on/off —
with every cell carrying its own latency-tail gate (``p99 <= K * p50``
and ``retraces == 1``) and the spec-enabled cells paired against their
baselines in a tokens-per-decode-step A/B.  "Handles many scenarios"
thereby becomes a committed, machine-checked artifact instead of a
claim, and the speculative-decoding latency win is a gated number.

Contradiction rejection, like every gate schema in this family: a
cell's recorded ``gate`` verdict must AGREE with its own numbers (the
tail bound re-derived from p50/p99 and ``gate_k``, the retrace bound
from ``retraces``), an A/B row's ``spec_wins`` must agree with the two
tokens-per-step numbers it cites (which must in turn match the cells
they cite), and the document verdict must be the conjunction of every
cell gate plus every GATED A/B win — so the artifact can never say
"ok" over numbers that derive otherwise.

The committed round must cover at least :data:`MIN_CELLS` cells —
the scenario matrix is the point; a two-cell document is not one.

This module is deliberately **stdlib-only** (no jax import):
``tools/gate_hygiene.py`` loads it directly by file path in tier-1.

Document shape::

    {
      "round": 1,
      "platform": "cpu",
      "model": "gpt_tiny",
      "gate_k": 20.0,               # the p99 <= K * p50 multiplier
      "cells": {
        "ctx128_steady_greedy": {
          "config": {"context": 128, "new_tokens": 16, "num_slots": 4,
                     "arrival": "steady", "sampling": "greedy",
                     "kv8": false, "spec": false, "churn": false},
          "tok_s": ..., "p50_ms": ..., "p99_ms": ...,
          "decode_steps": ..., "decode_tokens": ...,
          "tokens_per_step": ..., "retraces": 1, "preemptions": 0,
          "acceptance_rate": 0.62,           # spec cells only
          "prefix": {"probes": 4, "hits": 3,  # optional: engines with
                     "hit_rate": 0.75},       # the prefix cache on
          "gate": {"tail_ok": true, "retrace_ok": true, "ok": true}
        }, ...
      },
      "ab": [
        {"on": "ctx128_steady_greedy_spec", "off": "ctx128_steady_greedy",
         "tokens_per_step_on": 1.9, "tokens_per_step_off": 1.0,
         "spec_wins": true, "gated": true},
        ...
      ],
      "gate": {"cells_ok": true, "ab_ok": true, "ok": true},
      "note": "..."
    }
"""

from __future__ import annotations

import json
from typing import List

#: a committed scenario round must actually be a matrix
MIN_CELLS = 10

ARRIVALS = ("steady", "burst")
SAMPLINGS = ("greedy", "mixed")

#: the closed SLO status vocabulary (apex_tpu.obs.slo) — cells may
#: carry an OPTIONAL ``slo`` verdict block; when present it is
#: validated: statuses from this vocabulary only, and the block's
#: ``ok`` must re-derive from them (no self-citing SLO verdicts).
SLO_STATUSES = ("met", "violated", "insufficient_window")


def _check_slo_block(name: str, slo, problems: List[str]):
    """Validate one optional SLO verdict block; returns its ok when
    well-formed, else None."""
    if not isinstance(slo, dict) or \
            not isinstance(slo.get("objectives"), dict) or \
            not isinstance(slo.get("ok"), bool):
        problems.append(f"{name} must carry an 'objectives' map and "
                        f"an 'ok' bool")
        return None
    violated = False
    for oname, rec in slo["objectives"].items():
        st = rec.get("status") if isinstance(rec, dict) else None
        if st not in SLO_STATUSES:
            problems.append(f"{name}.objectives[{oname}].status "
                            f"{st!r} not in {SLO_STATUSES}")
            return None
        violated = violated or (st == "violated")
    if slo["ok"] != (not violated):
        problems.append(
            f"CONTRADICTORY verdict: {name}.ok={slo['ok']} but the "
            f"objective statuses derive {not violated}")
    return slo["ok"]


def _num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def _check_prefix_block(name: str, prefix, problems: List[str]):
    """Validate one optional per-cell prefix-sharing block: the hit
    rate must RE-DERIVE from the recorded probe/hit counts (the
    PREFIXCACHE_r*.json discipline at cell granularity)."""
    if not isinstance(prefix, dict) or \
            not isinstance(prefix.get("probes"), int) or \
            not isinstance(prefix.get("hits"), int) or \
            not _num(prefix.get("hit_rate")):
        problems.append(f"{name} must carry probes/hits ints and a "
                        f"hit_rate number")
        return
    if not 0 <= prefix["hits"] <= prefix["probes"]:
        problems.append(f"{name}: hits {prefix['hits']} outside "
                        f"[0, probes={prefix['probes']}]")
        return
    derived = round(prefix["hits"] / max(prefix["probes"], 1), 6)
    if abs(prefix["hit_rate"] - derived) > 1e-6:
        problems.append(
            f"CONTRADICTORY record: {name}.hit_rate="
            f"{prefix['hit_rate']} but hits/probes derives {derived}")


def _check_cell(name: str, cell, gate_k, problems: List[str]):
    """Validate one cell; returns its (ok, tokens_per_step) when the
    record is well-formed enough to cite, else None."""
    if not isinstance(cell, dict):
        problems.append(f"cells[{name}] is not an object")
        return None
    cfg = cell.get("config")
    if not isinstance(cfg, dict):
        problems.append(f"cells[{name}].config missing")
        return None
    if not (isinstance(cfg.get("context"), int) and cfg["context"] > 0):
        problems.append(f"cells[{name}].config.context must be a "
                        f"positive int")
    if cfg.get("arrival") not in ARRIVALS:
        problems.append(f"cells[{name}].config.arrival "
                        f"{cfg.get('arrival')!r} not in {ARRIVALS}")
    if cfg.get("sampling") not in SAMPLINGS:
        problems.append(f"cells[{name}].config.sampling "
                        f"{cfg.get('sampling')!r} not in {SAMPLINGS}")
    for flag in ("kv8", "spec", "churn"):
        if not isinstance(cfg.get(flag), bool):
            problems.append(f"cells[{name}].config.{flag} missing "
                            f"(bool)")
    for k in ("tok_s", "p50_ms", "p99_ms", "tokens_per_step"):
        if not _num(cell.get(k)) or cell[k] < 0:
            problems.append(f"cells[{name}].{k} missing or not a "
                            f"non-negative number: {cell.get(k)!r}")
            return None
    if cell["p99_ms"] < cell["p50_ms"]:
        problems.append(f"cells[{name}]: p99 {cell['p99_ms']} under "
                        f"p50 {cell['p50_ms']} — not a percentile pair")
    for k in ("decode_steps", "decode_tokens", "retraces"):
        if not isinstance(cell.get(k), int) or cell[k] < 1:
            problems.append(f"cells[{name}].{k} missing or < 1")
            return None
    # tokens_per_step must BE decode_tokens / decode_steps (the tool
    # records it at 4 decimals) — otherwise the whole A/B chain is
    # anchored to a free-floating number a fabricated win could edit
    derived_tps = round(cell["decode_tokens"] / cell["decode_steps"], 4)
    if cell["tokens_per_step"] != derived_tps:
        problems.append(
            f"CONTRADICTORY record: cells[{name}].tokens_per_step="
            f"{cell['tokens_per_step']} but decode_tokens/"
            f"decode_steps = {cell['decode_tokens']}/"
            f"{cell['decode_steps']} derives {derived_tps}")
    if cfg.get("spec") is True and not _num(cell.get("acceptance_rate")):
        problems.append(f"cells[{name}]: spec cell without a recorded "
                        f"acceptance_rate")
    if cfg.get("churn") is True and not (
            isinstance(cell.get("preemptions"), int)
            and cell["preemptions"] >= 1):
        problems.append(f"cells[{name}]: a churn cell that preempted "
                        f"nothing churned nothing (preemptions >= 1)")
    gate = cell.get("gate")
    if not isinstance(gate, dict) or not all(
            isinstance(gate.get(k), bool)
            for k in ("tail_ok", "retrace_ok", "ok")):
        problems.append(f"cells[{name}].gate missing tail_ok/"
                        f"retrace_ok/ok bools")
        return None
    # -- verdicts must agree with their own numbers -------------------
    if _num(gate_k):
        derived_tail = cell["p99_ms"] <= gate_k * cell["p50_ms"]
        if gate["tail_ok"] != derived_tail:
            problems.append(
                f"CONTRADICTORY verdict: cells[{name}].gate.tail_ok="
                f"{gate['tail_ok']} but p99 {cell['p99_ms']} vs "
                f"{gate_k} x p50 {cell['p50_ms']} derives "
                f"{derived_tail}")
    derived_retrace = cell["retraces"] == 1
    if gate["retrace_ok"] != derived_retrace:
        problems.append(
            f"CONTRADICTORY verdict: cells[{name}].gate.retrace_ok="
            f"{gate['retrace_ok']} but retraces={cell['retraces']}")
    if gate["ok"] != (gate["tail_ok"] and gate["retrace_ok"]):
        problems.append(
            f"CONTRADICTORY verdict: cells[{name}].gate.ok="
            f"{gate['ok']} but tail_ok={gate['tail_ok']} and "
            f"retrace_ok={gate['retrace_ok']}")
    if cell.get("slo") is not None:
        _check_slo_block(f"cells[{name}].slo", cell["slo"], problems)
    if cell.get("prefix") is not None:
        _check_prefix_block(f"cells[{name}].prefix", cell["prefix"],
                            problems)
    return gate["ok"], cell["tokens_per_step"]


def validate_scenario(doc) -> List[str]:
    """Problems with one parsed SCENARIO document (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("round"), int):
        problems.append("missing/invalid 'round' (int)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")
    if not isinstance(doc.get("model"), str):
        problems.append("missing/invalid 'model' (str)")
    gate_k = doc.get("gate_k")
    if not _num(gate_k) or gate_k <= 1:
        problems.append(f"missing/invalid 'gate_k' (> 1): {gate_k!r}")
        gate_k = None

    cells = doc.get("cells")
    cell_facts = {}
    if not isinstance(cells, dict) or not cells:
        problems.append("missing/empty 'cells' object")
        cells = {}
    elif len(cells) < MIN_CELLS:
        problems.append(
            f"only {len(cells)} cells — a scenario MATRIX round "
            f"covers >= {MIN_CELLS} (the coverage claim is the "
            f"artifact's whole point)")
    for name, cell in cells.items():
        fact = _check_cell(name, cell, gate_k, problems)
        if fact is not None:
            cell_facts[name] = fact

    # -- the spec-vs-baseline A/B table -------------------------------
    ab = doc.get("ab")
    ab_gated_wins = []
    if not isinstance(ab, list) or not ab:
        problems.append("missing/empty 'ab' list (the spec-vs-baseline "
                        "tokens-per-step A/B is the latency-win gate)")
        ab = []
    for i, row in enumerate(ab):
        if not isinstance(row, dict):
            problems.append(f"ab[{i}] is not an object")
            continue
        on, off = row.get("on"), row.get("off")
        ok_row = True
        for side, cid in (("on", on), ("off", off)):
            if cid not in cell_facts:
                problems.append(f"ab[{i}].{side} cites unknown/invalid "
                                f"cell {cid!r}")
                ok_row = False
        if not _num(row.get("tokens_per_step_on")) \
                or not _num(row.get("tokens_per_step_off")) \
                or not isinstance(row.get("spec_wins"), bool) \
                or not isinstance(row.get("gated"), bool):
            problems.append(f"ab[{i}] missing tokens_per_step_on/off "
                            f"numbers + spec_wins/gated bools")
            continue
        if ok_row:
            for side, cid in (("on", on), ("off", off)):
                if row[f"tokens_per_step_{side}"] != cell_facts[cid][1]:
                    problems.append(
                        f"ab[{i}].tokens_per_step_{side}="
                        f"{row[f'tokens_per_step_{side}']} does not "
                        f"match cells[{cid}].tokens_per_step="
                        f"{cell_facts[cid][1]}")
            spec_flags = (cells[on].get("config", {}).get("spec"),
                          cells[off].get("config", {}).get("spec"))
            if spec_flags != (True, False):
                problems.append(
                    f"ab[{i}]: 'on' must cite a spec cell and 'off' "
                    f"its baseline (got spec={spec_flags})")
        derived = row["tokens_per_step_on"] > row["tokens_per_step_off"]
        if row["spec_wins"] != derived:
            problems.append(
                f"CONTRADICTORY verdict: ab[{i}].spec_wins="
                f"{row['spec_wins']} but "
                f"{row['tokens_per_step_on']} vs "
                f"{row['tokens_per_step_off']} derives {derived}")
        if row["gated"]:
            ab_gated_wins.append(row["spec_wins"])

    # -- the optional document-level SLO verdict ----------------------
    doc_slo = doc.get("slo")
    if doc_slo is not None:
        if not isinstance(doc_slo, dict) or \
                not isinstance(doc_slo.get("ok"), bool):
            problems.append("'slo' block must carry an ok bool")
        else:
            derived_slo = all(
                c["slo"].get("ok") is True
                for c in cells.values()
                if isinstance(c, dict)
                and isinstance(c.get("slo"), dict))
            if doc_slo["ok"] != derived_slo:
                problems.append(
                    f"CONTRADICTORY verdict: slo.ok={doc_slo['ok']} "
                    f"but the cells' SLO blocks derive {derived_slo}")

    # -- the document verdict -----------------------------------------
    gate = doc.get("gate")
    if not isinstance(gate, dict) or not all(
            isinstance(gate.get(k), bool)
            for k in ("cells_ok", "ab_ok", "ok")):
        problems.append("missing/invalid 'gate' "
                        "(cells_ok + ab_ok + ok bools)")
    elif not problems:
        # only re-derive the top verdict from a structurally-valid
        # document: a malformed cell already failed the round
        derived_cells = all(ok for ok, _ in cell_facts.values())
        if gate["cells_ok"] != derived_cells:
            problems.append(
                f"CONTRADICTORY verdict: gate.cells_ok="
                f"{gate['cells_ok']} but the cell gates derive "
                f"{derived_cells}")
        derived_ab = bool(ab_gated_wins) and all(ab_gated_wins)
        if gate["ab_ok"] != derived_ab:
            problems.append(
                f"CONTRADICTORY verdict: gate.ab_ok={gate['ab_ok']} "
                f"but the gated A/B rows derive {derived_ab} "
                f"({sum(ab_gated_wins)}/{len(ab_gated_wins)} wins)")
        if gate["ok"] != (gate["cells_ok"] and gate["ab_ok"]):
            problems.append(
                f"CONTRADICTORY verdict: gate.ok={gate['ok']} but "
                f"cells_ok={gate['cells_ok']} and "
                f"ab_ok={gate['ab_ok']}")
    return problems


def validate_scenario_file(path: str) -> List[str]:
    """Problems with one SCENARIO_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable scenario JSON: {e}"]
    return validate_scenario(doc)
