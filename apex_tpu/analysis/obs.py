"""OBS_r*.json — schema for the committed observability artifact.

``tools/obs_report.py`` writes one of these per round: the telemetry
layer's own acceptance evidence — (a) the measured normal-path
overhead of instrumenting a train step (bare jitted loop vs the
``apex_tpu.obs``-instrumented one, min-of-interleaved-reps at the
bench-smoke scale, the ``tools/chaos_run.py --overhead`` methodology),
(b) the graph-lint **syncs** verdict over the instrumented serve and
train lanes (instrumentation must introduce zero host callbacks and
zero retrace hazards), and (c) a registry export snapshot that pins
the metric catalog and the JSON export shape.

Like MEMLINT/PRECLINT/INCIDENT records the artifact is gate memory:
``tools/gate_hygiene.py`` validates every committed ``OBS_r*.json``
against this schema, and the schema ENFORCES the acceptance bars —
overhead under :data:`OVERHEAD_BUDGET_PCT` and a clean syncs table —
so the telemetry layer can never quietly regress into a tax on the
step path.

This module is deliberately **stdlib-only** (no jax import):
``gate_hygiene`` loads it directly by file path the same way it loads
``analysis/memlint.py``.

Document shape::

    {
      "round": 1,
      "platform": "cpu",
      "overhead": {"scale": "bench-smoke", "steps": 40, "reps": 5,
                   "bare_s": ..., "instrumented_s": ...,
                   "overhead_pct": 0.4},     # must be <= 1.0
      "syncs": {"clean": true,               # must be true
                "lanes": {"serve_step": {"host_callbacks": 0,
                                         "static_scalars": 0,
                                         "errors": 0}, ...}},
      "tracing": {"per_event_us": 1.2,       # optional section (r02+):
                  "flight_note_us": 1.0,     # request-trace / flight
                  "events_per_step": 3,      # per-event record cost,
                  "decode_step_ms": 2.5,     # gated against the
                  "overhead_pct": 0.2},      # bench-smoke decode step
                                             # — must be <= 1.0.
                                             # r02 also records the
                                             # spec round's denser
                                             # 2-events/slot lane;
                                             # overhead_pct is the
                                             # WORSE lane
      "export": {"metrics": [{"name": ..., "type": "counter", ...}]},
      "note": "..."
    }

The ``tracing`` section (optional so the pre-tracing r01 stays valid)
carries the ISSUE-13 bar: the per-event cost of
:meth:`apex_tpu.obs.reqtrace.RequestTracer.record` times the events a
decode step records, as a percentage of the measured bench-smoke
decode step — the request-tracing layer must stay as far off the step
path as the metrics layer."""

from __future__ import annotations

import json
from typing import List

#: acceptance bar: instrumentation overhead on the normal step path
OVERHEAD_BUDGET_PCT = 1.0

#: acceptance bar: per-step request-tracing record cost as a fraction
#: of the bench-smoke decode step (the ISSUE-13 tracing lane)
TRACING_BUDGET_PCT = 1.0

#: acceptance bar: the continuous profiler's amortized cost — one
#: capture window (capture + parse + sentinel) as a percentage of the
#: step wall over the whole inter-capture interval
#: (``capture_every × step_wall``); the r03+ ``contprof`` lane
CONTPROF_BUDGET_PCT = 1.0

#: instrument kinds the export may carry
METRIC_TYPES = ("counter", "gauge", "histogram")

#: per-lane syncs counters that must all be zero
_SYNC_KEYS = ("host_callbacks", "static_scalars", "errors")


def validate_obs(doc) -> List[str]:
    """Problems with one parsed OBS document (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if not isinstance(doc.get("round"), int):
        problems.append("missing/invalid 'round' (int)")
    if not isinstance(doc.get("platform"), str):
        problems.append("missing/invalid 'platform' (str)")

    ov = doc.get("overhead")
    if not isinstance(ov, dict):
        problems.append("missing/invalid 'overhead' object")
    else:
        for key in ("bare_s", "instrumented_s", "overhead_pct"):
            if not isinstance(ov.get(key), (int, float)):
                problems.append(f"overhead missing numeric {key!r}")
        if not (isinstance(ov.get("steps"), int) and ov["steps"] > 0):
            problems.append("overhead missing positive int 'steps'")
        pct = ov.get("overhead_pct")
        if isinstance(pct, (int, float)) and pct > OVERHEAD_BUDGET_PCT:
            problems.append(
                f"overhead_pct {pct} over the {OVERHEAD_BUDGET_PCT}% "
                f"budget — the telemetry layer must stay off the step "
                f"path")

    sy = doc.get("syncs")
    if not isinstance(sy, dict):
        problems.append("missing/invalid 'syncs' object")
    else:
        if sy.get("clean") is not True:
            problems.append("'syncs.clean' must be true — committed "
                            "observability evidence with a dirty "
                            "syncs verdict is a contradiction")
        lanes = sy.get("lanes")
        if not isinstance(lanes, dict) or not lanes:
            problems.append("'syncs' missing non-empty 'lanes'")
        else:
            for name, lane in lanes.items():
                if not isinstance(lane, dict):
                    problems.append(f"syncs lane {name!r} not an object")
                    continue
                for key in _SYNC_KEYS:
                    v = lane.get(key)
                    if not isinstance(v, int) or v < 0:
                        problems.append(
                            f"syncs lane {name!r} missing count {key!r}")
                    elif v != 0:
                        problems.append(
                            f"syncs lane {name!r} has {key}={v} — "
                            f"instrumentation introduced a hazard")

    tr = doc.get("tracing")
    if tr is not None:                 # optional: r01 predates tracing
        if not isinstance(tr, dict):
            problems.append("'tracing' present but not an object")
        else:
            for key in ("per_event_us", "flight_note_us",
                        "decode_step_ms", "overhead_pct"):
                if not isinstance(tr.get(key), (int, float)) \
                        or isinstance(tr.get(key), bool):
                    problems.append(f"tracing missing numeric {key!r}")
            eps = tr.get("events_per_step")
            if not (isinstance(eps, int) and not isinstance(eps, bool)
                    and eps > 0):
                problems.append(
                    "tracing missing positive int 'events_per_step'")
            pct = tr.get("overhead_pct")
            if isinstance(pct, (int, float)) \
                    and not isinstance(pct, bool) \
                    and pct > TRACING_BUDGET_PCT:
                problems.append(
                    f"tracing overhead_pct {pct} over the "
                    f"{TRACING_BUDGET_PCT}% budget — request tracing "
                    f"must stay off the decode step path")

    cp = doc.get("contprof")
    if cp is not None:              # optional: r01/r02 predate contprof
        if not isinstance(cp, dict):
            problems.append("'contprof' present but not an object")
        else:
            complete = True
            for key in ("capture_s", "parse_s", "sentinel_s",
                        "window_cost_s", "step_wall_ms",
                        "overhead_pct"):
                if not isinstance(cp.get(key), (int, float)) \
                        or isinstance(cp.get(key), bool):
                    problems.append(f"contprof missing numeric {key!r}")
                    complete = False
            ce = cp.get("capture_every")
            if not (isinstance(ce, int) and not isinstance(ce, bool)
                    and ce > 0):
                problems.append(
                    "contprof missing positive int 'capture_every'")
                complete = False
            if complete:
                cost = cp["capture_s"] + cp["parse_s"] + \
                    cp["sentinel_s"]
                if abs(cp["window_cost_s"] - cost) > \
                        max(0.01, 0.05 * cost):
                    problems.append(
                        f"contprof window_cost_s "
                        f"{cp['window_cost_s']} does not re-derive "
                        f"from capture+parse+sentinel = {cost:.4f}")
                if cp["step_wall_ms"] <= 0:
                    # an inf 'derived' would make the re-derive
                    # comparison below vacuous (inf > inf is False) —
                    # a zero wall is itself a fabrication signal
                    problems.append(
                        "contprof step_wall_ms must be > 0 — the "
                        "overhead re-derivation is meaningless over a "
                        "zero step wall")
                else:
                    interval_s = ce * cp["step_wall_ms"] / 1e3
                    derived = 100.0 * cp["window_cost_s"] / interval_s
                    if abs(cp["overhead_pct"] - derived) > \
                            max(0.02, 0.05 * derived):
                        problems.append(
                            f"contprof overhead_pct "
                            f"{cp['overhead_pct']} does not re-derive "
                            f"from window_cost / (capture_every x "
                            f"step_wall) = {derived:.3f}")
                pct = cp.get("overhead_pct")
                if isinstance(pct, (int, float)) and \
                        pct > CONTPROF_BUDGET_PCT:
                    problems.append(
                        f"contprof overhead_pct {pct} over the "
                        f"{CONTPROF_BUDGET_PCT}% budget — the "
                        f"continuous profiler must stay off the step "
                        f"path at its recorded cadence")

    ex = doc.get("export")
    rows = ex.get("metrics") if isinstance(ex, dict) else None
    if not isinstance(rows, list) or not rows:
        problems.append("missing/empty 'export.metrics' list")
    else:
        for i, row in enumerate(rows):
            if not (isinstance(row, dict)
                    and isinstance(row.get("name"), str)
                    and row.get("type") in METRIC_TYPES):
                problems.append(
                    f"export.metrics[{i}] malformed (need name:str, "
                    f"type in {METRIC_TYPES}): {row!r}")
                break
    return problems


def validate_obs_file(path: str) -> List[str]:
    """Problems with one OBS_r*.json file (empty = valid)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable obs JSON: {e}"]
    return validate_obs(doc)
