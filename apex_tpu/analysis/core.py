"""Pass harness for the static graph lint.

The reference apex's guarantees are *structural* (patch the whole
``torch`` namespace, own the gradient buckets); apex_tpu's equivalents
are *checkable*: the program a user will actually run exists as text —
pre-optimization StableHLO (what the user asked for) and compiled HLO
(what the chip will execute) — and the silent TPU performance bugs are
all statically visible in one of the two:

===================  ====================================================
pass                 catches
===================  ====================================================
``donation``         ``donate_argnums`` that produced no input-output
                     alias in the compiled executable (double HBM)
``sharding``         large arrays left fully replicated / parameter-sized
                     all-gathers after SPMD partitioning
``collectives``      per-kind collective count/bytes vs a byte budget
                     (comm-volume regressions fail like MFU regressions)
``constant-capture`` weight-sized constants baked into the jaxpr instead
                     of passed as arguments (recompile / bloat hazard)
``policy``           FP32-list-category work executing in 16-bit
                     (:mod:`apex_tpu.analysis.policy`, the O1 audit)
``memory``           per-device peak HBM of the compiled step vs a
                     device budget; donation-aliasing table; largest
                     live buffers (:mod:`apex_tpu.analysis.memory`)
``cost``             XLA cost-model flops / HBM traffic and the static
                     roofline expectation they imply
                     (:mod:`apex_tpu.analysis.cost`)
``syncs``            host callbacks / infeed / outfeed on the step
                     path, retrace hazards, in-place buffers read
                     after dispatch (:mod:`apex_tpu.analysis.syncs`)
``precision``        the mixed-precision contract op-by-op: forced
                     sub-f32 matmul accumulation, long 16-bit
                     reductions, f32→16→f32 double rounds, non-f32
                     master weights/moments, loss-scale placement
                     (:mod:`apex_tpu.analysis.precision`)
``export-compat``    lanes whose compiled executables cannot become
                     AOT cache artifacts: host callbacks, platform-
                     pinned custom calls, statically-bound scalars,
                     baked weight constants
                     (:mod:`apex_tpu.analysis.export`)
``determinism``      bitwise-exactness hazards in the gated programs:
                     float argmax/top-k tie-breaks not in the
                     greedy_argmax form, unpinned values shared by a
                     sampling epilogue and a program output, scatters
                     with non-provably-disjoint windows, PRNG key
                     reuse (:mod:`apex_tpu.analysis.determinism`)
===================  ====================================================

:func:`analyze` lowers (and by default compiles) a jittable function on
example args, builds a :class:`PassContext`, and runs the named passes;
each pass is a plain function ``(ctx, **options) -> [Finding]`` looked
up in :data:`PASSES`.  ``DEFAULT_PASSES`` is the four whole-program
graph passes; ``policy`` is opt-in because it must run on the FORWARD
function, not the AD-generated train step (see
``apex_tpu/analysis/policy.py``).

The program is lowered EXACTLY ONCE per :func:`analyze` call and the
lowered object (plus the compiled executable, when ``compile=True``)
is shared through the :class:`PassContext` — a mixed pass list such as
``(*DEFAULT_PASSES, "memory", "policy")`` costs one lowering and at
most one compilation; lowering-only passes read
``ctx.stablehlo_text`` and never trigger a second lowering (the old
two-``analyze()``-call idiom paid that twice).
"""

from __future__ import annotations

import dataclasses
import math
import warnings
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import jax

from apex_tpu.analysis.report import Finding, Report, make_report


@dataclasses.dataclass(frozen=True)
class ArgInfo:
    """One flattened input of the analyzed program.

    ``index`` is the flat position in the traced signature; ``kept`` is
    False when jit pruned the argument as unused (``keep_unused=False``,
    the default) — pruned args do NOT appear in the lowered module's
    ``main`` signature or the compiled entry parameters, so text-side
    numbering counts kept args only (see :meth:`kept_position`)."""

    index: int
    path: str
    shape: Tuple[int, ...]
    dtype: str
    nbytes: int
    donated: bool
    kept: bool = True
    #: the aval's weak-type bit (True when the value was traced from a
    #: Python literal); ``None`` when the jax version didn't expose it.
    weak_type: Optional[bool] = None


@dataclasses.dataclass(frozen=True)
class OutInfo:
    """One flattened output of the analyzed program."""

    index: int
    path: str
    shape: Tuple[int, ...]
    dtype: str
    nbytes: int


@dataclasses.dataclass(frozen=True)
class PassContext:
    """Everything a lint pass may look at.

    ``hlo_text`` is ``None`` when the program was lowered but not
    compiled (``analyze(..., compile=False)``); passes that need the
    compiled program degrade to lowering-time evidence or report an
    ``info`` finding saying they were skipped.

    ``compiled`` carries the executable itself (``jax.stages.Compiled``)
    whenever the program was compiled: the memory/cost passes read
    XLA's own ``memory_analysis()`` / ``cost_analysis()`` from it —
    numbers the HLO text alone doesn't give.  ``static_scalars``
    records example arguments that jit bound STATICALLY at trace time
    (they vanish from ``args``); the syncs pass turns numeric ones into
    retrace-hazard findings.
    """

    stablehlo_text: str
    hlo_text: Optional[str] = None
    args: Tuple[ArgInfo, ...] = ()
    outputs: Tuple[OutInfo, ...] = ()
    compiled: Optional[Any] = None
    #: ``(position_label, type_name, repr)`` of statically-bound
    #: example args (positional index like ``"arg2"`` or the kwarg name)
    static_scalars: Tuple[Tuple[str, str, str], ...] = ()
    #: the resolved mixed-precision policy the program was built under
    #: (:class:`apex_tpu.amp.policy.Properties`), when the caller knows
    #: it — the precision pass reads opt level / half dtype / master-
    #: weight intent from here; ``None`` degrades it to policy-free
    #: dtype checks.
    policy: Optional[Any] = None
    #: the traced ``ClosedJaxpr`` of the program, when the caller
    #: captured one — the ``pallas-kernel`` pass reads grid/BlockSpec/
    #: index-map structure from here (StableHLO has already erased it);
    #: ``None`` degrades that pass to an info "skipped" finding.
    closed_jaxpr: Optional[Any] = None
    #: derived-table memo (alias set, kept-index map, donation table)
    #: shared across passes — every derived table is a pure function of
    #: one lowering's text, so it is parsed once per context, not once
    #: per consuming pass (see :meth:`memo`)
    _memo: Dict[str, Any] = dataclasses.field(
        default_factory=dict, repr=False, compare=False)

    @property
    def kept_args(self) -> Tuple[ArgInfo, ...]:
        """Args that survived pruning, in text/parameter order: the
        k-th entry corresponds to ``%argk`` in the lowered ``main``
        signature and ``parameter(k)`` in the compiled entry."""
        return tuple(a for a in self.args if a.kept)

    def memo(self, key: str, compute: Callable[[], Any]) -> Any:
        """``compute()`` once per context under ``key`` (``None``
        results are cached too — "numbering ambiguous" is as stable a
        fact of a lowering as the table itself)."""
        if key not in self._memo:
            self._memo[key] = compute()
        return self._memo[key]


#: registry: pass name -> ``fn(ctx, **options) -> [Finding]``.  Pass
#: modules register themselves on import (see ``analysis/__init__.py``).
PASSES: Dict[str, Callable[..., List[Finding]]] = {}

#: the whole-program graph passes, safe on any jittable (train steps
#: included).  ``policy`` is deliberately NOT here — it audits forwards.
DEFAULT_PASSES = ("donation", "sharding", "collectives",
                  "constant-capture")


def register_pass(name: str, fn: Callable[..., List[Finding]],
                  replace: bool = False) -> None:
    if name in PASSES and not replace:
        raise ValueError(f"pass {name!r} already registered")
    PASSES[name] = fn


def _leaf_nbytes(shape, dtype) -> int:
    try:
        itemsize = dtype.itemsize
    except AttributeError:
        import numpy as np
        itemsize = np.dtype(dtype).itemsize
    return int(math.prod(shape)) * int(itemsize)


def _args_info(lowered) -> Tuple[ArgInfo, ...]:
    flat, _ = jax.tree_util.tree_flatten_with_path(lowered.args_info)
    try:  # flat indices jit kept (pruned unused args vanish from the text)
        kept_idx = lowered._lowering.compile_args["kept_var_idx"]
    except (AttributeError, KeyError, TypeError):
        kept_idx = None
    try:  # KEPT-arg avals, in text order — the weak-type bits live here
        in_avals = tuple(lowered._lowering.compile_args["global_in_avals"])
    except (AttributeError, KeyError, TypeError):
        in_avals = None
    out = []
    kept_seen = 0
    for i, (path, a) in enumerate(flat):
        kept = True if kept_idx is None else i in kept_idx
        weak: Optional[bool] = None
        if kept and in_avals is not None and kept_seen < len(in_avals):
            weak = bool(getattr(in_avals[kept_seen], "weak_type", False))
        if kept:
            kept_seen += 1
        out.append(ArgInfo(
            index=i, path=jax.tree_util.keystr(path),
            shape=tuple(a.shape), dtype=str(a.dtype),
            nbytes=_leaf_nbytes(a.shape, a.dtype),
            donated=bool(getattr(a, "donated", False)),
            kept=kept, weak_type=weak))
    return tuple(out)


def _out_info(lowered) -> Tuple[OutInfo, ...]:
    try:
        flat, _ = jax.tree_util.tree_flatten_with_path(lowered.out_info)
    except (AttributeError, TypeError):
        return ()
    out = []
    for i, (path, o) in enumerate(flat):
        try:
            out.append(OutInfo(
                index=i, path=jax.tree_util.keystr(path),
                shape=tuple(o.shape), dtype=str(o.dtype),
                nbytes=_leaf_nbytes(o.shape, o.dtype)))
        except (AttributeError, TypeError):
            continue
    return tuple(out)


def _static_scalars(example_args, example_kwargs,
                    args_info) -> Tuple[Tuple[str, str, str], ...]:
    """Example args jit bound statically (they are absent from
    ``args_info``, whose top level mirrors ``(args, kwargs)`` with
    static entries REMOVED).  Position attribution is only sound when
    the split is unambiguous, so this records suspects conservatively:
    nothing unless fewer dynamic slots exist than example args, and
    then only the hashable Python-numeric candidates (arrays can never
    be static)."""
    try:
        dyn_pos, dyn_kw = args_info
        n_static_pos = len(example_args) - len(dyn_pos)
        static_kw = set(example_kwargs) - set(dyn_kw)
    except (TypeError, ValueError):
        return ()
    suspects = []
    if n_static_pos > 0:
        def is_array(v):
            return hasattr(v, "shape") and hasattr(v, "dtype")

        numeric = [(f"arg{i}", type(v).__name__, repr(v)[:40])
                   for i, v in enumerate(example_args)
                   if isinstance(v, (int, float))
                   and not isinstance(v, bool)]
        # static-able candidates: anything that isn't an array (arrays
        # are always dynamic).  The exact attribution is only sound
        # when the numerics are the ONLY candidates and their count
        # matches the static count — a non-numeric candidate (a mode
        # string, a config object) could be the real static, leaving
        # the numeric one dynamic.
        n_nonarray = sum(1 for v in example_args if not is_array(v))
        if numeric and len(numeric) == n_static_pos \
                and n_nonarray == n_static_pos:
            suspects.extend(numeric)
        elif numeric:
            # which example arg was static isn't recoverable from the
            # traced signature — report the candidate set rather than
            # guess (a wrong name would tell the user to fix the
            # already-dynamic argument)
            cands = ", ".join(f"{lbl}={val}"
                              for lbl, _, val in numeric)
            suspects.append(("ambiguous", "int/float",
                             f"{n_static_pos} static slot(s); numeric "
                             f"candidates: {cands}"))
    for k in sorted(static_kw):
        v = example_kwargs[k]
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            suspects.append((k, type(v).__name__, repr(v)[:40]))
    return tuple(suspects)


def run_passes(ctx: PassContext,
               passes: Optional[Sequence[str]] = None,
               options: Optional[Mapping[str, Mapping[str, Any]]] = None,
               ) -> Report:
    """Run the named passes (default :data:`DEFAULT_PASSES`) over a
    prepared context.  ``options`` maps pass name -> keyword options for
    that pass (e.g. ``{"collectives": {"budget": {"total": 0}}}``)."""
    names = tuple(passes) if passes is not None else DEFAULT_PASSES
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        raise KeyError(f"unknown lint pass(es) {unknown}; registered: "
                       f"{sorted(PASSES)}")
    findings: List[Finding] = []
    for name in names:
        findings.extend(PASSES[name](ctx, **dict((options or {})
                                                 .get(name, {}))))
    return make_report(findings, names)


def build_context(lowered, compile: bool = True,
                  static_scalars=(), policy=None,
                  closed_jaxpr=None) -> PassContext:
    """One :class:`PassContext` from one lowering: the lowered text,
    the arg/output tables, and (when ``compile``) the compiled
    executable plus its HLO text — shared by every pass so a mixed
    pass list never lowers or compiles twice.  ``policy`` (the resolved
    ``amp.policy.Properties``) rides along for the precision pass;
    ``closed_jaxpr`` (from ``jitted.trace(...).jaxpr``) for the
    ``pallas-kernel`` pass."""
    compiled = lowered.compile() if compile else None
    return PassContext(
        stablehlo_text=lowered.as_text(),
        hlo_text=compiled.as_text() if compiled is not None else None,
        args=_args_info(lowered), outputs=_out_info(lowered),
        compiled=compiled, static_scalars=tuple(static_scalars),
        policy=policy, closed_jaxpr=closed_jaxpr)


def lower_quiet(jitted, *args, **kwargs):
    """Lower with JAX's lowering-time "Some donated buffers were not
    usable" warning suppressed: turning that warning into a
    structured, gateable finding is the donation pass's job — shared
    by :func:`analyze` and the lane drivers (``tools/graph_lint.py``)
    so the suppression policy cannot drift between them."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        return jitted.lower(*args, **kwargs)


def analyze_lowered(lowered,
                    passes: Optional[Sequence[str]] = None,
                    compile: bool = True,
                    options: Optional[Mapping] = None,
                    policy=None) -> Report:
    """Run lint passes over an already-``.lower()``-ed program."""
    ctx = build_context(lowered, compile=compile, policy=policy)
    return run_passes(ctx, passes=passes, options=options)


def analyze(fn: Callable, *args,
            passes: Optional[Sequence[str]] = None,
            compile: bool = True,
            donate_argnums=(),
            options: Optional[Mapping] = None,
            policy=None,
            **kwargs) -> Report:
    """Lower (and compile) ``fn`` on example ``args`` and lint it.

    ``fn`` may already be jitted — its own ``donate_argnums``/sharding
    configuration is kept (re-jitting would drop donation info, which is
    exactly what the donation pass exists to check).  Otherwise it is
    jitted here with ``donate_argnums``.

    The program is lowered once and (when ``compile=True``) compiled
    once; every requested pass — compiled-evidence passes and
    lowering-only passes alike — shares the resulting
    :class:`PassContext`.  Prefer one ``analyze`` call with the full
    pass list over stacked calls: each ``analyze`` pays its own
    lowering.

    JAX's lowering-time "Some donated buffers were not usable" warning
    is suppressed: turning that warning into a structured, gateable
    finding is the donation pass's job.
    """
    jitted = fn if hasattr(fn, "lower") else \
        jax.jit(fn, donate_argnums=donate_argnums)
    lowered = lower_quiet(jitted, *args, **kwargs)
    closed_jaxpr = None
    if passes is not None and "pallas-kernel" in passes:
        # the pallas pass needs jaxpr-level structure (StableHLO has
        # already erased BlockSpecs) — trace it alongside the lowering
        try:
            closed_jaxpr = jitted.trace(*args, **kwargs).jaxpr
        except Exception:  # noqa: BLE001 - pass degrades to "skipped"
            closed_jaxpr = None
    ctx = build_context(
        lowered, compile=compile, policy=policy,
        static_scalars=_static_scalars(args, kwargs, lowered.args_info),
        closed_jaxpr=closed_jaxpr)
    return run_passes(ctx, passes=passes, options=options)
