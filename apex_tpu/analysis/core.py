"""Pass harness for the static graph lint.

The reference apex's guarantees are *structural* (patch the whole
``torch`` namespace, own the gradient buckets); apex_tpu's equivalents
are *checkable*: the program a user will actually run exists as text —
pre-optimization StableHLO (what the user asked for) and compiled HLO
(what the chip will execute) — and the silent TPU performance bugs are
all statically visible in one of the two:

===================  ====================================================
pass                 catches
===================  ====================================================
``donation``         ``donate_argnums`` that produced no input-output
                     alias in the compiled executable (double HBM)
``sharding``         large arrays left fully replicated / parameter-sized
                     all-gathers after SPMD partitioning
``collectives``      per-kind collective count/bytes vs a byte budget
                     (comm-volume regressions fail like MFU regressions)
``constant-capture`` weight-sized constants baked into the jaxpr instead
                     of passed as arguments (recompile / bloat hazard)
``policy``           FP32-list-category work executing in 16-bit
                     (:mod:`apex_tpu.analysis.policy`, the O1 audit)
===================  ====================================================

:func:`analyze` lowers (and by default compiles) a jittable function on
example args, builds a :class:`PassContext`, and runs the named passes;
each pass is a plain function ``(ctx, **options) -> [Finding]`` looked
up in :data:`PASSES`.  ``DEFAULT_PASSES`` is the four whole-program
graph passes; ``policy`` is opt-in because it must run on the FORWARD
function, not the AD-generated train step (see
``apex_tpu/analysis/policy.py``).
"""

from __future__ import annotations

import dataclasses
import math
import warnings
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import jax

from apex_tpu.analysis.report import Finding, Report, make_report


@dataclasses.dataclass(frozen=True)
class ArgInfo:
    """One flattened input of the analyzed program.

    ``index`` is the flat position in the traced signature; ``kept`` is
    False when jit pruned the argument as unused (``keep_unused=False``,
    the default) — pruned args do NOT appear in the lowered module's
    ``main`` signature or the compiled entry parameters, so text-side
    numbering counts kept args only (see :meth:`kept_position`)."""

    index: int
    path: str
    shape: Tuple[int, ...]
    dtype: str
    nbytes: int
    donated: bool
    kept: bool = True


@dataclasses.dataclass(frozen=True)
class PassContext:
    """Everything a lint pass may look at.

    ``hlo_text`` is ``None`` when the program was lowered but not
    compiled (``analyze(..., compile=False)``); passes that need the
    compiled program degrade to lowering-time evidence or report an
    ``info`` finding saying they were skipped.
    """

    stablehlo_text: str
    hlo_text: Optional[str] = None
    args: Tuple[ArgInfo, ...] = ()

    @property
    def kept_args(self) -> Tuple[ArgInfo, ...]:
        """Args that survived pruning, in text/parameter order: the
        k-th entry corresponds to ``%argk`` in the lowered ``main``
        signature and ``parameter(k)`` in the compiled entry."""
        return tuple(a for a in self.args if a.kept)


#: registry: pass name -> ``fn(ctx, **options) -> [Finding]``.  Pass
#: modules register themselves on import (see ``analysis/__init__.py``).
PASSES: Dict[str, Callable[..., List[Finding]]] = {}

#: the whole-program graph passes, safe on any jittable (train steps
#: included).  ``policy`` is deliberately NOT here — it audits forwards.
DEFAULT_PASSES = ("donation", "sharding", "collectives",
                  "constant-capture")


def register_pass(name: str, fn: Callable[..., List[Finding]],
                  replace: bool = False) -> None:
    if name in PASSES and not replace:
        raise ValueError(f"pass {name!r} already registered")
    PASSES[name] = fn


def _leaf_nbytes(shape, dtype) -> int:
    try:
        itemsize = dtype.itemsize
    except AttributeError:
        import numpy as np
        itemsize = np.dtype(dtype).itemsize
    return int(math.prod(shape)) * int(itemsize)


def _args_info(lowered) -> Tuple[ArgInfo, ...]:
    flat, _ = jax.tree_util.tree_flatten_with_path(lowered.args_info)
    try:  # flat indices jit kept (pruned unused args vanish from the text)
        kept_idx = lowered._lowering.compile_args["kept_var_idx"]
    except (AttributeError, KeyError, TypeError):
        kept_idx = None
    out = []
    for i, (path, a) in enumerate(flat):
        out.append(ArgInfo(
            index=i, path=jax.tree_util.keystr(path),
            shape=tuple(a.shape), dtype=str(a.dtype),
            nbytes=_leaf_nbytes(a.shape, a.dtype),
            donated=bool(getattr(a, "donated", False)),
            kept=True if kept_idx is None else i in kept_idx))
    return tuple(out)


def run_passes(ctx: PassContext,
               passes: Optional[Sequence[str]] = None,
               options: Optional[Mapping[str, Mapping[str, Any]]] = None,
               ) -> Report:
    """Run the named passes (default :data:`DEFAULT_PASSES`) over a
    prepared context.  ``options`` maps pass name -> keyword options for
    that pass (e.g. ``{"collectives": {"budget": {"total": 0}}}``)."""
    names = tuple(passes) if passes is not None else DEFAULT_PASSES
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        raise KeyError(f"unknown lint pass(es) {unknown}; registered: "
                       f"{sorted(PASSES)}")
    findings: List[Finding] = []
    for name in names:
        findings.extend(PASSES[name](ctx, **dict((options or {})
                                                 .get(name, {}))))
    return make_report(findings, names)


def analyze_lowered(lowered,
                    passes: Optional[Sequence[str]] = None,
                    compile: bool = True,
                    options: Optional[Mapping] = None) -> Report:
    """Run lint passes over an already-``.lower()``-ed program."""
    hlo_text = lowered.compile().as_text() if compile else None
    ctx = PassContext(stablehlo_text=lowered.as_text(),
                      hlo_text=hlo_text, args=_args_info(lowered))
    return run_passes(ctx, passes=passes, options=options)


def analyze(fn: Callable, *args,
            passes: Optional[Sequence[str]] = None,
            compile: bool = True,
            donate_argnums=(),
            options: Optional[Mapping] = None,
            **kwargs) -> Report:
    """Lower (and compile) ``fn`` on example ``args`` and lint it.

    ``fn`` may already be jitted — its own ``donate_argnums``/sharding
    configuration is kept (re-jitting would drop donation info, which is
    exactly what the donation pass exists to check).  Otherwise it is
    jitted here with ``donate_argnums``.

    JAX's lowering-time "Some donated buffers were not usable" warning
    is suppressed: turning that warning into a structured, gateable
    finding is the donation pass's job.
    """
    jitted = fn if hasattr(fn, "lower") else \
        jax.jit(fn, donate_argnums=donate_argnums)
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        lowered = jitted.lower(*args, **kwargs)
    return analyze_lowered(lowered, passes=passes, compile=compile,
                           options=options)
