"""Structured findings for the static graph lint (:mod:`apex_tpu.analysis`).

Every lint pass — donation, sharding, collectives, constant-capture,
policy — reports through the same two types so results compose: a
:class:`Finding` is one located fact about the program (pass, severity,
op, bytes, message, source line), a :class:`Report` is the ordered
collection for one analyzed program plus the list of passes that ran.

Severity semantics are the gate contract:

- ``error`` — fails the lint (``Report.ok`` is False): dropped buffer
  donations, over-budget collective bytes, captured weight-sized
  constants, FP32-list work executing in 16-bit, a sharding that
  contradicts the declared intent.
- ``warning`` — suspicious but not gated by default: a large fully
  replicated array with no declared intent, a parameter-sized
  all-gather inside a step.
- ``info`` — measurements worth recording (per-kind collective volume,
  fp32-matmul and custom-call counters).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

SEVERITIES = ("error", "warning", "info")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One located fact a lint pass reports about the program."""

    pass_name: str
    severity: str
    message: str
    #: op / object the finding is about (an opcode, a collective kind, an
    #: argument path) — whatever locates it for a human.
    op: Optional[str] = None
    dtype: Optional[str] = None
    #: bytes at stake: wasted HBM for a dropped donation, buffer size for
    #: a replicated array or captured constant, volume for collectives.
    bytes: Optional[int] = None
    count: int = 1
    lineno: Optional[int] = None
    example: Optional[str] = None

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity {self.severity!r} not in "
                             f"{SEVERITIES}")

    def to_dict(self) -> dict:
        """JSON-ready dict, ``None`` fields omitted (stable wire shape
        for ``tools/graph_lint.py`` output lines)."""
        d = {"pass": self.pass_name, "severity": self.severity,
             "message": self.message}
        for k in ("op", "dtype", "bytes", "count", "lineno", "example"):
            v = getattr(self, k)
            if v is not None and not (k == "count" and v == 1):
                d[k] = v
        return d


@dataclasses.dataclass(frozen=True)
class Report:
    """All findings from one analyzed program.

    ``passes`` records which passes actually ran (a pass that ran and
    found nothing is evidence of cleanliness; a pass that never ran is
    not).
    """

    findings: Tuple[Finding, ...] = ()
    passes: Tuple[str, ...] = ()

    @property
    def ok(self) -> bool:
        """No ``error``-severity finding (warnings/info don't gate)."""
        return not any(f.severity == "error" for f in self.findings)

    def by_pass(self, name: str) -> List[Finding]:
        return [f for f in self.findings if f.pass_name == name]

    def by_severity(self, severity: str) -> List[Finding]:
        return [f for f in self.findings if f.severity == severity]

    @property
    def errors(self) -> List[Finding]:
        return self.by_severity("error")

    def merged(self, other: "Report") -> "Report":
        """Combine reports of two programs linted as one unit (e.g. the
        train step's graph passes + the forward's policy pass)."""
        return Report(self.findings + other.findings,
                      self.passes + tuple(p for p in other.passes
                                          if p not in self.passes))

    def to_dict(self) -> dict:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        return {"ok": self.ok, "passes": list(self.passes),
                "counts": counts,
                "findings": [f.to_dict() for f in self.findings]}

    def format(self, max_findings: Optional[int] = None) -> str:
        """Human-readable rendering, errors first."""
        order = {s: i for i, s in enumerate(SEVERITIES)}
        ranked = sorted(self.findings,
                        key=lambda f: (order[f.severity], f.pass_name))
        shown = ranked if max_findings is None else ranked[:max_findings]
        lines = [f"graph lint: {'OK' if self.ok else 'FAIL'} — "
                 f"{len(self.errors)} error(s), "
                 f"{len(self.by_severity('warning'))} warning(s) from "
                 f"passes {', '.join(self.passes) or '(none)'}"]
        for f in shown:
            loc = f" (line {f.lineno})" if f.lineno else ""
            extra = "".join(
                f" {k}={v}" for k, v in (("op", f.op), ("dtype", f.dtype),
                                         ("bytes", f.bytes))
                if v is not None)
            cnt = f" x{f.count}" if f.count != 1 else ""
            lines.append(f"  [{f.severity}] {f.pass_name}: "
                         f"{f.message}{extra}{cnt}{loc}")
        if max_findings is not None and len(ranked) > max_findings:
            lines.append(f"  ... {len(ranked) - max_findings} more")
        return "\n".join(lines)


def make_report(findings: Sequence[Finding],
                passes: Sequence[str]) -> Report:
    return Report(tuple(findings), tuple(passes))
