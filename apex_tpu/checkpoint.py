"""Checkpoint / resume for amp training state.

The reference persisted fp32 masters + scaler state only through the two
FP16_Optimizer wrappers' ``state_dict`` ("option 2: save masters
separately", ``apex/fp16_utils/fp16_optimizer.py:298-359``,
``apex/optimizers/fp16_optimizer.py:211-274``) and had **no** amp-level
checkpoint — the scaler states in ``_amp_state.loss_scalers`` were lost on
restart (SURVEY.md §5.4).  This module closes that gap: the whole
:class:`~apex_tpu.amp.frontend.AmpState` (fp32 masters, optimizer state,
every loss scaler, step counter) plus arbitrary extras (e.g. BatchNorm
running stats, epoch counters) round-trips through the durable snapshot
layer (:mod:`apex_tpu.resilience.durable`): crash-atomic commits
(tmp-dir + fsync + rename), per-leaf sha256 checksums in a manifest,
async save off the step path, and restore that skips a corrupted or
truncated snapshot in favor of the last good one.  Leaves are gathered
to full host arrays on save and placed onto the *template's* shardings
on restore, so a state saved sharded on an 8-device mesh restores
bit-identically onto a 4-device mesh (or a single device).

App-level pattern (the reference's epoch checkpointing,
``examples/imagenet/main_amp.py:170-185,244-254``)::

    mgr = CheckpointManager(dir, max_to_keep=3)
    mgr.save(step, state, extras={"batch_stats": bs, "epoch": e})
    state, extras = mgr.restore(state, extras=...)   # on resume
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Tuple

import jax
import numpy as np

from apex_tpu.amp.frontend import AmpState
from apex_tpu.amp.scaler import LossScaleState
from apex_tpu.resilience.durable import DurableCheckpointManager


def payload_template(state: AmpState,
                     extras: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """The nested-dict *layout* of a checkpoint payload, with the state's
    own leaves (no host transfer) — what the durable layer flattens to
    name leaves, and what :func:`state_dict` materializes."""
    return {
        "master_params": state.master_params,
        "opt_state": state.opt_state,
        "scaler_states": [
            {"loss_scale": s.loss_scale, "unskipped": s.unskipped}
            for s in state.scaler_states],
        "step": state.step,
        # O4's delayed-scaling state (quant.fp8.Fp8TrainState) — None
        # below O4, which contributes no leaves, so pre-fp8 checkpoints
        # and templates keep matching structurally.
        "fp8_state": state.fp8_state,
        # Always present (possibly empty) so save/restore tree structures
        # match whenever both sides pass the same extras template.
        "extras": extras if extras else {},
    }


def state_dict(state: AmpState, extras: Optional[Dict[str, Any]] = None
               ) -> Dict[str, Any]:
    """AmpState → plain nested dict (the ``amp.state_dict`` the reference
    snapshot lacked).  Everything is converted to host numpy so the result
    pickles / serializes with any backend.  For a sharded (but fully
    addressable) state this gathers each leaf to one full host array —
    the layout-free form the durable snapshot layer stores."""
    return jax.tree.map(np.asarray, payload_template(state, extras))


def check_same_structure(saved_keys: Iterable[str],
                         template_keys: Iterable[str],
                         context: str = "checkpoint") -> None:
    """Raise a debuggable error when saved and template leaf sets differ.

    The reference's ``load_state_dict`` had the same structural contract
    (optimizer/model constructed identically, ``fp16_optimizer.py:330-359``)
    but a mismatch surfaced as a cryptic zip/tree error.  Here the first
    diverging tree path is named explicitly, for both directions."""
    saved, tmpl = set(saved_keys), set(template_keys)
    if saved == tmpl:
        return
    missing = sorted(tmpl - saved)      # template expects, checkpoint lacks
    extra = sorted(saved - tmpl)        # checkpoint has, template lacks
    first = (missing + extra)[0] if missing else extra[0]
    detail = []
    if missing:
        detail.append(f"missing from {context}: {missing[:3]}"
                      + (" ..." if len(missing) > 3 else ""))
    if extra:
        detail.append(f"not in template: {extra[:3]}"
                      + (" ..." if len(extra) > 3 else ""))
    raise ValueError(
        f"structural mismatch between {context} and template at leaf "
        f"{first!r} ({'; '.join(detail)}; {len(saved)} saved vs "
        f"{len(tmpl)} template leaves).  The model/optimizer must be "
        "constructed identically to the run that saved — the reference's "
        "load_state_dict contract (fp16_optimizer.py:330-359).")


def _leaf_keys(tree: Any) -> Iterable[str]:
    return (jax.tree_util.keystr(p)
            for p, _ in jax.tree_util.tree_leaves_with_path(tree))


def load_state_dict(template: AmpState, d: Dict[str, Any]
                    ) -> Tuple[AmpState, Dict[str, Any]]:
    """Rebuild an AmpState from :func:`state_dict` output.  ``template``
    (e.g. a freshly ``Amp.init``-ed state) supplies the tree structure and
    dtypes; saved leaves are matched structurally, so the optimizer and
    model must be constructed identically — the same contract as the
    reference's ``load_state_dict`` (``fp16_optimizer.py:330-359``).  A
    structural mismatch raises naming the first diverging leaf path."""
    target = payload_template(template)
    del target["extras"]    # extras follow their own (optional) contract
    # O2→O4 warm start: a pre-fp8 checkpoint (no "fp8_state" key)
    # restoring into an fp8 template keeps the template's FRESH
    # delayed-scaling state — the amax history is a running statistic
    # of the new regime, not trained state, so "start fresh" is the
    # correct semantics (masters/moments/scalers still restore).
    warm_start_fp8 = template.fp8_state is not None \
        and "fp8_state" not in d
    if warm_start_fp8:
        del target["fp8_state"]
    saved = {k: d.get(k) for k in target}
    check_same_structure(_leaf_keys(saved), _leaf_keys(target))

    def like(saved_tree, ref):
        return jax.tree.map(
            lambda s, r: jax.numpy.asarray(s, dtype=r.dtype), saved_tree, ref)

    scalers = tuple(
        LossScaleState(
            loss_scale=jax.numpy.asarray(sd["loss_scale"],
                                         dtype=ref.loss_scale.dtype),
            unskipped=jax.numpy.asarray(sd["unskipped"],
                                        dtype=ref.unskipped.dtype))
        for sd, ref in zip(d["scaler_states"], template.scaler_states))
    fp8_state = None
    if template.fp8_state is not None:
        fp8_state = template.fp8_state if warm_start_fp8 \
            else like(d["fp8_state"], template.fp8_state)
    state = AmpState(
        master_params=like(d["master_params"], template.master_params),
        opt_state=like(d["opt_state"], template.opt_state),
        scaler_states=scalers,
        step=jax.numpy.asarray(d["step"], dtype=template.step.dtype),
        fp8_state=fp8_state,
    )
    return state, d.get("extras", {})


class CheckpointManager(DurableCheckpointManager):
    """Durable epoch/step checkpointing with retention.

    Persists the full amp training state; ``restore`` resumes the scaler
    exactly (loss scale + unskipped counter), which the reference could
    not do.  Backed by :class:`~apex_tpu.resilience.durable.
    DurableCheckpointManager` (crash-atomic commit, per-leaf checksums,
    async save, corrupted-snapshot fallback, mesh-reshape restore); this
    subclass only pins the historical constructor signature.
    """

    def __init__(self, directory: str, max_to_keep: int = 3, **kwargs: Any):
        super().__init__(directory, max_to_keep=max_to_keep, **kwargs)
