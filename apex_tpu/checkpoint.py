"""Checkpoint / resume for amp training state.

The reference persisted fp32 masters + scaler state only through the two
FP16_Optimizer wrappers' ``state_dict`` ("option 2: save masters
separately", ``apex/fp16_utils/fp16_optimizer.py:298-359``,
``apex/optimizers/fp16_optimizer.py:211-274``) and had **no** amp-level
checkpoint — the scaler states in ``_amp_state.loss_scalers`` were lost on
restart (SURVEY.md §5.4).  This module closes that gap: the whole
:class:`~apex_tpu.amp.frontend.AmpState` (fp32 masters, optimizer state,
every loss scaler, step counter) plus arbitrary extras (e.g. BatchNorm
running stats, epoch counters) round-trips through orbax.

App-level pattern (the reference's epoch checkpointing,
``examples/imagenet/main_amp.py:170-185,244-254``)::

    mgr = CheckpointManager(dir, max_to_keep=3)
    mgr.save(step, state, extras={"batch_stats": bs, "epoch": e})
    state, extras = mgr.restore(state, extras=...)   # on resume
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from apex_tpu.amp.frontend import AmpState
from apex_tpu.amp.scaler import LossScaleState


def state_dict(state: AmpState, extras: Optional[Dict[str, Any]] = None
               ) -> Dict[str, Any]:
    """AmpState → plain nested dict (the ``amp.state_dict`` the reference
    snapshot lacked).  Everything is converted to host numpy so the result
    pickles / serializes with any backend."""
    return {
        "master_params": jax.tree.map(np.asarray, state.master_params),
        "opt_state": jax.tree.map(np.asarray, state.opt_state),
        "scaler_states": [
            {"loss_scale": np.asarray(s.loss_scale),
             "unskipped": np.asarray(s.unskipped)}
            for s in state.scaler_states],
        "step": np.asarray(state.step),
        # Always present (possibly empty) so save/restore tree structures
        # match whenever both sides pass the same extras template.
        "extras": jax.tree.map(np.asarray, extras if extras else {}),
    }


def load_state_dict(template: AmpState, d: Dict[str, Any]
                    ) -> Tuple[AmpState, Dict[str, Any]]:
    """Rebuild an AmpState from :func:`state_dict` output.  ``template``
    (e.g. a freshly ``Amp.init``-ed state) supplies the tree structure and
    dtypes; saved leaves are matched structurally, so the optimizer and
    model must be constructed identically — the same contract as the
    reference's ``load_state_dict`` (``fp16_optimizer.py:330-359``)."""
    def like(saved, ref):
        return jax.tree.map(
            lambda s, r: jax.numpy.asarray(s, dtype=r.dtype), saved, ref)

    scalers = tuple(
        LossScaleState(
            loss_scale=jax.numpy.asarray(sd["loss_scale"],
                                         dtype=ref.loss_scale.dtype),
            unskipped=jax.numpy.asarray(sd["unskipped"],
                                        dtype=ref.unskipped.dtype))
        for sd, ref in zip(d["scaler_states"], template.scaler_states))
    state = AmpState(
        master_params=like(d["master_params"], template.master_params),
        opt_state=like(d["opt_state"], template.opt_state),
        scaler_states=scalers,
        step=jax.numpy.asarray(d["step"], dtype=template.step.dtype),
    )
    return state, d.get("extras", {})


class CheckpointManager:
    """Orbax-backed epoch/step checkpointing with retention.

    Persists the full amp training state; ``restore`` resumes the scaler
    exactly (loss scale + unskipped counter), which the reference could
    not do.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def save(self, step: int, state: AmpState,
             extras: Optional[Dict[str, Any]] = None) -> None:
        """Write asynchronously — the training loop is not blocked on disk
        (call :meth:`wait` / :meth:`close` before exiting, as the imagenet
        example does; ``restore`` waits automatically)."""
        payload = state_dict(state, extras)
        self._mgr.save(int(step),
                       args=self._ocp.args.StandardSave(payload))

    def wait(self) -> None:
        """Block until any in-flight async save has committed."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def latest_step(self) -> Optional[int]:
        self._mgr.wait_until_finished()
        return self._mgr.latest_step()

    def restore(self, template: AmpState,
                step: Optional[int] = None,
                extras: Optional[Dict[str, Any]] = None
                ) -> Tuple[AmpState, Dict[str, Any]]:
        """Restore the given (or latest) step.

        ``extras`` must be a structure template matching what the
        checkpoint was *saved* with (same keys/shapes; values are ignored)
        — the same structural contract as ``load_state_dict``.  A save
        without extras restores without them.
        """
        self._mgr.wait_until_finished()
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found in {self._dir}")
        target = state_dict(template, extras)
        payload = self._mgr.restore(
            int(step), args=self._ocp.args.StandardRestore(target))
        return load_state_dict(template, payload)
