"""The multi-tensor applier singleton.

Port of ``apex/multi_tensor_apply/multi_tensor_apply.py:3-30``: a callable
holding the chunk size, applied as ``multi_tensor_applier(op, tensor_lists,
*args)``.  Differences forced by functional JAX:

- no ``noop_flag_buffer`` argument — ops *return* the overflow flag instead
  of writing into a caller-owned buffer;
- ``available`` is always True: the fused path has no optional native build
  (the Pallas/jnp choice is made inside each op, see
  :mod:`apex_tpu.ops`).
"""

from __future__ import annotations

from apex_tpu.ops.multi_tensor import DEFAULT_CHUNK_SIZE


class MultiTensorApply:
    available = True
    import_err = None

    def __init__(self, chunk_size: int = DEFAULT_CHUNK_SIZE):
        self.chunk_size = int(chunk_size)

    def __call__(self, op, tensor_lists, *args, **kwargs):
        return op(self.chunk_size, tensor_lists, *args, **kwargs)


multi_tensor_applier = MultiTensorApply(DEFAULT_CHUNK_SIZE)
