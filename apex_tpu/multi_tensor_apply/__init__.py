from apex_tpu.multi_tensor_apply.multi_tensor_apply import (
    MultiTensorApply,
    multi_tensor_applier,
)

__all__ = ["MultiTensorApply", "multi_tensor_applier"]
