"""ctypes bindings for the native host-runtime library (``csrc/``).

The reference shipped five CUDA extension modules whose *host* halves did
tensor-list packing and metadata planning (``csrc/flatten_unflatten.cpp``,
``csrc/multi_tensor_apply.cuh:39-125``).  On TPU the device kernels are
Pallas; this module is the native host runtime: multithreaded
flatten/unflatten of numpy buffers, DDP bucket planning, and the digest
primitive for the L1 conformance harness.

The library auto-builds from ``csrc/`` on first import when a toolchain is
present (``make -C csrc``); everything has a pure-numpy fallback, and
``available`` mirrors ``multi_tensor_applier.available`` in the reference —
consumers probe it and degrade gracefully.  Set ``APEX_TPU_NATIVE=0`` to
force the fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "libapex_tpu_C.so")
_CSRC = os.path.normpath(os.path.join(_HERE, "..", "..", "csrc"))

available = False
import_err: Optional[BaseException] = None
_lib = None


def _load() -> None:
    global available, import_err, _lib
    if os.environ.get("APEX_TPU_NATIVE", "1") == "0":
        import_err = RuntimeError("disabled via APEX_TPU_NATIVE=0")
        return
    try:
        if not os.path.exists(_LIB_PATH) and os.path.isdir(_CSRC):
            subprocess.run(["make", "-C", _CSRC], check=True,
                           capture_output=True)
        lib = ctypes.CDLL(_LIB_PATH)
        lib.apex_flatten.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_char_p,
            ctypes.c_int]
        lib.apex_unflatten.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int]
        lib.apex_plan_buckets.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
        lib.apex_plan_buckets.restype = ctypes.c_int64
        lib.apex_fingerprint64.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64]
        lib.apex_fingerprint64.restype = ctypes.c_uint64
        lib.apex_native_abi_version.restype = ctypes.c_int
        if lib.apex_native_abi_version() != 1:
            raise RuntimeError("apex_tpu_C ABI version mismatch")
        _lib = lib
        available = True
    except BaseException as e:  # noqa: BLE001 — mirror reference import probe
        import_err = e


_load()

_N_THREADS = min(8, os.cpu_count() or 1)


def _as_i64(seq) -> "ctypes.Array":
    return (ctypes.c_int64 * len(seq))(*seq)


def flatten(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """Pack host arrays (same dtype) into one flat 1-D array
    (``apex_C.flatten``)."""
    if not arrays:
        raise ValueError("flatten requires at least one array")
    arrays = [np.ascontiguousarray(a) for a in arrays]
    dtype = arrays[0].dtype
    if any(a.dtype != dtype for a in arrays):
        raise ValueError("flatten requires a single dtype per call "
                         "(group_by_dtype first)")
    nbytes = [a.nbytes for a in arrays]
    offsets = np.concatenate([[0], np.cumsum(nbytes[:-1])]).astype(np.int64)
    out = np.empty(sum(nbytes) // dtype.itemsize, dtype=dtype)
    if not available:
        for a, off in zip(arrays, offsets):
            start = int(off) // dtype.itemsize
            out[start:start + a.size] = a.ravel()
        return out
    srcs = (ctypes.c_void_p * len(arrays))(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays])
    _lib.apex_flatten(srcs, _as_i64(nbytes),
                      _as_i64([int(o) for o in offsets]),
                      len(arrays), out.ctypes.data_as(ctypes.c_char_p),
                      _N_THREADS)
    return out


def unflatten(flat: np.ndarray,
              shapes: Sequence[Tuple[int, ...]]) -> List[np.ndarray]:
    """Split a flat array back into arrays of ``shapes``
    (``apex_C.unflatten``)."""
    flat = np.ascontiguousarray(flat)
    sizes = [int(np.prod(s)) if len(s) else 1 for s in shapes]
    if sum(sizes) != flat.size:
        raise ValueError(f"flat buffer has {flat.size} elements, shapes "
                         f"require {sum(sizes)}")
    outs = [np.empty(s, dtype=flat.dtype) for s in shapes]
    offsets = np.concatenate([[0], np.cumsum(sizes[:-1])]).astype(np.int64)
    if not available:
        for o, size, off in zip(outs, sizes, offsets):
            start = int(off)
            o.ravel()[:] = flat[start:start + size]
        return outs
    itemsize = flat.dtype.itemsize
    nbytes = [s * itemsize for s in sizes]
    byte_offsets = [int(o) * itemsize for o in offsets]
    dsts = (ctypes.c_void_p * len(outs))(
        *[o.ctypes.data_as(ctypes.c_void_p).value for o in outs])
    _lib.apex_unflatten(flat.ctypes.data_as(ctypes.c_char_p),
                        _as_i64(nbytes), _as_i64(byte_offsets),
                        len(outs), dsts, _N_THREADS)
    return outs


def plan_buckets(numels: Sequence[int], message_numel: int,
                 triggers: Optional[Sequence[bool]] = None) -> np.ndarray:
    """Greedy in-order bucket assignment (apex DDP first-iteration bucketing,
    ``apex/parallel/distributed.py:339-362``): close the running bucket once
    its cumulative numel reaches ``message_numel`` or at a trigger tensor.

    Returns an int64 array of bucket ids, one per tensor.
    """
    n = len(numels)
    ids = np.empty(n, dtype=np.int64)
    if triggers is not None and len(triggers) != n:
        raise ValueError(f"triggers has {len(triggers)} entries for "
                         f"{n} tensors")
    trig = (np.asarray(triggers, dtype=np.uint8) if triggers is not None
            else np.zeros(n, dtype=np.uint8))
    if not available:
        bucket = acc = 0
        for i in range(n):
            ids[i] = bucket
            acc += int(numels[i])
            if acc >= message_numel or trig[i]:
                bucket += 1
                acc = 0
        return ids
    _lib.apex_plan_buckets(
        _as_i64([int(x) for x in numels]),
        trig.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n, int(message_numel),
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    return ids


def fingerprint64(data, seed: int = 0) -> int:
    """FNV-1a digest of an array's (or bytes') raw contents — the primitive
    behind the L1 golden-digest comparisons."""
    if isinstance(data, (bytes, bytearray)):
        buf = np.frombuffer(bytes(data), dtype=np.uint8)
    else:
        buf = np.ascontiguousarray(data).view(np.uint8).ravel()
    if not available:
        h = seed if seed else 0xCBF29CE484222325
        for b in buf.tobytes():
            h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
        return h
    return int(_lib.apex_fingerprint64(
        buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes, seed))
