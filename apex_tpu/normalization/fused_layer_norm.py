"""FusedLayerNorm.

Port of ``apex/normalization/fused_layer_norm.py`` +
``csrc/layer_norm_cuda_kernel.cu``.  The CUDA implementation computes (μ, σ²)
with warp-level Welford + Chan merging in fp32 even for fp16 inputs
(``layer_norm_cuda.cpp:132,154``), applies the normalization elementwise, and
has a two-stage backward for (γ, β).  The TPU equivalent keeps the same
numerics contract — statistics in fp32, output in input dtype — as a Pallas
kernel with a custom VJP (:mod:`apex_tpu.ops.pallas.layer_norm_kernels`),
with this jnp path as the always-available reference
(the analog of the reference's CPU ``F.layer_norm`` fallback,
``fused_layer_norm.py:148-150``).

Input is reshaped to ``(n1, n2)`` around ``normalized_shape`` exactly like
the C++ host side (``layer_norm_cuda.cpp:6-98``).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.ops import use_pallas


def _normalized_shape(shape: Union[int, Sequence[int]]) -> Tuple[int, ...]:
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def fused_layer_norm(x: jax.Array,
                     normalized_shape: Union[int, Sequence[int]],
                     eps: float = 1e-5) -> jax.Array:
    """Non-affine layer norm (``fused_layer_norm_cuda.forward``,
    ``layer_norm_cuda.cpp:234-239``)."""
    return fused_layer_norm_affine(x, None, None, normalized_shape, eps)


def fused_layer_norm_affine(x: jax.Array,
                            weight: Optional[jax.Array],
                            bias: Optional[jax.Array],
                            normalized_shape: Union[int, Sequence[int]],
                            eps: float = 1e-5) -> jax.Array:
    """Affine layer norm (``fused_layer_norm_cuda.forward_affine``).

    Statistics are computed in fp32 regardless of input dtype; the affine
    transform runs in fp32 and the result is cast back to the input dtype.
    """
    nshape = _normalized_shape(normalized_shape)
    assert x.shape[len(x.shape) - len(nshape):] == nshape, (
        f"trailing dims of {x.shape} must equal normalized_shape {nshape}")
    n2 = 1
    for d in nshape:
        n2 *= d
    n1 = x.size // n2

    from apex_tpu.ops.pallas import layer_norm_kernels as lnk
    if use_pallas() and lnk.supported(n2, x.dtype):
        x2d = x.reshape(n1, n2)
        w = None if weight is None else weight.reshape(n2)
        b = None if bias is None else bias.reshape(n2)
        return lnk.layer_norm_fwd_vjp(x2d, w, b, eps).reshape(x.shape)

    x32 = x.reshape(n1, n2).astype(jnp.float32)
    mean = x32.mean(axis=1, keepdims=True)
    var = x32.var(axis=1, keepdims=True)
    invvar = jax.lax.rsqrt(var + eps)
    y = (x32 - mean) * invvar
    if weight is not None:
        y = y * weight.reshape(1, n2).astype(jnp.float32)
    if bias is not None:
        y = y + bias.reshape(1, n2).astype(jnp.float32)
    return y.astype(x.dtype).reshape(x.shape)


class FusedLayerNorm(nn.Module):
    """Module mirroring ``torch.nn.LayerNorm`` semantics
    (``fused_layer_norm.py:64-160``): ``normalized_shape``, ``eps``,
    ``elementwise_affine``; params initialized to γ=1, β=0."""

    normalized_shape: Union[int, Sequence[int]]
    eps: float = 1e-5
    elementwise_affine: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        nshape = _normalized_shape(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("scale", nn.initializers.ones, nshape,
                                self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros, nshape,
                              self.param_dtype)
        else:
            weight = bias = None
        return fused_layer_norm_affine(x, weight, bias, nshape, self.eps)
