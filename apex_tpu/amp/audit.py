"""Compatibility wrapper: the O1 policy audit now lives behind the
shared graph-lint pass API as :mod:`apex_tpu.analysis.policy` (the
``"policy"`` pass of :func:`apex_tpu.analysis.analyze`).

``amp.audit`` / ``amp.audit_text`` / ``amp.format_report`` keep their
original signatures and report-dict shape (``{ok, violations,
fp32_matmul_count, custom_call_count}``) — existing callers and
``tests/l0/test_policy_audit.py`` run unchanged.  New code should
prefer the structured pass API::

    from apex_tpu import analysis
    report = analysis.analyze(forward, *args, passes=("policy",),
                              compile=False)

See ``apex_tpu/analysis/policy.py`` for the audit's full design notes
(why the walk runs on pre-optimization StableHLO, why it audits the
forward rather than the train step, and what is deliberately not
flagged).
"""

from __future__ import annotations

from apex_tpu.analysis.policy import (  # noqa: F401
    BLACKLIST_POINTWISE,
    audit,
    audit_text,
    format_report,
)

__all__ = ["audit", "audit_text", "format_report", "BLACKLIST_POINTWISE"]
