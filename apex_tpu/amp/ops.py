"""Policy-aware op layer — the TPU-native O1 casting engine.

The reference implements O1 by monkey-patching ``torch`` / ``torch.nn.functional``
/ ``torch.Tensor`` at runtime (``apex/amp/amp.py:68-177``, ``wrap.py:10-147``).
JAX has no mutable eager namespace worth patching — everything is traced — so
the same capability is delivered as:

1. a **policy-aware op namespace** (this module): ``ops.matmul``, ``ops.conv``,
   ``ops.softmax``, … consulted by this framework's layers.  Each op casts its
   floating inputs per the tables in :mod:`apex_tpu.amp.lists` *when a cast
   policy is active* (i.e. while tracing inside an O1 train step), and is a
   transparent passthrough otherwise;
2. **decorators/registrars for user functions** — :func:`half_function`,
   :func:`float_function`, :func:`promote_function` and their ``register_*``
   variants (reference ``apex/amp/__init__.py:1-4``, ``frontend.py:356-395``);
3. :func:`cast_context` / :func:`disable_casts` context managers (reference
   ``handle.py:159-163``).

The reference's per-iteration cast cache (``utils.py:87-119``) has no analog
here on purpose: repeated casts of the same parameter inside one trace are
deduplicated by XLA CSE, which is exactly the memory/compute saving the cache
bought in eager mode.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp import lists
from apex_tpu.amp.policy import Properties


class _CastState(threading.local):
    def __init__(self):
        self.policy: Optional[Properties] = None
        self.disable_depth: int = 0


_state = _CastState()


def active_policy() -> Optional[Properties]:
    """The policy in effect for op casting, or None."""
    if _state.disable_depth > 0:
        return None
    p = _state.policy
    if p is not None and p.enabled and p.cast_ops:
        return p
    return None


@contextlib.contextmanager
def cast_context(props: Properties):
    """Activate O1 op casting for the dynamic extent (typically: while tracing
    the loss function inside an O1 train step)."""
    prev = _state.policy
    _state.policy = props
    try:
        yield
    finally:
        _state.policy = prev


@contextlib.contextmanager
def disable_casts():
    """Suspend op casting (reference ``handle.py:159-163``) — e.g. to run a
    numerically sensitive user region in fp32 inside an O1 step.  Under an
    fp8 policy this is also the opt-OUT hook for operand quantization
    (the deny-side override the FP8 lists document)."""
    _state.disable_depth += 1
    try:
        yield
    finally:
        _state.disable_depth -= 1


# ---------------------------------------------------------------------------
# fp8 (O4) operand quantization
# ---------------------------------------------------------------------------
# Under an fp8 policy (``Properties.fp8``, the O4 opt level) the
# contraction family quantizes its two operands onto the e4m3 grid with
# the DELAYED scales carried in ``AmpState.fp8_state`` and rounds the
# output's cotangent onto the e5m2 grid (``quant.fp8.bwd_qdq``).  The
# scales enter — and the per-callsite amaxes leave — through a
# trace-local context (:func:`fp8_trace`) opened by ``make_train_step``
# around the loss: everything in it is a traced value of the SAME
# trace, so the state stays purely functional (the collected amaxes
# return through the loss aux and roll the history at end of step).
#
# Every e4m3/e5m2 value is exactly representable in bf16 (both formats'
# exponent and mantissa ranges are strict subsets), so running the op
# itself on the quantize-dequantized bf16 values accumulates EXACTLY
# what an fp8-operand dot with ``preferred_element_type=f32`` would —
# the native-operand spelling lives in :func:`apex_tpu.quant.fp8.
# scaled_matmul` for callers that manage per-tensor states themselves.


class _Fp8TraceState(threading.local):
    def __init__(self):
        self.scales = None    # {"input","weight","grad"} -> traced f32
        self.amaxes = None    # {"input","weight"} -> [traced amaxes]


_fp8_state = _Fp8TraceState()


@contextlib.contextmanager
def fp8_trace(fp8_train_state, grad_scale=None):
    """Activate fp8 operand quantization for the traced extent: the
    carried :class:`~apex_tpu.quant.fp8.Fp8TrainState` supplies the
    delayed scales; per-callsite forward amaxes collect on the yielded
    object (``.amaxes``) for the end-of-step history roll.

    ``grad_scale`` overrides the e5m2 cotangent scale — the train step
    passes ``grad.scale / loss_scale`` because the cotangents the
    rounding point sees are LOSS-SCALED while the grad amax history is
    recorded in unscaled units (unit-stable across loss-scale moves,
    and what keeps the precision lint's scale-placement dataflow able
    to prove the program's outputs unscaled)."""
    prev = (_fp8_state.scales, _fp8_state.amaxes)
    _fp8_state.scales = {"input": fp8_train_state.input.scale,
                         "weight": fp8_train_state.weight.scale,
                         "grad": (grad_scale if grad_scale is not None
                                  else fp8_train_state.grad.scale)}
    _fp8_state.amaxes = {"input": [], "weight": []}
    try:
        yield _fp8_state
    finally:
        _fp8_state.scales, _fp8_state.amaxes = prev


def _active_fp8():
    """The live fp8 trace context, or None — requires an fp8 policy in
    effect AND an open :func:`fp8_trace` (a bare ``Amp.run`` under O4
    has no scales to quantize with and degrades to the O2-style half
    cast, documented in the policy docstring)."""
    p = active_policy()
    if p is None or not getattr(p, "fp8", False):
        return None
    if _fp8_state.scales is None:
        return None
    return _fp8_state


def collected_fp8_amaxes(trace) -> "tuple":
    """Reduce the per-callsite amaxes to one (input, weight) pair of
    traced f32 scalars (zeros when nothing quantized)."""
    import jax.numpy as _jnp
    out = []
    for kind in ("input", "weight"):
        vals = trace.amaxes.get(kind, [])
        out.append(_jnp.max(_jnp.stack(vals)) if vals
                   else _jnp.asarray(0.0, _jnp.float32))
    return tuple(out)


def _fp8_call(fn, args, kwargs, p):
    """The fp8 operand-quantization path: qdq the first two floating
    array operands (input class, weight class) onto e4m3 at the delayed
    scales, round the output's cotangent onto e5m2, record amaxes.
    Returns None when the call shape doesn't look like a 2-operand
    contraction (caller falls back to the half cast)."""
    tr = _active_fp8()
    if tr is None:
        return None
    flat = list(args)
    arr_idx = [i for i, a in enumerate(flat) if _is_float_array(a)]
    if len(arr_idx) < 2:
        return None
    from apex_tpu.quant import fp8 as fp8_lib
    i, j = arr_idx[0], arr_idx[1]
    x = jnp.asarray(flat[i]).astype(p.half_dtype)
    w = jnp.asarray(flat[j]).astype(p.half_dtype)
    tr.amaxes["input"].append(fp8_lib.tensor_amax(x))
    tr.amaxes["weight"].append(fp8_lib.tensor_amax(w))
    # straight-through qdq: rounding the cotangent is bwd_qdq's job
    # (e5m2), not a side effect of differentiating the forward casts
    flat[i] = fp8_lib.qdq_ste(x, tr.scales["input"], p.fp8_dtype_fwd)
    flat[j] = fp8_lib.qdq_ste(w, tr.scales["weight"], p.fp8_dtype_fwd)
    rest, rkw = _cast_tree((flat[j + 1:], kwargs), p.half_dtype)
    out = fn(*flat[:j + 1], *rest, **rkw)
    return fp8_lib.bwd_qdq(out, tr.scales["grad"])


# ---------------------------------------------------------------------------
# cast helpers
# ---------------------------------------------------------------------------

def _is_float_array(x: Any) -> bool:
    return isinstance(x, (jax.Array, jnp.ndarray)) and jnp.issubdtype(
        jnp.asarray(x).dtype, jnp.floating)


def _cast_tree(tree: Any, dtype) -> Any:
    """Cast every floating array leaf to ``dtype`` (reference ``utils.py``
    ``casted_args``), leaving ints/bools/non-arrays untouched."""
    def cast(x):
        if _is_float_array(x) and jnp.asarray(x).dtype != dtype:
            return jnp.asarray(x).astype(dtype)
        return x
    return jax.tree.map(cast, tree)


def _widest_float(tree: Any):
    """Widest floating dtype among array leaves (reference ``utils.py``
    ``type_string`` + ``wrap.promote`` widest-type rule)."""
    widest = None
    for leaf in jax.tree.leaves(tree):
        if _is_float_array(leaf):
            dt = jnp.asarray(leaf).dtype
            if widest is None or jnp.finfo(dt).bits > jnp.finfo(widest).bits:
                widest = dt
    return widest


# ---------------------------------------------------------------------------
# wrapper factories (reference wrap.py)
# ---------------------------------------------------------------------------

def half_function(fn: Callable, fp8_eligible: bool = True) -> Callable:
    """Run ``fn`` with floating inputs cast to the policy half dtype
    (reference ``wrap.cached_cast`` → fp16, ``wrap.py:31-39``).  Under
    an fp8 policy with a live :func:`fp8_trace`, the two contraction
    operands additionally quantize onto the e4m3 grid at the delayed
    scales (and the cotangent onto e5m2) — the FP8_OPS behavior; calls
    that don't look like a 2-operand contraction keep the half cast.
    ``fp8_eligible=False`` pins a half op to the plain 16-bit cast
    under O4 too — how the namespace enforces FP8_DENY_OPS membership
    for ops that are HALF ops but not contractions (``prelu``)."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        p = active_policy()
        if p is None:
            return fn(*args, **kwargs)
        if fp8_eligible and getattr(p, "fp8", False):
            out = _fp8_call(fn, args, kwargs, p)
            if out is not None:
                return out
        args, kwargs = _cast_tree((args, kwargs), p.half_dtype)
        return fn(*args, **kwargs)
    wrapper.__amp_wrapped__ = "half"
    return wrapper


def fp8_function(fn: Callable) -> Callable:
    """Opt a user contraction into fp8 operand quantization — the
    override hook the FP8 lists document, mirroring
    :func:`half_function` exactly (it IS the half wrapper: under an fp8
    policy the operands quantize, under a 16-bit policy they half-cast,
    and :func:`disable_casts` suspends both)."""
    wrapper = half_function(fn)
    wrapper.__amp_wrapped__ = "fp8"
    return wrapper


def float_function(fn: Callable) -> Callable:
    """Run ``fn`` with floating inputs cast to fp32 (reference
    ``wrap.make_cast_wrapper`` on the blacklist)."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        p = active_policy()
        if p is None:
            return fn(*args, **kwargs)
        args, kwargs = _cast_tree((args, kwargs), jnp.float32)
        return fn(*args, **kwargs)
    wrapper.__amp_wrapped__ = "float"
    return wrapper


def promote_function(fn: Callable) -> Callable:
    """Run ``fn`` with floating inputs cast to the widest floating input type
    (reference ``wrap.promote``, ``wrap.py:44-69``)."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if active_policy() is None:
            return fn(*args, **kwargs)
        widest = _widest_float((args, kwargs))
        if widest is not None:
            args, kwargs = _cast_tree((args, kwargs), widest)
        return fn(*args, **kwargs)
    wrapper.__amp_wrapped__ = "promote"
    return wrapper


def banned_function(fn: Callable, message: str = lists.BANNED_MESSAGE,
                    allow_banned: bool = False) -> Callable:
    """Raise if called with any half input under an active policy (reference
    ``wrap.err_if_any_half``, ``wrap.py:114-147``)."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        p = active_policy()
        if p is not None and not allow_banned:
            for leaf in jax.tree.leaves((args, kwargs)):
                if _is_float_array(leaf) and jnp.asarray(leaf).dtype == p.half_dtype:
                    raise NotImplementedError(message)
        return fn(*args, **kwargs)
    wrapper.__amp_wrapped__ = "banned"
    return wrapper


# -- module-attribute registrars (reference apex/amp/frontend.py:356-395) ----

_saved_registrations = []


def _register(module: Any, name: str, maker: Callable[[Callable], Callable]):
    orig = getattr(module, name)
    if getattr(orig, "__amp_wrapped__", None) is not None:
        return  # idempotent
    _saved_registrations.append((module, name, orig))
    setattr(module, name, maker(orig))


def register_half_function(module: Any, name: str) -> None:
    _register(module, name, half_function)


def register_float_function(module: Any, name: str) -> None:
    _register(module, name, float_function)


def register_promote_function(module: Any, name: str) -> None:
    _register(module, name, promote_function)


def register_fp8_function(module: Any, name: str) -> None:
    """The fp8 analog of :func:`register_half_function` (FP8_OPS's
    module-attribute override hook)."""
    _register(module, name, fp8_function)


def deactivate_registrations() -> None:
    """Undo all ``register_*`` patches (reference ``AmpHandle._deactivate``,
    ``handle.py:225-241``)."""
    while _saved_registrations:
        module, name, orig = _saved_registrations.pop()
        setattr(module, name, orig)


# ---------------------------------------------------------------------------
# the policy-aware op namespace
# ---------------------------------------------------------------------------
# HALF_OPS — MXU-bound work cast to the half dtype.

matmul = half_function(jnp.matmul)
dot = half_function(jnp.dot)
tensordot = half_function(jnp.tensordot)
einsum = half_function(jnp.einsum)
dot_general = half_function(lax.dot_general)


def _conv_general_dilated(x, kernel, window_strides, padding,
                          lhs_dilation=None, rhs_dilation=None,
                          dimension_numbers=None, feature_group_count=1,
                          batch_group_count=1, precision=None,
                          preferred_element_type=None, **kwargs):
    """Full lax.conv_general_dilated positional signature (so callers
    passing feature/batch_group_count or precision positionally stay
    drop-in compatible), with eligible 1x1 stride-1 NHWC convs routed to
    the fused-backward kernel when opted in (the RN50 conv-MFU
    campaign — see :mod:`apex_tpu.ops.pallas.experimental.conv1x1`)."""
    from apex_tpu.ops.pallas.experimental import conv1x1 as c1
    # only NON-default extras disqualify kernel routing
    extras = dict(kwargs)
    if feature_group_count != 1:
        extras["feature_group_count"] = feature_group_count
    if batch_group_count != 1:
        extras["batch_group_count"] = batch_group_count
    if precision is not None:
        extras["precision"] = precision
    if preferred_element_type is not None:
        extras["preferred_element_type"] = preferred_element_type
    if (lhs_dilation is None and rhs_dilation is None
            and c1.routeable(x, kernel, window_strides, padding,
                             dimension_numbers, extras)):
        return c1.conv1x1(x, kernel)
    return lax.conv_general_dilated(x, kernel, window_strides, padding,
                                    lhs_dilation=lhs_dilation,
                                    rhs_dilation=rhs_dilation,
                                    dimension_numbers=dimension_numbers,
                                    feature_group_count=feature_group_count,
                                    batch_group_count=batch_group_count,
                                    precision=precision,
                                    preferred_element_type=preferred_element_type,
                                    **kwargs)


conv_general_dilated = half_function(_conv_general_dilated)
conv_transpose = half_function(lax.conv_transpose)


def _linear(x, kernel, bias=None):
    y = jnp.matmul(x, kernel)
    if bias is not None:
        y = y + bias
    return y


linear = half_function(_linear)

def _conv(x, kernel, bias=None, *, window_strides=None, padding="SAME",
          dimension_numbers=None, **kw):
    """``F.conv*`` spelling (functional_overrides.py:18-24): one N-D entry
    point with an optional bias — dimensionality is carried by the operand
    ranks, unlike torch's conv1d/2d/3d.  Defaults: stride 1, SAME padding,
    channels-last (``NHWC``-style) dimension numbers, the TPU-native layout."""
    spatial = x.ndim - 2
    if window_strides is None:
        window_strides = (1,) * spatial
    if dimension_numbers is None:
        if not 1 <= spatial <= 3:
            raise ValueError(
                f"conv input must have 1-3 spatial dims (got rank {x.ndim} "
                f"= {spatial} spatial); give dimension_numbers explicitly")
        chars = "DHW"[-spatial:]
        dimension_numbers = (f"N{chars}C", f"{chars}IO", f"N{chars}C")
    # one routing point: eligible 1x1 cases reach the fused-backward
    # kernel through the same dispatch as ops.conv_general_dilated
    y = _conv_general_dilated(x, kernel, window_strides, padding,
                              dimension_numbers=dimension_numbers, **kw)
    if bias is not None:
        y = y + bias
    return y


conv = half_function(_conv)


def _prelu(x, alpha):
    return jnp.where(x >= 0, x, alpha * x)


# torch_overrides.py:7-26 FP16 list — but FP8_DENY_OPS: prelu is a
# pointwise select, not a contraction, so under O4 it keeps the plain
# 16-bit cast (quantizing alpha would pollute the weight amax history)
prelu = half_function(_prelu, fp8_eligible=False)

# FP32_OPS — numerically sensitive work cast to fp32.

exp = float_function(jnp.exp)
expm1 = float_function(jnp.expm1)
log = float_function(jnp.log)
log1p = float_function(jnp.log1p)
log2 = float_function(jnp.log2)
log10 = float_function(jnp.log10)
pow = float_function(jnp.power)  # noqa: A001 - mirrors reference list name
reciprocal = float_function(jnp.reciprocal)
rsqrt = float_function(lax.rsqrt)
sinh = float_function(jnp.sinh)
cosh = float_function(jnp.cosh)
tan = float_function(jnp.tan)
acos = float_function(jnp.arccos)
asin = float_function(jnp.arcsin)
erfinv = float_function(jax.scipy.special.erfinv)
sum = float_function(jnp.sum)  # noqa: A001
prod = float_function(jnp.prod)
mean = float_function(jnp.mean)
var = float_function(jnp.var)
std = float_function(jnp.std)
cumsum = float_function(jnp.cumsum)
cumprod = float_function(jnp.cumprod)
logsumexp = float_function(jax.scipy.special.logsumexp)
softmax = float_function(jax.nn.softmax)
log_softmax = float_function(jax.nn.log_softmax)
softplus = float_function(jax.nn.softplus)


def _norm(x, ord=None, axis=None, keepdims=False):
    return jnp.linalg.norm(x, ord=ord, axis=axis, keepdims=keepdims)


norm = float_function(_norm)


def _softmin(x, axis=-1):
    return jax.nn.softmax(-x, axis=axis)


softmin = float_function(_softmin)


def _layer_norm(x, normalized_shape, weight=None, bias=None, eps=1e-5):
    """``F.layer_norm`` semantics: normalize over the trailing
    ``len(normalized_shape)`` dims (functional_overrides.py:29-65; the fused
    module lives in :mod:`apex_tpu.normalization`)."""
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight
    if bias is not None:
        y = y + bias
    return y


layer_norm = float_function(_layer_norm)


def _group_norm(x, num_groups, weight=None, bias=None, eps=1e-5):
    """``F.group_norm`` with channels LAST (TPU-native layout; torch is
    channels-first)."""
    c = x.shape[-1]
    if c % num_groups:
        raise ValueError(f"channels {c} not divisible by groups {num_groups}")
    shape = x.shape
    g = x.reshape(shape[:-1] + (num_groups, c // num_groups))
    axes = tuple(range(1, g.ndim - 2)) + (g.ndim - 1,)
    mean = jnp.mean(g, axis=axes, keepdims=True)
    var = jnp.var(g, axis=axes, keepdims=True)
    y = ((g - mean) * lax.rsqrt(var + eps)).reshape(shape)
    if weight is not None:
        y = y * weight
    if bias is not None:
        y = y + bias
    return y


group_norm = float_function(_group_norm)


def _batch_norm(x, running_mean, running_var, weight=None, bias=None,
                training=False, eps=1e-5):
    """``F.batch_norm`` normalization over the channels-last axis.  Pure
    function: in training mode it normalizes with batch statistics; running
    stats are carried by the caller (the stateful module is
    :class:`apex_tpu.parallel.SyncBatchNorm`)."""
    if training:
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
    else:
        mean, var = running_mean, running_var
    y = (x - mean) * lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight
    if bias is not None:
        y = y + bias
    return y


batch_norm = float_function(_batch_norm)


def _nll_loss(log_probs, targets):
    picked = jnp.take_along_axis(log_probs, targets[..., None], axis=-1)
    return -jnp.mean(picked)


nll_loss = float_function(_nll_loss)


def _cross_entropy(logits, targets):
    return _nll_loss(jax.nn.log_softmax(logits, axis=-1), targets)


cross_entropy = float_function(_cross_entropy)


def _l1_loss(pred, target):
    return jnp.mean(jnp.abs(pred - target))


l1_loss = float_function(_l1_loss)


def _mse_loss(pred, target):
    return jnp.mean(jnp.square(pred - target))


mse_loss = float_function(_mse_loss)


def _smooth_l1_loss(pred, target, beta=1.0):
    d = jnp.abs(pred - target)
    return jnp.mean(jnp.where(d < beta, 0.5 * d * d / beta, d - 0.5 * beta))


smooth_l1_loss = float_function(_smooth_l1_loss)


def _kl_div(log_pred, target):
    """``F.kl_div`` pointwise ``target * (log(target) - log_pred)``,
    mean-reduced, with the 0·log0 = 0 convention."""
    pointwise = jnp.where(target > 0,
                          target * (jnp.log(jnp.maximum(target, 1e-38))
                                    - log_pred),
                          0.0)
    return jnp.mean(pointwise)


kl_div = float_function(_kl_div)


def _poisson_nll_loss(log_input, target):
    return jnp.mean(jnp.exp(log_input) - target * log_input)


poisson_nll_loss = float_function(_poisson_nll_loss)


def _cosine_embedding_loss(x1, x2, y, margin=0.0, eps=1e-8):
    cos = jnp.sum(x1 * x2, axis=-1) * lax.rsqrt(
        jnp.maximum(jnp.sum(x1 * x1, axis=-1) * jnp.sum(x2 * x2, axis=-1),
                    eps * eps))
    loss = jnp.where(y == 1, 1.0 - cos, jnp.maximum(0.0, cos - margin))
    return jnp.mean(loss)


cosine_embedding_loss = float_function(_cosine_embedding_loss)

# PROMOTE_OPS — jnp binary promotion already picks the widest type; exported
# wrapped anyway so user code routed through ops.* is policy-auditable.

add = promote_function(jnp.add)
sub = promote_function(jnp.subtract)
mul = promote_function(jnp.multiply)
div = promote_function(jnp.divide)
atan2 = promote_function(jnp.arctan2)
maximum = promote_function(jnp.maximum)
minimum = promote_function(jnp.minimum)
equal = promote_function(jnp.equal)
greater = promote_function(jnp.greater)
less = promote_function(jnp.less)

# SEQUENCE_PROMOTE_OPS (reference wrap.sequence_promote, wrap.py:71-90)


def _sequence_promote(fn):
    @functools.wraps(fn)
    def wrapper(arrays, *args, **kwargs):
        if active_policy() is None:
            return fn(arrays, *args, **kwargs)
        widest = _widest_float(list(arrays))
        if widest is not None:
            arrays = [_cast_tree(a, widest) for a in arrays]
        return fn(arrays, *args, **kwargs)
    wrapper.__amp_wrapped__ = "sequence_promote"
    return wrapper


concatenate = _sequence_promote(jnp.concatenate)
stack = _sequence_promote(jnp.stack)

# BANNED_OPS


def _binary_cross_entropy(probs, targets):
    p = probs.astype(jnp.float32)
    t = targets.astype(jnp.float32)
    return -jnp.mean(t * jnp.log(p) + (1.0 - t) * jnp.log1p(-p))


binary_cross_entropy = banned_function(_binary_cross_entropy)
