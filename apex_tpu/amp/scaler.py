"""Jit-safe dynamic loss scaling.

TPU-native port of the reference's ``apex/amp/scaler.py``.  The reference
keeps a device-side overflow buffer and performs exactly one D2H sync per
iteration (``scaler.py:192-193`` reads ``_overflow_buf.item()`` in
``update_scale``).  On TPU we go further: the scale, the good-step counter,
and the overflow flag are all device-side pytree state, the scale update is
pure ``jnp`` arithmetic, and step skipping is a ``lax.cond`` inside the
compiled step — there is **no** host sync anywhere in the hot loop.

Semantics matched to the reference:

- dynamic scale starts at ``2**16``, doubles after ``scale_window`` (2000)
  consecutive overflow-free steps, halves on overflow, clamped to
  ``[min_loss_scale, max_loss_scale]`` with ``max_loss_scale=2**24``
  (``scaler.py:39-72,190-210``).
- a *static* scale never changes, but overflow still skips the step
  (``scaler.py:190-198`` adjusts only when ``dynamic``).
- unscaling fuses the fp16→fp32 copy, the multiply by ``1/scale``, and the
  non-finite check into one pass (``scaler.py:113-116`` via
  ``amp_C.multi_tensor_scale``); here that is
  :func:`apex_tpu.multi_tensor_apply.multi_tensor_scale`, and on top XLA
  fuses it into neighbouring ops.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from apex_tpu.amp.policy import DYNAMIC


class LossScaleState(NamedTuple):
    """Device-side scaler state (a pytree; carry it through your step fn)."""

    loss_scale: jax.Array  # f32 scalar
    unskipped: jax.Array   # i32 scalar: consecutive overflow-free steps


def all_finite(tree: Any) -> jax.Array:
    """Single boolean: every element of every leaf is finite.

    Reference analog: the ``noop_flag`` set by ``multi_tensor_scale_kernel.cu:71``
    (any non-finite value flips a shared flag), or the Python fallback's
    per-tensor ``sum()`` check (``scaler.py:6-17``).
    """
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    flags = [jnp.all(jnp.isfinite(leaf)) for leaf in leaves
             if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact)]
    if not flags:
        return jnp.asarray(True)
    return jnp.stack(flags).all()


@dataclasses.dataclass(frozen=True)
class LossScaler:
    """Configuration + pure state-transition functions (``scaler.py:39-210``).

    ``loss_scale="dynamic"`` selects dynamic scaling; a number selects a
    static scale.
    """

    loss_scale: Union[float, str] = DYNAMIC
    init_scale: float = 2.0 ** 16
    scale_factor: float = 2.0
    scale_window: int = 2000
    min_loss_scale: Optional[float] = None
    max_loss_scale: float = 2.0 ** 24

    @property
    def dynamic(self) -> bool:
        return self.loss_scale == DYNAMIC

    def init_state(self) -> LossScaleState:
        scale = self.init_scale if self.dynamic else float(self.loss_scale)
        return LossScaleState(
            loss_scale=jnp.asarray(scale, jnp.float32),
            unskipped=jnp.asarray(0, jnp.int32),
        )

    @property
    def floor(self) -> float:
        """The effective minimum scale of the dynamic transition —
        ``min_loss_scale`` or the 1.0 default :meth:`update` clamps to."""
        return self.min_loss_scale if self.min_loss_scale is not None else 1.0

    def pinned_at_floor(self, state: LossScaleState) -> jax.Array:
        """Device-side flag: the dynamic scale sits at its floor, i.e. the
        next overflow CANNOT shrink it further.  ``overflow AND pinned``
        sustained for K steps is the divergence sentinel's signal that
        the run is in an overflow *storm*, not a normal transient skip
        (:mod:`apex_tpu.resilience.loop`).  Always False for a static
        scale (it never moves, so "pinned" carries no information)."""
        if not self.dynamic:
            return jnp.asarray(False)
        return state.loss_scale <= jnp.asarray(self.floor, jnp.float32)

    # -- hot-loop ops (all traceable) ------------------------------------

    def scale_loss(self, loss: jax.Array, state: LossScaleState) -> jax.Array:
        """``loss.float() * loss_scale`` (``handle.py:116``)."""
        return loss.astype(jnp.float32) * state.loss_scale

    def unscale(self, grads: Any, state: LossScaleState,
                out_dtype=jnp.float32) -> Tuple[Any, jax.Array]:
        """Fused unscale: grads * (1/scale) cast to ``out_dtype``, plus a
        single finite flag (``scaler.py:95-123``).

        Returns ``(unscaled_grads, grads_finite)``.  The finite check runs on
        the *incoming* (still-scaled) grads so that an overflow that saturates
        to inf is always seen, matching the fused kernel which checks the
        input values it reads (``multi_tensor_scale_kernel.cu:57-71``).
        """
        with jax.named_scope("amp_unscale"):
            inv = (1.0 / state.loss_scale).astype(jnp.float32)
            finite = all_finite(grads)
            unscaled = jax.tree.map(
                lambda g: (g.astype(jnp.float32) * inv).astype(out_dtype),
                grads)
            return unscaled, finite

    def unscale_with_stashed(self, new_grads: Any, stashed: Any,
                             state: LossScaleState,
                             out_dtype=jnp.float32) -> Tuple[Any, jax.Array]:
        """Gradient-accumulation path: ``out = (1/scale)·new + 1.0·stashed``
        with the inf-check restricted to the *new* grads
        (``scaler.py:149-182``, ``multi_tensor_axpby`` with arg_to_check=0).
        """
        inv = (1.0 / state.loss_scale).astype(jnp.float32)
        finite = all_finite(new_grads)
        out = jax.tree.map(
            lambda n, s: (n.astype(jnp.float32) * inv
                          + s.astype(jnp.float32)).astype(out_dtype),
            new_grads, stashed)
        return out, finite

    def update(self, state: LossScaleState,
               grads_finite: jax.Array) -> Tuple[LossScaleState, jax.Array]:
        """State transition of ``update_scale`` (``scaler.py:190-210``).

        Returns ``(new_state, should_skip)``; ``should_skip`` is the overflow
        flag (step skipping itself belongs to the optimizer wrapper so the
        whole thing stays one compiled graph).
        """
        overflow = jnp.logical_not(grads_finite)
        if not self.dynamic:
            return state, overflow

        shrunk = jnp.maximum(state.loss_scale / self.scale_factor,
                             jnp.asarray(self.floor, jnp.float32))
        unskipped = jnp.where(overflow, 0, state.unskipped + 1)
        window_hit = unskipped >= self.scale_window
        grown = jnp.minimum(state.loss_scale * self.scale_factor,
                            jnp.asarray(self.max_loss_scale, jnp.float32))
        new_scale = jnp.where(overflow, shrunk,
                              jnp.where(window_hit, grown, state.loss_scale))
        unskipped = jnp.where(window_hit, 0, unskipped)
        return LossScaleState(loss_scale=new_scale, unskipped=unskipped), overflow
