"""apex_tpu.amp — automatic mixed precision for JAX/TPU.

Public surface mirroring the reference ``apex/amp/__init__.py:1-4``
(``initialize``, ``scale_loss``-style flow, ``disable_casts``,
``half_function``/``float_function``/``promote_function`` + ``register_*``)
plus the functional state machine pieces that replace eager monkey-patching:
:class:`Amp`, :class:`AmpState`, :class:`LossScaler`, :func:`make_train_step`.
"""

from apex_tpu.amp import lists, ops
from apex_tpu.amp.audit import audit, audit_text, format_report
from apex_tpu.amp.frontend import (
    Amp,
    AmpState,
    default_keep_fp32_filter,
    initialize,
    make_train_step,
)
from apex_tpu.amp.handle import (
    AmpHandle,
    NoOpHandle,
    active_amp,
    init,
    scale_loss,
)
from apex_tpu.amp.ops import (
    banned_function,
    cast_context,
    disable_casts,
    float_function,
    fp8_function,
    fp8_trace,
    half_function,
    promote_function,
    register_float_function,
    register_fp8_function,
    register_half_function,
    register_promote_function,
)
from apex_tpu.amp.policy import (DYNAMIC, O0, O1, O2, O3, O4, Properties,
                                 opt_levels, resolve)
from apex_tpu.amp.scaler import LossScaler, LossScaleState, all_finite

__all__ = [
    "Amp", "AmpState", "initialize", "make_train_step",
    "init", "scale_loss", "active_amp", "AmpHandle", "NoOpHandle",
    "default_keep_fp32_filter",
    "Properties", "O0", "O1", "O2", "O3", "O4", "opt_levels", "resolve",
    "DYNAMIC",
    "LossScaler", "LossScaleState", "all_finite",
    "ops", "lists",
    "audit", "audit_text", "format_report",
    "cast_context", "disable_casts",
    "half_function", "float_function", "promote_function", "banned_function",
    "fp8_function", "fp8_trace",
    "register_half_function", "register_float_function",
    "register_promote_function", "register_fp8_function",
]


def master_params(state: AmpState):
    """Generator over the fp32 master params (reference ``amp.master_params``,
    ``apex/amp/_initialize.py`` / ``frontend.py`` export): iterate these for
    gradient clipping or inspection."""
    import jax
    yield from jax.tree.leaves(state.master_params)
