"""Casting policy tables — the O1 white/black/promote lists.

Port of ``apex/amp/lists/{torch,functional,tensor}_overrides.py``.  The
reference enumerates *torch* function names to monkey-patch; here the tables
enumerate the ops this framework's policy-aware op layer
(:mod:`apex_tpu.amp.ops`) exposes.  The categories and their members follow
the reference:

- ``HALF_OPS`` (reference ``FP16_FUNCS``, ``torch_overrides.py:7-26`` and
  ``functional_overrides.py:18-27``): compute-bound MXU work — convolutions
  and the BLAS family — which is both faster and accurate enough in 16-bit.
- ``FP32_OPS`` (reference ``FP32_FUNCS``, ``torch_overrides.py:29-56`` and
  ``functional_overrides.py:29-65``): pointwise transcendentals, reductions,
  softmax/norms/losses — numerically sensitive, bandwidth-bound work kept in
  fp32.
- ``PROMOTE_OPS`` (reference ``CASTS``, ``torch_overrides.py:75-97``): binary
  math that should run in the *widest* input type.  ``jnp`` already promotes
  mixed bf16/fp32 operands to fp32, so these need no wrapper at all — the
  table exists for documentation and for the conformance tests.
- ``SEQUENCE_PROMOTE_OPS`` (reference ``SEQUENCE_CASTS``,
  ``torch_overrides.py:100-103``): concatenate/stack of mixed-dtype lists.
- ``BANNED_OPS`` (reference ``functional_overrides.py:67-77``): ops that are
  numerically unsafe in 16-bit no matter what — binary cross entropy on
  probabilities; use a with-logits formulation instead.
"""

HALF_OPS = [
    # BLAS / matmul family (torch_overrides.py:7-26)
    "matmul", "dot", "einsum", "dot_general", "tensordot",
    # convolutions (functional_overrides.py:18-27)
    "conv", "conv_general_dilated", "conv_transpose",
    # linear layers
    "linear", "prelu",
]

FP32_OPS = [
    # transcendental pointwise (torch_overrides.py:29-56)
    "acos", "asin", "cosh", "erfinv", "exp", "expm1", "log", "log10",
    "log1p", "log2", "pow", "reciprocal", "rsqrt", "sinh", "tan",
    # reductions
    "cumprod", "cumsum", "sum", "prod", "mean", "var", "std", "norm",
    "logsumexp",
    # softmax / norms / losses (functional_overrides.py:29-65)
    "softmax", "log_softmax", "softmin", "layer_norm", "group_norm",
    "batch_norm", "cross_entropy", "nll_loss", "l1_loss", "mse_loss",
    "smooth_l1_loss", "kl_div", "poisson_nll_loss", "cosine_embedding_loss",
    "softplus",
]

PROMOTE_OPS = [
    # binary math / comparison (torch_overrides.py:75-97) — jnp type
    # promotion already yields widest-type behavior.
    "add", "div", "mul", "sub", "atan2", "equal", "greater", "less",
    "maximum", "minimum",
]

SEQUENCE_PROMOTE_OPS = ["concatenate", "stack"]  # torch_overrides.py:100-103

BANNED_OPS = ["binary_cross_entropy"]  # functional_overrides.py:67-77

# -- fp8 (O4) lists ---------------------------------------------------------
# The same shape as the 16-bit tables, one level down: under an fp8
# policy only the MXU contraction family quantizes its operands to e4m3
# (f32 accumulation via preferred_element_type); everything in
# FP8_DENY_OPS keeps its 16-bit/fp32 behavior from the tables above —
# fp8's 3 (e4m3) or 2 (e5m2) mantissa bits destroy pointwise
# transcendentals, normalization statistics, and reductions outright
# (Micikevicius et al., 2022 quantize GEMM operands only; so does every
# production fp8 recipe).  Override hooks mirror the 16-bit lists':
# wrap a user function with :func:`apex_tpu.amp.ops.fp8_function` (or
# ``register_fp8_function``) to opt it into operand quantization, and
# ``apex_tpu.amp.disable_casts()`` opts a region out — the exact knobs
# HALF_OPS/FP32_OPS expose.

FP8_OPS = [
    # the contraction family — the only ops whose operands quantize
    "matmul", "dot", "einsum", "dot_general", "tensordot", "linear",
    "conv", "conv_general_dilated", "conv_transpose",
]

FP8_DENY_OPS = [
    # never quantized below the 16-bit tables' decision: pointwise
    # transcendentals + reductions (FP32_OPS) and the remaining half
    # ops whose fp8 error is unbounded relative to their magnitude
    "prelu",
] + FP32_OPS

BANNED_MESSAGE = (
    "amp does not work out-of-the-box with binary_cross_entropy on "
    "probabilities: the op requires inputs in [0,1] that a 16-bit sigmoid "
    "cannot guarantee, and log(0) saturates. Use a *_with_logits loss "
    "(sigmoid folded into the loss, computed in fp32) instead, or wrap the "
    "call in apex_tpu.amp.disable_casts() if you accept the risk. "
    "(Reference: apex/amp/lists/functional_overrides.py:67-77.)"
)
