"""amp frontend: ``initialize`` + the mixed-precision train-step machinery.

TPU-native port of the reference frontend/initialization/optimizer-surgery
stack (``apex/amp/frontend.py:194-353``, ``_initialize.py:150-268``,
``_process_optimizer.py``, ``handle.py:15-154``).  The reference mutates the
user's model and optimizer in place (monkey-patched ``step``/``zero_grad``,
fp32 master clones swapped into param groups, grad hooks).  Here the same
observable semantics are a pure state machine:

- fp32 master params are a pytree in :class:`AmpState` (reference
  ``_process_optimizer.py:29-36`` master clones);
- the half-precision *compute* params are derived by :meth:`Amp.model_params`
  each step (reference ``_master_params_to_model_params`` copy-back,
  ``_process_optimizer.py:242-253`` — under jit, XLA keeps the cast fused
  into the consumers, so the "copy" costs one pass at most);
- loss scaling / unscaling / overflow-skip are the
  :class:`~apex_tpu.amp.scaler.LossScaler` transitions wired into
  :meth:`Amp.apply_gradients` with ``lax.cond`` skip (reference
  ``handle.py:110-150`` scale_loss enter/exit + skip_step patching);
- the whole iteration compiles to one XLA program with **zero** host syncs
  (the reference needed one ``.item()`` per step, ``scaler.py:192-193``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import optax

from apex_tpu.amp import ops as amp_ops
from apex_tpu.amp import policy as policy_lib
from apex_tpu.amp import scaler as scaler_lib
from apex_tpu.amp.policy import Properties
from apex_tpu.amp.scaler import LossScaler, LossScaleState

# Default name fragments identifying normalization params kept in fp32 under
# keep_batchnorm_fp32 (reference skips _BatchNorm modules during the O2 cast,
# fp16util.py:44-70). Matches flax's BatchNorm_*/LayerNorm_*/GroupNorm_* and
# common hand-rolled names.
_NORM_NAME_FRAGMENTS = ("batchnorm", "layernorm", "groupnorm", "norm", "bn")


def default_keep_fp32_filter(path: Tuple[Any, ...]) -> bool:
    """True for param paths that look like normalization-layer params."""
    for entry in path:
        name = str(getattr(entry, "key", getattr(entry, "name", entry))).lower()
        if any(frag in name for frag in _NORM_NAME_FRAGMENTS):
            return True
    return False


class AmpState(NamedTuple):
    """Carried training state for one (model, optimizer) pair.

    ``master_params`` is fp32 when master weights are on; otherwise it holds
    the params at model dtype (O0/O1/O3 semantics — the optimizer runs
    directly on them, ``_process_optimizer.py:165-239``).

    ``fp8_state`` is the delayed-scaling state of the O4 fp8 regime
    (:class:`apex_tpu.quant.fp8.Fp8TrainState`: one amax-history +
    scale per tensor class) and ``None`` below O4.  It sits next to
    the loss-scaler states on purpose: both are "how far can this
    step's values stretch" estimators carried as pure pytree state, so
    ``apply_gradients``, the resilience rewind path, and
    ``DurableCheckpointManager`` handle it with no special cases —
    it's just more leaves.
    """

    master_params: Any
    opt_state: Any
    scaler_states: Tuple[LossScaleState, ...]
    step: jax.Array
    fp8_state: Any = None


@dataclasses.dataclass(frozen=True)
class Amp:
    """Bound mixed-precision configuration (the return of :func:`initialize`)."""

    properties: Properties
    scaler: LossScaler
    tx: optax.GradientTransformation
    apply_fn: Optional[Callable] = None
    num_losses: int = 1
    keep_fp32_filter: Callable[[Tuple[Any, ...]], bool] = default_keep_fp32_filter

    # ------------------------------------------------------------------
    # setup
    # ------------------------------------------------------------------
    def init(self, params: Any) -> AmpState:
        """Build the initial state from user fp32 params (reference
        ``_initialize.py:176-177`` requires incoming fp32; we cast to be safe,
        mirroring ``allow_incoming_model_not_fp32`` leniency)."""
        master = self._master_from(params)
        fp8_state = None
        if self.properties.enabled and self.properties.fp8:
            from apex_tpu.quant import fp8 as fp8_lib
            fp8_state = fp8_lib.init_train_state(
                self.properties.fp8_amax_history_len)
        return AmpState(
            master_params=master,
            opt_state=self.tx.init(master),
            scaler_states=tuple(self.scaler.init_state()
                                for _ in range(self.num_losses)),
            step=jnp.zeros((), jnp.int32),
            fp8_state=fp8_state,
        )

    def _master_from(self, params: Any) -> Any:
        """Derive the carried ("master") representation of a param subtree
        — fp32 clones under master weights, compute-precision otherwise.
        Shared by :meth:`init` and :meth:`add_params` so the policy cannot
        diverge between original and later-added subtrees.

        Every leaf is a genuine CLONE (reference ``_initialize.py``
        ``.clone()`` semantics): ``astype`` to an unchanged dtype is an
        aliasing no-op in JAX, and an aliased master means a
        ``donate_argnums`` train step silently deletes the CALLER'S
        params — a later ``a.init(params)`` then builds a state of dead
        buffers (surfaces as an opaque INVALID_ARGUMENT on TPU)."""
        p = self.properties

        def clone(x, dtype=None):
            if dtype is not None and jnp.issubdtype(x.dtype, jnp.floating):
                return jnp.array(x, dtype=dtype, copy=True)
            return jnp.array(x, copy=True)

        if p.enabled and self._use_master_weights():
            return jax.tree.map(lambda x: clone(x, jnp.float32), params)
        # single pass: clone() with the policy's cast dtype materializes
        # copy and cast together (model_params_from-then-clone would
        # copy changed-dtype leaves twice)
        return jax.tree_util.tree_map_with_path(
            lambda path, x: clone(x, self._cast_leaf_dtype(path)), params)

    def _use_master_weights(self) -> bool:
        return self.properties.use_master_weights

    def _cast_leaf_dtype(self, path) -> Any:
        p = self.properties
        if not p.enabled or p.cast_model_dtype is None:
            return None  # leave as-is
        if p.keep_batchnorm_fp32 and self.keep_fp32_filter(path):
            return jnp.float32
        return p.cast_model_dtype

    def model_params_from(self, params: Any) -> Any:
        """Cast a param pytree to compute precision per the policy
        (reference ``_initialize.py:183-189`` model cast, batchnorm-safe via
        ``convert_network``)."""
        def cast(path, x):
            dt = self._cast_leaf_dtype(path)
            if dt is None or not jnp.issubdtype(x.dtype, jnp.floating):
                return x
            return x.astype(dt)
        return jax.tree_util.tree_map_with_path(cast, params)

    def model_params(self, state: AmpState) -> Any:
        """Compute-precision view of the masters — the per-step equivalent of
        the reference's master→model fused copy
        (``_process_optimizer.py:242-253``)."""
        return self.model_params_from(state.master_params)

    def add_params(self, state: AmpState, new_params: Any) -> AmpState:
        """Grow the carried state with a new top-level param subtree — the
        functional analog of the reference's patched
        ``optimizer.add_param_group`` (``_process_optimizer.py:331-407``),
        which extends the master/fp16 group lists consistently.

        Both ``state.master_params`` and ``new_params`` must be dicts at
        the top level, with disjoint keys.  Optimizer state for existing
        params (moments, step counters) is preserved: the new union state
        is initialized fresh and every leaf whose tree path already
        existed (same shape/dtype) is grafted back from the old state.

        FusedAdam/FusedLAMB carry a per-leaf ``leaf_step`` pytree (the
        reference's per-param ``state['step']``, ``fused_adam.py:119-125``),
        so grafting preserves existing leaves' counts while new leaves
        start at step 0 — bias correction treats the new subtree as
        freshly initialized, exactly like the reference's
        ``add_param_group``.  Only the global schedule counter
        ``state.step`` is shared.
        """
        master = state.master_params
        if not isinstance(master, dict) or not isinstance(new_params, dict):
            raise TypeError("add_params requires dict param trees")
        overlap = set(master) & set(new_params)
        if overlap:
            raise ValueError(f"params already present: {sorted(overlap)}")

        merged = {**master, **self._master_from(new_params)}

        old_leaves = {
            jax.tree_util.keystr(path): leaf
            for path, leaf in jax.tree_util.tree_leaves_with_path(
                state.opt_state)
        }

        def graft(path, fresh_leaf):
            old = old_leaves.get(jax.tree_util.keystr(path))
            if old is not None and hasattr(old, "shape") and \
                    getattr(old, "shape", None) == fresh_leaf.shape and \
                    getattr(old, "dtype", None) == fresh_leaf.dtype:
                return old
            return fresh_leaf

        fresh = self.tx.init(merged)
        opt_state = jax.tree_util.tree_map_with_path(graft, fresh)
        return AmpState(merged, opt_state, state.scaler_states, state.step,
                        state.fp8_state)

    # ------------------------------------------------------------------
    # model application (reference _initialize.py:197-208 forward patch)
    # ------------------------------------------------------------------
    def apply(self, params: Any, *args, **kwargs):
        """Run the bound model with policy-correct input/output casting and,
        under O1, the cast-ops context active."""
        if self.apply_fn is None:
            raise ValueError("This Amp was initialized without a model apply_fn.")
        return self.run(self.apply_fn, params, *args, **kwargs)

    def run(self, fn: Callable, params: Any, *args, **kwargs):
        """Like :meth:`apply` for an arbitrary function taking ``params``."""
        p = self.properties
        if not p.enabled:
            return fn(params, *args, **kwargs)
        if p.cast_model_dtype is not None and p.cast_model_dtype != jnp.float32:
            args, kwargs = amp_ops._cast_tree((args, kwargs), p.cast_model_dtype)
        if p.cast_ops:
            with amp_ops.cast_context(p):
                out = fn(params, *args, **kwargs)
        else:
            out = fn(params, *args, **kwargs)
        out_dtype = (p.cast_model_outputs if p.cast_model_outputs is not None
                     else jnp.float32)
        if p.cast_model_dtype is not None and p.cast_model_dtype != jnp.float32:
            out = amp_ops._cast_tree(out, out_dtype)
        return out

    # ------------------------------------------------------------------
    # loss scaling (reference handle.py scale_loss)
    # ------------------------------------------------------------------
    def scale_loss(self, loss: jax.Array, state: AmpState,
                   loss_id: int = 0) -> jax.Array:
        """``loss * loss_scale`` for the selected scaler
        (``handle.py:96,116``)."""
        if not self.properties.enabled:
            return loss
        return self.scaler.scale_loss(loss, state.scaler_states[loss_id])

    # ------------------------------------------------------------------
    # gradient application (reference handle.py exit + patched step)
    # ------------------------------------------------------------------
    def apply_gradients(
        self,
        state: AmpState,
        grads: Any,
        loss_id: int = 0,
        stashed_grads: Optional[Any] = None,
        reduce_fn: Optional[Callable[[Any], Any]] = None,
        finite_axes: Optional[Sequence[str]] = None,
    ) -> Tuple[AmpState, dict]:
        """Unscale → finite-check → scaler update → conditionally step.

        ``grads`` are w.r.t. the *compute* params (still loss-scaled, at
        compute dtype — exactly what materializes from the backward pass in
        the reference).  ``reduce_fn`` (e.g. a data-parallel psum from
        :mod:`apex_tpu.parallel`) runs on the scaled grads, matching the
        reference DDP which allreduces scaled fp16 grads before unscaling.
        ``stashed_grads`` selects the gradient-accumulation path
        (``unscale_with_stashed``, ``_process_optimizer.py:125-129``).
        On that path the finite check covers the *combined* unscaled
        grads, not just the new micro-batch: an inf from any earlier
        micro-batch persists through the stashed adds, so checking the
        combination reproduces the reference's shared overflow buffer
        (which accumulates across every unscale of the iteration) with no
        caller cooperation.

        ``finite_axes`` names mesh axes over which params (and so grads)
        are *sharded* — pipeline stages over "pipe", experts over
        "expert", tensor-parallel shards.  The finite flag is AND-reduced
        over them so an overflow on any rank skips the step on every
        rank, keeping the skip decision (and the scaler trajectory)
        globally consistent.  DDP's replicated params don't need this:
        the reduced grads are identical everywhere.

        Returns ``(new_state, info)`` with ``info = {"overflow", "loss_scale"}``
        — both device arrays; nothing here syncs to the host.
        """
        if reduce_fn is not None:
            grads = reduce_fn(grads)

        if not self.properties.enabled:
            updates, opt_state = self.tx.update(grads, state.opt_state,
                                                state.master_params)
            master = optax.apply_updates(state.master_params, updates)
            return (AmpState(master, opt_state, state.scaler_states,
                             state.step + 1, state.fp8_state),
                    {"overflow": jnp.asarray(False),
                     "loss_scale": jnp.asarray(1.0, jnp.float32),
                     "pinned_at_floor": jnp.asarray(False)})

        sstate = state.scaler_states[loss_id]
        if stashed_grads is not None:
            grads_unscaled, _ = self.scaler.unscale_with_stashed(
                grads, stashed_grads, sstate)
            # Stale non-finites from earlier micro-batches survive the
            # adds (inf+x = inf / nan), so checking the combination
            # subsumes the reference's arg-0 check with no caller
            # cooperation (see unscale_gradients for the strict arg-0
            # per-loss policy).
            finite = scaler_lib.all_finite(grads_unscaled)
        else:
            grads_unscaled, finite = self.scaler.unscale(grads, sstate)
        for ax in (finite_axes or ()):
            # AND across ranks sharing the step decision (min of {0,1})
            finite = jax.lax.pmin(finite.astype(jnp.int32), ax).astype(bool)
        state, overflow = self.update_scaler(state, loss_id, finite)
        new_state = self.step_if(state, grads_unscaled, overflow)
        new_sstate = new_state.scaler_states[loss_id]
        return new_state, {
            "overflow": overflow,
            "loss_scale": new_sstate.loss_scale,
            # device-side storm signal for the resilience sentinel: this
            # overflow found the scale already at (or shrank it to) the
            # min_loss_scale floor (scaler.pinned_at_floor)
            "pinned_at_floor": self.scaler.pinned_at_floor(new_sstate)}

    # ------------------------------------------------------------------
    # composable pieces for multi-loss / multi-optimizer topologies
    # (reference: one `with amp.scale_loss(loss_i, opts_j, loss_id=k)` per
    # backward, each exit unscaling into the shared master grads, updating
    # scaler k, and arming skip_step on every optimizer it was passed —
    # handle.py:110-150, tests/L0/run_amp/test_multiple_models_optimizers_losses.py)
    # ------------------------------------------------------------------
    def unscale_gradients(
        self, state: AmpState, grads: Any, loss_id: int = 0,
        stashed_grads: Optional[Any] = None,
    ) -> Tuple[Any, jax.Array]:
        """Unscale one backward's grads with scaler ``loss_id``; returns
        ``(unscaled, finite)``.  The finite check follows the reference's
        arg-0 policy on the stashed path (``scaler.py:167-172``): only the
        *new* grads are checked, so a stale inf in ``stashed_grads`` (from
        another loss's backward) is never attributed to this scaler."""
        sstate = state.scaler_states[loss_id]
        if stashed_grads is not None:
            return self.scaler.unscale_with_stashed(grads, stashed_grads,
                                                    sstate)
        return self.scaler.unscale(grads, sstate)

    def update_scaler(self, state: AmpState, loss_id: int,
                      grads_finite: jax.Array) -> Tuple[AmpState, jax.Array]:
        """Run scaler ``loss_id``'s post-backward transition
        (``update_scale``, ``scaler.py:190-210``) without stepping.
        Returns ``(state_with_new_scaler, overflow)``."""
        new_sstate, overflow = self.scaler.update(
            state.scaler_states[loss_id], grads_finite)
        scaler_states = tuple(
            new_sstate if i == loss_id else s
            for i, s in enumerate(state.scaler_states))
        return state._replace(scaler_states=scaler_states), overflow

    def step_if(self, state: AmpState, grads_unscaled: Any,
                skip: jax.Array) -> AmpState:
        """Conditionally apply the optimizer step on already-unscaled grads
        — the ``lax.cond`` core of :meth:`apply_gradients`, split out so
        multi-loss/multi-optimizer drivers can route overflow flags across
        optimizers (the reference arms ``skip_step`` on every optimizer a
        ``scale_loss`` context was passed, ``handle.py:131-150``)."""
        grads_unscaled = jax.tree.map(
            lambda g, p: g.astype(p.dtype) if hasattr(p, "dtype") else g,
            grads_unscaled, state.master_params)

        def do_step(operand):
            master, opt_state = operand
            updates, new_opt_state = self.tx.update(grads_unscaled, opt_state,
                                                    master)
            return optax.apply_updates(master, updates), new_opt_state

        master, opt_state = jax.lax.cond(
            skip, lambda op: op, do_step,
            (state.master_params, state.opt_state))
        return AmpState(master, opt_state, state.scaler_states,
                        state.step + 1, state.fp8_state)

    def apply_gradients_multi(
        self,
        state: AmpState,
        grads_list: Sequence[Any],
        loss_ids: Optional[Sequence[int]] = None,
        reduce_fn: Optional[Callable[[Any], Any]] = None,
        finite_axes: Optional[Sequence[str]] = None,
    ) -> Tuple[AmpState, dict]:
        """One optimizer fed by several backward passes, each scaled by its
        own (or a shared) loss scaler — the reference's ``num_losses`` /
        ``loss_id`` machinery driven to completion in one call.

        ``grads_list[i]`` is the (still-scaled) grad pytree of loss ``i``;
        zeros where a loss does not touch a param (what ``.backward()``
        accumulation leaves untouched in the reference).  Per backward:
        unscale with scaler ``loss_ids[i]``, per-backward finite check,
        per-scaler ``update_scale``; the unscaled grads sum into the master
        grads and the step is skipped iff **any** backward overflowed
        (each exit arms ``skip_step`` on the shared optimizer,
        ``handle.py:131-150``).

        With a shared scaler (repeated loss_id) all backwards here unscale
        at the iteration-entry scale, while the reference re-scales later
        losses after an earlier overflow halved the shared scaler
        mid-iteration.  Scale and unscale cancel per backward, so master
        grads — and every observable outcome — are identical.

        ``finite_axes``: as in :meth:`apply_gradients` — each backward's
        finite flag is AND-reduced over the param-sharding mesh axes so
        skip decisions and per-loss scaler trajectories stay globally
        consistent.
        """
        if loss_ids is None:
            loss_ids = list(range(len(grads_list)))
        if len(loss_ids) != len(grads_list):
            raise ValueError("loss_ids and grads_list length mismatch")

        if not self.properties.enabled:
            total = jax.tree.map(lambda *gs: sum(gs), *grads_list)
            new_state, info = self.apply_gradients(state, total,
                                                   reduce_fn=reduce_fn)
            # Same metrics pytree shape as the enabled path below.
            return new_state, {
                "overflow": info["overflow"],
                "loss_scale": tuple(jnp.asarray(1.0, jnp.float32)
                                    for _ in new_state.scaler_states),
                "pinned_at_floor": tuple(jnp.asarray(False)
                                         for _ in new_state.scaler_states)}

        # Callers scale every loss at iteration entry, so unscale against the
        # entry-time scaler states even as the per-loss updates land below
        # (scale/unscale must use the same value to cancel).
        entry_state = state
        total = None
        any_overflow = None
        for grads, lid in zip(grads_list, loss_ids):
            if reduce_fn is not None:
                grads = reduce_fn(grads)
            unscaled, finite = self.unscale_gradients(entry_state, grads,
                                                      loss_id=lid)
            for ax in (finite_axes or ()):
                finite = jax.lax.pmin(finite.astype(jnp.int32),
                                      ax).astype(bool)
            state, overflow = self.update_scaler(state, lid, finite)
            total = unscaled if total is None else jax.tree.map(
                jnp.add, total, unscaled)
            any_overflow = overflow if any_overflow is None else \
                jnp.logical_or(any_overflow, overflow)

        new_state = self.step_if(state, total, any_overflow)
        return new_state, {
            "overflow": any_overflow,
            "loss_scale": tuple(s.loss_scale
                                for s in new_state.scaler_states),
            "pinned_at_floor": tuple(self.scaler.pinned_at_floor(s)
                                     for s in new_state.scaler_states),
        }


def initialize(
    apply_fn: Optional[Callable] = None,
    optimizer: Optional[optax.GradientTransformation] = None,
    opt_level: str = "O1",
    enabled: bool = True,
    half_dtype=jnp.bfloat16,
    cast_model_dtype=None,
    cast_ops: Optional[bool] = None,
    keep_batchnorm_fp32: Union[None, bool, str] = None,
    master_weights: Optional[bool] = None,
    loss_scale: Union[None, float, str] = None,
    cast_model_outputs=None,
    num_losses: int = 1,
    min_loss_scale: Optional[float] = None,
    max_loss_scale: float = 2.0 ** 24,
    keep_fp32_filter: Callable = default_keep_fp32_filter,
    verbosity: int = 1,
) -> Amp:
    """Resolve an opt level + overrides into a bound :class:`Amp`
    (reference ``amp.initialize``, ``frontend.py:194-353``).

    Unlike the reference this does not mutate a model/optimizer — it returns
    the pure state machine; pair it with :func:`make_train_step` or drive
    ``init`` / ``model_params`` / ``scale_loss`` / ``apply_gradients``
    yourself (the explicit analog of the ``with amp.scale_loss(...)`` loop).
    """
    props = policy_lib.resolve(
        opt_level=opt_level, half_dtype=half_dtype, enabled=enabled,
        cast_model_dtype=cast_model_dtype, cast_ops=cast_ops,
        keep_batchnorm_fp32=keep_batchnorm_fp32, master_weights=master_weights,
        loss_scale=loss_scale, cast_model_outputs=cast_model_outputs)
    scaler = LossScaler(
        loss_scale=props.loss_scale,
        min_loss_scale=min_loss_scale,
        max_loss_scale=max_loss_scale)
    if optimizer is None:
        optimizer = optax.identity()
    if verbosity > 0:
        from apex_tpu.utils.logging import maybe_print
        maybe_print(f"apex_tpu.amp configured: {props}")
    amp = Amp(properties=props, scaler=scaler, tx=optimizer,
              apply_fn=apply_fn, num_losses=num_losses,
              keep_fp32_filter=keep_fp32_filter)
    # Record for module-level amp.scale_loss (the reference's _amp_state
    # global, apex/amp/_amp_state.py).
    from apex_tpu.amp import handle as handle_lib
    handle_lib._set_active_amp(amp)
    return amp


def make_train_step(
    amp: Amp,
    loss_fn: Callable,
    axis_name: Optional[str] = None,
    reduce_fn: Optional[Callable[[Any], Any]] = None,
    has_aux: bool = False,
    finite_axes: Optional[Sequence[str]] = None,
    accum_steps: Optional[int] = None,
    aot_cache: Optional[str] = None,
):
    """Build a jittable single-loss train step.

    ``loss_fn(model_params, *batch) -> loss`` (or ``(loss, aux)`` with
    ``has_aux``) is evaluated at compute precision; the returned
    ``step(state, *batch) -> (state, metrics)`` does forward, backward,
    unscale, scaler update, and the conditional optimizer step in one
    compiled graph (the whole of reference §3.2's hot loop).

    ``axis_name`` marks the compute params device-varying (so grads
    materialize per-rank, exactly like the reference's backward hooks) and
    applies a mean-``psum`` (plain DP); for the full knob set (predivide,
    fp32 wire, compression) also pass ``reduce_fn`` from
    ``DistributedDataParallel(...).reduce``.  When running under shard_map
    with a ``reduce_fn``, ``axis_name`` must be given — without it, SPMD
    autodiff auto-sums grads of replicated params and an explicit reduce
    would double-count.

    ``finite_axes``: mesh axes the *params* are sharded over (pipeline /
    expert / tensor shards) — the overflow-skip decision is AND-reduced
    across them (see :meth:`Amp.apply_gradients`).

    ``accum_steps``: gradient accumulation over N micro-batches — the
    reference's stashed-grad iteration (``_process_optimizer.py:125-129``)
    and the ``Reducer``'s every-N cadence, as one compiled ``lax.scan``:
    every batch argument's leading dim splits into ``(N, batch/N)``,
    scaled grads accumulate across micro-steps, and ONE
    unscale/scaler-update/conditional-step runs at the end.  Grads
    accumulate in fp32 (like the reference's fp32 master grads) and,
    with the reported loss, are averaged over micro-steps, so the step
    is numerically the large-batch mean-loss step (an inf in ANY
    micro-batch skips it — the accumulated sum stays non-finite, the
    reference's shared overflow buffer).  ``reduce_fn``/``axis_name``
    reduction applies once to the accumulated grads, the
    ``delay_allreduce=True`` economics.  Every batch argument must carry
    the leading batch dim; with ``has_aux`` the aux comes back stacked
    per micro-step (leading ``(N,)`` dim).

    ``aot_cache``: directory of the content-addressed AOT executable
    cache (:mod:`apex_tpu.analysis.export`).  When set, the returned
    step is self-jitting (state donated) and its FIRST call probes the
    cache: a verified key hit — same program, same mesh, same resolved
    policy, same jax — loads the serialized executable instead of
    paying XLA compilation (the cold-start cost of every new training
    replica today); a miss compiles, relints under the export gate,
    and populates the cache for the next replica.  The resolved
    provenance is exposed as ``step.aot_info``.  Without it the step
    is the plain jittable (jit and donate it yourself).
    """
    if axis_name is None and reduce_fn is not None:
        axis_name = getattr(reduce_fn, "__self__", None) and \
            getattr(reduce_fn.__self__, "axis_name", None)
    if axis_name is not None and reduce_fn is None:
        def reduce_fn(grads):
            return jax.lax.pmean(grads, axis_name)

    def step(state: AmpState, *batch):
        from apex_tpu.parallel.distributed import pvary_params
        params_c = amp.model_params(state)
        if axis_name is not None:
            params_c = pvary_params(params_c, axis_name)
        fp8_on = amp.properties.enabled and amp.properties.fp8 \
            and state.fp8_state is not None

        def scaled_loss(p, micro):
            if fp8_on:
                # O4: the delayed scales enter (and the per-callsite
                # forward amaxes leave) through the trace-local fp8
                # context — all values of THIS trace, so the state
                # stays purely functional and the collected amaxes
                # ride the loss aux back out.  The e5m2 cotangent
                # scale is grad.scale/loss_scale: the rounding point
                # sees loss-scaled cotangents while the grad history
                # records unscaled units (stable across scaler moves)
                eff_gs = state.fp8_state.grad.scale \
                    / state.scaler_states[0].loss_scale
                with amp_ops.fp8_trace(state.fp8_state,
                                       grad_scale=eff_gs) as tr:
                    out = amp.run(loss_fn, p, *micro)
                    amaxes = amp_ops.collected_fp8_amaxes(tr)
            else:
                out = amp.run(loss_fn, p, *micro)
                amaxes = None
            loss, aux = out if has_aux else (out, None)
            return amp.scale_loss(loss, state), (loss, aux, amaxes)

        if accum_steps is None or accum_steps == 1:
            grads, (loss, aux, fp8_amaxes) = jax.grad(
                lambda p: scaled_loss(p, batch), has_aux=True)(params_c)
        else:
            def split(t):
                t = jnp.asarray(t)
                if t.ndim == 0 or t.shape[0] % accum_steps:
                    raise ValueError(
                        f"accum_steps={accum_steps}: every batch argument "
                        f"leaf must have a leading dim divisible by it; "
                        f"got shape {t.shape} (broadcast non-batched "
                        "extras inside loss_fn instead of passing them "
                        "as batch args)")
                return t.reshape((accum_steps, t.shape[0] // accum_steps)
                                 + t.shape[1:])

            micro_batches = jax.tree.map(split, batch)

            def body(acc, micro):
                g, (loss, aux, amaxes) = jax.grad(
                    lambda p: scaled_loss(p, micro),
                    has_aux=True)(params_c)
                # accumulate in fp32 regardless of compute dtype: summing
                # in bf16 would absorb small micro-contributions (the
                # reference accumulates into fp32 master grads)
                acc = jax.tree.map(
                    lambda a, gi: a + gi.astype(a.dtype), acc, g)
                return acc, (loss, aux, amaxes)

            zero = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params_c)
            if axis_name is not None:
                # under shard_map the per-rank grads are device-varying;
                # fresh zeros are not — mark them varying so the scan
                # carry types agree (grads stay per-rank until reduce_fn)
                zero = pvary_params(zero, axis_name)
            grads, (losses, auxes, fp8_amaxes) = jax.lax.scan(
                body, zero, micro_batches)
            # mean-loss semantics: the accumulated step equals the
            # large-batch mean-loss step (grads scaled by 1/N; an inf in
            # any micro-batch survives the sum and skips the step)
            grads = jax.tree.map(lambda g: g / accum_steps, grads)
            loss = jnp.mean(losses)
            if fp8_on:
                # per-micro amaxes stacked (accum_steps,): the history
                # entry is the iteration's max, like every other class
                fp8_amaxes = jax.tree.map(jnp.max, fp8_amaxes)
            # per-micro aux stacked with a leading (accum_steps,) dim —
            # documented; reduce it yourself (e.g. take aux[-1] for
            # carried stats)
            aux = auxes if has_aux else None

        fp8_metrics = {}
        if fp8_on:
            # end-of-step history roll (quant.fp8): forward amaxes from
            # the op layer's collector, grad amax from THIS step's
            # still-scaled grads (the e5m2 rounding point sees scaled
            # cotangents, so the delayed grad scale tracks the scaled
            # magnitude) — everything stays on device, and
            # apply_gradients below threads the new state through with
            # no special case (it's just more pytree leaves)
            from apex_tpu.quant import fp8 as fp8_lib
            amax_in, amax_w = fp8_amaxes
            # grads are still loss-scaled here: record the UNSCALED
            # amax (divide the scale back out) so the grad history is
            # unit-stable across loss-scale moves — and so the
            # precision lint's scale-placement dataflow can prove the
            # returned state carries no scaled value
            amax_g = fp8_lib.tree_amax(grads) \
                * (1.0 / state.scaler_states[0].loss_scale)
            margin = amp.properties.fp8_margin
            new_fp8 = fp8_lib.update_train_state(
                state.fp8_state, amax_in, amax_w, amax_g, margin)
            fp8_metrics = {
                "fp8_amax_saturation": fp8_lib.step_saturation(
                    state.fp8_state, amax_in, amax_w, amax_g, margin),
                "fp8_rescales": fp8_lib.rescale_events(
                    state.fp8_state, new_fp8),
            }
            state = state._replace(fp8_state=new_fp8)

        new_state, info = amp.apply_gradients(state, grads,
                                              reduce_fn=reduce_fn,
                                              finite_axes=finite_axes)
        metrics = {"loss": loss, **info, **fp8_metrics}
        if has_aux:
            metrics["aux"] = aux
        return new_state, metrics

    if aot_cache is None:
        return step
    return _aot_cached_step(step, amp, aot_cache)


def _aot_cached_step(step: Callable, amp: Amp, cache_dir: str):
    """Wrap a train step so its first call resolves the executable
    through the AOT cache (:func:`apex_tpu.analysis.export.probe`):
    load on a verified key hit, compile + relint + export on a miss.
    Later calls dispatch straight to the resolved executable — the
    wrapper adds one dict lookup to the hot path, nothing else."""
    import functools

    jitted = jax.jit(step, donate_argnums=0)
    box: dict = {}

    @functools.wraps(step)
    def cached_step(state, *batch):
        if "compiled" not in box:
            from apex_tpu.analysis import export as aot
            compiled, info = aot.probe(
                jitted, state, *batch, cache_dir=cache_dir,
                policy=amp.properties, lane="train_step",
                export_on_miss=True)
            box["compiled"] = compiled
            cached_step.aot_info = info
        return box["compiled"](state, *batch)

    cached_step.aot_info = None
    return cached_step
