"""Optimization-level policy system.

TPU-native equivalent of the reference's ``apex/amp/frontend.py:6-190``
(``Properties`` + the ``O0``–``O3`` opt-level callables).  The reference
routes an options dict through ``__setattr__`` consistency checks; here the
policy is an immutable dataclass validated at construction, because under JAX
the policy is applied once when the train step is built, not mutated at
runtime.

Differences from the reference, by design:

- The "half" dtype defaults to ``bfloat16`` — the native TPU 16-bit format —
  instead of ``float16``.  ``float16`` remains selectable for conformance
  testing (``half_dtype=jnp.float16``).
- ``patch_torch_functions`` becomes ``cast_ops``: there is no global namespace
  to monkey-patch in JAX, so O1 is expressed as a policy-aware op layer
  (:mod:`apex_tpu.amp.ops`) consulted by this package's own layers, plus a
  registry for user functions.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Optional, Union

import jax.numpy as jnp

#: Accepted spelling of a dynamic loss scale, as in the reference
#: (``frontend.py:88-92`` accepts a float or the string ``"dynamic"``).
DYNAMIC = "dynamic"


def _parse_tristate(value: Union[None, bool, str], name: str) -> Optional[bool]:
    """Parse ``None | bool | "True" | "False"`` like ``frontend.py:74-82``.

    The reference deliberately accepts the *strings* "True"/"False" so that
    argparse-produced values work unmodified; we keep that behavior.
    """
    if value is None or isinstance(value, bool):
        return value
    if isinstance(value, str):
        if value == "True":
            return True
        if value == "False":
            return False
    raise ValueError(f"{name} must be None, a bool, or 'True'/'False'; got {value!r}")


def _parse_loss_scale(value: Union[None, float, int, str]) -> Union[None, float, str]:
    """Parse a loss scale: float, int, or the string "dynamic" (``frontend.py:88-92``)."""
    if value is None or value == DYNAMIC:
        return value
    try:
        return float(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"loss_scale must be a number or 'dynamic'; got {value!r}"
        ) from None


@dataclasses.dataclass(frozen=True)
class Properties:
    """Resolved mixed-precision options (reference ``frontend.py:6-96``).

    Attributes:
      enabled: master on/off switch; when False everything is a no-op
        passthrough (reference ``_amp_state``/``frontend.py:204-230``).
      opt_level: the selected level string, for logging.
      cast_model_dtype: dtype the model params/compute are cast to (O2/O3), or
        None to leave the model in fp32 (O0/O1).
      cast_ops: O1-style policy casting of individual ops via
        :mod:`apex_tpu.amp.ops` (reference ``patch_torch_functions``).
      keep_batchnorm_fp32: keep normalization params/stats in fp32 when the
        model is cast (reference semantics; only meaningful with
        ``cast_model_dtype`` set).
      master_weights: maintain fp32 master params and run the optimizer on
        them (reference ``master_weights``).
      loss_scale: float for a static scale, or ``"dynamic"``.
      half_dtype: the 16-bit compute dtype (bfloat16 on TPU by default).
      cast_model_outputs: if set, model outputs are cast to this dtype instead
        of fp32 (reference ``frontend.py:194`` kwarg).
      fp8: O4's switch — matmul-family ops quantize their operands to
        fp8 with delayed per-tensor scales (:mod:`apex_tpu.quant.fp8`)
        and accumulate f32; the delayed-scaling state rides in
        ``AmpState`` next to the loss scaler.  Below-16-bit is the same
        contract one level down (Micikevicius et al., 2022), so the
        fields live here in the same table as the 16-bit knobs.
      fp8_dtype_fwd / fp8_dtype_bwd: the forward (e4m3) and backward
        (e5m2) storage formats.
      fp8_amax_history_len: rolling amax-window length of the delayed
        scaling (the ``DelayedScalingState`` history).
      fp8_margin: power-of-two headroom subtracted from the derived
        scale (scale = fp8_max / (2**margin * amax_max)).
    """

    enabled: bool = True
    opt_level: str = "O1"
    cast_model_dtype: Optional[Any] = None
    cast_ops: bool = True
    keep_batchnorm_fp32: Optional[bool] = None
    master_weights: Optional[bool] = None
    loss_scale: Union[float, str] = DYNAMIC
    half_dtype: Any = jnp.bfloat16
    cast_model_outputs: Optional[Any] = None
    fp8: bool = False
    fp8_dtype_fwd: Optional[Any] = None
    fp8_dtype_bwd: Optional[Any] = None
    fp8_amax_history_len: int = 16
    fp8_margin: int = 0

    def __post_init__(self):
        object.__setattr__(
            self, "keep_batchnorm_fp32",
            _parse_tristate(self.keep_batchnorm_fp32, "keep_batchnorm_fp32"))
        object.__setattr__(self, "loss_scale", _parse_loss_scale(self.loss_scale))
        if self.fp8:
            # resolve the fp8 formats lazily so a no-fp8 policy never
            # touches the dtypes (older jax builds may lack them)
            if self.fp8_dtype_fwd is None:
                object.__setattr__(self, "fp8_dtype_fwd", jnp.float8_e4m3fn)
            if self.fp8_dtype_bwd is None:
                object.__setattr__(self, "fp8_dtype_bwd", jnp.float8_e5m2)
            if self.fp8_amax_history_len < 1:
                raise ValueError(
                    f"fp8_amax_history_len must be >= 1; got "
                    f"{self.fp8_amax_history_len}")
        # Consistency checks mirroring frontend.py:54-82.
        if self.cast_ops and self.cast_model_dtype is not None \
                and not self.fp8:
            warnings.warn(
                "O1-style op casting (cast_ops=True) together with a cast model "
                "dtype is unusual; O1 expects the model left in fp32 "
                "(reference frontend.py:54-63)."
            )
        if self.keep_batchnorm_fp32 and self.cast_model_dtype is None:
            warnings.warn(
                "keep_batchnorm_fp32 has no effect when the model is not cast "
                "(reference frontend.py:66-72)."
            )

    @property
    def is_dynamic_loss_scale(self) -> bool:
        return self.loss_scale == DYNAMIC

    @property
    def use_master_weights(self) -> bool:
        """Whether fp32 master params are resolved ON under this policy
        — the single source of truth shared by the runtime
        (``frontend.Amp``) and the precision lint
        (:mod:`apex_tpu.analysis.precision`), so the lint's notion of
        "masters on" can never drift from the runtime's."""
        if self.master_weights is not None:
            return bool(self.master_weights)
        # O1 leaves params fp32: the "masters" are the params themselves.
        return self.cast_model_dtype is not None \
            and self.cast_model_dtype != jnp.float32

    def replace(self, **kw) -> "Properties":
        return dataclasses.replace(self, **kw)


def O0(half_dtype=jnp.bfloat16) -> Properties:
    """Pure fp32 (reference ``frontend.py:174-184``)."""
    return Properties(
        opt_level="O0", cast_model_dtype=jnp.float32, cast_ops=False,
        keep_batchnorm_fp32=None, master_weights=False, loss_scale=1.0,
        half_dtype=half_dtype)


def O1(half_dtype=jnp.bfloat16) -> Properties:
    """Policy-cast ops, fp32 model, dynamic scale (reference ``frontend.py:155-165``)."""
    return Properties(
        opt_level="O1", cast_model_dtype=None, cast_ops=True,
        keep_batchnorm_fp32=None, master_weights=None, loss_scale=DYNAMIC,
        half_dtype=half_dtype)


def O2(half_dtype=jnp.bfloat16) -> Properties:
    """Half model + fp32 norm layers + fp32 masters + dynamic scale
    (reference ``frontend.py:133-143``)."""
    return Properties(
        opt_level="O2", cast_model_dtype=half_dtype, cast_ops=False,
        keep_batchnorm_fp32=True, master_weights=True, loss_scale=DYNAMIC,
        half_dtype=half_dtype)


def O3(half_dtype=jnp.bfloat16) -> Properties:
    """Pure half "speed of light" mode (reference ``frontend.py:110-120``)."""
    return Properties(
        opt_level="O3", cast_model_dtype=half_dtype, cast_ops=False,
        keep_batchnorm_fp32=False, master_weights=False, loss_scale=1.0,
        half_dtype=half_dtype)


def O4(half_dtype=jnp.bfloat16) -> Properties:
    """FP8 training: the O2 safety rig (fp32 masters + norm layers,
    dynamic loss scale, 16-bit network dtype) with matmul-family ops
    quantized to fp8 under delayed per-tensor scales — e4m3 forward,
    e5m2 backward, f32 accumulation.  This level EXTENDS the paper's
    table: below-16-bit needs every piece of the O2 contract plus an
    amax-history state next to the loss scaler
    (:class:`apex_tpu.quant.fp8.Fp8TrainState`, carried in
    ``AmpState.fp8_state``)."""
    return Properties(
        opt_level="O4", cast_model_dtype=half_dtype, cast_ops=True,
        keep_batchnorm_fp32=True, master_weights=True, loss_scale=DYNAMIC,
        half_dtype=half_dtype, fp8=True)


opt_levels = {"O0": O0, "O1": O1, "O2": O2, "O3": O3, "O4": O4}


def resolve(opt_level: str = "O1",
            half_dtype=jnp.bfloat16,
            enabled: bool = True,
            **overrides) -> Properties:
    """Select an opt level then apply explicit per-kwarg overrides, the
    resolution order of the reference (``frontend.py:307-347``)."""
    if opt_level not in opt_levels:
        raise ValueError(
            f"Unexpected optimization level {opt_level!r}; options are "
            "'O0', 'O1', 'O2', 'O3', 'O4' (the letter O, not zero; "
            "O4 = fp8 training with delayed scaling, see "
            "apex_tpu.quant).")
    props = opt_levels[opt_level](half_dtype=half_dtype)
    overrides = {k: v for k, v in overrides.items() if v is not None}
    # The reference accepts cast_model_type=False as an explicit "do not cast
    # the model" override on top of O2/O3 (frontend.py:334-347; used heavily
    # by tests/L0/run_amp/test_multiple_models_optimizers_losses.py).
    cast_override = overrides.pop("cast_model_dtype", None)
    if cast_override is False:
        # Force both through explicitly (the None-filter above would
        # otherwise treat them as "keep the opt level's default").
        props = props.replace(
            cast_model_dtype=None,
            keep_batchnorm_fp32=overrides.pop("keep_batchnorm_fp32", None))
    elif cast_override is not None:
        overrides["cast_model_dtype"] = cast_override
    props = props.replace(enabled=enabled, **overrides)
    return props
