"""Module-level ``scale_loss`` / legacy ``init`` handle API.

Parity surface for the reference's two amp entry styles:

- the **modern** ``amp.initialize`` + ``with amp.scale_loss(...)`` flow
  (``apex/amp/handle.py:15-154``).  JAX has no imperative backward to wrap a
  context manager around, so ``scale_loss`` here is the *functional* analog:
  it returns the scaled loss to differentiate, and the exit-time work of the
  reference's context manager (unscale, overflow check, scaler update,
  conditional skip) lives in :meth:`apex_tpu.amp.Amp.apply_gradients` /
  :func:`apex_tpu.amp.make_train_step`, compiled into the step.
- the **legacy** ``handle = amp.init(...)`` / ``handle.wrap_optimizer`` API
  (``apex/amp/amp.py:68-177`` init, ``handle.py:166-277`` AmpHandle /
  NoOpHandle, ``opt.py:9-103`` OptimWrapper — "old API, kept for tests").
  ``init`` activates the O1 op-cast policy process-wide (the analog of
  monkey-patching torch) and hands back a handle whose ``wrap_optimizer``
  builds a bound :class:`~apex_tpu.amp.frontend.Amp` — the OptimWrapper
  equivalent.

``initialize`` records the most recent :class:`Amp` so module-level
``scale_loss`` can resolve a scaler without threading the object through
user code — the role of the reference's global ``_amp_state``
(``apex/amp/_amp_state.py``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp import ops as amp_ops
from apex_tpu.amp import policy as policy_lib
from apex_tpu.amp.frontend import Amp, AmpState
from apex_tpu.amp.scaler import LossScaler

_active_amp: Optional[Amp] = None


def _set_active_amp(a: Optional[Amp]) -> None:
    global _active_amp
    _active_amp = a


def active_amp() -> Optional[Amp]:
    """The :class:`Amp` from the most recent ``initialize`` call, if any."""
    return _active_amp


def scale_loss(loss: jax.Array, state: AmpState, loss_id: int = 0,
               amp: Optional[Amp] = None) -> jax.Array:
    """``loss * loss_scale`` for scaler ``loss_id`` (reference
    ``amp.scale_loss`` enter, ``handle.py:96,116``).

    Functional analog of the reference context manager: differentiate the
    returned value; the unscale / overflow / scaler-update exit work is in
    ``Amp.apply_gradients``.  ``amp`` defaults to the most recently
    ``initialize``\\ d one (the reference's ``_amp_state`` global).
    """
    a = amp if amp is not None else _active_amp
    if a is None:
        raise RuntimeError(
            "amp.scale_loss called before amp.initialize (reference "
            "handle.py:78-86 raises the same way)")
    return a.scale_loss(loss, state, loss_id=loss_id)


class AmpHandle:
    """Legacy handle (reference ``apex/amp/handle.py:166-248``).

    Construction activates the op-cast policy process-wide until
    :meth:`_deactivate` — the declarative analog of ``amp.init`` patching the
    torch namespace.  The reference handle's per-iteration cast cache has no
    analog: XLA CSE deduplicates repeated casts inside a trace, so
    ``_clear_cache`` is a no-op kept for API compatibility.
    """

    def __init__(self, properties: policy_lib.Properties,
                 verbose: bool = False):
        self._properties = properties
        self._verbose = verbose
        self._all_wrappers = []
        self._ctx = None
        if properties.enabled and properties.cast_ops:
            self._ctx = amp_ops.cast_context(properties)
            self._ctx.__enter__()

    @property
    def is_active(self) -> bool:
        return self._properties.enabled

    @property
    def has_cache(self) -> bool:
        return False

    def wrap_optimizer(self, optimizer, num_loss: int = 1) -> Amp:
        """Bind an optax transformation (reference ``wrap_optimizer`` →
        ``OptimWrapper``, ``opt.py:9-103``): returns an :class:`Amp` whose
        ``init`` / ``apply_gradients`` carry the loss-scaling state."""
        amp = Amp(properties=self._properties,
                  scaler=LossScaler(loss_scale=self._properties.loss_scale),
                  tx=optimizer, num_losses=num_loss)
        self._all_wrappers.append(amp)
        return amp

    def scale_loss(self, loss: jax.Array, state: AmpState,
                   loss_id: int = 0) -> jax.Array:
        if not self.is_active:
            return loss
        if not self._all_wrappers:
            raise RuntimeError("wrap_optimizer before scale_loss "
                               "(legacy-flow ordering, opt.py:16-20)")
        return self._all_wrappers[-1].scale_loss(loss, state,
                                                 loss_id=loss_id)

    def _clear_cache(self) -> None:
        pass  # XLA CSE replaces the eager cast cache (utils.py:87-119)

    def _deactivate(self) -> None:
        """Undo global activation (reference ``AmpHandle._deactivate``,
        ``handle.py:225-241``): pops the cast policy and any ``register_*``
        namespace patches."""
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None
        amp_ops.deactivate_registrations()


class NoOpHandle:
    """Disabled-amp handle (reference ``handle.py:250-277``)."""

    @property
    def is_active(self) -> bool:
        return False

    @property
    def has_cache(self) -> bool:
        return False

    def wrap_optimizer(self, optimizer, num_loss: int = 1) -> Amp:
        props = policy_lib.resolve(opt_level="O0", enabled=False)
        return Amp(properties=props, scaler=LossScaler(loss_scale=1.0),
                   tx=optimizer, num_losses=num_loss)

    def scale_loss(self, loss, state, loss_id: int = 0):
        return loss

    def _clear_cache(self) -> None:
        pass

    def _deactivate(self) -> None:
        pass


def init(enabled: bool = True, opt_level: str = "O1",
         half_dtype=jnp.bfloat16, loss_scale="dynamic",
         enable_caching: bool = True, verbose: bool = False):
    """Legacy global-activation entry point (reference ``amp.init``,
    ``apex/amp/amp.py:68-177``): turn on the op-cast policy and return a
    handle.  ``enable_caching`` is accepted for signature parity (see
    :meth:`AmpHandle._clear_cache`).  Prefer :func:`apex_tpu.amp.initialize`.
    """
    if not enabled:
        return NoOpHandle()
    props = policy_lib.resolve(opt_level=opt_level, enabled=True,
                               half_dtype=half_dtype, loss_scale=loss_scale)
    return AmpHandle(props, verbose=verbose)
