"""Generic parameter reparameterization over pytrees.

Port of ``apex/reparameterization/reparameterization.py`` — which in the
reference snapshot is *dead code* (its ``weight_norm`` sibling imports the
deleted ``Fused_Weight_Norm`` symbol, so ``import apex.reparameterization``
raises — SURVEY.md §0.3).  This is the working TPU-native equivalent.

The reference mechanism is an nn.Module forward-pre hook that recomputes a
weight from auxiliary parameters before every forward
(``reparameterization.py:57-145``).  The functional analog: the params
pytree stores the auxiliary decomposition (e.g. ``kernel_g``/``kernel_v``),
and :func:`merge` recomputes the original leaves *inside the traced step*,
so autodiff differentiates through the decomposition exactly like the
reference's hook — and XLA fuses the recompute into the consumers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

# Suffixes marking decomposed leaves (torch's weight_norm uses weight_g /
# weight_v; we keep the convention relative to the original leaf name).
G_SUFFIX = "_g"
V_SUFFIX = "_v"


class Reparameterization:
    """Decompose/recompose one parameter array.

    Subclasses implement :meth:`reparameterize` (array → dict of auxiliary
    arrays) and :meth:`compute_weight` (auxiliary dict → array) — the same
    pair the reference requires (``reparameterization.py:28-55``).
    """

    def reparameterize(self, name: str, weight: jax.Array) -> Dict[str, jax.Array]:
        raise NotImplementedError

    def compute_weight(self, name: str, aux: Dict[str, jax.Array]) -> jax.Array:
        raise NotImplementedError


def default_filter(name: str, leaf: Any) -> bool:
    """Reference default: every parameter except 1-d vectors and scalars
    (``apex/reparameterization/__init__.py`` apply_weight_norm docstring)."""
    return (hasattr(leaf, "ndim") and leaf.ndim >= 2
            and jnp.issubdtype(leaf.dtype, jnp.floating))


def _is_leaf_dict(node) -> bool:
    return isinstance(node, dict)


def apply_reparameterization(
    params: Any,
    reparam: Reparameterization,
    name: str = "",
    filter_fn: Callable[[str, Any], bool] = default_filter,
) -> Any:
    """Replace selected leaves with their decomposition.

    ``name``: restrict to leaves with this dict key ("" = all passing
    ``filter_fn``, the reference's "no parameter provided" mode).  Returns a
    new pytree of plain nested dicts where each selected ``k`` is replaced
    by ``k+"_g"`` / ``k+"_v"`` entries.
    """
    def walk(node):
        if not _is_leaf_dict(node):
            return node
        out = {}
        for k, v in node.items():
            if isinstance(v, dict):
                out[k] = walk(v)
            elif (name == "" or k == name) and filter_fn(k, v):
                out.update(reparam.reparameterize(k, v))
            else:
                out[k] = v
        return out

    return walk(_to_plain_dict(params))


def remove_reparameterization(params: Any,
                              reparam: Reparameterization) -> Any:
    """Merge decomposed leaves back into plain parameters — the reference's
    ``remove`` (``reparameterization.py:127-137``), which bakes the current
    effective weight back in."""
    return merge(params, reparam)


def merge(params: Any, reparam: Reparameterization) -> Any:
    """Recompute every decomposed leaf (``k_g``/``k_v`` → ``k``).  Call
    inside the traced step (or via :func:`reparameterized_apply`)."""
    def walk(node):
        if not _is_leaf_dict(node):
            return node
        out = {}
        for k, v in node.items():
            if isinstance(v, dict):
                out[k] = walk(v)
            elif k.endswith(G_SUFFIX):
                base = k[: -len(G_SUFFIX)]
                vkey = base + V_SUFFIX
                if vkey in node:
                    out[base] = reparam.compute_weight(
                        base, {k: node[k], vkey: node[vkey]})
            elif k.endswith(V_SUFFIX) and (k[: -len(V_SUFFIX)] + G_SUFFIX) in node:
                pass  # consumed with its _g partner
            else:
                out[k] = v
        return out

    return walk(_to_plain_dict(params))


def reparameterized_apply(apply_fn: Callable, reparam: Reparameterization,
                          ) -> Callable:
    """Wrap ``apply_fn(variables, ...)`` so it accepts decomposed params —
    the functional analog of installing the forward-pre hook
    (``reparameterization.py:139-145``).

    Handles both a bare params tree and a flax ``{"params": ..., ...}``
    variables dict.
    """
    def wrapped(variables, *args, **kwargs):
        if isinstance(variables, dict) and "params" in variables:
            merged = dict(variables)
            merged["params"] = merge(variables["params"], reparam)
        else:
            merged = merge(variables, reparam)
        return apply_fn(merged, *args, **kwargs)

    return wrapped


def _to_plain_dict(params: Any):
    """Unfreeze flax FrozenDicts / mappings into plain nested dicts."""
    if hasattr(params, "unfreeze"):
        params = params.unfreeze()
    if isinstance(params, dict):
        return {k: _to_plain_dict(v) for k, v in params.items()}
    return params
