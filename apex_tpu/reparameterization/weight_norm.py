"""Weight normalization: w = g · v / ‖v‖  (Salimans & Kingma, 1602.07868).

Port of ``apex/reparameterization/weight_norm.py`` — broken in the
reference snapshot (imports the deleted ``Fused_Weight_Norm`` CUDA backend,
SURVEY.md §0.3); this is the working TPU-native version.  No hand-written
kernel is needed: the norm + scale is a tiny reduction/broadcast pair that
XLA fuses into the consuming matmul's prologue, which is exactly what the
deleted fused CUDA kernel bought.

Axis convention: ``dim`` is the axis *retained* (per-output-channel norms);
the norm reduces over all other axes.  torch layouts put output channels at
dim 0 (the reference default); flax kernels put them last, so the default
here is ``dim=-1``.  ``dim=None`` computes one norm over the whole tensor
(same as the reference's ``dim=None``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from apex_tpu.reparameterization.reparameterization import (
    G_SUFFIX,
    V_SUFFIX,
    Reparameterization,
    apply_reparameterization,
    default_filter,
    merge,
    remove_reparameterization,
)


def _norm_axes(ndim: int, dim: Optional[int]):
    if dim is None:
        return tuple(range(ndim)), None
    dim = dim % ndim
    return tuple(a for a in range(ndim) if a != dim), dim


@dataclasses.dataclass(frozen=True)
class WeightNorm(Reparameterization):
    """g/v decomposition with norms in fp32 (the reference's fused kernel
    accumulated in fp32 for half inputs — ``weight_norm.py:39-60``)."""

    dim: Optional[int] = -1
    eps: float = 0.0

    def reparameterize(self, name: str, weight: jax.Array) -> Dict[str, jax.Array]:
        axes, kept = _norm_axes(weight.ndim, self.dim)
        w32 = weight.astype(jnp.float32)
        g = jnp.sqrt(jnp.sum(jnp.square(w32), axis=axes, keepdims=True))
        return {name + G_SUFFIX: g.astype(weight.dtype),
                name + V_SUFFIX: weight}

    def compute_weight(self, name: str, aux: Dict[str, jax.Array]) -> jax.Array:
        g = aux[name + G_SUFFIX]
        v = aux[name + V_SUFFIX]
        axes, _ = _norm_axes(v.ndim, self.dim)
        v32 = v.astype(jnp.float32)
        norm = jnp.sqrt(jnp.sum(jnp.square(v32), axis=axes, keepdims=True)
                        + self.eps)
        w = g.astype(jnp.float32) * v32 / norm
        return w.astype(v.dtype)


def apply_weight_norm(params: Any, name: str = "", dim: Optional[int] = -1,
                      filter_fn: Callable = default_filter) -> Any:
    """Decompose selected leaves into ``*_g``/``*_v``
    (``apex.reparameterization.apply_weight_norm``; ``name=""`` applies to
    every ≥2-d float param).  Initialization preserves the effective weight:
    ``merge(apply_weight_norm(p)) == p``."""
    return apply_reparameterization(params, WeightNorm(dim=dim), name=name,
                                    filter_fn=filter_fn)


def remove_weight_norm(params: Any, dim: Optional[int] = -1) -> Any:
    """Bake current effective weights back into plain parameters
    (``apex.reparameterization.remove_weight_norm``)."""
    return remove_reparameterization(params, WeightNorm(dim=dim))
