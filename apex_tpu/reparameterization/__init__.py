"""apex_tpu.reparameterization — weight normalization and the generic
reparameterization transform.

The reference subsystem (``apex/reparameterization/``) is broken in the
snapshot (dead ``Fused_Weight_Norm`` import, SURVEY.md §0.3); this package
provides the *working* capability with the same API names.  Like the
reference, it is not imported by the package root — ``import
apex_tpu.reparameterization`` explicitly (but unlike the reference, doing
so succeeds).
"""

from apex_tpu.reparameterization.reparameterization import (
    Reparameterization,
    apply_reparameterization,
    default_filter,
    merge,
    remove_reparameterization,
    reparameterized_apply,
)
from apex_tpu.reparameterization.weight_norm import (
    WeightNorm,
    apply_weight_norm,
    remove_weight_norm,
)

__all__ = [
    "Reparameterization", "apply_reparameterization",
    "remove_reparameterization", "merge", "reparameterized_apply",
    "default_filter",
    "WeightNorm", "apply_weight_norm", "remove_weight_norm",
]
