"""Autoregressive decoding for :class:`~apex_tpu.models.gpt.GPTModel`.

The reference (2019-era apex) has no inference story; an LM family
without a decode path is incomplete for users, so this adds KV-cached
generation as a standalone pure function over the TRAINING checkpoint's
parameter tree — no separate inference model, no weight conversion.

TPU-shaped design:

- **Static shapes end to end**: the cache is allocated at
  ``prompt_len + max_new_tokens`` up front, the decode loop is a
  ``lax.scan`` over steps (one compiled step body), and cache writes
  are ``dynamic_update_slice`` at the carried position — nothing
  re-traces as the sequence grows (the classic XLA decode recipe).
- **Layers run under ``lax.scan``** over a stacked parameter tree, so
  the per-step body compiles once regardless of depth; loop-layout
  checkpoints (``block_{i}``) are stacked automatically and
  scan-layout ones (``layers/block``) pass through.
- **Exact training semantics**: the manual layer math mirrors
  ``GPTModel.apply`` op for op (fused layer norm fp32 stats, rope with
  global positions, fp32 softmax, tanh-approximate gelu), asserted to
  the final logit in ``tests/l1/test_generate.py``.

Greedy (``temperature=0``) or temperature sampling (``rng`` required).
Prompts are dense ``(B, L)`` token ids (no padding support — batch
same-length prompts or decode per row).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.gpt import GPTConfig
from apex_tpu.normalization.fused_layer_norm import fused_layer_norm_affine
from apex_tpu.ops.rope import apply_rope, rope_tables

NEG_INF = -1e30


def greedy_argmax(logits: jax.Array) -> jax.Array:
    """Lowest-index argmax over the last axis, REASSOCIATION-PROOF:
    ``(..., V) -> (...) i32``.

    ``jnp.argmax``'s tie-breaking is not stable across fusion
    contexts: XLA may partition the reduction differently depending on
    what the argmax is fused with, and on XLA:CPU an EXACT logit tie
    (two bf16 logits with the same value — observed on a real gpt_tiny
    stream, PR 10's verification drive) resolved to the LOWER index
    when the logits were a program output but the HIGHER index inside
    the serve engine's fused sampling epilogue, making batched decode
    greedy-diverge from solo ``generate()`` with bitwise-identical
    caches and bitwise-identical logits.  This helper pins the
    convention structurally instead of trusting the backend: ``max``
    is exact (no rounding, fully associative over floats), the
    equality compare is exact, and the index ``min`` is an integer
    reduction — every step is reassociation-safe, so the lowest tied
    index wins under ANY fusion, batch width, or backend.  Every
    greedy pick on a parity-pinned path (solo ``generate()``, the
    serve sampling epilogue, the speculative-decoding verifier) MUST
    route through this one function — the serve-vs-solo bitwise
    contract lives here.

    An all-NaN row (a numerically-poisoned forward — precondition
    violation, not a supported state) matches nothing (NaN != NaN);
    the clamp keeps the returned id in-vocabulary (``v - 1``,
    arbitrary like ``jnp.argmax``'s 0 was) instead of emitting an
    out-of-range token into the stream."""
    v = logits.shape[-1]
    mx = jnp.max(logits, axis=-1, keepdims=True)
    idx = jnp.arange(v, dtype=jnp.int32)
    cand = jnp.where(logits == mx, idx, jnp.int32(v))
    return jnp.minimum(jnp.min(cand, axis=-1), v - 1)


def pin_logits(logits: jax.Array) -> jax.Array:
    """Materialize the lm-head logits ONCE per program
    (``lax.optimization_barrier``) so every consumer reads the same
    buffer.

    The companion hazard to :func:`greedy_argmax`'s tie instability:
    on XLA:CPU a bf16 matmul lowers to a fusable loop (not an opaque
    GEMM call), so when the logits have several consumers — the
    program output AND a fused sampling epilogue — XLA may
    REMATERIALIZE the matmul per consumer with different blocking,
    and the two copies of the "same" logit can differ in the last
    ulp.  Observed for real (PR 10 drive + this PR's stress streams):
    a near-tied logit pair ranked one way in the returned buffer and
    the other way inside the fused sampler, greedy-diverging batched
    decode from solo ``generate()`` with bitwise-identical caches.
    The barrier forbids fusing/recomputing ACROSS it, so the matmul
    runs exactly once and sampler, argmax, and output all see that
    one result.  Every lm-head logits production on a parity-pinned
    path (solo decode, serve decode/prefill, the speculative-decoding
    verifier) must wrap itself in this."""
    return jax.lax.optimization_barrier(logits)


def _concrete_zero(v) -> bool:
    """True iff ``v`` is statically known to be 0: a Python/numpy int,
    or a CONCRETE 0-d array (``jnp.int32(0)`` from a caller that keeps
    positions on-device) — a traced value is never statically zero, so
    the prefill guard still rejects it."""
    if isinstance(v, jax.core.Tracer):
        return False
    if isinstance(v, (int, np.integer)):
        return int(v) == 0
    if getattr(v, "ndim", None) == 0 and jnp.issubdtype(
            getattr(v, "dtype", np.float32), jnp.integer):
        return int(v) == 0
    return False


def _stack_layer_params(params, num_layers: int):
    """Loop layout (``block_{i}`` subtrees) → scan layout (one subtree
    of ``(num_layers, ...)`` leaves); scan layout passes through."""
    if "layers" in params:
        return params["layers"]["block"]
    blocks = [params[f"block_{i}"] for i in range(num_layers)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)


def _ln(x, p, eps):
    return fused_layer_norm_affine(x, p["scale"], p["bias"],
                                   x.shape[-1], eps)


def _attn_cached(q, k_cache, v_cache, valid_mask, scale,
                 k_scale=None, v_scale=None):
    """fp32-softmax attention of ``q (B, Lq, H, D)`` against the full
    cache ``(B, M, H, D)`` with a validity mask (True = attend) of
    shape ``(Lq, M)`` (shared across the batch — this module's decode/
    prefill) or ``(B, Lq, M)`` (per-row — the serve engine's per-slot
    live lengths, :func:`apex_tpu.serve.paged.paged_attention`
    delegates here so the parity-critical math exists ONCE).

    The fp32 accumulation rides ``preferred_element_type`` instead of
    an ``astype(f32)`` on the cache operands: the bf16→f32 embed is
    exact, so the scores are bitwise what the cast form produced, but
    the (B, M, H, D) f32 cache copies are no longer in the program for
    XLA to materialize — DECODE_DECOMPOSE_r01 found the per-step cache
    converts/slice-copies to be the largest static candidates for the
    b8 0.43-of-ceiling gap (kv_read is 69% of modeled step traffic).

    ``k_scale``/``v_scale`` ``(B, M)`` select the **int8 KV** read
    path (``kv_dtype="int8"``): the caches hold int8 values with one
    f32 scale per cached position, and dequantization FUSES into the
    attention math — the per-position K scale multiplies the (B, H,
    Lq, M) scores and the V scale folds into the probability weights,
    exact in real arithmetic because each scale is constant over the
    contracted (H, D) axes (:func:`apex_tpu.quant.int8.
    kv_dequant_scales`).  The int8→f32 operand embed is exact like the
    bf16 one, so no dequantized (B, M, H, D) cache ever materializes —
    the read stays at 1 byte/element, which is the entire point (the
    ~2x decode-ceiling lift of the kv8 bench config).

    **V-side convert status (the PR-6 candidate, resolved):** the K
    side's ``preferred_element_type`` removed its cache convert, but
    the V-side contraction here is f32 probabilities x bf16/int8
    cache, and under jax 0.4.37 EVERY expressible form of that dot
    still lowers with a materialized ``(B, M, H, D)`` cache convert:
    ``einsum`` type-promotes the operands before dispatching to
    ``dot_general``; a raw mixed-dtype ``lax.dot_general`` ACCEPTS
    the operands but its StableHLO lowering inserts the same
    ``convert`` on the narrow operand (verified on the lowered text);
    and the ``DotAlgorithm``/``precision`` API that would express
    "bf16 operand, f32 accumulation" to XLA directly raises
    (``ValueError: precision ... not supported``) in this pin.  So
    the convert
    is STRUCTURALLY unavoidable at this jax version — documented
    here rather than half-fixed.  The direct ``dot_general`` form
    (contract k, batch (b, h) — then transpose to ``bqhd``) is
    bitwise-equal to this einsum and ready to ride a future jax
    whose lowering honors mixed-operand dots;
    ``tests/l0/test_serve_prefix.py::test_v_side_convert_pin`` pins
    both facts and will flag the upgrade that unblocks it."""
    mask = valid_mask[None, None] if valid_mask.ndim == 2 \
        else valid_mask[:, None]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_cache,
                   preferred_element_type=jnp.float32) * scale
    if k_scale is not None:
        s = s * k_scale[:, None, None, :]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if v_scale is not None:
        p = p * v_scale[:, None, None, :]
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v_cache,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _block(x, p, cfg, kc, vc, layer_i, cos, sin, valid_mask, write_at,
           ks=None, vs=None):
    """One transformer block over ``x (B, Lq, E)`` with cache update at
    ``(layer_i, :, write_at)``; mirrors GPTBlock/CausalSelfAttention
    exactly.

    ``kc``/``vc`` are the FULL ``(L, B, M, H, D)`` caches, updated with
    one tiny ``dynamic_update_slice`` at this layer's row.  Threading
    the whole buffers through the layer scan's carry (instead of
    per-layer slices through its xs/ys) is a measured 1.27x decode
    win: scan ys are STACKED into fresh outputs, so the slice form
    re-copied both full caches every decode step (profiled as two
    ~264 ms ``copy`` ops per 256-token generation — ~30% of step
    time), while carry buffers alias in place across ``while``-loop
    iterations and only the written slot touches memory.

    ``ks``/``vs`` ``(L, B, M)`` f32 select the int8 KV format: each
    written token quantizes with its own per-position absmax scale
    (:func:`apex_tpu.quant.int8.quantize_kv`) and the read fuses the
    dequant into the attention math (:func:`_attn_cached`)."""
    c = cfg
    head_dim = c.hidden_size // c.num_heads
    scale = 1.0 / float(head_dim) ** 0.5
    b, lq = x.shape[0], x.shape[1]

    h = _ln(x, p["ln1"], c.layer_norm_eps)
    qkv = h @ p["attention"]["qkv"]["kernel"] \
        + p["attention"]["qkv"]["bias"].astype(h.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, lq, c.num_heads, head_dim)
    k = k.reshape(b, lq, c.num_heads, head_dim)
    v = v.reshape(b, lq, c.num_heads, head_dim)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)  # rotated keys cached (standard layout)
    if ks is not None:
        from apex_tpu.quant import int8 as int8_lib
        qk, sk = int8_lib.quantize_kv(k)      # (B,Lq,H,D) i8, (B,Lq) f32
        qv, sv = int8_lib.quantize_kv(v)
        kc = jax.lax.dynamic_update_slice(
            kc, qk[None], (layer_i, 0, write_at, 0, 0))
        vc = jax.lax.dynamic_update_slice(
            vc, qv[None], (layer_i, 0, write_at, 0, 0))
        ks = jax.lax.dynamic_update_slice(
            ks, sk[None], (layer_i, 0, write_at))
        vs = jax.lax.dynamic_update_slice(
            vs, sv[None], (layer_i, 0, write_at))
    else:
        kc = jax.lax.dynamic_update_slice(
            kc, k.astype(kc.dtype)[None], (layer_i, 0, write_at, 0, 0))
        vc = jax.lax.dynamic_update_slice(
            vc, v.astype(vc.dtype)[None], (layer_i, 0, write_at, 0, 0))
    if lq > 1 and _concrete_zero(write_at):
        # full prefill: rows 0..lq-1 attending to cache slots <= their
        # own position IS causal self-attention over the
        # (already-rotated) prompt q/k/v — run the production flash
        # kernel instead of the cached einsum, whose (B, H, Lq, M) fp32
        # score tensor would materialize ~450 MB at b8/L2048.  Valid
        # only from an empty cache: the kernel attends within the chunk.
        from apex_tpu.attention import attention
        o = attention(q, k, v, causal=True)
    else:
        # single-token decode, or CHUNKED prefill (lq > 1 at a possibly
        # traced mid-sequence ``write_at``): the chunk's own k/v are
        # already in the cache (written above), so attending against
        # the full cache under ``valid_mask`` — cache slot <= the row's
        # global position — is causal-within-chunk PLUS full attention
        # over the cached history.  The (B, H, Lq, M) score tensor is
        # fine at serving chunk sizes (the serve engine admits prefills
        # in ``ServeConfig.prefill_chunk``-token chunks).
        kc_l = jax.lax.dynamic_index_in_dim(kc, layer_i, 0,
                                            keepdims=False)
        vc_l = jax.lax.dynamic_index_in_dim(vc, layer_i, 0,
                                            keepdims=False)
        ks_l = vs_l = None
        if ks is not None:
            ks_l = jax.lax.dynamic_index_in_dim(ks, layer_i, 0,
                                                keepdims=False)
            vs_l = jax.lax.dynamic_index_in_dim(vs, layer_i, 0,
                                                keepdims=False)
        o = _attn_cached(q, kc_l, vc_l, valid_mask, scale,
                         k_scale=ks_l, v_scale=vs_l)
    o = o.reshape(b, lq, c.hidden_size)
    x = x + (o @ p["attention"]["out"]["kernel"]
             + p["attention"]["out"]["bias"].astype(o.dtype))
    h = _ln(x, p["ln2"], c.layer_norm_eps)
    h = h @ p["ffn_in"]["kernel"] + p["ffn_in"]["bias"].astype(h.dtype)
    h = jax.nn.gelu(h)  # tanh approximation, as flax nn.gelu in training
    return (x + (h @ p["ffn_out"]["kernel"]
                 + p["ffn_out"]["bias"].astype(h.dtype)),
            kc, vc, ks, vs)


def _forward_cached(params, stacked, cfg, ids, kc, vc, start: int,
                    ks=None, vs=None):
    """Embed ``ids (B, Lq)`` at global positions ``start..start+Lq-1``,
    run all layers with cache writes at ``start``, return final-token
    logits and updated caches.  ``start`` may be traced (decode and
    chunked prefill — a multi-token chunk appended mid-sequence
    attends to the cached history through the einsum path) or a
    concrete 0 (full prefill through the flash kernel).  ``ks``/``vs``
    carry the int8 KV format's per-position scales (None = dense
    16/32-bit cache)."""
    c = cfg
    b, lq = ids.shape
    m = kc.shape[2]
    head_dim = c.hidden_size // c.num_heads
    x = params["tok_emb"]["embedding"][ids]
    positions = start + jnp.arange(lq)[None, :]
    positions = jnp.broadcast_to(positions, (b, lq))
    cos, sin = rope_tables(positions, head_dim, c.rope_theta)
    # rows attend to cache slots <= their own global position
    qpos = start + jnp.arange(lq)[:, None]
    valid = jnp.arange(m)[None, :] <= qpos          # (Lq, M)

    # caches ride the CARRY as whole (L, B, M, H, D) buffers — scan ys
    # would restack (copy) both full caches every call (see _block)
    def layer(carry, inputs):
        x, kc, vc, ks, vs = carry
        p_l, layer_i = inputs
        x, kc, vc, ks, vs = _block(x, p_l, c, kc, vc, layer_i, cos, sin,
                                   valid, write_at=start, ks=ks, vs=vs)
        return (x, kc, vc, ks, vs), None

    (x, kc, vc, ks, vs), _ = jax.lax.scan(
        layer, (x, kc, vc, ks, vs), (stacked, jnp.arange(c.num_layers)))
    x = _ln(x[:, -1:], params["ln_f"], c.layer_norm_eps)
    logits = pin_logits(x[:, 0] @ params["lm_head"]["kernel"])
    return logits, kc, vc, ks, vs


def generate(params, cfg: GPTConfig, prompt_ids, max_new_tokens: int,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None,
             kv_dtype: Optional[str] = None):
    """Decode ``max_new_tokens`` tokens after ``prompt_ids (B, L)``.

    Returns ``(B, L + max_new_tokens)`` ids.  ``temperature=0`` is
    greedy argmax; ``temperature>0`` samples ``softmax(logits/T)`` and
    requires ``rng`` (temperature is traced, so sweeping/annealing it
    never recompiles — only the greedy↔sampling mode switch does).
    Works with loop- and scan-layout checkpoints; loop layouts are
    stacked to the scan form OUTSIDE the compiled graph on each call —
    for repeated generation from a big loop-layout checkpoint, pre-pack
    once with the scan layout (``params["layers"]["block"]``) to skip
    the per-call copy.

    ``kv_dtype="int8"`` stores the KV cache as int8 with one f32 scale
    per cached position (quantized on write, dequant fused into the
    attention read — :mod:`apex_tpu.quant.int8`): half the cache bytes
    of the bf16 layout, a ~2x ceiling lift on the HBM-bound decode
    step, within the documented greedy token-match tolerance of the
    dense cache (``docs/source/quantization.rst``).
    """
    sample = float(temperature) > 0.0
    if sample and rng is None:
        raise ValueError("temperature sampling requires rng")
    if kv_dtype not in (None, "int8"):
        raise ValueError(f"kv_dtype must be None or 'int8'; got "
                         f"{kv_dtype!r}")
    stacked = _stack_layer_params(params, cfg.num_layers)
    top = {k: v for k, v in params.items()
           if not k.startswith("block_") and k != "layers"}
    if rng is None:
        rng = jax.random.PRNGKey(0)  # unused on the greedy path
    return _generate_impl(top, stacked, prompt_ids,
                          jnp.float32(temperature), rng, cfg=cfg,
                          max_new_tokens=int(max_new_tokens),
                          sample=sample, kv_dtype=kv_dtype)


@functools.partial(jax.jit, static_argnames=("cfg", "max_new_tokens",
                                             "sample", "kv_dtype"))
def _generate_impl(top, stacked, prompt_ids, temperature, rng, *,
                   cfg: GPTConfig, max_new_tokens: int, sample: bool,
                   kv_dtype: Optional[str] = None):
    c = cfg
    b, lp = prompt_ids.shape
    m = lp + max_new_tokens
    head_dim = c.hidden_size // c.num_heads
    dtype = top["tok_emb"]["embedding"].dtype
    if kv_dtype == "int8":
        kc = jnp.zeros((c.num_layers, b, m, c.num_heads, head_dim),
                       jnp.int8)
        ks = jnp.zeros((c.num_layers, b, m), jnp.float32)
        vs = jnp.zeros_like(ks)
    else:
        kc = jnp.zeros((c.num_layers, b, m, c.num_heads, head_dim),
                       dtype)
        ks = vs = None
    vc = jnp.zeros_like(kc)

    logits, kc, vc, ks, vs = _forward_cached(top, stacked, c, prompt_ids,
                                             kc, vc, start=0, ks=ks,
                                             vs=vs)

    def pick(logits, key):
        if sample:
            return jax.random.categorical(
                key, logits.astype(jnp.float32) / temperature, axis=-1)
        # the shared tie-stable greedy pick (see greedy_argmax): solo
        # and serve MUST break exact logit ties identically or the
        # bitwise parity contract dies on tied bf16 logits
        return greedy_argmax(logits.astype(jnp.float32))

    rng, key0 = jax.random.split(rng)
    first = pick(logits, key0).astype(prompt_ids.dtype)

    def step(carry, key):
        tok, t, kc, vc, ks, vs = carry
        logits, kc, vc, ks, vs = _forward_cached(
            top, stacked, c, tok[:, None], kc, vc, start=t, ks=ks, vs=vs)
        nxt = pick(logits, key).astype(tok.dtype)
        return (nxt, t + 1, kc, vc, ks, vs), nxt

    keys = jax.random.split(rng, max(max_new_tokens - 1, 1))
    (_, _, _, _, _, _), rest = jax.lax.scan(
        step, (first, jnp.asarray(lp, jnp.int32), kc, vc, ks, vs),
        keys[: max_new_tokens - 1])
    out = jnp.concatenate(
        [prompt_ids, first[:, None], jnp.moveaxis(rest, 0, 1)], axis=1)
    return out[:, :m]
