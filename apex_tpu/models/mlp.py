"""MNIST-scale MLP — the minimal end-to-end workload.

Port of BASELINE config 1 ("examples/simple amp O1 MNIST MLP").  The layers
route their matmuls through :mod:`apex_tpu.amp.ops` so the O1 policy governs
their precision exactly as the reference's monkey-patched ``torch.nn.functional
.linear`` did.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.amp import ops as amp_ops
from apex_tpu.layers import Dense

class AmpDense(Dense):
    """Dense layer whose matmul is policy-cast (O1 whitelists ``linear``,
    reference ``functional_overrides.py:18-27``).  Subclass (not alias) so
    Flax keeps deriving ``AmpDense_N`` param scopes."""


class MLP(nn.Module):
    """ReLU MLP classifier."""

    features: Sequence[int] = (256, 256)
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        for f in self.features:
            x = AmpDense(f)(x)
            x = nn.relu(x)
        return AmpDense(self.num_classes)(x)


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Softmax cross entropy in fp32 (O1 blacklists softmax/losses)."""
    logp = amp_ops.log_softmax(logits, axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=logp.dtype)
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))
