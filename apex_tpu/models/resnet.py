"""ResNet-50 (v1.5) — the flagship ImageNet workload.

Port of BASELINE configs 2 and 3 ("examples/imagenet ResNet-50 amp O2 +
FusedAdam (single chip)" / "DDP + SyncBatchNorm (v5e-8)"); the reference's
examples consume torchvision's resnet50 (``examples/imagenet/main_amp.py``),
so the model itself is re-authored TPU-first:

- channels-last (NHWC) layout throughout — the layout the reference's
  ``_c_last`` SyncBN kernels existed for, and the MXU-friendly one;
- v1.5 bottleneck (stride on the 3x3, like torchvision);
- BatchNorms are :class:`apex_tpu.parallel.SyncBatchNorm` threaded with the
  ``bn_axis_name`` / ``bn_process_group`` fields, making the model
  ``convert_syncbn_model``-convertible (``apex/parallel/__init__.py:21-53``).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.layers import Conv, Dense
from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm


def _bn(name, axis_name, process_group):
    """Shared BN constructor for all blocks (SyncBatchNorm defaults match
    the reference: momentum 0.1, eps 1e-5)."""
    return SyncBatchNorm(axis_name=axis_name, process_group=process_group,
                         momentum=0.1, epsilon=1e-5, name=name)


class Bottleneck(nn.Module):
    features: int               # base width; output is expansion-x
    strides: int = 1
    downsample: bool = False
    bn_axis_name: Optional[str] = None
    bn_process_group: Optional[Sequence[Sequence[int]]] = None

    #: output-channel multiplier — the property the stage-0 projection
    #: decision keys on (torchvision's ``expansion``)
    expansion = 4

    def _bn(self, name):
        return _bn(name, self.bn_axis_name, self.bn_process_group)

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = Conv(self.features, 1, name="conv1")(x)
        y = self._bn("bn1")(y, use_running_average=not train)
        y = nn.relu(y)
        y = Conv(self.features, 3, strides=self.strides, name="conv2")(y)
        y = self._bn("bn2")(y, use_running_average=not train)
        y = nn.relu(y)
        y = Conv(self.features * 4, 1, name="conv3")(y)
        y = self._bn("bn3")(y, use_running_average=not train)
        if self.downsample:
            residual = Conv(self.features * 4, 1, strides=self.strides,
                            name="downsample_conv")(x)
            residual = self._bn("downsample_bn")(
                residual, use_running_average=not train)
        return nn.relu(y + residual.astype(y.dtype))


class BasicBlock(nn.Module):
    """Two-conv residual block (torchvision ``BasicBlock``) — the block of
    ResNet-18/34; no channel expansion."""

    features: int
    strides: int = 1
    downsample: bool = False
    bn_axis_name: Optional[str] = None
    bn_process_group: Optional[Sequence[Sequence[int]]] = None

    expansion = 1

    def _bn(self, name):
        return _bn(name, self.bn_axis_name, self.bn_process_group)

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = Conv(self.features, 3, strides=self.strides, name="conv1")(x)
        y = self._bn("bn1")(y, use_running_average=not train)
        y = nn.relu(y)
        y = Conv(self.features, 3, name="conv2")(y)
        y = self._bn("bn2")(y, use_running_average=not train)
        if self.downsample:
            residual = Conv(self.features, 1, strides=self.strides,
                            name="downsample_conv")(x)
            residual = self._bn("downsample_bn")(
                residual, use_running_average=not train)
        return nn.relu(y + residual.astype(y.dtype))


class ResNet(nn.Module):
    """ResNet-v1.5; ``stage_sizes=(3,4,6,3)`` is ResNet-50.

    ``stem="s2d"`` replaces the 7x7/2 conv + 3x3/2 maxpool with a 4x4
    space-to-depth reshuffle and a 2x2 conv — the MXU-friendly input
    stem (the 7x7 conv's C_in=3 leaves the systolic array ~97% idle):
    measured +8% ResNet-50 training throughput on v5e (2372 -> 2558
    img/s at b256/224px, amp O2).  Same 56x56x``width`` stem output;
    a from-scratch variant, not a reparameterization of the conv7 stem
    (its checkpoints are not interchangeable).  Requires spatial dims
    divisible by 4.
    """

    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)
    num_classes: int = 1000
    width: int = 64
    block_cls: Any = Bottleneck
    bn_axis_name: Optional[str] = None
    bn_process_group: Optional[Sequence[Sequence[int]]] = None
    stem: str = "conv7"

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.stem == "s2d":
            b, h, w, c = x.shape
            if h % 4 or w % 4:
                raise ValueError(
                    f"stem='s2d' needs spatial dims divisible by 4, got "
                    f"{(h, w)}")
            x = x.reshape(b, h // 4, 4, w // 4, 4, c)\
                 .transpose(0, 1, 3, 2, 4, 5)\
                 .reshape(b, h // 4, w // 4, 16 * c)
            y = Conv(self.width, 2, name="stem_conv")(x)
        elif self.stem == "conv7":
            y = Conv(self.width, 7, strides=2, name="stem_conv")(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")
        y = SyncBatchNorm(axis_name=self.bn_axis_name,
                          process_group=self.bn_process_group,
                          name="stem_bn")(y, use_running_average=not train)
        y = nn.relu(y)
        if self.stem == "conv7":
            y = nn.max_pool(y, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                # Expanding blocks need a projection even at stage 0's
                # first block (channel count changes); expansion-1 blocks
                # only when the shape actually changes (stride-2 entry of
                # stages 1+).
                downsample = block == 0 and (
                    stage > 0
                    or getattr(self.block_cls, "expansion", 1) != 1)
                y = self.block_cls(
                    features=self.width * (2 ** stage),
                    strides=strides,
                    downsample=downsample,
                    bn_axis_name=self.bn_axis_name,
                    bn_process_group=self.bn_process_group,
                    name=f"stage{stage}_block{block}",
                )(y, train=train)
        y = jnp.mean(y, axis=(1, 2))  # global average pool
        return Dense(self.num_classes,
                     kernel_init=nn.initializers.normal(0.01), name="fc")(y)


def ResNet50(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), **kw)


def ResNet101(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 23, 3), **kw)


def ResNet152(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 8, 36, 3), **kw)


def ResNet18(**kw) -> ResNet:
    """torchvision-style ResNet-18: BasicBlock, (2,2,2,2) stages."""
    kw.setdefault("block_cls", BasicBlock)
    return ResNet(stage_sizes=(2, 2, 2, 2), **kw)


def ResNet34(**kw) -> ResNet:
    kw.setdefault("block_cls", BasicBlock)
    return ResNet(stage_sizes=(3, 4, 6, 3), **kw)


def ResNet50S2D(**kw) -> ResNet:
    """ResNet-50 with the TPU-native space-to-depth stem (see
    :class:`ResNet`)."""
    kw.setdefault("stem", "s2d")
    return ResNet(stage_sizes=(3, 4, 6, 3), **kw)


#: ``--arch`` string → constructor (the torchvision ``models.__dict__``
#: lookup of the reference example, ``examples/imagenet/main_amp.py``;
#: ``resnet50_s2d`` is the TPU-native-stem variant beyond that list).
ARCHS = {"resnet18": ResNet18, "resnet34": ResNet34, "resnet50": ResNet50,
         "resnet101": ResNet101, "resnet152": ResNet152,
         "resnet50_s2d": ResNet50S2D}
