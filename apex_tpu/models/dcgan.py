"""DCGAN generator/discriminator — the two-loss-scaler workload.

Port of BASELINE config 5 ("examples/dcgan amp O1 two-optimizer GAN"): the
reference's ``examples/dcgan`` README is a stub (SURVEY.md §0), so the
workload is defined by the amp machinery it exercises — ``num_losses=2``
with independent ``loss_id`` scalers (``apex/amp/handle.py:53-58``) across a
generator and a discriminator optimizer.  Architecture follows the standard
DCGAN recipe in NHWC.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.layers import Conv, ConvTranspose, Dense
from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm


class Generator(nn.Module):
    """z (B, zdim) → image (B, S, S, channels) with S = 8 * 2**n_up."""

    feature_maps: int = 64
    channels: int = 3
    n_upsample: int = 2

    @nn.compact
    def __call__(self, z, train: bool = True):
        f = self.feature_maps * (2 ** self.n_upsample)
        x = Dense(4 * 4 * f, name="project")(z)
        x = x.reshape(z.shape[0], 4, 4, f)
        x = SyncBatchNorm(name="bn_in")(x, use_running_average=not train)
        x = nn.relu(x)
        for i in range(self.n_upsample):
            f //= 2
            x = ConvTranspose(f, 4, strides=2, name=f"up{i}")(x)
            x = SyncBatchNorm(name=f"bn{i}")(x, use_running_average=not train)
            x = nn.relu(x)
        x = ConvTranspose(self.channels, 4, strides=2, name="to_rgb")(x)
        return jnp.tanh(x)


class Discriminator(nn.Module):
    feature_maps: int = 64
    n_down: int = 3

    @nn.compact
    def __call__(self, img, train: bool = True):
        x = img
        f = self.feature_maps
        for i in range(self.n_down):
            x = Conv(f, 4, strides=2, name=f"down{i}", use_bias=True)(x)
            if i > 0:
                x = SyncBatchNorm(name=f"bn{i}")(
                    x, use_running_average=not train)
            x = nn.leaky_relu(x, 0.2)
            f *= 2
        x = x.reshape(x.shape[0], -1)
        return Dense(1, name="logit")(x)  # logits; loss uses with-logits


def gan_losses(d_real_logits, d_fake_logits, g_fake_logits):
    """Non-saturating GAN losses in fp32 via with-logits BCE (the banned-op
    guidance: never probability-space BCE in half,
    ``functional_overrides.py:67-77``)."""
    def bce_logits(logits, target):
        logits = logits.astype(jnp.float32)
        # log(1+exp(-|x|)) formulation, stable in fp32
        return jnp.mean(jnp.maximum(logits, 0) - logits * target
                        + jnp.log1p(jnp.exp(-jnp.abs(logits))))

    d_loss = bce_logits(d_real_logits, 1.0) + bce_logits(d_fake_logits, 0.0)
    g_loss = bce_logits(g_fake_logits, 1.0)
    return d_loss, g_loss
