"""BERT — the FusedLayerNorm + FusedLAMB pretraining workload.

Port of BASELINE config 4 ("BERT-large pretraining FusedLAMB +
FusedLayerNorm (v5e-16)").  The reference carries no BERT model (its role
there is played by downstream users pairing apex's FusedLayerNorm/LAMB
kernels with their own BERT); the model here is authored TPU-first:

- every LayerNorm is :class:`apex_tpu.normalization.FusedLayerNorm`
  (Pallas-fused on TPU, fp32 statistics);
- attention/FFN matmuls route through the policy-cast op layer, softmax in
  fp32 (``lists/functional_overrides.py:29-65`` puts softmax on the fp32
  list);
- shapes default to BERT-large (hidden 1024, 24 layers, 16 heads).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.amp import ops as amp_ops
from apex_tpu.layers import Dense
from apex_tpu.normalization import FusedLayerNorm


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    #: Stack the encoder as one ``nn.scan`` over a single compiled layer
    #: body — layer params carry a leading ``num_layers`` axis (shardable
    #: over an fsdp/pipeline mesh axis), and ``remat`` composes per layer.
    #: Measured on one chip: step time identical to the unrolled loop
    #: (XLA dedups the 24 copies), compile slightly slower at 24 layers,
    #: so the named ``layer_{i}`` loop stays the default; turn this on for
    #: remat, per-layer sharding, or very deep stacks.
    scan_layers: bool = False
    #: Rematerialize each layer's activations in the backward pass
    #: (``jax.checkpoint`` through ``nn.remat``) — trades recompute FLOPs
    #: for HBM, the lever for long sequences / big batches.  Effective on
    #: both the scanned and the unrolled encoder.
    remat: bool = False


def bert_large() -> BertConfig:
    return BertConfig()


def bert_large_tpu() -> BertConfig:
    """bert-large with TPU-native head geometry: 8 heads of 128 instead
    of 16 of 64 — head_dim 128 fills the MXU/VPU lane width in the flash
    kernels at identical parameter count and FLOPs (see
    :func:`apex_tpu.models.gpt.gpt_small_tpu` for the measured kernel
    speedup).  Prefer this shape for models pretrained from scratch on
    TPU; :func:`bert_large` keeps the conventional 16x64 for checkpoint
    parity."""
    return BertConfig(num_heads=8)


def bert_base() -> BertConfig:
    return BertConfig(hidden_size=768, num_layers=12, num_heads=12,
                      intermediate_size=3072)


def bert_tiny() -> BertConfig:
    """Test-scale config."""
    return BertConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                      num_heads=4, intermediate_size=256,
                      max_position_embeddings=64)


class SelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask=None):
        c = self.cfg
        head_dim = c.hidden_size // c.num_heads
        from apex_tpu.ops import use_pallas
        kv_mask = None if mask is None else mask.astype(bool)
        scale = 1.0 / float(head_dim) ** 0.5
        if use_pallas() and head_dim < 128:
            # Head-major fast path: projections emit/consume
            # (B, H, L, D) with the permutation inside their dots, and
            # the flash kernel runs layout="bhld" — no (B*H, L, D)
            # relayout copies (BERT has no rotary step in between, so
            # the path is pure).  Gated to narrow heads: measured +3.1%
            # at 16x64 (bert_large) but -1% at 8x128 (bert_large_tpu),
            # where XLA's relayouts are cheap and the head-major einsum
            # spelling costs slightly more than it saves (same-day v5e
            # A/B, round 3).
            from apex_tpu.layers import HeadMajorOutProj, HeadMajorQKVProj
            from apex_tpu.ops.pallas.flash_attention import flash_attention
            qkv = HeadMajorQKVProj(c.hidden_size, c.num_heads,
                                   name="qkv")(x)
            out = flash_attention(qkv[0], qkv[1], qkv[2], kv_mask=kv_mask,
                                  scale=scale, layout="bhld")
            return HeadMajorOutProj(c.hidden_size, c.num_heads,
                                    name="out")(out)

        qkv = Dense(3 * c.hidden_size, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(t.shape[0], t.shape[1], c.num_heads, head_dim)

        q, k, v = heads(q), heads(k), heads(v)
        if use_pallas():
            # wide heads (>= 128): split layout + the flash kernel — the
            # (L, L) scores never hit HBM and the relayout is cheap here
            from apex_tpu.ops.pallas.flash_attention import flash_attention
            out = flash_attention(q, k, v, kv_mask=kv_mask, scale=scale)
            out = out.reshape(x.shape[0], x.shape[1], c.hidden_size)
            return Dense(c.hidden_size, name="out")(out)
        scores = amp_ops.einsum("bqhd,bkhd->bhqk", q, k) \
            / jnp.sqrt(head_dim)
        if mask is not None:
            # mask: (B, L) 1 = attend; large negative in fp32
            bias = (1.0 - mask[:, None, None, :]
                    .astype(jnp.float32)) * -1e9
            scores = scores.astype(jnp.float32) + bias
        probs = amp_ops.softmax(scores, axis=-1).astype(v.dtype)
        if mask is not None:
            # all-padding rows emit zeros, matching the flash branch
            probs = jnp.where(mask[:, None, None, :].astype(bool),
                              probs, 0)
        out = amp_ops.einsum("bhqk,bkhd->bqhd", probs, v)
        out = out.reshape(x.shape[0], x.shape[1], c.hidden_size)
        return Dense(c.hidden_size, name="out")(out)


class TransformerLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask=None):
        c = self.cfg
        a = SelfAttention(c, name="attention")(x, mask)
        x = FusedLayerNorm(c.hidden_size, eps=c.layer_norm_eps,
                           name="attention_ln")(x + a)
        h = Dense(c.intermediate_size, name="ffn_in")(x)
        h = nn.gelu(h)
        h = Dense(c.hidden_size, name="ffn_out")(h)
        return FusedLayerNorm(c.hidden_size, eps=c.layer_norm_eps,
                              name="ffn_ln")(x + h)


class _ScanBody(nn.Module):
    """Carry-shaped wrapper over :class:`TransformerLayer` for ``nn.scan``."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask):
        return TransformerLayer(self.cfg, name="layer")(x, mask), None


class BertModel(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        c = self.cfg
        B, L = input_ids.shape
        tok = nn.Embed(c.vocab_size, c.hidden_size, name="tok_emb")(input_ids)
        pos = nn.Embed(c.max_position_embeddings, c.hidden_size,
                       name="pos_emb")(jnp.arange(L)[None, :])
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        seg = nn.Embed(c.type_vocab_size, c.hidden_size,
                       name="seg_emb")(token_type_ids)
        x = FusedLayerNorm(c.hidden_size, eps=c.layer_norm_eps,
                           name="emb_ln")(tok + pos + seg)
        if c.scan_layers:
            # One compiled layer body scanned num_layers times; params get
            # a leading layer axis (shard it over a pipeline/fsdp mesh axis
            # if desired).  remat composes inside the scan: each layer's
            # activations recompute in backward instead of living in HBM.
            body = _ScanBody
            if c.remat:
                body = nn.remat(body, prevent_cse=False)
            x, _ = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast,),
                length=c.num_layers,
                metadata_params={nn.meta.PARTITION_NAME: "layers"},
            )(c, name="layers")(x, attention_mask)
        else:
            layer_cls = (nn.remat(TransformerLayer, prevent_cse=False)
                         if c.remat else TransformerLayer)
            for i in range(c.num_layers):
                x = layer_cls(c, name=f"layer_{i}")(x, attention_mask)
        return x


class BertForPreTraining(nn.Module):
    """MLM + NSP heads over the encoder (the pretraining objective LAMB was
    built for)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        c = self.cfg
        seq = BertModel(c, name="bert")(input_ids, token_type_ids,
                                        attention_mask)
        # MLM head: transform + LN + vocab projection.
        h = Dense(c.hidden_size, name="mlm_transform")(seq)
        h = nn.gelu(h)
        h = FusedLayerNorm(c.hidden_size, eps=c.layer_norm_eps,
                           name="mlm_ln")(h)
        mlm_logits = Dense(c.vocab_size, name="mlm_decoder")(h)
        # NSP head over the [CLS] (first) token.
        pooled = jnp.tanh(Dense(c.hidden_size, name="pooler")(seq[:, 0]))
        nsp_logits = Dense(2, name="nsp")(pooled)
        return mlm_logits, nsp_logits


def pretraining_loss(mlm_logits, nsp_logits, mlm_labels, nsp_labels,
                     mlm_mask):
    """Masked-LM + NSP cross entropy in fp32; ``mlm_mask`` selects the
    masked positions (1.0 where a prediction is scored)."""
    # -logp[label] = logsumexp - logits[label]: identical math to
    # log_softmax + gather without materializing the (B, L, V) fp32
    # log-probability tensor (see models/gpt.py lm_loss) — the fp32
    # policy rides amp_ops.logsumexp, the gather reads the raw logits.
    lse = amp_ops.logsumexp(mlm_logits, axis=-1)
    picked = jnp.take_along_axis(mlm_logits, mlm_labels[..., None],
                                 axis=-1).squeeze(-1).astype(lse.dtype)
    denom = jnp.maximum(mlm_mask.sum(), 1.0)
    mlm_loss = ((lse - picked) * mlm_mask).sum() / denom
    nsp_logp = amp_ops.log_softmax(nsp_logits, axis=-1)
    nsp_loss = -jnp.mean(
        jnp.take_along_axis(nsp_logp, nsp_labels[:, None], axis=-1))
    return mlm_loss + nsp_loss
