"""apex_tpu.models — reference workload model families (BASELINE configs).

1. MLP (MNIST, amp O1) — :mod:`apex_tpu.models.mlp`
2./3. ResNet-50 (ImageNet, O2 + FusedAdam; DDP + SyncBN) —
   :mod:`apex_tpu.models.resnet`
4. BERT-large (FusedLAMB + FusedLayerNorm) — :mod:`apex_tpu.models.bert`
5. DCGAN (two-loss-scaler GAN) — :mod:`apex_tpu.models.dcgan`

Plus, beyond the reference: a GPT-style causal LM for the long-context /
sequence-parallel training path — :mod:`apex_tpu.models.gpt`.
"""

from apex_tpu.models.bert import (
    BertConfig,
    BertForPreTraining,
    BertModel,
    bert_base,
    bert_large,
    bert_tiny,
    pretraining_loss,
)
from apex_tpu.models.dcgan import Discriminator, Generator, gan_losses
from apex_tpu.models.generate import generate
from apex_tpu.models.gpt import (
    GPTConfig,
    GPTModel,
    gpt_small,
    gpt_tiny,
    lm_loss,
)
from apex_tpu.models.mlp import MLP, AmpDense, cross_entropy_loss
from apex_tpu.models.resnet import (
    ARCHS,
    BasicBlock,
    Bottleneck,
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet50S2D,
    ResNet101,
    ResNet152,
)

__all__ = [
    "MLP", "AmpDense", "cross_entropy_loss",
    "ResNet", "ResNet50", "ResNet50S2D", "ResNet18", "ResNet34", "ResNet101", "ResNet152",
    "ARCHS", "BasicBlock", "Bottleneck",
    "BertConfig", "BertModel", "BertForPreTraining",
    "bert_large", "bert_base", "bert_tiny", "pretraining_loss",
    "Generator", "Discriminator", "gan_losses",
    "GPTConfig", "GPTModel", "gpt_small", "gpt_tiny", "lm_loss",
    "generate",
]
