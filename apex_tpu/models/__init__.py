"""apex_tpu.models — reference workload model families (BASELINE configs)."""

from apex_tpu.models.mlp import MLP, AmpDense, cross_entropy_loss

__all__ = ["MLP", "AmpDense", "cross_entropy_loss"]
