"""GPT-style causal language model — the long-context training workload.

Beyond the reference (2019-era apex has no LM and no long-context story,
SURVEY.md section 5.7); this model exists so the framework's long-context
machinery trains a *real* architecture end-to-end:

- causal Pallas flash attention (``apex_tpu.ops.pallas.flash_attention``)
  with rotary position embeddings — no (L, L) tensor in HBM, no learned
  position table capping the context;
- ``seq_axis_name`` switches attention to
  :func:`~apex_tpu.attention.ring_attention` so the sequence dimension
  shards over a mesh axis (context parallelism) while everything else is
  untouched;
- ``scan_layers`` / ``remat`` as in :class:`~apex_tpu.models.bert.BertModel`
  (one compiled layer body; recompute-for-HBM);
- FusedLayerNorm everywhere, matmuls at amp compute precision.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.layers import Dense
from apex_tpu.normalization import FusedLayerNorm
# Rope math lives in ops (the flash kernel applies it in-kernel); the
# historical spellings stay importable from here.
from apex_tpu.ops.rope import (  # noqa: F401  (re-exports)
    apply_rope,
    apply_rope_mxu,
    rope,
    rope_tables,
    _rope_rot_matrix,
)


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    layer_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    #: shard the sequence over this mesh axis (ring attention); None = local
    seq_axis_name: Optional[str] = None
    scan_layers: bool = False
    remat: bool = False


def gpt_small() -> GPTConfig:
    return GPTConfig()


def gpt_small_tpu() -> GPTConfig:
    """gpt-small with TPU-native head geometry: 6 heads of 128 instead
    of 12 of 64.  head_dim 128 fills the MXU/VPU lane width, measured
    35-40% faster flash attention at identical FLOPs and parameter
    count (B8·L2048 on v5e: fwd 2.50 -> 1.63 ms/layer, fwd+bwd 6.51 ->
    3.89 ms/layer).  Prefer this shape for models trained from scratch
    on TPU; :func:`gpt_small` keeps the GPU-conventional 12x64 for
    checkpoint parity."""
    return GPTConfig(num_heads=6)


def gpt_medium_tpu() -> GPTConfig:
    """gpt-medium (~368M params) with TPU-native 8x128 heads.  The
    bigger matmuls lift single-chip MFU past the small model (measured
    53% at B8·L2048 amp O2 on v5e, 43.4K tok/s)."""
    return GPTConfig(hidden_size=1024, num_layers=24, num_heads=8,
                     intermediate_size=4096)


def gpt_tiny() -> GPTConfig:
    """Test-scale config."""
    return GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                     num_heads=4, intermediate_size=128)


class CausalSelfAttention(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, rope_cs):
        c = self.cfg
        head_dim = c.hidden_size // c.num_heads
        b, l = x.shape[0], x.shape[1]
        scale = 1.0 / float(head_dim) ** 0.5
        from apex_tpu.attention import attention
        from apex_tpu.ops.rope import KernelRopeTables

        qkv = Dense(3 * c.hidden_size, name="qkv")(x)
        q, k, v = (t.reshape(b, l, c.num_heads, head_dim)
                   for t in jnp.split(qkv, 3, axis=-1))

        if isinstance(rope_cs, KernelRopeTables):
            # Kernel-fused rope (GPTModel builds the kernel-format
            # tables once per step, outside the scanned/remat body):
            # q/k reach the flash kernel UNROTATED and the rotation
            # happens on VMEM blocks right before the score matmul —
            # the rotated tensors never exist in HBM and the four rope
            # elementwise passes (q/k fwd, dq/dk bwd) disappear from
            # the step.  Same-day v5e A/B (round 4, B8·L2048 O2 train
            # step): split+fused-rope beats the round-3 prerotated path
            # ~+2% at both 12x64 and 6x128, and beats a head-major
            # (HeadMajorQKVProj + layout="bhld" + fused rope) variant
            # by ~5% at 12x64 — unlike BERT, GPT loses more to the
            # head-major projection einsum than the reshape relayout
            # costs, so the split spelling stays.
            out = attention(q, k, v, causal=True, scale=scale,
                            rope=rope_cs)
        else:
            cos, sin = rope_cs
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            # with seq_axis_name: ring attention over the mesh axis
            out = attention(q, k, v, axis_name=c.seq_axis_name,
                            causal=True, scale=scale)
        out = out.reshape(b, l, c.hidden_size)
        return Dense(c.hidden_size, name="out")(out)


class GPTBlock(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, rope_cs):
        c = self.cfg
        h = FusedLayerNorm(c.hidden_size, eps=c.layer_norm_eps,
                           name="ln1")(x)
        x = x + CausalSelfAttention(c, name="attention")(h, rope_cs)
        h = FusedLayerNorm(c.hidden_size, eps=c.layer_norm_eps,
                           name="ln2")(x)
        h = Dense(c.intermediate_size, name="ffn_in")(h)
        h = nn.gelu(h)
        return x + Dense(c.hidden_size, name="ffn_out")(h)


class _ScanBody(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, rope_cs):
        return GPTBlock(self.cfg, name="block")(x, rope_cs), None


class GPTModel(nn.Module):
    """Decoder-only transformer; ``__call__(input_ids, positions=None)``
    returns logits ``(B, L, vocab)``.

    ``positions`` are *global* token indices ``(B, L)``; when the sequence
    is sharded over ``seq_axis_name``, pass each rank its own slice (see
    :func:`lm_loss` and the sp dryrun slice) — defaults to ``0..L-1``.
    """

    cfg: GPTConfig

    @nn.compact
    def __call__(self, input_ids, positions=None):
        c = self.cfg
        B, L = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(L)[None, :], (B, L))
        x = nn.Embed(c.vocab_size, c.hidden_size, name="tok_emb")(input_ids)
        # rope tables depend only on positions: compute once, share across
        # q/k and every layer (kept out of the scanned/remat body)
        head_dim = c.hidden_size // c.num_heads
        rope_cs = rope_tables(positions, head_dim, c.rope_theta)
        from apex_tpu.ops import use_pallas
        if use_pallas() and c.seq_axis_name is None:
            # Local flash path: pre-build the KERNEL-format tables here
            # too (concat/sign-fold/cast), so under scan_layers/remat
            # the per-layer attention calls reuse them instead of
            # rebuilding (B, L, D) tables inside the compiled loop body.
            from apex_tpu.ops.rope import rope_kernel_tables
            table_dtype = (jnp.bfloat16 if x.dtype == jnp.bfloat16
                           else jnp.float32)
            rope_cs = rope_kernel_tables(
                rope_cs[0], rope_cs[1], B, input_ids.shape[1], head_dim,
                table_dtype)
        if c.scan_layers:
            body = _ScanBody
            if c.remat:
                body = nn.remat(body, prevent_cse=False)
            x, _ = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast,),
                length=c.num_layers,
                metadata_params={nn.meta.PARTITION_NAME: "layers"},
            )(c, name="layers")(x, rope_cs)
        else:
            block_cls = (nn.remat(GPTBlock, prevent_cse=False)
                         if c.remat else GPTBlock)
            for i in range(c.num_layers):
                x = block_cls(c, name=f"block_{i}")(x, rope_cs)
        x = FusedLayerNorm(c.hidden_size, eps=c.layer_norm_eps,
                           name="ln_f")(x)
        return Dense(c.vocab_size, use_bias=False, name="lm_head")(x)


def lm_loss(logits: jax.Array, targets: jax.Array,
            mask: Optional[jax.Array] = None,
            seq_axis_name: Optional[str] = None) -> jax.Array:
    """Mean next-token cross entropy in fp32.  ``targets`` are the
    *shifted* labels (callers shift; under sequence sharding each rank
    shifts within its shard and masks the seam or supplies the neighbor's
    first token).

    With ``seq_axis_name`` (sequence-sharded training) the normalizer is
    the *global* token count (``psum`` of the mask over the axis), so each
    shard returns ``local_sum / global_count``.  SPMD autodiff sums the
    replicated params' grads across shards, which then reconstructs
    exactly the gradient of the global mean — normalizing per shard
    instead would silently scale gradients by the shard count.  Report
    the global loss as ``lax.psum(loss, axis)`` (not pmean).
    """
    # -logp[target] = logsumexp(logits) - logits[target]: same math as
    # log_softmax + gather, but the (B, L, V) fp32 log-probability tensor
    # is never materialized in HBM — the cast fuses into the reduction
    # and only the (B, L) lse/picked rows are written (the gather reads
    # the bf16 logits directly).  At (8, 2047, 32000) that saves a ~2 GB
    # fp32 round-trip per step.
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logits, targets[..., None],
                                 axis=-1)[..., 0].astype(jnp.float32)
    if mask is None:
        m = jnp.ones(picked.shape, jnp.float32)
    else:
        m = mask.astype(jnp.float32)
    total = jnp.sum((lse - picked) * m)
    count = jnp.sum(m)
    if seq_axis_name is not None:
        count = jax.lax.psum(count, seq_axis_name)
    return total / jnp.maximum(count, 1.0)


def train_toy_lm(cfg=None, steps: int = 50, period: int = 16):
    """``(cfg, params, ids)``: a gpt_tiny BRIEFLY TRAINED on a
    periodic token stream, in the bf16 O2 serving layout, plus the
    ``(8, 64)`` int32 training ids its prompts should come from.

    The shared fixture behind every test/bench/tool that needs a
    model with REAL argmax margins (``tests/l0/test_serve_spec.py``,
    ``tests/l0/test_quant.py``'s tolerance checks,
    ``bench.bench_serve_spec``, ``tools/serve_scenarios.py``): a
    random-init model's near-uniform logits put ulp/quantization
    noise above the margins — measuring tie-breaking, not the thing
    under test — and make speculative acceptance structurally
    ~1/vocab.  ONE recipe (seed 8, FusedAdam lr 3e-3, ``steps``
    steps on ``(arange * 7) % period``) keeps every consumer
    measuring the same model; imports are lazy so the models module
    stays importable without the amp/optimizer stack."""
    from apex_tpu import amp
    from apex_tpu.optimizers import FusedAdam

    cfg = cfg or gpt_tiny()
    model = GPTModel(cfg)
    ids = (jnp.arange(8 * 64).reshape(8, 64) * 7) % period
    params = model.init(jax.random.PRNGKey(8),
                        ids[:1, :8].astype(jnp.int32))["params"]
    a = amp.initialize(optimizer=FusedAdam(lr=3e-3), opt_level="O2",
                       verbosity=0)
    state = a.init(params)

    def loss_fn(p, xb):
        logits = model.apply({"params": p}, xb)
        return lm_loss(logits[:, :-1], xb[:, 1:])

    step = jax.jit(amp.make_train_step(a, loss_fn))
    for _ in range(steps):
        state, _m = step(state, ids.astype(jnp.int32))
    import numpy as np
    return cfg, a.model_params(state), np.asarray(ids, np.int32)
