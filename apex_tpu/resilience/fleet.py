"""Elastic self-healing training fleet: shrink on preemption, regrow
on recovery — chaos-gated bitwise (``tools/train_fleet.py``).

The serve side already has a fleet layer (disaggregated prefill/decode,
SLO gates); this module builds the *training* one the paper's DDP +
amp-O2 story actually needs on preemptible capacity.  The moving parts
are all pre-existing and individually tested — ``run_resilient``'s
watchdog/rewind, ``DurableCheckpointManager``'s mesh-reshape restore,
``multiproc``'s bounded-retry init + SPMD preflight, the lint-gated AOT
cache, the flight recorder — and this module composes them into an
*elastic* loop that survives rank death:

- **heartbeat lease, never a collective** — each rank's liveness is a
  lease file in a shared :class:`FleetLedger` directory (atomic
  tmp+rename writes; on a real pod a shared filesystem mount, in the
  drill a tmpdir).  Liveness detection deliberately rides a side
  channel, like the PR-15 preflight's KV exchange: the detector of a
  wedged collective must never itself be a collective.  (The
  coordination-service KV store is *not* usable here: it dies with the
  coordinator process, which is exactly the rank whose death the fleet
  must survive; the preflight still uses it within a generation.)
- **bounded-window detection** — a membership gate runs before every
  dispatch: a member whose lease is older than ``lease_ttl_s`` means
  *shrink*; a fresh lease from a non-member means *regrow*.  The gate
  raises :class:`FleetMembershipChange` before the next collective is
  dispatched, so at most one in-flight step is exposed to the dead
  peer (and a gloo peer-close error from that step is caught and
  classified through the same lease check).
- **generations** — each cluster formation is a *generation* with an
  immutable plan (``gen/gen_NNNN.json``: members, coordinator port,
  restore step).  A membership change ends the generation: every
  surviving child exits with :data:`EXIT_MEMBERSHIP`, the per-rank
  supervisor re-elects a leader (min *surviving member* — a freshly
  returned rank waits as a joiner and never leads a replan, so a
  regrow cannot deadlock on the smallest rank's return; a joiner takes
  over only when every member's lease is stale), the leader writes the
  next plan (O_EXCL create — exactly one wins), and each supervisor
  spawns a fresh child that re-forms the cluster via
  :func:`multiproc.initialize` (bounded retry), re-runs the SPMD
  preflight on the new mesh, and *loads* its step from the AOT cache
  instead of compiling when a same-shape generation exported it.
- **checkpoint-or-rewind** — the generation leader (min member rank)
  owns the :class:`DurableCheckpointManager`; the plan's
  ``restore_step`` is the newest snapshot that *verifies*, so every
  member restores the same step (steps lost ≤ ``checkpoint_every`` by
  construction — the bound ``analysis/trainfleet.py`` re-derives).
  Training state is fully replicated (pure DDP), so snapshots written
  on an N-rank mesh restore onto any other world size through the
  reshape-capable template path.

Every kill/shrink/restore/regrow lands in the flight recorder and in a
schema-valid incident (``incidents/`` in the ledger), and the chaos
drill's committed ``TRAINFLEET_r01.json`` re-derives its verdicts from
the recorded event log + per-rank state digests
(:mod:`apex_tpu.analysis.trainfleet`).  See ``docs/source/fleet.rst``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "EXIT_MEMBERSHIP", "FleetError", "FleetMembershipChange",
    "FleetConfig", "FleetLedger", "HeartbeatLease", "FleetMetrics",
    "latest_verified_step", "load_snapshot_state", "snapshot_digest",
    "state_digest", "membership_gate", "run_generation", "supervise",
]

#: child exit code meaning "the generation ended because membership
#: changed (shrink/regrow/new plan) — replan and respawn me"
EXIT_MEMBERSHIP = 17


class FleetError(RuntimeError):
    """Fleet-level orchestration failure (formation/replan timeout,
    malformed plan, generation budget exhausted)."""


class FleetMembershipChange(FleetError):
    """The membership gate saw the fleet change shape: a member lease
    expired (``reason="shrink"``), a non-member published a fresh lease
    (``"regrow"``), or a newer generation plan appeared (``"plan"``).
    Raised *before* the next step is dispatched — ending the generation
    is the recovery, not an error."""

    def __init__(self, reason: str, ranks: Sequence[int], step: int):
        self.reason = reason
        self.ranks = list(ranks)
        self.step = int(step)
        super().__init__(
            f"fleet membership change at step {step}: {reason} "
            f"(ranks {self.ranks})")


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FleetConfig:
    """Drill/fleet parameters, serialized to ``config.json`` in the
    ledger so every supervisor and generation child reads one source of
    truth.  Times are seconds."""

    num_steps: int = 24
    checkpoint_every: int = 4
    world_size: int = 2
    seed: int = 0
    # liveness
    lease_ttl_s: float = 2.0
    heartbeat_s: float = 0.25
    poll_s: float = 0.1
    # cluster formation / replanning
    init_timeout_s: float = 60.0
    init_retries: int = 1
    form_window_s: float = 60.0
    replan_window_s: float = 60.0
    max_generations: int = 8
    # child supervision
    stall_budget_s: float = 90.0
    child_grace_s: float = 5.0
    watchdog_timeout_s: float = 60.0
    # workload (tiny DDP + amp-O2 MLP; per-rank batch)
    batch: int = 4
    d_in: int = 8
    hidden: int = 16
    min_loss_scale: float = 2.0 ** 14
    #: host-side sleep per step (drill pacing: a CPU toy step runs in
    #: ~ms, so an unthrottled generation finishes before a returning
    #: rank can possibly rejoin mid-run; pure wall time, zero effect on
    #: the math — the bitwise replays run with it at 0)
    step_delay_s: float = 0.0
    # fault specs (``resilience/faults.py`` vocabulary, e.g.
    # ``rank_kill@10:1``) — applied inside generation children
    faults: Tuple[str, ...] = ()

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["faults"] = list(self.faults)
        return d

    @classmethod
    def from_json(cls, d: dict) -> "FleetConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in known}
        kw["faults"] = tuple(kw.get("faults", ()))
        return cls(**kw)


# ---------------------------------------------------------------------------
# the ledger: atomic-write JSON files in a shared directory
# ---------------------------------------------------------------------------

def _atomic_write_json(path: str, obj: Any, exclusive: bool = False) -> bool:
    """Write ``obj`` as JSON via tmp+rename (readers never see a torn
    file).  With ``exclusive`` the final link is created with O_EXCL —
    exactly one concurrent writer wins; returns whether *this* call
    won."""
    tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.write("\n")
    if not exclusive:
        os.replace(tmp, path)
        return True
    try:
        os.link(tmp, path)
    except FileExistsError:
        return False
    finally:
        os.unlink(tmp)
    return True


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None    # absent or mid-replace: the caller re-polls


class FleetLedger:
    """File-based coordination state for one fleet run.

    Layout (all JSON, all atomic writes)::

        root/
          config.json             # FleetConfig
          hb/rank_R.json          # heartbeat lease (supervisor-owned)
          progress/rank_R.json    # child training progress (child-owned)
          member/rank_R.json      # announcements {rank, incarnation}
          gen/gen_NNNN.json       # immutable generation plans
          events/<ns>_<pid>_R_kind.json   # append-only event log
          finals/rank_R.json      # per-rank final digest on completion
          incidents/*.json        # schema-valid incident records
          ckpt/ aot/ logs/        # durable snapshots, AOT cache, child logs

    The lease file is written by the rank's *supervisor* process (it
    keeps beating while a generation child runs, and a SIGKILLed rank
    loses both processes, so the lease goes stale within one TTL);
    ``progress`` is written by the child and is the supervisor's stall
    detector.
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        for sub in ("hb", "progress", "member", "gen", "events",
                    "finals", "incidents", "ckpt", "aot", "logs"):
            os.makedirs(os.path.join(self.root, sub), exist_ok=True)

    # -- paths -----------------------------------------------------------
    def path(self, *parts: str) -> str:
        return os.path.join(self.root, *parts)

    @property
    def ckpt_dir(self) -> str:
        return self.path("ckpt")

    @property
    def aot_dir(self) -> str:
        return self.path("aot")

    # -- config ----------------------------------------------------------
    def write_config(self, cfg: FleetConfig) -> None:
        _atomic_write_json(self.path("config.json"), cfg.to_json())

    def read_config(self) -> FleetConfig:
        doc = _read_json(self.path("config.json"))
        if doc is None:
            raise FleetError(f"no config.json in ledger {self.root}")
        return FleetConfig.from_json(doc)

    # -- heartbeats ------------------------------------------------------
    def heartbeat(self, rank: int, **info: Any) -> None:
        _atomic_write_json(self.path("hb", f"rank_{rank}.json"),
                           {"rank": int(rank), "ts": time.time(),
                            "pid": os.getpid(), **info})

    def read_heartbeat(self, rank: int) -> Optional[dict]:
        return _read_json(self.path("hb", f"rank_{rank}.json"))

    def lease_age(self, rank: int) -> Optional[float]:
        hb = self.read_heartbeat(rank)
        return None if hb is None else max(0.0, time.time() - hb["ts"])

    def fresh(self, rank: int, ttl_s: float) -> bool:
        age = self.lease_age(rank)
        return age is not None and age <= ttl_s

    def live_ranks(self, ttl_s: float) -> List[int]:
        return sorted(r for r in self.announced() if self.fresh(r, ttl_s))

    # -- progress (child-owned) ------------------------------------------
    def progress(self, rank: int, **info: Any) -> None:
        _atomic_write_json(self.path("progress", f"rank_{rank}.json"),
                           {"rank": int(rank), "ts": time.time(),
                            "pid": os.getpid(), **info})

    def read_progress(self, rank: int) -> Optional[dict]:
        return _read_json(self.path("progress", f"rank_{rank}.json"))

    # -- membership announcements ----------------------------------------
    def announce(self, rank: int) -> int:
        """Register (or re-register) a rank; returns its incarnation
        number (0 on first join, +1 per relaunch) — plans record these
        so a relaunched supervisor never adopts a plan written for its
        previous life."""
        path = self.path("member", f"rank_{rank}.json")
        prev = _read_json(path)
        inc = 0 if prev is None else int(prev.get("incarnation", 0)) + 1
        _atomic_write_json(path, {"rank": int(rank), "incarnation": inc,
                                  "ts": time.time(), "pid": os.getpid()})
        return inc

    def announced(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        for name in os.listdir(self.path("member")):
            if name.startswith("rank_") and name.endswith(".json"):
                doc = _read_json(self.path("member", name))
                if doc is not None:
                    out[int(doc["rank"])] = doc
        return out

    def incarnation(self, rank: int) -> Optional[int]:
        doc = self.announced().get(rank)
        return None if doc is None else int(doc.get("incarnation", 0))

    # -- generation plans ------------------------------------------------
    def _plan_path(self, gen: int) -> str:
        return self.path("gen", f"gen_{int(gen):04d}.json")

    def write_plan(self, plan: dict) -> bool:
        """Atomically create the plan for its generation; returns False
        when a concurrent leader already committed one (the caller then
        reads and follows the winner)."""
        return _atomic_write_json(self._plan_path(plan["gen"]), plan,
                                  exclusive=True)

    def read_plan(self, gen: int) -> Optional[dict]:
        return _read_json(self._plan_path(gen))

    def latest_plan(self) -> Optional[dict]:
        gens = []
        for name in os.listdir(self.path("gen")):
            if name.startswith("gen_") and name.endswith(".json"):
                try:
                    gens.append(int(name[4:-5]))
                except ValueError:
                    pass
        return self.read_plan(max(gens)) if gens else None

    # -- event log -------------------------------------------------------
    def event(self, rank: int, kind: str, **data: Any) -> dict:
        from apex_tpu.resilience.incidents import utc_now
        rec = {"ts": time.time(), "utc": utc_now(), "rank": int(rank),
               "kind": kind, **data}
        name = f"{time.time_ns():020d}_{os.getpid()}_{rank}_{kind}.json"
        _atomic_write_json(self.path("events", name), rec)
        return rec

    def events(self) -> List[dict]:
        out = []
        for name in sorted(os.listdir(self.path("events"))):
            if name.endswith(".json"):
                doc = _read_json(self.path("events", name))
                if doc is not None:
                    out.append(doc)
        return sorted(out, key=lambda d: d.get("ts", 0.0))

    # -- finals ----------------------------------------------------------
    def final(self, rank: int, **data: Any) -> None:
        _atomic_write_json(self.path("finals", f"rank_{rank}.json"),
                           {"rank": int(rank), "ts": time.time(), **data})

    def finals(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        for name in os.listdir(self.path("finals")):
            if name.startswith("rank_") and name.endswith(".json"):
                doc = _read_json(self.path("finals", name))
                if doc is not None:
                    out[int(doc["rank"])] = doc
        return out


class HeartbeatLease:
    """Daemon thread renewing one rank's lease (or progress record)
    every ``interval_s``.  ``info_fn`` is sampled at each beat — the
    child publishes its current absolute step through it, which is both
    the supervisor's stall detector and the drill's timeline."""

    def __init__(self, ledger: FleetLedger, rank: int, interval_s: float,
                 info_fn: Optional[Callable[[], dict]] = None,
                 kind: str = "hb"):
        self._ledger = ledger
        self._rank = int(rank)
        self._interval = float(interval_s)
        self._info_fn = info_fn
        self._write = (ledger.heartbeat if kind == "hb" else ledger.progress)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        info = {}
        if self._info_fn is not None:
            try:
                info = dict(self._info_fn())
            except Exception:   # a flaky sampler must not kill the lease
                info = {}
        try:
            self._write(self._rank, **info)
        except OSError:
            pass    # one missed beat is absorbed by the TTL

    def start(self) -> "HeartbeatLease":
        self.beat()     # lease exists before start() returns
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"apex-tpu-lease-{self._rank}")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.beat()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def __enter__(self) -> "HeartbeatLease":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# read-only snapshot helpers (non-leader ranks NEVER construct a
# DurableCheckpointManager: construction sweeps .tmp-* staging dirs and
# would race the leader's in-flight commit)
# ---------------------------------------------------------------------------

def latest_verified_step(directory: str) -> Optional[int]:
    """Newest snapshot step in ``directory`` that passes full checksum
    verification (corrupt/truncated snapshots are skipped, exactly like
    ``DurableCheckpointManager.restore``'s fallback) — the step a new
    generation plan pins as ``restore_step``."""
    from apex_tpu.resilience import durable
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith(durable._STEP_PREFIX):
            try:
                steps.append(int(name[len(durable._STEP_PREFIX):]))
            except ValueError:
                pass
    for step in sorted(steps, reverse=True):
        ok, _problems = durable.verify_snapshot(
            os.path.join(directory, durable._step_dirname(step)))
        if ok:
            return step
    return None


def load_snapshot_state(directory: str, step: int, template: Any,
                        extras: Optional[dict] = None) -> Tuple[Any, dict]:
    """Read-only restore of one pinned snapshot step onto ``template``
    (checksum-verified; raises ``CheckpointCorruptError`` on damage).
    Every fleet member restores THE step its generation plan names —
    never "my newest", which async saves can skew across ranks."""
    from apex_tpu import checkpoint as ckpt
    from apex_tpu.resilience import durable

    path = os.path.join(directory, durable._step_dirname(step))
    values, _manifest = durable.read_snapshot(path)
    target = ckpt.payload_template(template, extras)
    import jax
    flat, treedef = jax.tree_util.tree_flatten_with_path(target)
    keys = [jax.tree_util.keystr(p) for p, _ in flat]
    ckpt.check_same_structure(set(values), set(keys),
                              context=f"fleet snapshot step {step}")
    payload = jax.tree_util.tree_unflatten(treedef, [values[k] for k in keys])
    state, ex = ckpt.load_state_dict(template, payload)
    return durable._place_like(state, template), ex


def _combine_leaf_hashes(pairs: Sequence[Tuple[str, str]]) -> str:
    import hashlib
    h = hashlib.sha256()
    for key, sha in sorted(pairs):
        h.update(f"{key}:{sha}\n".encode("utf-8"))
    return h.hexdigest()


def state_digest(state: Any, extras: Optional[dict] = None) -> str:
    """Order-independent digest over every leaf of a state's checkpoint
    payload — BY CONSTRUCTION equal to :func:`snapshot_digest` of a
    snapshot of the same state (same ``state_dict`` flattening, same
    ``np.save`` serialization, same per-leaf sha256), so an in-memory
    replay can be compared bit-for-bit against a drill's on-disk
    snapshot without writing one."""
    import hashlib
    import io

    import numpy as np

    from apex_tpu import checkpoint as ckpt
    from apex_tpu.resilience.durable import _flatten_payload

    pairs = []
    for key, arr in _flatten_payload(ckpt.state_dict(state, extras)):
        buf = io.BytesIO()
        np.save(buf, arr, allow_pickle=False)
        pairs.append((key, hashlib.sha256(buf.getvalue()).hexdigest()))
    return _combine_leaf_hashes(pairs)


def snapshot_digest(directory: str, step: int) -> str:
    """The :func:`state_digest`-comparable digest of one committed
    snapshot, computed from manifest checksums alone (no array IO)."""
    from apex_tpu.resilience import durable
    manifest = _read_json(os.path.join(
        directory, durable._step_dirname(step), durable.MANIFEST))
    if manifest is None:
        raise FileNotFoundError(
            f"no snapshot manifest for step {step} in {directory}")
    return _combine_leaf_hashes(
        [(k, meta["sha256"]) for k, meta in manifest["leaves"].items()])


# ---------------------------------------------------------------------------
# fleet metrics (satellite: emitted by run_resilient at the
# lag-resolved boundary — every value is a host scalar, zero syncs)
# ---------------------------------------------------------------------------

#: recovery wall-clock buckets (seconds): replan + re-init + restore on
#: the CPU drill lands in the low seconds; a real pod rejoin in minutes
RECOVERY_BUCKETS = (0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


class FleetMetrics:
    """The ``train_fleet_*`` instrument family on one registry.

    ``run_resilient(fleet_metrics=...)`` calls :meth:`on_resolve` at
    its existing lag-resolved boundary (re-asserting the active-ranks
    gauge from a host int) and :meth:`on_rewind` when a divergence
    rewind executes; the fleet layer itself drives the
    preemption/recovery counters.  Nothing here ever touches a device
    value, so the instrumented step's lowering stays syncs-clean."""

    def __init__(self, registry: Any, active_ranks: int = 1):
        self._active = int(active_ranks)
        self.active = registry.gauge(
            "train_fleet_active_ranks",
            "ranks in the current generation's plan")
        self.preemptions = registry.counter(
            "train_fleet_preemptions_total",
            "rank-death shrink events observed")
        self.recoveries = registry.counter(
            "train_fleet_recoveries_total",
            "generations resumed from a durable snapshot")
        self.rewinds = registry.counter(
            "train_fleet_rewinds_total",
            "divergence rewinds inside fleet generations")
        self.recovery_seconds = registry.histogram(
            "train_fleet_recovery_seconds",
            "plan creation to first post-restore dispatch",
            buckets=RECOVERY_BUCKETS)
        self.active.set(self._active)

    def set_active(self, n: int) -> None:
        self._active = int(n)
        self.active.set(self._active)

    def on_resolve(self) -> None:
        self.active.set(self._active)

    def on_rewind(self) -> None:
        self.rewinds.inc()

    def on_preemption(self, n: int = 1) -> None:
        self.preemptions.inc(n)

    def on_recovery(self, seconds: float) -> None:
        self.recoveries.inc()
        self.recovery_seconds.observe(float(seconds))


# ---------------------------------------------------------------------------
# the membership gate
# ---------------------------------------------------------------------------

def membership_gate(ledger: FleetLedger, cfg: FleetConfig, plan: dict,
                    rank: int,
                    on_change: Optional[Callable[..., None]] = None
                    ) -> Callable[[int], None]:
    """A ``gate(abs_step)`` callable run before every dispatch.

    Raises :class:`FleetMembershipChange` when a member lease expired
    (shrink), a fresh non-member lease appeared (regrow), or a newer
    plan exists.  Checks are throttled to one ledger scan per
    ``cfg.poll_s`` — detection latency is bounded by
    ``lease_ttl_s + poll_s``, cost is a couple of file reads."""
    members = [int(r) for r in plan["members"]]
    peers = [r for r in members if r != rank]
    gen = int(plan["gen"])
    last_check = [0.0]

    def gate(abs_step: int) -> None:
        now = time.monotonic()
        if now - last_check[0] < cfg.poll_s:
            return
        last_check[0] = now
        dead = [r for r in peers if not ledger.fresh(r, cfg.lease_ttl_s)]
        if dead:
            if on_change is not None:
                on_change("shrink", dead, abs_step)
            raise FleetMembershipChange("shrink", dead, abs_step)
        joiners = sorted(
            r for r in ledger.announced()
            if r not in members and ledger.fresh(r, cfg.lease_ttl_s))
        if joiners:
            if on_change is not None:
                on_change("regrow", joiners, abs_step)
            raise FleetMembershipChange("regrow", joiners, abs_step)
        latest = ledger.latest_plan()
        if latest is not None and int(latest["gen"]) > gen:
            if on_change is not None:
                on_change("plan", latest["members"], abs_step)
            raise FleetMembershipChange("plan", latest["members"], abs_step)

    return gate


# ---------------------------------------------------------------------------
# leader-only checkpoint manager behind a step offset
# ---------------------------------------------------------------------------

class _StepOffsetManager:
    """Adapter translating ``run_resilient``'s generation-local step
    indices to absolute fleet steps on the wrapped
    :class:`DurableCheckpointManager` (and back on restore), so the
    snapshot directory always speaks absolute steps across
    generations."""

    def __init__(self, inner: Any, start: int):
        self._inner = inner
        self._start = int(start)
        self.last_restore: Optional[dict] = None

    def save(self, step: int, state: Any, extras: Optional[dict] = None
             ) -> None:
        self._inner.save(self._start + int(step), state, extras)

    def all_steps(self) -> List[int]:
        return [s - self._start for s in self._inner.all_steps()
                if s >= self._start]

    def restore(self, template: Any, step: Optional[int] = None,
                extras: Optional[dict] = None) -> Tuple[Any, dict]:
        out = self._inner.restore(
            template, None if step is None else self._start + int(step),
            extras)
        lr = dict(self._inner.last_restore or {})
        lr["step"] = lr.get("step", self._start) - self._start
        self.last_restore = lr
        return out

    def wait(self) -> None:
        self._inner.wait()

    def close(self) -> None:
        self._inner.close()


# ---------------------------------------------------------------------------
# the per-generation workload (DDP + amp-O2 over a real process mesh)
# ---------------------------------------------------------------------------

class _Workload:
    """The drill's miniature DDP + amp-O2 train step, built for one
    generation's world size.  Same shape as the PR-15 preflight worker:
    ``shard_map`` over a Mesh of the generation's global devices, grads
    reduced by ``DistributedDataParallel.reduce``, loss ``pmean``-ed,
    all training state fully replicated (``P()``) so checkpoints
    round-trip through plain host arrays on any world size."""

    def __init__(self, cfg: FleetConfig, world: int, idx: int):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu import amp
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.parallel import DistributedDataParallel
        from apex_tpu.utils.jax_compat import shard_map

        self.cfg = cfg
        self.world = int(world)
        self.idx = int(idx)
        self.mesh = Mesh(np.array(jax.devices()), ("data",))
        self._P = P

        key = jax.random.PRNGKey(cfg.seed)
        k1, k2 = jax.random.split(key)
        params = {
            "w1": jax.random.normal(k1, (cfg.d_in, cfg.hidden),
                                    dtype=jnp.float32),
            "w2": jax.random.normal(k2, (cfg.hidden, cfg.d_in),
                                    dtype=jnp.float32),
        }

        def loss_fn(p, xb):
            h = jax.nn.relu(xb @ p["w1"])
            return jnp.mean(jnp.square(h @ p["w2"] - xb))

        ddp = DistributedDataParallel(axis_name="data")
        self.amp = amp.initialize(optimizer=FusedAdam(lr=1e-3),
                                  opt_level="O2",
                                  min_loss_scale=cfg.min_loss_scale,
                                  verbosity=0)
        self.local_template = self.amp.init(params)
        step = amp.make_train_step(self.amp, loss_fn, axis_name="data",
                                   reduce_fn=ddp.reduce)

        def inner(s, xb):
            s2, m = step(s, xb[0])
            return s2, {"loss": jax.lax.pmean(m["loss"], "data"),
                        "overflow": m["overflow"],
                        "pinned_at_floor": m["pinned_at_floor"]}

        self.jit_fn = jax.jit(shard_map(
            inner, mesh=self.mesh, in_specs=(P(), P("data")),
            out_specs=(P(), P())))

    # -- host-local <-> global -------------------------------------------
    def to_global(self, state_local: Any) -> Any:
        from jax.experimental import multihost_utils
        return multihost_utils.host_local_array_to_global_array(
            state_local, self.mesh, self._P())

    def to_local(self, state_global: Any) -> Any:
        from jax.experimental import multihost_utils
        return multihost_utils.global_array_to_host_local_array(
            state_global, self.mesh, self._P())

    def make_global_batch(self, abs_step: int) -> Any:
        """Deterministic per-step batch: the full ``(world, batch,
        d_in)`` pool is derived from ``(seed, abs_step, world)`` alone,
        each rank keeps its own row — so a replay of the same schedule
        on the same world size sees bit-identical data."""
        import numpy as np
        from jax.experimental import multihost_utils
        rng = np.random.default_rng(
            (self.cfg.seed * 1_000_003 + abs_step) * 17 + self.world)
        pool = rng.standard_normal(
            (self.world, self.cfg.batch, self.cfg.d_in)).astype(np.float32)
        shard = pool[self.idx:self.idx + 1]
        return multihost_utils.host_local_array_to_global_array(
            shard, self.mesh, self._P("data"))

    def lower(self) -> Any:
        state_g = self.to_global(self.local_template)
        return self.jit_fn.lower(state_g, self.make_global_batch(0))


def _parse_fleet_faults(specs: Sequence[str], start: int) -> list:
    """Fault specs → fault instances with steps shifted into the
    generation's local index space (``run_resilient`` drives the
    injector with local steps); faults already behind ``start`` are
    dropped — they belong to a previous generation's timeline."""
    from apex_tpu.resilience.faults import HangStep, RankKill, parse_fault
    out = []
    for spec in specs:
        f = parse_fault(spec)
        if not isinstance(f, (RankKill, HangStep)):
            raise ValueError(
                f"fault {spec!r} is not supported in the fleet lane "
                "(rank_kill/hang only: batch/IO faults are not "
                "SPMD-consistent across a process mesh)")
        if f.step >= start:
            out.append(dataclasses.replace(f, step=f.step - start))
    return out


# ---------------------------------------------------------------------------
# generation child
# ---------------------------------------------------------------------------

def run_generation(ledger: FleetLedger, cfg: FleetConfig, gen: int,
                   rank: int) -> int:
    """Run one generation on one rank: form the cluster, preflight,
    load-or-compile via the AOT cache, restore the plan's step, train
    until completion or membership change.  Returns the child exit
    code (0 done, :data:`EXIT_MEMBERSHIP` on shrink/regrow/new-plan)."""
    import numpy as np

    from apex_tpu.analysis import export as export_mod
    from apex_tpu.obs.flight import FlightRecorder
    from apex_tpu.obs.metrics import Registry
    from apex_tpu.parallel import multiproc
    from apex_tpu.resilience import incidents as incidents_lib
    from apex_tpu.resilience.durable import DurableCheckpointManager
    from apex_tpu.resilience.faults import FaultInjector, RankKill
    from apex_tpu.resilience.loop import ResilienceConfig, run_resilient

    plan = ledger.read_plan(gen)
    if plan is None:
        raise FleetError(f"no plan for generation {gen} in {ledger.root}")
    members = [int(r) for r in plan["members"]]
    if rank not in members:
        raise FleetError(f"rank {rank} is not in generation {gen}'s plan "
                         f"{members}")
    idx = members.index(rank)
    world = len(members)
    restore_step = plan.get("restore_step")
    start = 0 if restore_step is None else int(restore_step) + 1
    step_cell = {"step": start, "phase": "init"}

    progress = HeartbeatLease(
        ledger, rank, cfg.heartbeat_s, kind="progress",
        info_fn=lambda: dict(step_cell, gen=gen)).start()
    ledger.event(rank, "gen_start", gen=gen, members=members,
                 restore_step=restore_step, world=world)

    fr = FlightRecorder()
    reg = Registry()
    fm = FleetMetrics(reg, active_ranks=world)

    def _incident(status: str, summary: str, evidence: list,
                  **extra: Any) -> None:
        path = ledger.path("incidents",
                           f"gen{gen}_rank{rank}_{status}.json")
        extra.setdefault("metrics", reg.snapshot())
        extra.setdefault("flight", fr.dump())
        incidents_lib.write_incident(path, status, summary, evidence,
                                     gen=gen, rank=rank, **extra)

    def _on_change(reason: str, ranks: Sequence[int],
                   abs_step: int) -> None:
        if reason == "shrink":
            fr.note("kill", ranks=list(ranks), step=abs_step)
        fr.note(f"{reason}_detected", ranks=list(ranks), step=abs_step)

    def _classified_end(e: BaseException) -> Optional[int]:
        """Route a failure through the lease check: a stale peer lease
        means the failure IS a membership change (return
        EXIT_MEMBERSHIP via the common epilogue); ``None`` means a
        genuine program error the caller must re-raise."""
        change = _classify_failure(ledger, cfg, plan, rank, e,
                                   step_cell["step"])
        if change is None:
            ledger.event(rank, "child_error", gen=gen,
                         phase=step_cell["phase"],
                         error=f"{type(e).__name__}: {e}"[:500])
            return None
        _on_change(change.reason, change.ranks, change.step)
        return _end_generation(ledger, cfg, fm, fr, _incident, gen,
                               rank, world, members, change,
                               cause=repr(e)[:300])

    manager = None
    try:
        try:
            step_cell["phase"] = "cluster_init"
            multiproc.initialize(
                coordinator_address=f"localhost:{plan['port']}",
                num_processes=world, process_id=idx,
                timeout_s=cfg.init_timeout_s, retries=cfg.init_retries)
            wl = _Workload(cfg, world, idx)

            step_cell["phase"] = "preflight"
            pre = multiproc.spmd_preflight(wl.lower(),
                                           label=f"fleet_gen{gen}")
            ledger.event(rank, "preflight", gen=gen, ok=bool(pre["ok"]),
                         n_collectives=pre["n_collectives"],
                         schedule_hash=pre["schedule_hash"])
            fr.note("preflight", gen=gen,
                    n_collectives=pre["n_collectives"])

            step_cell["phase"] = "aot"
            state_g0 = wl.to_global(wl.local_template)
            try:
                compiled, ainfo = export_mod.probe(
                    wl.jit_fn, state_g0, wl.make_global_batch(start),
                    cache_dir=ledger.aot_dir, lane=f"world{world}",
                    export_on_miss=True)
                step_fn = lambda s, xb: compiled(s, xb)   # noqa: E731
                aot_source = ainfo["source"]
            except Exception as e:  # noqa: BLE001 - cache is optional
                step_fn = wl.jit_fn
                aot_source = f"disabled: {type(e).__name__}"
            ledger.event(rank, "aot", gen=gen, source=aot_source,
                         world=world)
            fr.note("aot", gen=gen, source=aot_source)

            step_cell["phase"] = "restore"
            if restore_step is not None:
                state_local, _extras = load_snapshot_state(
                    ledger.ckpt_dir, int(restore_step), wl.local_template)
                digest = snapshot_digest(ledger.ckpt_dir,
                                         int(restore_step))
                state_g = wl.to_global(state_local)
                ledger.event(rank, "restore", gen=gen,
                             step=int(restore_step), digest=digest)
                fr.note("restore", gen=gen, step=int(restore_step))
                if gen > 0:
                    fm.on_recovery(max(
                        0.0, time.time()
                        - float(plan.get("created_ts", 0.0))))
                    _incident(
                        "fleet-restored",
                        f"generation {gen} (world {world}) resumed from "
                        f"durable step {restore_step}",
                        [f"restored step {restore_step} digest "
                         f"{digest[:16]}…",
                         f"members {members}",
                         f"aot source {aot_source}"],
                        restore_step=int(restore_step))
            else:
                state_g = state_g0
        except Exception as e:  # noqa: BLE001 - classify via the lease
            # a peer dying during FORMATION (init timeout, preflight
            # barrier, restore) must end in a replan like a mid-step
            # death — letting it propagate exits every survivor fatal,
            # stops their leases, and cascades to total fleet death
            code = _classified_end(e)
            if code is None:
                raise
            return code

        remaining = cfg.num_steps - start
        if remaining <= 0:
            final_digest = state_digest(wl.to_local(state_g))
            ledger.final(rank, gen=gen, step=cfg.num_steps - 1,
                         digest=final_digest)
            return 0

        if idx == 0:    # leader-only: construction sweeps .tmp-* dirs
            manager = _StepOffsetManager(
                DurableCheckpointManager(ledger.ckpt_dir,
                                         max_to_keep=10_000), start)

        gate = membership_gate(ledger, cfg, plan, rank,
                               on_change=_on_change)

        def batch_fn(i: int) -> tuple:
            abs_step = start + i
            step_cell["step"] = abs_step
            step_cell["phase"] = "train"
            if cfg.step_delay_s > 0:
                time.sleep(cfg.step_delay_s)
            gate(abs_step)
            return (wl.make_global_batch(abs_step),)

        inj = FaultInjector(_parse_fleet_faults(cfg.faults, start),
                            seed=cfg.seed, rank=rank)

        def _on_rank_kill(fault: RankKill, local_step: int) -> None:
            # the forensic record must hit disk BEFORE the SIGKILL —
            # a preempted rank gets no other chance to say why it died
            ledger.event(rank, "kill", gen=gen, step=start + local_step,
                         signal=int(fault.signal),
                         kill_parent=bool(fault.kill_parent))
            inj.execute_rank_kill(fault)

        inj.on_rank_kill = _on_rank_kill

        rcfg = ResilienceConfig(
            watchdog_timeout_s=cfg.watchdog_timeout_s,
            checkpoint_every=cfg.checkpoint_every,
            incident_path=ledger.path(
                "incidents", f"gen{gen}_rank{rank}_loop.json"))

        try:
            result = run_resilient(
                step_fn, state_g, batch_fn, remaining, amp_obj=wl.amp,
                manager=manager, config=rcfg, injector=inj, registry=reg,
                flight=fr, fleet_metrics=fm)
        except FleetMembershipChange as e:
            return _end_generation(ledger, cfg, fm, fr, _incident, gen,
                                   rank, world, members, e)
        except Exception as e:  # noqa: BLE001 - classify via the lease
            code = _classified_end(e)
            if code is None:
                raise
            return code

        state_local = wl.to_local(result.state)
        final_digest = state_digest(state_local)
        loss = result.losses[-1][1] if result.losses else float("nan")
        ledger.event(rank, "gen_complete", gen=gen,
                     step=cfg.num_steps - 1, digest=final_digest,
                     rewinds=result.rewinds, loss=loss)
        ledger.final(rank, gen=gen, step=cfg.num_steps - 1,
                     digest=final_digest, loss=loss,
                     scale=float(np.asarray(
                         state_local.scaler_states[0].loss_scale)))
        print(f"FLEET RANK {rank} GEN {gen} FINAL "
              f"step={cfg.num_steps - 1} digest={final_digest}",
              flush=True)
        return 0
    finally:
        if manager is not None:
            try:
                manager.close()
            except Exception:   # noqa: BLE001 - exit code already decided
                pass
        progress.stop()


def _classify_failure(ledger: FleetLedger, cfg: FleetConfig, plan: dict,
                      rank: int, exc: Optional[BaseException],
                      abs_step: int) -> Optional[FleetMembershipChange]:
    """A failure mid-generation is a *shrink* iff a peer's lease is
    (or within one TTL becomes) stale — the gloo peer-close error
    races the lease file, so wait out one TTL before deciding it was a
    genuine program error.  The evidence is the lease state, never the
    exception text (``exc`` may be ``None``: the supervisor applies
    the same test to a child that died too hard to raise at all)."""
    peers = [int(r) for r in plan["members"] if int(r) != rank]
    deadline = time.monotonic() + cfg.lease_ttl_s + 3 * cfg.heartbeat_s
    while time.monotonic() < deadline:
        dead = [r for r in peers if not ledger.fresh(r, cfg.lease_ttl_s)]
        if dead:
            return FleetMembershipChange("shrink", dead, abs_step)
        time.sleep(cfg.poll_s)
    return None


def _end_generation(ledger: FleetLedger, cfg: FleetConfig,
                    fm: FleetMetrics, fr: Any, incident: Callable,
                    gen: int, rank: int, world: int,
                    members: Sequence[int], change: FleetMembershipChange,
                    cause: Optional[str] = None) -> int:
    """Common membership-change epilogue: counters, ledger event,
    schema-valid incident with the flight tail, exit code."""
    if change.reason == "shrink":
        fm.on_preemption(len(change.ranks))
    candidate = latest_verified_step(ledger.ckpt_dir)
    ledger.event(rank, f"{change.reason}_detected", gen=gen,
                 step=change.step, ranks=change.ranks,
                 restore_candidate=candidate)
    status = {"shrink": "fleet-shrink", "regrow": "fleet-regrow"}.get(
        change.reason, "fleet-replan")
    evidence = [
        f"membership change at step {change.step}: {change.reason} "
        f"(ranks {change.ranks})",
        f"generation {gen} members {list(members)} (world {world})",
        f"latest verified durable step: {candidate}",
    ]
    if cause is not None:
        evidence.append(f"surfaced by: {cause}")
    incident(status,
             f"generation {gen} ended at step {change.step}: "
             f"{change.reason} of ranks {change.ranks}",
             evidence, step=change.step, ranks=change.ranks,
             restore_candidate=candidate)
    return EXIT_MEMBERSHIP


def _record_reclassified_death(ledger: FleetLedger, gen: int, rank: int,
                               code: int,
                               change: FleetMembershipChange) -> None:
    """The child died too hard to record its own membership-change
    trace (jax's distributed client ``LOG(FATAL)``\\ s the process when
    a peer vanishes during formation or takes the coordination service
    with it), so the supervisor emits the same canonical events and
    incident the child's :func:`_end_generation` would have — auditors
    (the ``TRAINFLEET`` schema, the drill gate) must see one
    vocabulary regardless of which side detected the change."""
    from apex_tpu.obs.flight import FlightRecorder
    from apex_tpu.resilience import incidents as incidents_lib
    ledger.event(rank, "child_death_reclassified", gen=gen, code=code,
                 reason=change.reason, ranks=change.ranks,
                 step=change.step)
    candidate = latest_verified_step(ledger.ckpt_dir)
    ledger.event(rank, f"{change.reason}_detected", gen=gen,
                 step=change.step, ranks=change.ranks,
                 restore_candidate=candidate, via="supervisor")
    fr = FlightRecorder()
    if change.reason == "shrink":
        fr.note("kill", ranks=list(change.ranks), step=change.step)
    fr.note(f"{change.reason}_detected", ranks=list(change.ranks),
            step=change.step)
    status = {"shrink": "fleet-shrink", "regrow": "fleet-regrow"}.get(
        change.reason, "fleet-replan")
    incidents_lib.write_incident(
        ledger.path("incidents",
                    f"gen{gen}_rank{rank}_{status}_supervisor.json"),
        status,
        f"generation {gen} ended at step {change.step}: {change.reason} "
        f"of ranks {change.ranks} (child died hard, exit {code})",
        [f"child exit code {code}: classified via peer leases — the "
         f"child never raised, its own recorder died with it",
         f"membership change at step {change.step}: {change.reason} "
         f"(ranks {change.ranks})",
         f"latest verified durable step: {candidate}"],
        gen=gen, rank=rank, step=change.step, ranks=change.ranks,
        restore_candidate=candidate, flight=fr.dump())


# ---------------------------------------------------------------------------
# per-rank supervisor
# ---------------------------------------------------------------------------

def _child_env() -> dict:
    env = dict(os.environ)
    # children form their own cluster with the plan's explicit shape;
    # inherited launcher/test config must not leak in
    for var in ("XLA_FLAGS", "COORDINATOR_ADDRESS", "WORLD_SIZE", "RANK"):
        env.pop(var, None)
    return env


def _spawn_child(ledger: FleetLedger, gen: int, rank: int
                 ) -> Tuple[subprocess.Popen, list]:
    out = open(ledger.path("logs", f"child_g{gen}_r{rank}.out"), "w")
    err = open(ledger.path("logs", f"child_g{gen}_r{rank}.err"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "apex_tpu.resilience.fleet",
         "--role", "child", "--ledger", ledger.root,
         "--gen", str(gen), "--rank", str(rank)],
        stdout=out, stderr=err, env=_child_env())
    return proc, [out, err]


def _monitor_child(ledger: FleetLedger, cfg: FleetConfig, gen: int,
                   rank: int, proc: subprocess.Popen) -> int:
    """Wait for the generation child, with a progress watchdog: a child
    whose progress record stops advancing for ``stall_budget_s`` (e.g.
    wedged in a collective whose peer died without the lease noticing)
    is terminated → killed, and treated as a membership change so the
    fleet replans around the stall instead of hanging forever."""
    last_seen = time.monotonic()
    last_payload: Optional[tuple] = None
    while True:
        code = proc.poll()
        if code is not None:
            return code
        pr = ledger.read_progress(rank)
        payload = None if pr is None else (pr.get("gen"), pr.get("step"),
                                           pr.get("phase"), pr.get("ts"))
        if payload != last_payload:
            last_payload = payload
            last_seen = time.monotonic()
        if time.monotonic() - last_seen > cfg.stall_budget_s:
            ledger.event(rank, "child_stalled", gen=gen,
                         budget_s=cfg.stall_budget_s, progress=pr)
            proc.terminate()
            try:
                proc.wait(timeout=cfg.child_grace_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            return EXIT_MEMBERSHIP
        time.sleep(min(cfg.poll_s, 0.1))


def supervise(root: str, rank: int,
              cfg: Optional[FleetConfig] = None) -> int:
    """The per-rank supervisor: announce membership, keep the rank's
    heartbeat lease alive, run one generation child per plan that
    includes this rank (spawned fresh each generation — ``jax.
    distributed`` cannot re-form a cluster in-process after a peer
    died), elect the leader (min live rank) to write replacement plans,
    and interpret child exit codes (0 done / EXIT_MEMBERSHIP replan /
    anything else fatal, which stops the lease so peers shrink around
    this rank)."""
    ledger = FleetLedger(root)
    if cfg is None:
        cfg = ledger.read_config()
    inc = ledger.announce(rank)
    ledger.event(rank, "announce", incarnation=inc)
    lease = HeartbeatLease(ledger, rank, cfg.heartbeat_s,
                           info_fn=lambda: {"incarnation": inc}).start()
    try:
        form_deadline = time.monotonic() + cfg.form_window_s
        join_gen: Optional[int] = None     # generation we wait on as a
        join_t0 = 0.0                      # non-member, and since when
        while True:
            plan = ledger.latest_plan()
            if plan is None:
                if not _try_lead_initial_plan(ledger, cfg, rank,
                                              form_deadline):
                    if time.monotonic() > form_deadline + cfg.form_window_s:
                        raise FleetError(
                            f"rank {rank}: no generation 0 plan within "
                            f"{cfg.form_window_s}s")
                    time.sleep(cfg.poll_s)
                continue
            gen = int(plan["gen"])
            if gen >= cfg.max_generations:
                raise FleetError(
                    f"generation budget exhausted ({gen} >= "
                    f"{cfg.max_generations})")
            mine = (rank in [int(r) for r in plan["members"]]
                    and int(plan.get("incarnations", {}).get(
                        str(rank), inc)) == inc)
            if not mine:
                # joiner: our fresh lease IS the regrow signal — the
                # running generation's gate sees it and replans us in
                finals = ledger.finals()
                if all(int(r) in finals for r in plan["members"]):
                    ledger.event(rank, "join_after_done", gen=gen)
                    return 0
                if join_gen != gen:
                    join_gen, join_t0 = gen, time.monotonic()
                if _take_over_dead_generation(ledger, cfg, rank, plan):
                    continue
                # bounded: live members replan around a fresh joiner
                # within lease_ttl + poll + replan_window — a joiner
                # still planless past that is stuck, not patient
                join_budget = cfg.form_window_s + cfg.replan_window_s
                if time.monotonic() - join_t0 > join_budget:
                    raise FleetError(
                        f"rank {rank}: generation {gen} never replanned "
                        f"around this joiner within {join_budget:g}s")
                time.sleep(cfg.poll_s)
                continue
            ledger.event(rank, "spawn_child", gen=gen)
            proc, logs = _spawn_child(ledger, gen, rank)
            try:
                code = _monitor_child(ledger, cfg, gen, rank, proc)
            finally:
                for f in logs:
                    f.close()
            ledger.event(rank, "child_exit", gen=gen, code=code)
            if code == 0:
                ledger.event(rank, "rank_done", gen=gen)
                return 0
            if code != EXIT_MEMBERSHIP:
                # the child died HARD: jax's distributed client
                # LOG(FATAL)s the process (SIGABRT) when a peer dies
                # during cluster formation, so the child's own
                # classifier never ran.  Apply the same lease test
                # here: a stale peer means this death is a membership
                # casualty and the rank REPLANS; only a peer-less
                # death is fatal (stopping our lease via finally, so
                # the fleet shrinks around this rank instead of
                # cascading every survivor to rank_fatal)
                pr = ledger.read_progress(rank) or {}
                step = pr.get("step")
                change = _classify_failure(
                    ledger, cfg, plan, rank, None,
                    step if isinstance(step, int) else -1)
                if change is None:
                    ledger.event(rank, "rank_fatal", gen=gen, code=code)
                    return code if code > 0 else 1
                _record_reclassified_death(ledger, gen, rank, code,
                                           change)
            _await_next_plan(ledger, cfg, rank, gen)
    finally:
        lease.stop()


def _try_lead_initial_plan(ledger: FleetLedger, cfg: FleetConfig,
                           rank: int, form_deadline: float) -> bool:
    """Write the generation-0 plan if this rank should lead it: leader
    is the min announced live rank, and it waits for the full expected
    world until the formation window closes (then sails with whoever
    arrived — a fleet that can start degraded is the whole point)."""
    live = ledger.live_ranks(cfg.lease_ttl_s)
    if not live or min(live) != rank:
        return False
    if len(live) < cfg.world_size and time.monotonic() < form_deadline:
        return False
    restore = latest_verified_step(ledger.ckpt_dir)
    return _commit_plan(ledger, cfg, rank, gen=0, members=live,
                        restore_step=restore, reason="initial")


def _commit_plan(ledger: FleetLedger, cfg: FleetConfig, rank: int,
                 gen: int, members: List[int], restore_step: Optional[int],
                 reason: str) -> bool:
    from apex_tpu.parallel.multiproc import _free_port
    from apex_tpu.resilience.incidents import utc_now
    announced = ledger.announced()
    plan = {
        "gen": int(gen), "members": [int(r) for r in members],
        "port": _free_port(), "restore_step": restore_step,
        "reason": reason, "created_by": int(rank),
        "created_ts": time.time(), "utc": utc_now(),
        "incarnations": {str(r): int(announced.get(r, {})
                                     .get("incarnation", 0))
                         for r in members},
    }
    won = ledger.write_plan(plan)
    if won:
        ledger.event(rank, "plan", gen=gen, members=plan["members"],
                     restore_step=restore_step, reason=reason,
                     port=plan["port"])
    return won


def _replan_reason(old: set, new: set) -> str:
    return ("regrow" if new > old else
            "shrink" if new < old else "reform")


def _await_next_plan(ledger: FleetLedger, cfg: FleetConfig, rank: int,
                     gen: int) -> dict:
    """After EXIT_MEMBERSHIP: elect the next plan.  The leader is the
    minimum live rank AMONG THE ENDED GENERATION'S MEMBERS — only they
    reach this replan loop; a rank that just returned sits in
    ``supervise``'s joiner branch and never writes plans, so electing
    the bare minimum live rank would deadlock the regrow exactly when
    the returning rank has the smallest id (kill rank 0, not rank 1).
    Membership is live leases ∪ nobody else, the restore step the
    newest verifying snapshot.  If the elected member stalls, after
    half the window every waiting member attempts the commit itself
    (the O_EXCL create arbitrates: exactly one wins, losers adopt).
    Bounded by ``replan_window_s``."""
    nxt = gen + 1
    prev = ledger.read_plan(gen) or {"members": []}
    prev_members = set(int(r) for r in prev["members"])
    start = time.monotonic()
    deadline = start + cfg.replan_window_s
    grace = start + cfg.replan_window_s / 2.0
    while time.monotonic() < deadline:
        plan = ledger.read_plan(nxt)
        if plan is not None:
            return plan
        live = ledger.live_ranks(cfg.lease_ttl_s)
        leaders = [r for r in live if r in prev_members]
        if live and ((leaders and min(leaders) == rank)
                     or time.monotonic() >= grace):
            restore = latest_verified_step(ledger.ckpt_dir)
            _commit_plan(ledger, cfg, rank, gen=nxt, members=live,
                         restore_step=restore,
                         reason=_replan_reason(prev_members, set(live)))
            continue
        time.sleep(cfg.poll_s)
    raise FleetError(
        f"rank {rank}: no generation {nxt} plan within "
        f"{cfg.replan_window_s}s of the membership change")


def _take_over_dead_generation(ledger: FleetLedger, cfg: FleetConfig,
                               rank: int, plan: dict) -> bool:
    """A joiner waiting on a generation NONE of whose members is alive
    (every lease stale — the whole previous fleet crashed fatally)
    must not poll forever for a replan nobody is left to write: the
    minimum live rank commits the next plan itself.  Racing a reviving
    member is safe — the O_EXCL plan create arbitrates, and a loser
    adopts the committed winner on its next poll."""
    members = [int(r) for r in plan["members"]]
    if any(ledger.fresh(r, cfg.lease_ttl_s) for r in members):
        return False
    live = ledger.live_ranks(cfg.lease_ttl_s)
    if not live or min(live) != rank:
        return False
    nxt = int(plan["gen"]) + 1
    ledger.event(rank, "takeover", gen=nxt, dead_members=members,
                 members=live)
    _commit_plan(ledger, cfg, rank, gen=nxt, members=live,
                 restore_step=latest_verified_step(ledger.ckpt_dir),
                 reason=_replan_reason(set(members), set(live)))
    return True


# ---------------------------------------------------------------------------
# process entry (``python -m apex_tpu.resilience.fleet``)
# ---------------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="elastic training fleet process entry")
    p.add_argument("--role", choices=("supervisor", "child"),
                   required=True)
    p.add_argument("--ledger", required=True)
    p.add_argument("--rank", type=int, required=True)
    p.add_argument("--gen", type=int, default=None,
                   help="generation to run (child role)")
    args = p.parse_args(argv)

    import jax
    jax.config.update("jax_platforms", "cpu")
    # the CPU backend only runs cross-process collectives through gloo
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    ledger = FleetLedger(args.ledger)
    if args.role == "supervisor":
        return supervise(args.ledger, args.rank)
    if args.gen is None:
        print("--gen is required for --role child", file=sys.stderr)
        return 2
    cfg = ledger.read_config()
    code = run_generation(ledger, cfg, args.gen, args.rank)
    if code == EXIT_MEMBERSHIP:
        # skip interpreter teardown: jax's distributed shutdown barrier
        # waits on the very peer whose death ended this generation
        # (observed: ~90s wedge until the coordination-service heartbeat
        # gave up).  Everything durable — events, incident, progress —
        # is already fsync'd/renamed; nothing of value runs at exit.
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(EXIT_MEMBERSHIP)
    return code


if __name__ == "__main__":
    sys.exit(main())
