"""Durable, crash-atomic, shard-portable checkpointing.

The reference's whole resume contract was "save fp32 masters + scaler
state and restart exactly" (``apex/fp16_utils/fp16_optimizer.py:298-359``)
— written with one ``torch.save`` that a preemption mid-write turns into
an unreadable pickle, silently.  This manager makes the failure modes
first-class:

- **crash-atomic commit**: a snapshot is staged in a ``.tmp-*`` sibling
  directory, every file is fsync'd, the manifest is written last, the
  directory fsync'd, then atomically renamed into place and the parent
  directory fsync'd.  A crash at ANY point leaves either the previous
  snapshots untouched or an ignorable tmp dir — never a half-checkpoint
  that parses.
- **per-leaf checksums**: the manifest records a sha256 per leaf file;
  :meth:`restore` verifies every one and *skips* a corrupted/truncated
  snapshot in favor of the newest older snapshot that verifies (the
  report of what was skipped and why is kept on ``last_restore``).
- **async save off the step path**: :meth:`save` gathers leaves to host
  on the calling thread (a donated-buffer train step may invalidate the
  device arrays the moment the next step is dispatched, so the gather
  cannot be deferred) and enqueues the host payload to a writer thread —
  serialization, fsync and retention run off the training thread.
  ``wait()`` re-raises any background failure.
- **shard-portable**: leaves are gathered to full host arrays on save
  (any fully-addressable sharding), and on restore each leaf is placed
  onto the *template* leaf's sharding — so a state saved FSDP-sharded on
  an 8-device mesh restores bit-identically onto a 4-device mesh, a
  single device, or any other layout the template carries (VERDICT
  item 3).  Multi-host (non-addressable) arrays are out of scope here;
  gather-per-host frameworks should shard the *directory*, not the file.

Layout::

    dir/
      step_00000012/
        manifest.json      # {"format":1,"step":12,"leaves":{keystr: {...}}}
        leaf_00000.npy ...
      step_00000009/ ...
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import queue
import shutil
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

MANIFEST = "manifest.json"
_STEP_PREFIX = "step_"
FORMAT = 1


def _step_dirname(step: int) -> str:
    return f"{_STEP_PREFIX}{int(step):08d}"


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _flatten_payload(payload: Any) -> List[Tuple[str, np.ndarray]]:
    """``state_dict``-style nested dict → ``[(keystr, host array)]`` in
    canonical (tree-flatten) order."""
    flat = jax.tree_util.tree_leaves_with_path(payload)
    return [(jax.tree_util.keystr(path), np.asarray(leaf))
            for path, leaf in flat]


def write_snapshot(directory: str, step: int, payload: Any,
                   fsync: bool = True) -> str:
    """Stage + atomically commit one snapshot; returns the final path.

    A re-save of an existing step never deletes the old snapshot before
    the new one is committed: the old directory is renamed to an
    ``.old-*`` sibling, the new one renamed into place, and only then is
    the aside copy dropped.  A crash in ANY window leaves at least one
    good copy of the step — under its final name, or under the aside
    name that :func:`recover_asides` (run by every manager construction)
    renames back."""
    final = os.path.join(directory, _step_dirname(step))
    tmp = os.path.join(directory,
                       f".tmp-{_step_dirname(step)}-{os.getpid()}-"
                       f"{threading.get_ident()}")
    os.makedirs(tmp)
    aside = None
    try:
        leaves: Dict[str, Dict[str, Any]] = {}
        for i, (key, arr) in enumerate(_flatten_payload(payload)):
            fname = f"leaf_{i:05d}.npy"
            buf = io.BytesIO()
            np.save(buf, arr, allow_pickle=False)
            raw = buf.getvalue()
            with open(os.path.join(tmp, fname), "wb") as f:
                f.write(raw)
                if fsync:
                    f.flush()
                    os.fsync(f.fileno())
            leaves[key] = {
                "file": fname,
                "sha256": hashlib.sha256(raw).hexdigest(),
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "bytes": len(raw),
            }
        manifest = {"format": FORMAT, "step": int(step), "leaves": leaves}
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump(manifest, f, indent=1)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        if fsync:
            _fsync_dir(tmp)
        if os.path.exists(final):
            # re-save of a step: the old snapshot must survive until
            # the new one is committed.  Rename it aside (atomic),
            # commit the new directory, then drop the aside copy.
            aside = os.path.join(
                directory,
                f".old-{_step_dirname(step)}-{os.getpid()}-"
                f"{threading.get_ident()}")
            if os.path.exists(aside):
                shutil.rmtree(aside)
            os.replace(final, aside)
        os.replace(tmp, final)
        if fsync:
            _fsync_dir(directory)
        if aside is not None:
            shutil.rmtree(aside, ignore_errors=True)
        return final
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        if aside is not None and not os.path.exists(final) \
                and os.path.isdir(aside):
            os.replace(aside, final)   # put the old snapshot back
        raise


def recover_asides(directory: str) -> List[str]:
    """Finish re-saves interrupted between the rename-aside and the
    commit: an ``.old-step_*`` sibling whose ``step_*`` directory is
    missing IS the last good snapshot of that step — rename it back into
    place; one whose step directory exists is post-commit garbage and is
    dropped.  Returns the restored final paths.  Run by every
    :class:`DurableCheckpointManager` construction, before the
    ``.tmp-*`` sweep."""
    restored: List[str] = []
    for name in sorted(os.listdir(directory)):
        if not name.startswith(".old-" + _STEP_PREFIX):
            continue
        # ".old-step_00000012-<pid>-<tid>" -> "step_00000012"
        stepdir = name[len(".old-"):].split("-")[0]
        final = os.path.join(directory, stepdir)
        aside = os.path.join(directory, name)
        if os.path.isdir(final):
            shutil.rmtree(aside, ignore_errors=True)
        else:
            os.replace(aside, final)
            restored.append(final)
    return restored


def verify_snapshot(path: str) -> Tuple[bool, List[str]]:
    """Checksum-verify one snapshot directory (manifest + every leaf)."""
    problems: List[str] = []
    mpath = os.path.join(path, MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return False, [f"manifest unreadable: {e}"]
    if manifest.get("format") != FORMAT:
        return False, [f"unknown snapshot format {manifest.get('format')!r}"]
    for key, meta in manifest.get("leaves", {}).items():
        fpath = os.path.join(path, meta["file"])
        try:
            with open(fpath, "rb") as f:
                raw = f.read()
        except OSError as e:
            problems.append(f"{key}: leaf file unreadable: {e}")
            continue
        if hashlib.sha256(raw).hexdigest() != meta["sha256"]:
            problems.append(
                f"{key}: checksum mismatch in {meta['file']} "
                f"({len(raw)} bytes on disk, {meta['bytes']} expected)")
    return not problems, problems


def read_snapshot(path: str) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Load a snapshot, verifying every checksum as it reads — one pass
    of IO and hashing.  Malformation of the snapshot ITSELF
    (unreadable/alien manifest, missing leaf file, checksum mismatch,
    unparsable npy) raises :class:`CheckpointCorruptError` so callers
    have a single this-snapshot-is-bad signal to fall back on.  A
    transient IO failure — any :class:`OSError` other than the file
    being absent — propagates AS-IS: it says nothing about the snapshot
    on disk, and wrapping it as corruption would make
    ``loop.retry_io``-driven restores silently fall back to an older
    step instead of retrying the flake."""
    try:
        with open(os.path.join(path, MANIFEST)) as f:
            manifest = json.load(f)
    except FileNotFoundError as e:
        raise CheckpointCorruptError(f"{path}: manifest missing: {e}")
    except ValueError as e:
        raise CheckpointCorruptError(f"{path}: manifest unreadable: {e}")
    if manifest.get("format") != FORMAT:
        raise CheckpointCorruptError(
            f"{path}: unknown snapshot format {manifest.get('format')!r}")
    values: Dict[str, np.ndarray] = {}
    for key, meta in manifest.get("leaves", {}).items():
        try:
            with open(os.path.join(path, meta["file"]), "rb") as f:
                raw = f.read()
        except FileNotFoundError as e:
            # a leaf named by the manifest but absent on disk IS the
            # snapshot's structure being broken (truncated commit)
            raise CheckpointCorruptError(
                f"{path}: {key}: leaf file missing: {e}")
        if hashlib.sha256(raw).hexdigest() != meta["sha256"]:
            raise CheckpointCorruptError(
                f"{path}: {key}: checksum mismatch in {meta['file']} "
                f"({len(raw)} bytes on disk, {meta['bytes']} expected)")
        try:
            values[key] = np.load(io.BytesIO(raw), allow_pickle=False)
        except ValueError as e:
            raise CheckpointCorruptError(
                f"{path}: {key}: unparsable npy payload: {e}")
    return values, manifest


class CheckpointCorruptError(RuntimeError):
    """No snapshot in the directory survived checksum verification."""


class DurableCheckpointManager:
    """Crash-atomic checkpointing of :class:`~apex_tpu.amp.AmpState` with
    retention, async save, checksum-verified restore with fallback, and
    mesh-reshape restore (see module docstring).

    Drop-in for the historical (orbax-backed) manager's API::

        mgr = DurableCheckpointManager(dir, max_to_keep=3)
        mgr.save(step, state, extras={"epoch": e})   # async, off-step-path
        state, extras = mgr.restore(template, extras=...)
        mgr.wait(); mgr.close()

    ``io_hook(op)`` (op in ``{"save", "restore"}``) runs before each IO
    operation — the fault injector's seam for slow/flaky IO.
    ``on_commit(step, path)`` runs after a snapshot commits — the
    injector's seam for post-commit corruption, and a place to publish
    "checkpoint landed" metrics.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True, fsync: bool = True,
                 io_hook: Optional[Callable[[str], None]] = None,
                 on_commit: Optional[Callable[[int, str], None]] = None,
                 io_retries: int = 3, io_backoff_s: float = 0.05):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._max_to_keep = int(max_to_keep)
        self._io_retries = int(io_retries)
        self._io_backoff_s = float(io_backoff_s)
        self._fsync = fsync
        self._io_hook = io_hook
        self._on_commit = on_commit
        self._async = async_save
        self._queue: "queue.Queue" = queue.Queue()
        self._errors: List[BaseException] = []
        self._worker: Optional[threading.Thread] = None
        self._closed = False
        self.last_restore: Optional[Dict[str, Any]] = None
        # a crash between a re-save's rename-aside and its commit left
        # the step's last good snapshot under an .old-* name — restore
        # it first, THEN sweep the dead-weight .tmp-* staging dirs
        recover_asides(self._dir)
        for name in os.listdir(self._dir):
            if name.startswith(".tmp-"):
                shutil.rmtree(os.path.join(self._dir, name),
                              ignore_errors=True)

    # -- background writer ------------------------------------------------
    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._drain, name="apex-tpu-ckpt-writer", daemon=True)
            self._worker.start()

    def _drain(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                self._queue.task_done()
                return
            step, payload = job
            try:
                self._commit_with_retry(step, payload)
            except BaseException as e:  # surfaced on wait()/next save()
                self._errors.append(e)
            finally:
                self._queue.task_done()

    def _raise_pending(self) -> None:
        if self._errors:
            err = self._errors.pop(0)
            raise RuntimeError(
                f"background checkpoint save failed: {err!r}") from err

    # -- API ---------------------------------------------------------------
    def save(self, step: int, state: Any,
             extras: Optional[Dict[str, Any]] = None) -> None:
        """Snapshot; the training loop is not blocked on disk.  The
        device→host gather happens HERE, synchronously — under a
        ``donate_argnums`` train step the device buffers may be
        invalidated the moment the next step is dispatched, so it cannot
        be deferred to the worker.  Serialization/fsync/retention run on
        the writer thread (call :meth:`wait` / :meth:`close` before
        exiting; ``restore``/``latest_step`` wait automatically)."""
        if self._closed:
            raise RuntimeError("CheckpointManager is closed")
        self._raise_pending()
        from apex_tpu import checkpoint as ckpt
        payload = ckpt.state_dict(state, extras)   # host copy, race-free
        if not self._async:
            self._commit_with_retry(int(step), payload)
            return
        self._ensure_worker()
        self._queue.put((int(step), payload))

    def _commit_with_retry(self, step: int, payload: Any) -> str:
        # transient IO (OSError) retries here, wherever the commit runs
        # (writer thread in async mode, the caller in sync mode)
        from apex_tpu.resilience.loop import retry_io
        return retry_io(lambda: self._commit(step, payload),
                        retries=self._io_retries,
                        backoff_s=self._io_backoff_s)

    def _commit(self, step: int, payload: Any) -> str:
        if self._io_hook is not None:
            self._io_hook("save")
        path = write_snapshot(self._dir, step, payload, fsync=self._fsync)
        self._retain()
        if self._on_commit is not None:
            self._on_commit(step, path)
        return path

    def _retain(self) -> None:
        steps = self.all_steps()
        for s in steps[:-self._max_to_keep] if self._max_to_keep > 0 else []:
            shutil.rmtree(os.path.join(self._dir, _step_dirname(s)),
                          ignore_errors=True)

    def wait(self) -> None:
        """Block until every queued save has committed; re-raise the
        first background failure."""
        self._queue.join()
        self._raise_pending()

    def close(self) -> None:
        self.wait()
        self._closed = True
        if self._worker is not None and self._worker.is_alive():
            self._queue.put(None)           # shut the writer down; a
            self._worker.join(timeout=5.0)  # closed manager must not
        self._worker = None                 # leak a parked thread

    def all_steps(self) -> List[int]:
        """Committed snapshot steps, oldest → newest (no verification)."""
        steps = []
        for name in os.listdir(self._dir):
            if name.startswith(_STEP_PREFIX):
                try:
                    steps.append(int(name[len(_STEP_PREFIX):]))
                except ValueError:
                    pass
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        self.wait()
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore(self, template: Any, step: Optional[int] = None,
                extras: Optional[Dict[str, Any]] = None) -> Tuple[Any, Dict]:
        """Restore the given (or newest *verifying*) step.

        Every leaf checksum is verified; a snapshot that fails — truncated
        by a preemption, corrupted on disk — is skipped and the next older
        one tried (unless ``step`` pins one explicitly, which fails hard).
        ``template`` supplies structure, dtypes AND placement: each leaf
        is ``device_put`` onto the template leaf's sharding, which is what
        makes 8-device-saved → 4-device-restored work.  ``last_restore``
        records the chosen step and any skipped snapshots.
        """
        from apex_tpu import checkpoint as ckpt
        self.wait()
        if self._io_hook is not None:
            self._io_hook("restore")
        candidates = [int(step)] if step is not None \
            else list(reversed(self.all_steps()))
        if not candidates:
            raise FileNotFoundError(f"no checkpoint found in {self._dir}")
        skipped: List[Dict[str, Any]] = []
        for s in candidates:
            path = os.path.join(self._dir, _step_dirname(s))
            if not os.path.isdir(path):
                if step is not None:
                    raise FileNotFoundError(f"no snapshot for step {s} in "
                                            f"{self._dir}")
                continue
            try:    # read verifies every checksum in the same IO pass
                values, _manifest = read_snapshot(path)
            except CheckpointCorruptError as e:
                if step is not None:
                    raise
                skipped.append({"step": s, "problems": [str(e)]})
                continue
            target = ckpt.payload_template(template, extras)
            flat_target = jax.tree_util.tree_flatten_with_path(target)
            target_keys = [jax.tree_util.keystr(p)
                           for p, _ in flat_target[0]]
            ckpt.check_same_structure(set(values), set(target_keys),
                                      context=f"snapshot step {s}")
            payload = jax.tree_util.tree_unflatten(
                flat_target[1], [values[k] for k in target_keys])
            state, ex = ckpt.load_state_dict(template, payload)
            state = _place_like(state, template)
            ex = _place_like(ex, extras) if extras else ex
            self.last_restore = {"step": s, "skipped": skipped}
            return state, ex
        raise CheckpointCorruptError(
            f"every snapshot in {self._dir} failed verification: {skipped}")


def _place_like(values: Any, template: Any) -> Any:
    """Place each restored leaf onto its template leaf's sharding — full
    arrays + template placement is the whole mesh-reshape story.  Only
    leaves the template explicitly commits to a mesh (``NamedSharding``)
    are placed; everything else stays an uncommitted device array, so a
    restored state mixes with jit default placement exactly like a
    freshly ``Amp.init``-ed one (committing scalars to one device while
    matrices live on a mesh makes jit refuse the mix)."""
    from jax.sharding import NamedSharding

    def place(v, t):
        if isinstance(t, jax.Array) and isinstance(t.sharding, NamedSharding):
            return jax.device_put(v, t.sharding)
        return v
    return jax.tree.map(place, values, template)
