"""Incident-record schema: the machine-readable artifact a failure leaves.

The r02 chip-lease wedge (``INCIDENT_r02_wedge.json``) set the precedent:
when a run dies — or survives something that should have killed it — the
evidence goes into a JSON artifact with a fixed minimal shape, so the
next round (and ``tools/gate_hygiene.py``) can machine-check it instead
of re-reading prose.  This module is the single source of truth for that
shape; the resilience loop, the watchdog, and ``tools/chaos_run.py`` all
write through :func:`write_incident`, and gate hygiene validates every
committed ``INCIDENT_r*.json`` through :func:`validate_incident`.

Deliberately **stdlib-only** (no jax/numpy): ``tools/gate_hygiene.py``
loads this file directly via importlib so the hygiene CLI never pays the
jax import.

Schema (the r02 artifact is the reference instance):

- ``status``    (required, non-empty str) — e.g. ``"recovered"``,
  ``"preempted"``, ``"watchdog-timeout"``, ``"partial - ..."``;
- ``utc`` or ``date`` (required, non-empty str) — when it happened;
- evidence      (required) — a non-empty list of str/dict entries, either
  top-level ``"evidence"``, nested under ``"incident"``, or any key
  containing ``"evidence"`` (the r02 artifact uses both of the last two);
- ``metrics``   (optional) — a runtime-telemetry snapshot in the
  :meth:`apex_tpu.obs.metrics.Registry.snapshot` shape
  (``{"metrics": [{"name", "type", ...}, ...]}``): what the counters
  and gauges said when the incident fired.  The resilience loop embeds
  one automatically; records without it (the r02 wedge predates the
  obs layer) stay valid;
- ``flight``    (optional) — the flight-recorder tail in the
  :meth:`apex_tpu.obs.flight.FlightRecorder.dump` shape
  (``{"capacity": int, "dropped": int, "events": [{"ts": number,
  "kind": str, ...}, ...]}``): the last-N-events black box of what led
  to the incident, not just the end-state gauges.  The resilience loop
  and the disaggregated router's replica-death path embed one; records
  without it (the r02 wedge predates the recorder) stay valid;
- anything else is free-form context (``artifact``, ``summary``,
  ``harness``, ``mitigations_added``, ...).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence

SCHEMA_DOC = "status:str, utc|date:str, *evidence*: non-empty list"


def utc_now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _evidence_lists(d: Dict[str, Any]) -> List[Any]:
    """Every value reachable under a key containing ``evidence`` —
    top-level or one dict level down (covers the r02 layout where the
    list lives at ``incident.evidence``)."""
    found = []
    for key, val in d.items():
        if "evidence" in str(key).lower():
            found.append(val)
        elif isinstance(val, dict):
            for k2, v2 in val.items():
                if "evidence" in str(k2).lower():
                    found.append(v2)
    return found


def validate_incident(obj: Any) -> List[str]:
    """Problems with ``obj`` as an incident record; ``[]`` when valid."""
    problems: List[str] = []
    if not isinstance(obj, dict):
        return [f"incident record must be a JSON object, got {type(obj).__name__}"]
    status = obj.get("status")
    if not (isinstance(status, str) and status.strip()):
        problems.append("missing/empty required field 'status' (str)")
    when = obj.get("utc") or obj.get("date")
    if not (isinstance(when, str) and when.strip()):
        problems.append("missing/empty required field 'utc' (or 'date')")
    ev_lists = _evidence_lists(obj)
    good = [e for e in ev_lists if isinstance(e, (list, tuple)) and len(e)]
    if not good:
        problems.append("no non-empty *evidence* list found (top-level or "
                        "nested one level, e.g. incident.evidence)")
    else:
        for lst in good:
            for i, entry in enumerate(lst):
                if not isinstance(entry, (str, dict)):
                    problems.append(
                        f"evidence[{i}] must be str or object, got "
                        f"{type(entry).__name__}")
    problems.extend(_validate_flight(obj.get("flight")))
    snap = obj.get("metrics")
    if snap is not None:
        rows = snap.get("metrics") if isinstance(snap, dict) else None
        if not isinstance(rows, list) or not all(
                isinstance(r, dict) and isinstance(r.get("name"), str)
                and isinstance(r.get("type"), str) for r in rows):
            problems.append(
                "'metrics' present but not a registry snapshot "
                "({'metrics': [{'name': ..., 'type': ...}, ...]})")
    return problems


def _validate_flight(flight: Any) -> List[str]:
    """Problems with an optional ``flight`` field (``[]`` when absent
    or valid): the :meth:`~apex_tpu.obs.flight.FlightRecorder.dump`
    shape — bounded ring metadata plus ordered event records each
    carrying a numeric ``ts`` and a non-empty ``kind``."""
    if flight is None:
        return []
    if not isinstance(flight, dict):
        return [f"'flight' must be an object, got "
                f"{type(flight).__name__}"]
    problems: List[str] = []
    cap = flight.get("capacity")
    if not (isinstance(cap, int) and not isinstance(cap, bool)
            and cap >= 1):
        problems.append("flight.capacity must be an int >= 1")
    dropped = flight.get("dropped")
    if not (isinstance(dropped, int) and not isinstance(dropped, bool)
            and dropped >= 0):
        problems.append("flight.dropped must be an int >= 0")
    events = flight.get("events")
    if not isinstance(events, list):
        problems.append("flight.events must be a list")
        return problems
    if isinstance(cap, int) and not isinstance(cap, bool) \
            and len(events) > cap:
        problems.append(
            f"flight holds {len(events)} events over its stated "
            f"capacity {cap} — a ring that overflows its own bound is "
            f"a contradiction")
    last_ts = None
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"flight.events[{i}] must be an object")
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or isinstance(ts, bool):
            problems.append(f"flight.events[{i}] missing numeric 'ts'")
        elif last_ts is not None and ts < last_ts:
            problems.append(
                f"flight.events[{i}] ts {ts} precedes its predecessor "
                f"{last_ts} — ring events must be ordered")
        else:
            last_ts = ts
        kind = ev.get("kind")
        if not (isinstance(kind, str) and kind.strip()):
            problems.append(
                f"flight.events[{i}] missing non-empty str 'kind'")
    return problems


def make_incident(status: str, summary: str,
                  evidence: Sequence[Any], **extra: Any) -> Dict[str, Any]:
    """Assemble a schema-valid incident dict (raises on an invalid one —
    a writer that emits records its own validator rejects is a bug)."""
    rec: Dict[str, Any] = {
        "artifact": extra.pop("artifact", "apex_tpu.resilience incident record"),
        "status": status,
        "utc": utc_now(),
        "summary": summary,
        "evidence": list(evidence),
    }
    rec.update(extra)
    problems = validate_incident(rec)
    if problems:
        raise ValueError(f"refusing to write invalid incident: {problems}")
    return rec


def write_incident(path: str, status: str, summary: str,
                   evidence: Sequence[Any], **extra: Any) -> Dict[str, Any]:
    """Write an incident artifact atomically (tmp + rename: a watchdog
    firing mid-crash must not leave a half-written record) and return it."""
    rec = make_incident(status, summary, evidence, **extra)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return rec


def validate_incident_file(path: str) -> List[str]:
    """Validate one on-disk artifact; parse failures are schema failures
    (a truncated incident file is exactly the rot this exists to catch)."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable incident JSON: {e}"]
    return validate_incident(obj)
