"""Seeded, composable fault injection for training-loop chaos tests.

Every failure mode the resilience layer claims to survive gets an
injectable analog, so the claims are regression-tested instead of
asserted: non-finite gradients (the r02-era overflow storms), checkpoint
corruption/truncation (preemption mid-write), simulated SIGTERM
mid-step, a hung step (the r02 chip-lease wedge,
``INCIDENT_r02_wedge.json``), and slow/flaky checkpoint IO.

Faults are plain frozen dataclasses; an injector composes any number of
them and is driven by the resilience loop's hooks (or by hand in a
test)::

    inj = FaultInjector([NaNStorm(step=4, duration=6),
                         CorruptCheckpoint(step=9, kind="truncate")])
    with inj:
        result = run_resilient(step, state, batches, ..., injector=inj)
    inj.events   # what fired, when — becomes incident evidence

Gradient poisoning is applied to the *batch* (first float leaf gets a
non-finite element), which drives non-finite values through the real
backward pass — the same route real bad data takes, and exactly what the
amp overflow machinery must absorb.  ``NaNStorm.duration`` counts
*firings*, not steps: after a rewind the replayed steps see clean data
(a transient storm, not a deterministic poison), which is what lets the
loop converge after recovery.
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal as signal_mod
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple


class SimulatedPreemption(RuntimeError):
    """Raised by the injector where SIGTERM would land mid-step."""

    def __init__(self, step: int):
        super().__init__(f"simulated preemption (SIGTERM) at step {step}")
        self.step = step


@dataclasses.dataclass(frozen=True)
class NaNStorm:
    """Non-finite gradients: from ``step``, the batch is poisoned for the
    next ``duration`` firings (``value``: inf by default — saturates in
    bf16 too, where a quiet NaN would)."""
    step: int
    duration: int = 1
    value: float = float("inf")


@dataclasses.dataclass(frozen=True)
class CorruptCheckpoint:
    """Damage the first checkpoint committed at/after ``step``:
    ``kind="truncate"`` (preemption mid-write) or ``"corrupt"`` (bit rot);
    target leaf file picked by the injector's seeded RNG."""
    step: int
    kind: str = "truncate"


@dataclasses.dataclass(frozen=True)
class Preempt:
    """Raise :class:`SimulatedPreemption` at the start of ``step``."""
    step: int


@dataclasses.dataclass(frozen=True)
class HangStep:
    """Host-level hang of ``seconds`` at the start of ``step`` — the
    watchdog's prey.  (A truly wedged device call can't be interrupted
    from Python; a host sleep exercises the same detection path.)"""
    step: int
    seconds: float = 5.0


@dataclasses.dataclass(frozen=True)
class RankKill:
    """Hard-kill a real fleet rank at the start of ``step`` — the
    elastic-fleet drill's preemption (``tools/train_fleet.py``).  Unlike
    :class:`Preempt` (an in-process exception the same loop catches),
    this is SIGKILL: no handlers, no flushes, the process is simply
    gone — which is what an actual TPU preemption looks like to the
    surviving ranks.  ``rank`` scopes the fault (None = whichever rank's
    injector sees the step); ``kill_parent`` also kills the rank's
    supervisor process so the heartbeat lease actually goes stale (a
    child-only kill leaves the lease beating and models a *stall*, not
    a preemption)."""
    step: int
    rank: Optional[int] = None
    signal: int = signal_mod.SIGKILL
    kill_parent: bool = True


@dataclasses.dataclass(frozen=True)
class FlakyIO:
    """First ``fails`` IO calls of ``op`` raise ``OSError`` — exercises
    the loop's retry-with-backoff."""
    op: str = "save"
    fails: int = 2


@dataclasses.dataclass(frozen=True)
class SlowIO:
    """Every IO call of ``op`` sleeps ``seconds`` first."""
    op: str = "save"
    seconds: float = 0.05


class FaultInjector:
    """Composes faults behind the hooks the resilience stack calls.

    Hooks (all no-ops when the fault list doesn't match):

    - :meth:`on_step_start` — may sleep (:class:`HangStep`), raise
      (:class:`Preempt`) or SIGKILL the process (:class:`RankKill`);
      call first thing in the step.
    - :meth:`poison_batch`  — returns the (possibly poisoned) batch.
    - :meth:`io_hook`       — pass as ``DurableCheckpointManager(io_hook=...)``.
    - :meth:`on_commit`     — pass as ``DurableCheckpointManager(on_commit=...)``.

    ``rank`` scopes rank-targeted faults (:class:`RankKill` with an
    explicit ``rank`` only fires on the matching injector);
    ``on_rank_kill`` is a seam for the fleet layer: when set, it is
    called as ``on_rank_kill(fault, step)`` INSTEAD of the default
    :meth:`execute_rank_kill`, so the caller can flush a forensic
    record to disk before pulling the trigger.

    Usable directly as a context manager (enter/exit just guard against
    reuse and close the event log)."""

    def __init__(self, faults: Sequence[Any] = (), seed: int = 0,
                 rank: Optional[int] = None):
        self.faults = list(faults)
        self.rng = random.Random(seed)
        self.rank = rank
        self.events: List[dict] = []
        self.on_rank_kill: Optional[Callable[[RankKill, int], None]] = None
        self._storm_left = {id(f): f.duration for f in self.faults
                            if isinstance(f, NaNStorm)}
        self._flaky_left = {id(f): f.fails for f in self.faults
                            if isinstance(f, FlakyIO)}
        self._fired_once: set = set()   # HangStep/Preempt/CorruptCheckpoint
        self._active = False

    def __enter__(self) -> "FaultInjector":
        self._active = True
        return self

    def __exit__(self, *exc) -> None:
        self._active = False

    def _record(self, fault: str, **info: Any) -> None:
        from apex_tpu.resilience.incidents import utc_now
        self.events.append({"fault": fault, "utc": utc_now(), **info})

    # -- hooks -----------------------------------------------------------
    def on_step_start(self, step: int) -> None:
        """Fire-once per fault instance: a rewound/restarted run replays
        step indices, and a hang or preemption is an *event*, not a
        property of the step number."""
        for f in self.faults:
            if id(f) in self._fired_once:
                continue
            if isinstance(f, HangStep) and f.step == step:
                self._fired_once.add(id(f))
                self._record("hang_step", step=step, seconds=f.seconds)
                time.sleep(f.seconds)
            elif isinstance(f, Preempt) and f.step == step:
                self._fired_once.add(id(f))
                self._record("preempt", step=step)
                raise SimulatedPreemption(step)
            elif isinstance(f, RankKill) and f.step == step \
                    and (f.rank is None or f.rank == self.rank):
                self._fired_once.add(id(f))
                self._record("rank_kill", step=step, rank=self.rank,
                             signal=int(f.signal),
                             kill_parent=bool(f.kill_parent))
                if self.on_rank_kill is not None:
                    self.on_rank_kill(f, step)
                else:
                    self.execute_rank_kill(f)

    def execute_rank_kill(self, fault: RankKill) -> None:
        """The default :class:`RankKill` trigger: SIGKILL the parent
        (the rank's supervisor — its death is what lets the heartbeat
        lease expire) and then this process.  ``os.kill(self, SIGKILL)``
        does not return; nothing after it runs."""
        if fault.kill_parent:
            try:
                os.kill(os.getppid(), fault.signal)
            except (OSError, ProcessLookupError):
                pass
        os.kill(os.getpid(), fault.signal)

    def poison_batch(self, step: int, batch: Tuple[Any, ...]
                     ) -> Tuple[Any, ...]:
        import jax
        import jax.numpy as jnp
        for f in self.faults:
            if not isinstance(f, NaNStorm) or step < f.step:
                continue
            if self._storm_left.get(id(f), 0) <= 0:
                continue
            self._storm_left[id(f)] -= 1
            self._record("nan_storm", step=step, value=repr(f.value))
            leaves, treedef = jax.tree.flatten(batch)
            for i, leaf in enumerate(leaves):
                arr = jnp.asarray(leaf)
                if jnp.issubdtype(arr.dtype, jnp.inexact):
                    flat = arr.reshape(-1)
                    flat = flat.at[0].set(jnp.asarray(f.value, arr.dtype))
                    leaves[i] = flat.reshape(arr.shape)
                    break
            return jax.tree.unflatten(treedef, leaves)
        return batch

    def io_hook(self, op: str) -> None:
        for f in self.faults:
            if isinstance(f, SlowIO) and f.op == op:
                self._record("slow_io", op=op, seconds=f.seconds)
                time.sleep(f.seconds)
            elif isinstance(f, FlakyIO) and f.op == op \
                    and self._flaky_left.get(id(f), 0) > 0:
                self._flaky_left[id(f)] -= 1
                self._record("flaky_io", op=op,
                             remaining=self._flaky_left[id(f)])
                raise OSError(f"injected flaky {op} IO")

    def on_commit(self, step: int, path: str) -> None:
        for f in self.faults:
            if not isinstance(f, CorruptCheckpoint) or id(f) in \
                    self._fired_once or step < f.step:
                continue
            self._fired_once.add(id(f))
            leaf_files = sorted(n for n in os.listdir(path)
                                if n.endswith(".npy"))
            if not leaf_files:
                continue
            victim = os.path.join(path, self.rng.choice(leaf_files))
            size = os.path.getsize(victim)
            if f.kind == "truncate":
                with open(victim, "r+b") as fh:
                    fh.truncate(max(0, size // 2))
            else:
                with open(victim, "r+b") as fh:
                    fh.seek(max(0, size // 2))
                    chunk = fh.read(8)
                    fh.seek(max(0, size // 2))
                    fh.write(bytes(b ^ 0xFF for b in chunk))
            self._record("corrupt_checkpoint", step=step, kind=f.kind,
                         file=os.path.basename(victim))


def parse_fault(spec: str) -> Any:
    """``name@step[:arg]`` / ``name[:arg]`` → fault dataclass — the ONE
    injector vocabulary shared by the single-process chaos harness
    (``tools/chaos_run.py``) and the fleet drill
    (``tools/train_fleet.py`` / the ``--fleet`` lane):

    - ``nan_storm@S[:D]``       — poison the batch for D firings from S
    - ``ckpt_truncate@S`` / ``ckpt_corrupt@S`` — damage the first
      checkpoint committed at/after S
    - ``preempt@S``             — in-process SIGTERM analog at S
    - ``rank_kill@S[:RANK]``    — SIGKILL a real fleet rank at S
      (all ranks when RANK omitted)
    - ``hang@S[:SEC]``          — host hang at S (watchdog prey)
    - ``flaky_io[:N]``          — first N saves raise OSError
    - ``slow_io[:SEC]``         — every save sleeps SEC first

    Raises ``ValueError`` on an unknown name or a missing required
    step (CLI front-ends wrap this into their usage error).
    """
    name, _, rest = spec.partition("@")
    step_s, _, arg = rest.partition(":")
    if not rest:          # no @: arg may ride on the name (flaky_io:3)
        name, _, arg = spec.partition(":")
        step_s = ""
    step = int(step_s) if step_s else None
    if step is None and name in ("nan_storm", "ckpt_truncate",
                                 "ckpt_corrupt", "preempt", "rank_kill",
                                 "hang"):
        raise ValueError(f"fault {name!r} needs a step: {name}@STEP[:arg]")
    if name == "nan_storm":
        return NaNStorm(step=step, duration=int(arg) if arg else 6)
    if name == "ckpt_truncate":
        return CorruptCheckpoint(step=step, kind="truncate")
    if name == "ckpt_corrupt":
        return CorruptCheckpoint(step=step, kind="corrupt")
    if name == "preempt":
        return Preempt(step=step)
    if name == "rank_kill":
        return RankKill(step=step, rank=int(arg) if arg else None)
    if name == "hang":
        return HangStep(step=step, seconds=float(arg) if arg else 2.0)
    if name == "flaky_io":
        return FlakyIO(op="save", fails=int(arg) if arg else 2)
    if name == "slow_io":
        return SlowIO(op="save", seconds=float(arg) if arg else 0.05)
    raise ValueError(f"unknown fault spec {spec!r}")
