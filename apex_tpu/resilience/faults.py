"""Seeded, composable fault injection for training-loop chaos tests.

Every failure mode the resilience layer claims to survive gets an
injectable analog, so the claims are regression-tested instead of
asserted: non-finite gradients (the r02-era overflow storms), checkpoint
corruption/truncation (preemption mid-write), simulated SIGTERM
mid-step, a hung step (the r02 chip-lease wedge,
``INCIDENT_r02_wedge.json``), and slow/flaky checkpoint IO.

Faults are plain frozen dataclasses; an injector composes any number of
them and is driven by the resilience loop's hooks (or by hand in a
test)::

    inj = FaultInjector([NaNStorm(step=4, duration=6),
                         CorruptCheckpoint(step=9, kind="truncate")])
    with inj:
        result = run_resilient(step, state, batches, ..., injector=inj)
    inj.events   # what fired, when — becomes incident evidence

Gradient poisoning is applied to the *batch* (first float leaf gets a
non-finite element), which drives non-finite values through the real
backward pass — the same route real bad data takes, and exactly what the
amp overflow machinery must absorb.  ``NaNStorm.duration`` counts
*firings*, not steps: after a rewind the replayed steps see clean data
(a transient storm, not a deterministic poison), which is what lets the
loop converge after recovery.
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Any, List, Optional, Sequence, Tuple


class SimulatedPreemption(RuntimeError):
    """Raised by the injector where SIGTERM would land mid-step."""

    def __init__(self, step: int):
        super().__init__(f"simulated preemption (SIGTERM) at step {step}")
        self.step = step


@dataclasses.dataclass(frozen=True)
class NaNStorm:
    """Non-finite gradients: from ``step``, the batch is poisoned for the
    next ``duration`` firings (``value``: inf by default — saturates in
    bf16 too, where a quiet NaN would)."""
    step: int
    duration: int = 1
    value: float = float("inf")


@dataclasses.dataclass(frozen=True)
class CorruptCheckpoint:
    """Damage the first checkpoint committed at/after ``step``:
    ``kind="truncate"`` (preemption mid-write) or ``"corrupt"`` (bit rot);
    target leaf file picked by the injector's seeded RNG."""
    step: int
    kind: str = "truncate"


@dataclasses.dataclass(frozen=True)
class Preempt:
    """Raise :class:`SimulatedPreemption` at the start of ``step``."""
    step: int


@dataclasses.dataclass(frozen=True)
class HangStep:
    """Host-level hang of ``seconds`` at the start of ``step`` — the
    watchdog's prey.  (A truly wedged device call can't be interrupted
    from Python; a host sleep exercises the same detection path.)"""
    step: int
    seconds: float = 5.0


@dataclasses.dataclass(frozen=True)
class FlakyIO:
    """First ``fails`` IO calls of ``op`` raise ``OSError`` — exercises
    the loop's retry-with-backoff."""
    op: str = "save"
    fails: int = 2


@dataclasses.dataclass(frozen=True)
class SlowIO:
    """Every IO call of ``op`` sleeps ``seconds`` first."""
    op: str = "save"
    seconds: float = 0.05


class FaultInjector:
    """Composes faults behind the hooks the resilience stack calls.

    Hooks (all no-ops when the fault list doesn't match):

    - :meth:`on_step_start` — may sleep (:class:`HangStep`) or raise
      (:class:`Preempt`); call first thing in the step.
    - :meth:`poison_batch`  — returns the (possibly poisoned) batch.
    - :meth:`io_hook`       — pass as ``DurableCheckpointManager(io_hook=...)``.
    - :meth:`on_commit`     — pass as ``DurableCheckpointManager(on_commit=...)``.

    Usable directly as a context manager (enter/exit just guard against
    reuse and close the event log)."""

    def __init__(self, faults: Sequence[Any] = (), seed: int = 0):
        self.faults = list(faults)
        self.rng = random.Random(seed)
        self.events: List[dict] = []
        self._storm_left = {id(f): f.duration for f in self.faults
                            if isinstance(f, NaNStorm)}
        self._flaky_left = {id(f): f.fails for f in self.faults
                            if isinstance(f, FlakyIO)}
        self._fired_once: set = set()   # HangStep/Preempt/CorruptCheckpoint
        self._active = False

    def __enter__(self) -> "FaultInjector":
        self._active = True
        return self

    def __exit__(self, *exc) -> None:
        self._active = False

    def _record(self, fault: str, **info: Any) -> None:
        from apex_tpu.resilience.incidents import utc_now
        self.events.append({"fault": fault, "utc": utc_now(), **info})

    # -- hooks -----------------------------------------------------------
    def on_step_start(self, step: int) -> None:
        """Fire-once per fault instance: a rewound/restarted run replays
        step indices, and a hang or preemption is an *event*, not a
        property of the step number."""
        for f in self.faults:
            if id(f) in self._fired_once:
                continue
            if isinstance(f, HangStep) and f.step == step:
                self._fired_once.add(id(f))
                self._record("hang_step", step=step, seconds=f.seconds)
                time.sleep(f.seconds)
            elif isinstance(f, Preempt) and f.step == step:
                self._fired_once.add(id(f))
                self._record("preempt", step=step)
                raise SimulatedPreemption(step)

    def poison_batch(self, step: int, batch: Tuple[Any, ...]
                     ) -> Tuple[Any, ...]:
        import jax
        import jax.numpy as jnp
        for f in self.faults:
            if not isinstance(f, NaNStorm) or step < f.step:
                continue
            if self._storm_left.get(id(f), 0) <= 0:
                continue
            self._storm_left[id(f)] -= 1
            self._record("nan_storm", step=step, value=repr(f.value))
            leaves, treedef = jax.tree.flatten(batch)
            for i, leaf in enumerate(leaves):
                arr = jnp.asarray(leaf)
                if jnp.issubdtype(arr.dtype, jnp.inexact):
                    flat = arr.reshape(-1)
                    flat = flat.at[0].set(jnp.asarray(f.value, arr.dtype))
                    leaves[i] = flat.reshape(arr.shape)
                    break
            return jax.tree.unflatten(treedef, leaves)
        return batch

    def io_hook(self, op: str) -> None:
        for f in self.faults:
            if isinstance(f, SlowIO) and f.op == op:
                self._record("slow_io", op=op, seconds=f.seconds)
                time.sleep(f.seconds)
            elif isinstance(f, FlakyIO) and f.op == op \
                    and self._flaky_left.get(id(f), 0) > 0:
                self._flaky_left[id(f)] -= 1
                self._record("flaky_io", op=op,
                             remaining=self._flaky_left[id(f)])
                raise OSError(f"injected flaky {op} IO")

    def on_commit(self, step: int, path: str) -> None:
        for f in self.faults:
            if not isinstance(f, CorruptCheckpoint) or id(f) in \
                    self._fired_once or step < f.step:
                continue
            self._fired_once.add(id(f))
            leaf_files = sorted(n for n in os.listdir(path)
                                if n.endswith(".npy"))
            if not leaf_files:
                continue
            victim = os.path.join(path, self.rng.choice(leaf_files))
            size = os.path.getsize(victim)
            if f.kind == "truncate":
                with open(victim, "r+b") as fh:
                    fh.truncate(max(0, size // 2))
            else:
                with open(victim, "r+b") as fh:
                    fh.seek(max(0, size // 2))
                    chunk = fh.read(8)
                    fh.seek(max(0, size // 2))
                    fh.write(bytes(b ^ 0xFF for b in chunk))
            self._record("corrupt_checkpoint", step=step, kind=f.kind,
                         file=os.path.basename(victim))
