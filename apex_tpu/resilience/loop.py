"""Self-healing training loop: watchdog, IO retry, divergence rewind.

The r02 incident (``INCIDENT_r02_wedge.json``) is the design brief: a
hung device call wedged a session for 6+ hours with no watchdog, no
incident artifact, and no resumable state.  :func:`run_resilient` wraps
a jitted train step so that the failure modes a production run actually
hits become *handled inputs*:

- **step watchdog** — a monitor thread tracks wall-clock per step; a
  step that neither dispatches nor resolves within the budget produces
  an incident artifact (with the main thread's stack as evidence) and a
  graceful :class:`WatchdogTimeout` instead of a silent wedge.  The
  monitor can only interrupt Python-level waits (``interrupt_main``); a
  truly wedged C call still gets its incident written within the budget
  — the artifact, not the unstick, is the contract (r02's gap).
- **IO retry** — checkpoint save/restore runs through
  :func:`retry_io` (bounded attempts, exponential backoff), so a flaky
  filesystem is absorbed instead of killing the run.
- **divergence sentinel** — distinguishes amp's *normal* overflow-skip
  (scale halves, training continues) from pathological states: ``K``
  consecutive overflows with the loss scale pinned at its floor
  (``metrics["pinned_at_floor"]``), or a non-finite loss that is NOT an
  overflow skip.  Response: rewind to the last good checkpoint with a
  re-initialized scaler; after ``max_rewinds`` rewinds, hard-fail with a
  structured incident instead of looping forever.

Normal-path cost: the loop adds **no host sync on the step path** — it
dispatches steps back-to-back and resolves each step's metrics one step
behind (``sentinel_lag``), by which point they are (on an accelerator)
already computed; the watchdog is a sleeping daemon thread and the
in-flight table is two dict ops per step.  Measured overhead on the CPU
bench smoke is recorded by ``tools/chaos_run.py --overhead`` (< 2%; see
``docs/source/checkpoint.rst``).
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.obs.flight import FlightRecorder
from apex_tpu.resilience import incidents as incidents_lib
from apex_tpu.resilience.faults import FaultInjector, SimulatedPreemption


class WatchdogTimeout(RuntimeError):
    """A step exceeded the wall-clock budget; an incident was recorded."""


class DivergenceError(RuntimeError):
    """Pathological state persisted past the rewind budget (or there was
    nothing to rewind to); an incident was recorded."""


def retry_io(fn: Callable[[], Any], retries: int = 3,
             backoff_s: float = 0.05,
             on_retry: Optional[Callable[[int, BaseException], None]] = None
             ) -> Any:
    """Run ``fn`` with bounded retries and exponential backoff on
    ``OSError`` (the checkpoint-IO failure class; anything else is a bug
    and propagates immediately)."""
    attempt = 0
    while True:
        try:
            return fn()
        except OSError as e:
            attempt += 1
            if attempt > retries:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            time.sleep(backoff_s * (2.0 ** (attempt - 1)))


@dataclasses.dataclass
class ResilienceConfig:
    watchdog_timeout_s: float = 300.0
    watchdog_poll_s: float = 0.05
    checkpoint_every: int = 0          # 0 = no checkpointing
    io_retries: int = 3
    io_backoff_s: float = 0.05
    max_rewinds: int = 2
    overflow_patience: int = 4         # K pinned-at-floor overflows
    sentinel_lag: int = 1              # steps to lag metric resolution
    incident_path: Optional[str] = None  # where watchdog/divergence artifacts go
    #: opt-in SPMD preflight re-run after every rewind/reshape: called
    #: as ``preflight(restored_state)`` before the loop resumes stepping
    #: (wire it to :func:`apex_tpu.parallel.multiproc.spmd_preflight`
    #: over the step's fresh lowering).  A fleet whose post-restore step
    #: compiles a divergent collective schedule — the elastic shrink/
    #: regrow hazard — aborts here with a named diff and an incident
    #: artifact, instead of deadlocking on the first resumed step.
    preflight: Optional[Callable[[Any], Any]] = None


@dataclasses.dataclass
class RunResult:
    state: Any
    steps_completed: int
    losses: List[Tuple[int, float]]
    rewinds: int
    events: List[dict]
    incidents: List[dict]
    #: the loop's flight recorder (ring of step/overflow/fault/rewind
    #: events) — callers writing their own post-run incident records
    #: embed ``flight.dump()`` the way the loop's in-flight incidents do
    flight: Optional[FlightRecorder] = None


def run_resilient(
    step_fn: Callable,
    state: Any,
    batches: Union[Sequence[Any], Callable[[int], Any]],
    num_steps: int,
    amp_obj: Any = None,
    manager: Any = None,
    config: Optional[ResilienceConfig] = None,
    injector: Optional[FaultInjector] = None,
    registry: Optional[obs_metrics.Registry] = None,
    flight: Optional[FlightRecorder] = None,
    profiler: Optional[Any] = None,
    fleet_metrics: Optional[Any] = None,
) -> RunResult:
    """Drive ``step_fn(state, *batch) -> (state, metrics)`` for
    ``num_steps`` with the protections in the module docstring.

    ``batches`` is a sequence or a ``step -> batch`` callable (batch may
    be a tuple of step-fn args or a single array).  ``amp_obj`` (the
    bound :class:`~apex_tpu.amp.frontend.Amp`) enables scaler re-init on
    rewind; ``manager`` (a
    :class:`~apex_tpu.resilience.durable.DurableCheckpointManager`)
    enables on-disk checkpointing and checksum-verified rewind — without
    one, an in-memory host snapshot at the same cadence backs rewind.

    The loop records its runtime telemetry into ``registry`` (default:
    the shared :data:`apex_tpu.obs.metrics.DEFAULT`): ``train_steps/
    overflows/rewinds/checkpoints_total`` counters, the ``train_loss``
    gauge, and ``train_watchdog_margin_s`` (budget minus the observed
    step wall at resolve time — how close the run sails to the
    watchdog).  Every update happens at the existing lag-resolved
    points where the scalars are already host values, so the shared
    registry adds **zero** host syncs; incident records embed a
    ``metrics`` snapshot of the resolved state (never a device fetch —
    a watchdog incident must not block on the very device that hung)
    and the ``flight`` tail of the loop's
    :class:`~apex_tpu.obs.flight.FlightRecorder` (``flight=`` to share
    one across restarts; default a fresh 256-event ring) — the
    step/overflow/checkpoint/fault/rewind history that LED to the
    incident, returned on :attr:`RunResult.flight` either way.
    Steps you hand here should NOT also be wrapped with
    :func:`apex_tpu.obs.metrics.instrument_step` (double counting).

    ``profiler`` (an :class:`apex_tpu.obs.contprof.ContinuousProfiler`,
    usually from :func:`apex_tpu.obs.contprof.train_profiler`) turns
    on continuous profiling: every ``capture_every`` dispatches a
    short window is captured around the step boundary and bucketed
    into the pinned train vocabulary (fwd/bwd/optimizer/collectives/
    host_gap) — the classifier is built lazily from THIS loop's
    jitted step.  Capture is SUPPRESSED across a rewind (an open
    window is aborted and the cadence restarts — the sentinel must
    never judge a half-rewound capture), and any window still open
    when the loop exits is aborted.

    On a :class:`~apex_tpu.resilience.faults.SimulatedPreemption` (or a
    real ``KeyboardInterrupt`` that is not the watchdog), in-flight saves
    are flushed and an incident recorded (status ``preempted`` /
    ``interrupted``) before re-raising — the next process's
    ``manager.restore`` lands on the last good snapshot.

    ``fleet_metrics`` (an
    :class:`apex_tpu.resilience.fleet.FleetMetrics`) hooks the elastic
    fleet's ``train_fleet_*`` family into the same lag-resolved
    boundaries: ``on_resolve()`` fires where the loop's own counters
    update (re-asserting the active-ranks gauge from a host int) and
    ``on_rewind()`` where a divergence rewind lands — both host-side
    only, so the instrumented step's lowering stays syncs-clean.
    """
    cfg = config or ResilienceConfig()
    from apex_tpu import checkpoint as ckpt
    from apex_tpu.amp.scaler import all_finite

    if callable(batches):
        batch_fn = batches
    else:
        batch_fn = lambda i: batches[i]  # noqa: E731

    events: List[dict] = []
    written_incidents: List[dict] = []
    losses: List[Tuple[int, float]] = []

    # the black box: every step/overflow/checkpoint/fault/rewind notes
    # into the bounded ring, and every incident written below ships the
    # ring's tail — the last-N-events history, not just final gauges
    fr = flight if flight is not None else FlightRecorder()
    seen_inj = len(injector.events) if injector is not None else 0

    reg = registry if registry is not None else obs_metrics.DEFAULT
    m_steps = reg.counter("train_steps_total",
                          "train steps resolved (1-step lag)")
    m_over = reg.counter("train_overflows_total",
                         "loss-scale overflow skips")
    m_rewinds = reg.counter("train_rewinds_total",
                            "divergence rewinds executed")
    m_ckpts = reg.counter("train_checkpoints_total",
                          "checkpoints committed (or snapshotted)")
    m_loss = reg.gauge("train_loss", "last resolved loss (1-step lag)")
    m_margin = reg.gauge(
        "train_watchdog_margin_s",
        "watchdog budget minus observed step wall at resolve")

    # -- watchdog ---------------------------------------------------------
    inflight: Dict[int, float] = {}
    lock = threading.Lock()
    abort = threading.Event()
    stop = threading.Event()
    # the thread driving this loop: its stack is the hang evidence, and
    # interrupt_main only helps when it IS the main thread
    entry_thread = threading.current_thread()

    def _note_new_faults() -> None:
        """Mirror freshly fired injector events into the flight ring
        (called after each dispatch and before every incident write —
        a Preempt raises out of the dispatch before the loop's own
        diff point)."""
        nonlocal seen_inj
        if injector is None:
            return
        # under the loop lock: the watchdog thread mirrors through
        # _write_incident concurrently with the main loop's per-step
        # call, and an unguarded cursor would duplicate fault events
        # in the forensic record
        with lock:
            fresh = injector.events[seen_inj:]
            seen_inj = len(injector.events)
        for ev in fresh:
            # injector payload keys may collide with the ring's own
            # fields (CorruptCheckpoint records kind="truncate") —
            # prefix those instead of exploding note()'s signature
            fr.note("fault", **{
                ("fault_" + k if k in ("kind", "ts") else k): v
                for k, v in ev.items() if k != "utc"})

    def _write_incident(status: str, summary: str,
                        evidence: List[Any], **extra: Any) -> None:
        try:
            # embed the RESOLVED metrics state (no flush: a watchdog
            # incident fires while the device may be wedged — snapshot
            # must never device_get) and the flight recorder's tail
            # (the event history that LED here, not just end gauges)
            _note_new_faults()
            extra.setdefault("metrics", reg.snapshot())
            extra.setdefault("flight", fr.dump())
            if cfg.incident_path:
                rec = incidents_lib.write_incident(
                    cfg.incident_path, status, summary, evidence, **extra)
            else:
                rec = incidents_lib.make_incident(status, summary, evidence,
                                                  **extra)
            written_incidents.append(rec)
        except Exception:  # incident writing must never mask the failure
            traceback.print_exc()

    def _monitor() -> None:
        while not stop.wait(cfg.watchdog_poll_s):
            with lock:
                if not inflight:
                    continue
                step_i, t0 = min(inflight.items(), key=lambda kv: kv[1])
            elapsed = time.monotonic() - t0
            if elapsed <= cfg.watchdog_timeout_s:
                continue
            frames = None
            try:
                import sys
                frame = sys._current_frames().get(entry_thread.ident)
                if frame is not None:
                    frames = traceback.format_stack(frame)
            except Exception:
                pass
            fr.note("watchdog", step=step_i,
                    elapsed_s=round(elapsed, 3),
                    budget_s=cfg.watchdog_timeout_s)
            _write_incident(
                "watchdog-timeout",
                f"step {step_i} exceeded the {cfg.watchdog_timeout_s}s "
                "wall-clock budget; aborting instead of wedging (r02 "
                "mitigation)",
                [f"step {step_i} in flight {elapsed:.3f}s > budget "
                 f"{cfg.watchdog_timeout_s}s"]
                + ([{"main_thread_stack": frames[-6:]}] if frames else []),
            )
            abort.set()
            if entry_thread is threading.main_thread():
                try:        # break a Python-level wait; a loop driven
                    import _thread      # from a worker thread relies on
                    _thread.interrupt_main()  # the abort flag instead
                except Exception:
                    pass
            return

    monitor = threading.Thread(target=_monitor, daemon=True,
                               name="apex-tpu-watchdog")
    monitor.start()

    # -- rewind machinery -------------------------------------------------
    rewinds = 0
    consecutive_pinned = 0
    # (step, ("amp", ckpt state_dict) | ("tree", host leaf copies))
    mem_snapshot: Optional[Tuple[int, Any]] = None

    def _reinit_scaler(st: Any) -> Any:
        if amp_obj is None or not hasattr(st, "scaler_states"):
            return st
        return st._replace(scaler_states=tuple(
            amp_obj.scaler.init_state() for _ in st.scaler_states))

    def _save(step_i: int, st: Any) -> None:
        if not bool(all_finite(st.master_params
                               if hasattr(st, "master_params") else st)):
            events.append({"event": "checkpoint_skipped_nonfinite",
                           "step": step_i})
            fr.note("checkpoint_skipped_nonfinite", step=step_i)
            return
        nonlocal mem_snapshot
        if manager is not None:
            retry_io(lambda: manager.save(step_i, st),
                     retries=cfg.io_retries, backoff_s=cfg.io_backoff_s,
                     on_retry=lambda a, e: events.append(
                         {"event": "save_retry", "step": step_i,
                          "attempt": a, "error": repr(e)}))
        else:   # managerless runs rewind from a host snapshot instead
            if hasattr(st, "master_params"):
                mem_snapshot = (step_i, ("amp", ckpt.state_dict(st)))
            else:
                # run_resilient never required AmpState — a generic
                # pytree state snapshots as a plain host copy of its
                # leaves (ckpt.state_dict reads AmpState fields and
                # would crash here)
                import jax
                mem_snapshot = (step_i,
                                ("tree", jax.tree.map(np.asarray, st)))
        events.append({"event": "checkpoint", "step": step_i})
        m_ckpts.inc()
        fr.note("checkpoint", step=step_i)
        # the periodic resolved-metrics snapshot riding the checkpoint
        # cadence — the "what did the gauges say then" half of the ring
        fr.note_metrics(reg)

    def _rewind(st: Any, reason: str) -> Tuple[Any, int]:
        nonlocal rewinds, consecutive_pinned
        rewinds += 1
        consecutive_pinned = 0
        if rewinds > cfg.max_rewinds:
            _write_incident(
                "diverged",
                f"pathological state persisted past max_rewinds="
                f"{cfg.max_rewinds}: {reason}",
                [reason] + events[-8:],
                rewinds=rewinds - 1)
            raise DivergenceError(
                f"exceeded max_rewinds={cfg.max_rewinds}: {reason}")
        restored = None
        if manager is not None:
            try:        # flush in-flight async saves before deciding
                manager.wait()   # whether there is anything to rewind to
            except RuntimeError as e:
                events.append({"event": "rewind_flush_error",
                               "error": repr(e)})
        if manager is not None and manager.all_steps():
            new_state, _ = retry_io(
                lambda: manager.restore(st),
                retries=cfg.io_retries, backoff_s=cfg.io_backoff_s)
            restored = manager.last_restore["step"]
        elif mem_snapshot is not None:
            snap_step, (kind, payload) = mem_snapshot
            if kind == "amp":
                new_state, _ = ckpt.load_state_dict(st, payload)
            else:       # generic-pytree snapshot: host leaves back to jax
                import jax
                new_state = jax.tree.map(
                    lambda s, _r: jax.numpy.asarray(s), payload, st)
            restored = snap_step
        else:
            _write_incident(
                "diverged", f"{reason} — and no checkpoint to rewind to",
                [reason], rewinds=rewinds)
            raise DivergenceError(f"{reason}; no checkpoint to rewind to")
        new_state = _reinit_scaler(new_state)
        if cfg.preflight is not None:
            try:
                cfg.preflight(new_state)
            except Exception as e:
                _write_incident(
                    "preflight-failed",
                    f"post-rewind SPMD preflight rejected the restored "
                    f"step (rewind to step {restored}): {e}",
                    [reason, repr(e)] + events[-8:],
                    rewinds=rewinds)
                raise
            events.append({"event": "preflight", "to_step": restored})
            fr.note("preflight", to_step=restored)
        events.append({"event": "rewind", "to_step": restored,
                       "reason": reason, "rewind_count": rewinds})
        m_rewinds.inc()
        if fleet_metrics is not None:
            fleet_metrics.on_rewind()
        fr.note("rewind", to_step=restored, reason=reason,
                rewind_count=rewinds)
        return new_state, restored + 1

    # -- main loop --------------------------------------------------------
    pending: deque = deque()   # (step, metrics) awaiting resolution
    i = 0
    steps_completed = 0

    def _resolve(entry: Tuple[int, dict], st: Any) -> Tuple[Any, Optional[int]]:
        """Consume one lagged metrics record; returns (state, jump)."""
        nonlocal consecutive_pinned, steps_completed
        j, m = entry
        # one host fetch for the three sentinel scalars (by now — one
        # step behind dispatch — they are already computed, so this does
        # not stall the device pipeline)
        import jax
        loss, overflow, pinned = jax.device_get(
            (m["loss"], m.get("overflow", False),
             m.get("pinned_at_floor", False)))
        loss = float(np.asarray(loss))
        # multi-loss metrics carry per-scaler tuples: any scaler counts
        overflow = bool(np.any(np.asarray(overflow)))
        pinned = bool(np.any(np.asarray(pinned)))
        with lock:
            t0 = inflight.pop(j, None)
        losses.append((j, loss))
        steps_completed = max(steps_completed, j + 1)
        # shared-registry telemetry: every value here is already a host
        # scalar at this (lag-resolved) point — zero added syncs
        m_steps.inc()
        m_loss.set(loss)
        if fleet_metrics is not None:
            fleet_metrics.on_resolve()
        if overflow:
            m_over.inc()
        if t0 is not None:
            m_margin.set(cfg.watchdog_timeout_s
                         - (time.monotonic() - t0))
        fr.note("step", step=j, loss=round(loss, 6),
                overflow=overflow)
        if overflow:
            fr.note("overflow", step=j, pinned_at_floor=pinned)
        if overflow and pinned:
            consecutive_pinned += 1
        else:
            consecutive_pinned = 0
        if consecutive_pinned >= cfg.overflow_patience:
            return _rewind(st, f"{consecutive_pinned} consecutive overflows "
                               "with loss scale pinned at min_loss_scale")
        if not math.isfinite(loss) and not overflow:
            return _rewind(st, f"non-finite loss {loss} at step {j} outside "
                               "an overflow skip")
        return st, None

    try:
        try:
            while i < num_steps or pending:
                if abort.is_set():
                    raise WatchdogTimeout(
                        "watchdog aborted the run; see incident record")
                if i < num_steps:
                    batch = batch_fn(i)
                    if not isinstance(batch, tuple):
                        batch = (batch,)
                    with lock:
                        inflight[i] = time.monotonic()
                    if injector is not None:
                        injector.on_step_start(i)
                        batch = injector.poison_batch(i, batch)
                        _note_new_faults()
                    if profiler is not None:
                        if not profiler.has_classifier_builder:
                            # the classifier comes from THIS loop's
                            # own jitted step (lowered lazily at the
                            # first window close, never executed)
                            from apex_tpu.obs.contprof import (
                                train_classifier_builder)
                            profiler.set_classifier_builder(
                                train_classifier_builder(
                                    step_fn, state, batch))
                        profiler.step_begin()
                        t_disp = time.perf_counter()
                    state, metrics = step_fn(state, *batch)
                    if profiler is not None:
                        # window close blocks on the step's loss (the
                        # capture must hold the device work it wraps);
                        # non-window steps record wall only
                        profiler.step_end(
                            time.perf_counter() - t_disp,
                            block_on=metrics.get("loss")
                            if isinstance(metrics, dict) else None)
                    pending.append((i, metrics))
                # resolve lagged metrics (all of them once dispatch is done)
                lag = cfg.sentinel_lag if i < num_steps else 0
                jump = None
                while len(pending) > lag and jump is None:
                    state, jump = _resolve(pending.popleft(), state)
                if jump is not None:
                    pending.clear()
                    with lock:
                        inflight.clear()
                    if profiler is not None:
                        # capture suppressed while rewinding: abort
                        # any open window and restart the cadence —
                        # the re-dispatched timeline must not feed
                        # the sentinel a half-rewound capture
                        profiler.suppress()
                    i = jump
                    continue
                if i < num_steps and cfg.checkpoint_every \
                        and (i + 1) % cfg.checkpoint_every == 0:
                    _save(i, state)
                i += 1
        except KeyboardInterrupt:
            if abort.is_set():
                raise WatchdogTimeout(
                    "watchdog aborted the run; see incident record") from None
            raise
    except (SimulatedPreemption, KeyboardInterrupt) as e:
        if manager is not None:
            try:
                manager.wait()
            except Exception:
                pass
        if isinstance(e, SimulatedPreemption):
            _write_incident(
                "preempted",
                f"SIGTERM at step {e.step}; in-flight checkpoints flushed — "
                "restart restores the last good snapshot",
                [str(e)] + ([{"injector_events": injector.events[-6:]}]
                            if injector else []))
        else:   # a real operator interrupt still leaves an artifact
            _write_incident(
                "interrupted",
                f"KeyboardInterrupt around step {i}; in-flight checkpoints "
                "flushed — restart restores the last good snapshot",
                [f"interrupted at step {i} of {num_steps}"])
        raise
    finally:
        stop.set()
        monitor.join(timeout=1.0)
        if profiler is not None:
            # a window still open on any exit path (preemption,
            # watchdog, normal drain mid-window) must not leak the
            # process-global tracer
            profiler.abort_window()
        if manager is not None:
            try:
                manager.wait()
            except Exception as e:
                # surface a tail async-save failure unless it would mask
                # the exception already propagating
                events.append({"event": "final_wait_error", "error": repr(e)})
                import sys as _sys
                if _sys.exc_info()[0] is None:
                    raise
        # a fault firing on an ASYNC commit (checkpoint corruption)
        # can land after the loop's last dispatch-side diff — sweep
        # the stragglers so the returned ring is complete
        _note_new_faults()

    return RunResult(state=state, steps_completed=steps_completed,
                     losses=losses, rewinds=rewinds, events=events,
                     incidents=written_incidents, flight=fr)
