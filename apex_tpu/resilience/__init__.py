"""apex_tpu.resilience — fault tolerance for training at production scale.

The reference's resume story was "save fp32 masters + scaler state and
restart exactly" (``apex/fp16_utils/fp16_optimizer.py:298-359``); this
subsystem extends that contract to the failure modes a long-lived TPU
run actually meets (the r02 chip-lease wedge, preemptions, NaN storms,
flaky checkpoint IO):

- :mod:`~apex_tpu.resilience.durable` — crash-atomic, checksum-verified,
  shard-portable checkpointing (:class:`DurableCheckpointManager`);
- :mod:`~apex_tpu.resilience.faults` — seeded, composable fault
  injection (:class:`FaultInjector` and the fault dataclasses);
- :mod:`~apex_tpu.resilience.loop` — the self-healing train loop
  (:func:`run_resilient`: watchdog, IO retry, divergence rewind);
- :mod:`~apex_tpu.resilience.incidents` — the machine-checkable incident
  artifact schema shared with ``tools/gate_hygiene.py``;
- :mod:`~apex_tpu.resilience.fleet` — the elastic training fleet
  (heartbeat-leased membership, shrink on preemption, regrow on
  recovery; :func:`supervise` / :func:`run_generation`).
"""

from apex_tpu.resilience.durable import (CheckpointCorruptError,
                                         DurableCheckpointManager,
                                         read_snapshot, verify_snapshot,
                                         write_snapshot)
from apex_tpu.resilience.faults import (CorruptCheckpoint, FaultInjector,
                                        FlakyIO, HangStep, NaNStorm,
                                        Preempt, RankKill,
                                        SimulatedPreemption, SlowIO,
                                        parse_fault)
from apex_tpu.resilience.fleet import (FleetConfig, FleetError,
                                       FleetLedger, FleetMembershipChange,
                                       FleetMetrics, HeartbeatLease,
                                       latest_verified_step, membership_gate,
                                       run_generation, snapshot_digest,
                                       state_digest, supervise)
from apex_tpu.resilience.incidents import (make_incident, validate_incident,
                                           validate_incident_file,
                                           write_incident)
from apex_tpu.resilience.loop import (DivergenceError, ResilienceConfig,
                                      RunResult, WatchdogTimeout,
                                      retry_io, run_resilient)

__all__ = [
    "CheckpointCorruptError", "DurableCheckpointManager", "read_snapshot",
    "verify_snapshot", "write_snapshot",
    "CorruptCheckpoint", "FaultInjector", "FlakyIO", "HangStep", "NaNStorm",
    "Preempt", "RankKill", "SimulatedPreemption", "SlowIO", "parse_fault",
    "FleetConfig", "FleetError", "FleetLedger", "FleetMembershipChange",
    "FleetMetrics", "HeartbeatLease", "latest_verified_step",
    "membership_gate", "run_generation", "snapshot_digest", "state_digest",
    "supervise",
    "make_incident", "validate_incident", "validate_incident_file",
    "write_incident",
    "DivergenceError", "ResilienceConfig", "RunResult", "WatchdogTimeout",
    "retry_io", "run_resilient",
]
