"""RNN cell math.

Port of ``apex/RNN/cells.py`` + the cell semantics ``apex/RNN/RNNBackend.py``
reuses from torch (``models.py:7-54``).  Each cell is a pure function
``cell(params, x_t, state) -> (new_state, output)``; the matmuls route
through :mod:`apex_tpu.amp.ops` so O1 policies govern them exactly as the
reference's cuDNN-cast interposition did (``wrap.py:157-265``) — without any
flat-weight aliasing, which has no TPU analog (SURVEY.md §7).

Gate layouts follow torch conventions so the ``gate_multiplier`` bookkeeping
of ``RNNBackend.RNNCell`` (``:232-365``) carries over: 1 for ReLU/Tanh,
3 for GRU (r, z, n), 4 for LSTM/mLSTM (i, f, g, o).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp import ops as amp_ops

GATE_MULTIPLIERS = {"relu": 1, "tanh": 1, "gru": 3, "lstm": 4, "mlstm": 4}


class LSTMState(NamedTuple):
    h: jax.Array
    c: jax.Array


def _linear(x, w, b=None):
    return amp_ops.linear(x, w, b)


def relu_cell(params, x, h):
    nh = jax.nn.relu(_linear(x, params["w_ih"], params.get("b_ih"))
                     + _linear(h, params["w_hh"], params.get("b_hh")))
    return nh, nh


def tanh_cell(params, x, h):
    nh = jnp.tanh(_linear(x, params["w_ih"], params.get("b_ih"))
                  + _linear(h, params["w_hh"], params.get("b_hh")))
    return nh, nh


def lstm_cell(params, x, state: LSTMState):
    gates = (_linear(x, params["w_ih"], params.get("b_ih"))
             + _linear(state.h, params["w_hh"], params.get("b_hh")))
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c = f * state.c.astype(g.dtype) + i * g
    h = o * jnp.tanh(c)
    return LSTMState(h=h, c=c), h


def mlstm_cell(params, x, state: LSTMState):
    """Multiplicative LSTM (``cells.py:12-84``): an intermediate
    ``m = (x·W_mi) ⊙ (h·W_mh)`` replaces h in the gate computation."""
    m = _linear(x, params["w_mi"]) * _linear(state.h, params["w_mh"])
    gates = (_linear(x, params["w_ih"], params.get("b_ih"))
             + _linear(m, params["w_hh"], params.get("b_hh")))
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c = f * state.c.astype(g.dtype) + i * g
    h = o * jnp.tanh(c)
    return LSTMState(h=h, c=c), h


def gru_cell(params, x, h):
    """torch-semantics GRU: n-gate uses r ⊙ (W_hn·h)."""
    gi = _linear(x, params["w_ih"], params.get("b_ih"))
    gh = _linear(h, params["w_hh"], params.get("b_hh"))
    i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
    h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(i_r + h_r)
    z = jax.nn.sigmoid(i_z + h_z)
    n = jnp.tanh(i_n + r * h_n)
    nh = (1.0 - z) * n + z * h.astype(n.dtype)
    return nh, nh


CELLS = {"relu": relu_cell, "tanh": tanh_cell, "gru": gru_cell,
         "lstm": lstm_cell, "mlstm": mlstm_cell}


def is_lstm_like(mode: str) -> bool:
    return mode in ("lstm", "mlstm")


def init_state(mode: str, batch: int, hidden: int, dtype=jnp.float32):
    """Zero hidden-state auto-init (``RNNBackend.py:286-309``)."""
    h = jnp.zeros((batch, hidden), dtype)
    if is_lstm_like(mode):
        return LSTMState(h=h, c=jnp.zeros((batch, hidden), dtype))
    return h
