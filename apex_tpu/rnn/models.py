"""Stacked / bidirectional RNN modules.

Port of ``apex/RNN/RNNBackend.py`` (``stackedRNN`` ``:90-230``,
``bidirectionalRNN`` ``:25-85``, ``RNNCell`` ``:232-365``) and the factory
functions of ``apex/RNN/models.py:7-54``.  The reference's explicit
per-timestep Python loop becomes ``jax.lax.scan`` — one compiled step reused
across time, the TPU-idiomatic recurrence (no unrolled graph, no cuDNN flat
weight buffer).

Layout: inputs are (time, batch, features), matching the reference.
Recurrent output projection (``output_size`` → ``w_ho``,
``RNNBackend.py:253-262``) projects h before it re-enters the recurrence.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.rnn import cells as C


class RNNLayer(nn.Module):
    """One direction of one layer, scanned over time."""

    mode: str
    hidden_size: int
    output_size: Optional[int] = None   # recurrent projection (w_ho)
    bias: bool = True
    reverse: bool = False
    param_dtype: Any = jnp.float32

    def _params(self, input_size: int):
        gm = C.GATE_MULTIPLIERS[self.mode]
        k = nn.initializers.uniform(scale=1.0 / jnp.sqrt(self.hidden_size))
        hidden_in = self.output_size or self.hidden_size
        p = {
            "w_ih": self.param("w_ih", k, (input_size, gm * self.hidden_size),
                               self.param_dtype),
            "w_hh": self.param("w_hh", k, (hidden_in, gm * self.hidden_size),
                               self.param_dtype),
        }
        if self.bias:
            p["b_ih"] = self.param("b_ih", nn.initializers.zeros,
                                   (gm * self.hidden_size,), self.param_dtype)
            p["b_hh"] = self.param("b_hh", nn.initializers.zeros,
                                   (gm * self.hidden_size,), self.param_dtype)
        if self.mode == "mlstm":
            p["w_mi"] = self.param("w_mi", k, (input_size, self.hidden_size),
                                   self.param_dtype)
            p["w_mh"] = self.param("w_mh", k, (hidden_in, self.hidden_size),
                                   self.param_dtype)
        if self.output_size is not None:
            p["w_ho"] = self.param("w_ho", k,
                                   (self.hidden_size, self.output_size),
                                   self.param_dtype)
        return p

    @nn.compact
    def __call__(self, xs: jax.Array, init_state=None, seq_lengths=None):
        from apex_tpu.amp import ops as amp_ops
        # Under an active O1 policy the whole recurrence runs at the half
        # dtype (the rnn_cast capability, wrap.py:157-265): cast inputs and
        # carry up front so the scan carry dtype is stable.
        policy = amp_ops.active_policy()
        if policy is not None:
            xs = xs.astype(policy.half_dtype)
            if init_state is not None:
                init_state = jax.tree.map(
                    lambda t: t.astype(policy.half_dtype), init_state)
        params = self._params(xs.shape[-1])
        batch = xs.shape[1]
        out_size = self.output_size or self.hidden_size
        if init_state is None:
            # h carries the (possibly projected) output size; c always the
            # raw hidden size (RNNBackend.py:253-262).
            if C.is_lstm_like(self.mode):
                init_state = C.LSTMState(
                    h=jnp.zeros((batch, out_size), xs.dtype),
                    c=jnp.zeros((batch, self.hidden_size), xs.dtype))
            else:
                init_state = jnp.zeros((batch, out_size), xs.dtype)
        cell = C.CELLS[self.mode]

        def cell_step(state, x_t):
            new_state, out = cell(params, x_t, state)
            if self.output_size is not None:
                # project h before it re-enters the recurrence
                # (RNNBackend.py:253-262)
                out = jnp.matmul(out, params["w_ho"])
                if C.is_lstm_like(self.mode):
                    new_state = C.LSTMState(h=out, c=new_state.c)
                else:
                    new_state = out
            return new_state, out

        if seq_lengths is None:
            final, ys = jax.lax.scan(cell_step, init_state, xs,
                                     reverse=self.reverse)
            return ys, final

        # Variable-length sequences: the TPU-native analog of torch's
        # PackedSequence (reference test exercises pack_padded_sequence
        # through the cast-patched cuDNN path, tests/L0/run_amp/
        # test_rnn.py:104-116).  cuDNN packs to skip padded work; under
        # XLA static shapes the idiom is padded batches + a validity mask
        # inside the scan: padded steps carry the state through unchanged
        # and emit zero outputs, so the final state is the state at
        # t = length-1 and padded output rows are zeros, exactly the
        # semantics pad_packed_sequence reconstructs.
        t_idx = jnp.arange(xs.shape[0], dtype=jnp.int32)
        valid = t_idx[:, None] < seq_lengths[None, :].astype(jnp.int32)

        def masked_step(state, inp):
            x_t, valid_t = inp
            new_state, out = cell_step(state, x_t)
            m = valid_t[:, None]
            new_state = jax.tree.map(
                lambda n, o: jnp.where(m, n, o), new_state, state)
            out = jnp.where(m, out, jnp.zeros_like(out))
            return new_state, out

        final, ys = jax.lax.scan(masked_step, init_state, (xs, valid),
                                 reverse=self.reverse)
        return ys, final


class RNN(nn.Module):
    """Stacked (optionally bidirectional) RNN
    (``stackedRNN``/``bidirectionalRNN``).

    Returns ``(outputs, final_states)``: outputs (T, B, H·dirs); final_states
    a list per layer (tuples of per-direction states when bidirectional).
    """

    mode: str
    hidden_size: int
    num_layers: int = 1
    bias: bool = True
    bidirectional: bool = False
    output_size: Optional[int] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xs: jax.Array, init_states=None, seq_lengths=None):
        finals = []
        h = xs
        for layer in range(self.num_layers):
            init = None if init_states is None else init_states[layer]
            fwd = RNNLayer(mode=self.mode, hidden_size=self.hidden_size,
                           output_size=self.output_size, bias=self.bias,
                           param_dtype=self.param_dtype,
                           name=f"layer_{layer}_fwd")
            if self.bidirectional:
                bwd = RNNLayer(mode=self.mode, hidden_size=self.hidden_size,
                               output_size=self.output_size, bias=self.bias,
                               reverse=True, param_dtype=self.param_dtype,
                               name=f"layer_{layer}_bwd")
                init_f, init_b = (None, None) if init is None else init
                ys_f, fin_f = fwd(h, init_f, seq_lengths)
                ys_b, fin_b = bwd(h, init_b, seq_lengths)
                h = jnp.concatenate([ys_f, ys_b], axis=-1)
                finals.append((fin_f, fin_b))
            else:
                h, fin = fwd(h, init, seq_lengths)
                finals.append(fin)
        return h, finals


# -- factory functions (models.py:7-54) -------------------------------------

def LSTM(hidden_size: int, **kw) -> RNN:
    return RNN(mode="lstm", hidden_size=hidden_size, **kw)


def GRU(hidden_size: int, **kw) -> RNN:
    return RNN(mode="gru", hidden_size=hidden_size, **kw)


def ReLU(hidden_size: int, **kw) -> RNN:
    return RNN(mode="relu", hidden_size=hidden_size, **kw)


def Tanh(hidden_size: int, **kw) -> RNN:
    return RNN(mode="tanh", hidden_size=hidden_size, **kw)


def mLSTM(hidden_size: int, **kw) -> RNN:
    return RNN(mode="mlstm", hidden_size=hidden_size, **kw)
