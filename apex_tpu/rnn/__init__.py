"""apex_tpu.rnn — scanned-cell RNN stack (reference ``apex/RNN``).

Exports the factory functions the reference's ``apex/RNN/__init__.py``
provides (LSTM/GRU/ReLU/Tanh/mLSTM) plus the module/cell building blocks.
"""

from apex_tpu.rnn.cells import (
    CELLS,
    GATE_MULTIPLIERS,
    LSTMState,
    init_state,
    is_lstm_like,
)
from apex_tpu.rnn.models import GRU, LSTM, RNN, ReLU, RNNLayer, Tanh, mLSTM

__all__ = [
    "RNN", "RNNLayer", "LSTM", "GRU", "ReLU", "Tanh", "mLSTM",
    "CELLS", "GATE_MULTIPLIERS", "LSTMState", "init_state", "is_lstm_like",
]
