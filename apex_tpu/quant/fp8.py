"""FP8 quantization with per-tensor scales and **delayed scaling**.

The contract (Micikevicius et al., *FP8 Formats for Deep Learning*,
2022, §4) is the loss scaler's contract one level down: a tensor class
is quantized as ``q = clip(x * scale)`` cast to e4m3 (forward
activations/weights) or e5m2 (backward cotangents — more exponent, less
mantissa, because gradients need range, not precision), and the scale
is **delayed** — derived from a rolling history of past steps' absolute
maxima, never from the same step's amax (which would serialize the
quantize behind a full reduction of the tensor it quantizes, and is the
seeded-bug pattern the precision lint's ``fp8-same-step-scale`` rule
fires on).  Everything here is a pure pytree transition so the state
jits, donates, and checkpoints exactly like
:class:`~apex_tpu.amp.scaler.LossScaleState` — the O4 opt level carries
one :class:`Fp8TrainState` in ``AmpState`` next to the loss scaler.

Matmuls run with genuinely-fp8 operands and **f32 accumulation** via
``preferred_element_type`` (:func:`scaled_matmul`): the MXU contract
for fp8 is the bf16 contract with one more octave of cheap — the
accumulator must never be the storage dtype (the precision lint's
``half-accum-matmul`` logic already owns that invariant; fp8 rides the
same machinery).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

#: the two FP8 storage formats (IEEE-754-2019 binary8 variants as
#: ml_dtypes spells them): e4m3 = forward (max 448, 3 mantissa bits),
#: e5m2 = backward (max 57344, gradients need range over precision)
FP8_E4M3 = jnp.float8_e4m3fn
FP8_E5M2 = jnp.float8_e5m2

_FP8_MAX = {jnp.dtype(FP8_E4M3): 448.0, jnp.dtype(FP8_E5M2): 57344.0}


def fp8_max(dtype) -> float:
    """Largest finite value of an fp8 storage dtype."""
    try:
        return _FP8_MAX[jnp.dtype(dtype)]
    except KeyError:
        raise ValueError(f"not an fp8 dtype: {dtype!r}") from None


class DelayedScalingState(NamedTuple):
    """Per-tensor(-class) delayed-scaling state — a pure pytree.

    ``amax_history`` is a rolling ``(history_len,)`` f32 window of past
    steps' absolute maxima (newest at index 0); ``scale`` is the
    quantization scale derived from that window at the END of the
    previous step — the *delayed* scale this step's quantize consumes.
    Carrying the derived scale (instead of re-deriving from history at
    use time) is what makes the delay statically visible: the quantize
    multiplies by a program INPUT, never by an in-graph amax.
    """

    amax_history: jax.Array   # (H,) f32, newest first
    scale: jax.Array          # () f32


def init_delayed_scaling(history_len: int = 16,
                         scale: float = 1.0) -> DelayedScalingState:
    """Fresh state: empty (zero) history, unit scale.  A zero history
    derives a unit scale too (:func:`delayed_scale`), so the first
    steps quantize conservatively until real amaxes fill the window."""
    if history_len < 1:
        raise ValueError(f"history_len={history_len}")
    return DelayedScalingState(
        amax_history=jnp.zeros((history_len,), jnp.float32),
        scale=jnp.asarray(scale, jnp.float32))


def delayed_scale(state: DelayedScalingState, dtype,
                  margin: int = 0) -> jax.Array:
    """Derive the next step's scale from the current history:
    ``fp8_max(dtype) / (2**margin * max(history))``, unit scale while
    the history is still all-zero (warmup) and clamped finite."""
    amax = jnp.max(state.amax_history)
    target = jnp.asarray(fp8_max(dtype) / (2.0 ** margin), jnp.float32)
    scale = jnp.where(amax > 0.0, target / jnp.maximum(amax, 1e-30), 1.0)
    return jnp.clip(scale, 1e-30, 1e30).astype(jnp.float32)


def record_amax(state: DelayedScalingState, amax: jax.Array, dtype,
                margin: int = 0) -> DelayedScalingState:
    """End-of-step transition: roll ``amax`` into the history (newest
    first) and re-derive the scale for the NEXT step.  The scale in the
    returned state is therefore always one step behind the newest amax
    it was derived from — the delayed-scaling contract.

    A non-finite amax records as 0 (no range information): an
    overflowed backward under dynamic loss scaling produces inf/nan
    gradients on exactly the steps the loss scaler SKIPS, and one nan
    in the window would otherwise poison ``max(history)`` for the next
    ``history_len`` steps."""
    amax = jnp.asarray(amax, jnp.float32)
    amax = jnp.where(jnp.isfinite(amax), amax, 0.0)
    hist = jnp.concatenate([amax[None], state.amax_history[:-1]])
    new = DelayedScalingState(amax_history=hist, scale=state.scale)
    return DelayedScalingState(amax_history=hist,
                               scale=delayed_scale(new, dtype, margin))


def quantize(x: jax.Array, scale: jax.Array, dtype=FP8_E4M3) -> jax.Array:
    """``clip(x * scale)`` cast to fp8.  ``scale`` is the DELAYED scale
    (a carried state leaf) — deriving it from ``x`` itself in the same
    program is the ``fp8-same-step-scale`` lint error."""
    m = fp8_max(dtype)
    return jnp.clip(x.astype(jnp.float32) * scale, -m, m).astype(dtype)


def dequantize(q: jax.Array, scale: jax.Array,
               dtype=jnp.float32) -> jax.Array:
    """``q / scale`` back at ``dtype`` (the value-space inverse; the
    rounding to the fp8 grid is of course not undone)."""
    return (q.astype(jnp.float32) / scale).astype(dtype)


def qdq(x: jax.Array, scale: jax.Array, dtype=FP8_E4M3) -> jax.Array:
    """Quantize-dequantize: ``x`` rounded onto the fp8 grid, returned
    at ``x.dtype`` — the emulation form for ops without an fp8-operand
    lowering (convolutions); numerically identical operand rounding to
    the real-fp8 dot, without requiring fp8 op support."""
    return dequantize(quantize(x, scale, dtype), scale, x.dtype)


def tensor_amax(x: jax.Array) -> jax.Array:
    """``max(|x|)`` as f32 — the per-step history entry."""
    return jnp.max(jnp.abs(x)).astype(jnp.float32)


def scaled_matmul(x: jax.Array, w: jax.Array,
                  x_scale: jax.Array, w_scale: jax.Array,
                  dtype=FP8_E4M3,
                  out_dtype=None) -> jax.Array:
    """``x @ w`` with both operands cast to fp8 and **f32 accumulation**
    via ``preferred_element_type`` — the scaled-matmul core.

    The operands are quantized with their (delayed) scales, the dot
    runs on the fp8 values, and the product of scales divides out of
    the f32 accumulator once: ``(x*sx) @ (w*sw) / (sx*sw)``.  Output at
    ``out_dtype`` (default: ``x.dtype`` — the network dtype, bf16 under
    O4)."""
    qx = quantize(x, x_scale, dtype)
    qw = quantize(w, w_scale, dtype)
    y = jnp.matmul(qx, qw, preferred_element_type=jnp.float32)
    y = y / (x_scale * w_scale)
    return y.astype(out_dtype if out_dtype is not None else x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def qdq_ste(x: jax.Array, scale: jax.Array, dtype=FP8_E4M3) -> jax.Array:
    """:func:`qdq` with a straight-through gradient: the cotangent
    passes UNROUNDED.  Differentiating through the raw casts instead
    would round the cotangent onto the forward (e4m3) grid — jax
    transposes ``convert`` as ``convert`` — on top of the deliberate
    e5m2 rounding of :func:`bwd_qdq`, a double quantize the precision
    lint's ``fp8-double-quantize`` rule caught on the first O4 lane
    this package ever linted (kept as a seeded-bug regression test)."""
    return qdq(x, scale, dtype)


def _qdq_ste_fwd(x, scale, dtype):
    return qdq(x, scale, dtype), scale


def _qdq_ste_bwd(dtype, scale, g):
    return g, jnp.zeros_like(scale)


qdq_ste.defvjp(_qdq_ste_fwd, _qdq_ste_bwd)


# ---------------------------------------------------------------------------
# the e5m2 backward: a straight-through qdq on the cotangent
# ---------------------------------------------------------------------------

@jax.custom_vjp
def _bwd_qdq(x: jax.Array, grad_scale: jax.Array) -> jax.Array:
    """Identity forward; backward rounds the cotangent onto the e5m2
    grid at ``grad_scale`` — how the O4 op layer puts real e5m2
    converts on the gradient path without threading a second state
    through every layer (the grad-class amax is recorded from the
    materialized gradients at ``apply_gradients`` time instead, one
    step lagged — delayed scaling either way)."""
    return x


def _bwd_qdq_fwd(x, grad_scale):
    return x, grad_scale


def _bwd_qdq_bwd(grad_scale, g):
    return qdq(g, grad_scale, FP8_E5M2), jnp.zeros_like(grad_scale)


_bwd_qdq.defvjp(_bwd_qdq_fwd, _bwd_qdq_bwd)


def bwd_qdq(x: jax.Array, grad_scale: jax.Array) -> jax.Array:
    """Public spelling of the e5m2 cotangent rounding point."""
    return _bwd_qdq(x, grad_scale)


# ---------------------------------------------------------------------------
# the O4 train-state: three tensor classes, one pytree
# ---------------------------------------------------------------------------

class Fp8TrainState(NamedTuple):
    """The fp8 state ``AmpState`` carries under O4 — one
    :class:`DelayedScalingState` per tensor *class* (the granularity a
    policy-level integration can own without knowing the model's
    parameter tree; per-tensor states remain available to callers that
    thread :class:`DelayedScalingState` themselves through
    :func:`scaled_matmul`):

    - ``input``: forward activations, e4m3;
    - ``weight``: forward weights, e4m3;
    - ``grad``: backward cotangents, e5m2 (amax recorded from the
      step's materialized gradients — one step lagged, like every
      other entry in the history).
    """

    input: DelayedScalingState
    weight: DelayedScalingState
    grad: DelayedScalingState


def init_train_state(history_len: int = 16) -> Fp8TrainState:
    return Fp8TrainState(input=init_delayed_scaling(history_len),
                         weight=init_delayed_scaling(history_len),
                         grad=init_delayed_scaling(history_len))


def update_train_state(state: Fp8TrainState,
                       amax_input: jax.Array,
                       amax_weight: jax.Array,
                       amax_grad: jax.Array,
                       margin: int = 0) -> Fp8TrainState:
    """End-of-step roll of all three classes (forward amaxes collected
    by the op layer, grad amax from the unscaled gradients)."""
    return Fp8TrainState(
        input=record_amax(state.input, amax_input, FP8_E4M3, margin),
        weight=record_amax(state.weight, amax_weight, FP8_E4M3, margin),
        grad=record_amax(state.grad, amax_grad, FP8_E5M2, margin))


def step_saturation(state: Fp8TrainState,
                    amax_input: jax.Array,
                    amax_weight: jax.Array,
                    amax_grad: jax.Array,
                    margin: int = 0) -> jax.Array:
    """Dynamic-range utilization of the worst class THIS step: ``max
    over classes of (this step's amax * the scale the step actually
    quantized with / fp8_max)``.  ~1.0 is healthy (amaxes ride the top
    of the representable range); > 1.0 means this step's values
    exceeded what the delayed scale assumed and were CLIPPED at the
    quantize — the amax-history-saturation signal the obs gauge
    watches, computed against ``state`` BEFORE the end-of-step roll.
    Non-finite amaxes (an overflowed, scaler-skipped backward) read
    as 0 here like they record as 0 in the history."""
    def _fin(a):
        a = jnp.asarray(a, jnp.float32)
        return jnp.where(jnp.isfinite(a), a, 0.0)
    parts = [_fin(amax_input) * state.input.scale * (2.0 ** margin)
             / fp8_max(FP8_E4M3),
             _fin(amax_weight) * state.weight.scale * (2.0 ** margin)
             / fp8_max(FP8_E4M3),
             _fin(amax_grad) * state.grad.scale * (2.0 ** margin)
             / fp8_max(FP8_E5M2)]
    return jnp.max(jnp.stack(parts)).astype(jnp.float32)


def rescale_events(old: Fp8TrainState, new: Fp8TrainState) -> jax.Array:
    """How many classes' scales SHRANK this step (i32 0..3) — each one
    an overflow-to-rescale event: the recorded amax exceeded what the
    old history justified, forcing the delayed scale down."""
    flags = [jnp.asarray(n.scale < o.scale, jnp.int32)
             for o, n in zip(old, new)]
    return jnp.sum(jnp.stack(flags))


def tree_amax(tree: Any) -> jax.Array:
    """``max(|leaf|)`` over every floating leaf of a pytree — the grad
    class's history entry, computed from the step's own gradients (no
    host sync: it's one more value on the device)."""
    leaves = [jnp.max(jnp.abs(x)) for x in jax.tree.leaves(tree)
              if hasattr(x, "dtype")
              and jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)]
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.max(jnp.stack(leaves)).astype(jnp.float32)
