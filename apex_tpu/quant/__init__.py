"""apex_tpu.quant — fp8/int8 as a first-class precision regime.

The paper's whole apparatus — the policy table, the cast lists, dynamic
loss scaling, fp32 master weights — is a machine for running *below*
fp32 safely, and it generalizes below 16-bit:

- :mod:`apex_tpu.quant.fp8` is the FP8-training half (Micikevicius et
  al., *FP8 Formats for Deep Learning*, 2022): e4m3/e5m2 quantization
  with per-tensor scales, a pure-pytree :class:`~apex_tpu.quant.fp8.
  DelayedScalingState` (amax history + scale derivation) that lives in
  ``AmpState`` next to the loss scaler, and scaled-matmul helpers that
  cast operands to fp8 and accumulate f32 via
  ``preferred_element_type``.  The O4 opt level
  (``amp.resolve("O4")``) drives it through the policy-aware op layer.
- :mod:`apex_tpu.quant.int8` is the inference half (Dettmers et al.,
  *LLM.int8()*, 2022): symmetric per-channel int8 weight quantization
  plus the per-slot int8 KV-cache format the decode path reads
  (``kv_dtype="int8"`` in :func:`apex_tpu.models.generate.generate`
  and :class:`apex_tpu.serve.ServeConfig`) — decode is HBM-bound with
  kv_read at 69% of the ideal step (DECODE_DECOMPOSE_r01), so halving
  the cache bytes is a ~2x decode-ceiling lift.

Both regimes are machine-checked from day one: the precision-flow lint
(:mod:`apex_tpu.analysis.precision`) carries the fp8 contract
(delayed-scale placement, amax-history recording, no-double-quantize)
and ``tools/graph_lint.py`` runs O4 train lanes and the int8-KV decode
lane.  See ``docs/source/quantization.rst``.
"""

from apex_tpu.quant.fp8 import (  # noqa: F401
    FP8_E4M3,
    FP8_E5M2,
    DelayedScalingState,
    bwd_qdq,
    Fp8TrainState,
    delayed_scale,
    dequantize,
    fp8_max,
    init_delayed_scaling,
    init_train_state,
    qdq,
    qdq_ste,
    quantize,
    record_amax,
    rescale_events,
    scaled_matmul,
    step_saturation,
    tree_amax,
    update_train_state,
)
from apex_tpu.quant.int8 import (  # noqa: F401
    dequantize_int8,
    kv_dequant_scales,
    quantize_int8,
    quantize_kv,
)
