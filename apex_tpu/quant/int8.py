"""Symmetric int8 quantization: per-channel weights + the KV-cache
format the decode path reads.

Decode is HBM-bandwidth-bound (DECODE_DECOMPOSE_r01: kv_read is 69% of
the b8 step's modeled traffic), so the cache *dtype* is the ceiling
knob: int8 KV halves the bytes per cached token vs bf16 — a ~2x lift
of the decode roofline the bench's ``gpt_small_tpu_decode_kv8`` config
derives from this module's byte model through
:func:`apex_tpu.analysis.cost.roofline_expectation`.

Format (the LLM.int8()-style absmax scheme, Dettmers et al., 2022,
restricted to the symmetric per-vector case — no outlier
decomposition, which matters for *weights* feeding matmuls, not for
the attention cache):

- **weights**: per-output-channel symmetric absmax —
  ``q = round(w / s)`` with ``s = amax_channel / 127`` (f32 scales,
  one per channel along ``axis``);
- **KV cache**: per *token-slot* symmetric absmax — each cached token's
  ``(H, D)`` key (or value) vector quantizes with its own f32 scale,
  computed ON WRITE (one token, one reduction — this is dynamic
  quantization, correct here because each slot is written exactly
  once; the *delayed*-scale contract belongs to fp8 training where the
  same class is re-quantized every step).  The scale array rides next
  to the int8 pool (monolithic: ``(L, B, M)``; paged:
  ``(L, num_blocks, block_size)``) and dequantization FUSES into the
  attention read: the per-slot scale multiplies the (tiny) score /
  probability tensors instead of re-materializing a dequantized cache
  (see :func:`apex_tpu.models.generate._attn_cached`).

Rounding is ``jnp.rint`` (round-half-to-even) with a clip to
[-127, 127]; -128 is unused so the grid is symmetric and negation is
exact.  Everything is deterministic — the decode-path tests pin
bitwise-identical outputs across runs.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

#: symmetric int8 grid edge (|-128| is excluded on purpose)
INT8_MAX = 127.0


def quantize_int8(x: jax.Array, axis=None
                  ) -> Tuple[jax.Array, jax.Array]:
    """Symmetric absmax int8 quantization.

    ``axis=None`` is per-tensor; an int/tuple quantizes per-channel
    with the scale REDUCED OVER ``axis`` (so for a ``(K, N)`` weight
    quantized per output channel, pass ``axis=0`` and get ``(1, N)``
    scales).  Returns ``(q int8, scale f32)`` with
    ``x ≈ q * scale``; an all-zero vector gets scale 1 (and zeros)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis,
                   keepdims=axis is not None)
    scale = jnp.where(amax > 0.0, amax / INT8_MAX, 1.0)
    q = jnp.clip(jnp.rint(x.astype(jnp.float32) / scale),
                 -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_int8(q: jax.Array, scale: jax.Array,
                    dtype=jnp.float32) -> jax.Array:
    """``q * scale`` at ``dtype``."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def quantize_kv(kv: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Quantize a K or V write ``(..., H, D)`` with one scale per
    leading position — per token-slot absmax over the trailing two
    (head, dim) axes.  Returns ``(q int8 (..., H, D),
    scales f32 (...,))`` — the write-side half of the int8 KV format;
    the read side folds the scales into the attention math
    (:func:`kv_dequant_scales` documents the exactness argument)."""
    amax = jnp.max(jnp.abs(kv.astype(jnp.float32)), axis=(-2, -1))
    scale = jnp.where(amax > 0.0, amax / INT8_MAX, 1.0)
    q = jnp.clip(jnp.rint(kv.astype(jnp.float32) / scale[..., None, None]),
                 -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def kv_dequant_scales(scale: jax.Array) -> jax.Array:
    """The per-position dequant factors to fold into the attention
    read.  Because the scale is constant over the contracted ``(H, D)``
    axes, ``sum_d q[d]*s*x[d] == s * sum_d q[d]*x[d]`` EXACTLY in real
    arithmetic — dequantization commutes with the dot, so multiplying
    the per-position scores (K side) or probability weights (V side)
    by ``s`` is the fused form of dequantizing the cache.  (In float
    arithmetic the two orderings can differ in the last ulp; the decode
    tests bound the int8-vs-f32 error as a whole, and bitwise
    determinism is across RUNS of the same program, which this is.)"""
    return scale.astype(jnp.float32)
