"""apex_tpu.obs — unified runtime telemetry.

The paper's value proposition is *measured* mixed-precision speed;
this package is the measuring instrument, shared by every subsystem
instead of re-implemented inside each:

- :mod:`apex_tpu.obs.metrics` — process-local counters / gauges /
  fixed-bucket histograms whose device-valued updates resolve with
  **1-step lag** (zero host syncs on the step path — the resilience
  loop's trick promoted to the registry contract), with Prometheus-text
  and JSON export (the committed ``OBS_r01.json`` artifact);
- :mod:`apex_tpu.obs.spans` — structured, nesting trace spans layered
  on the :mod:`apex_tpu.utils.profiling` shims: named regions land in
  the HLO metadata *and* captured xplanes, and span wall-durations
  feed the registry's histograms;
- :mod:`apex_tpu.obs.xplane` — the xplane / chrome-trace parsing
  library (extracted from ``tools/profile_step.py``; all profile
  tools import it), with device-time aggregation, step markers, and
  named-bucket attribution for ``tools/profile_decode.py``;
- :mod:`apex_tpu.obs.reqtrace` — per-request lifecycle traces across
  the serving fleet (request ids minted at router admission, a closed
  host-side event vocabulary recorded at the existing step
  boundaries, chrome-trace export, and the committed ``TRACE_r*.json``
  artifact behind ``apex_tpu/analysis/trace.py``);
- :mod:`apex_tpu.obs.flight` — the incident flight recorder (a
  bounded ring of recent events + resolved metric snapshots that
  incident records ship as their validated ``flight`` field);
- :mod:`apex_tpu.obs.fleet` — fleet-level registry merging (counter
  sums, bucket-union histogram quantiles, per-replica gauge tables) —
  the ONE implementation ``bench.py`` and the serving tools share;
- :mod:`apex_tpu.obs.stepclass` — the shared compiled-HLO op
  classifiers (decode / serve-decode seven-bucket vocabulary, the
  pinned fwd/bwd/optimizer/collectives/host_gap train vocabulary) the
  offline profile tools AND the continuous profiler bucket through —
  one copy, so online and offline attribution can never disagree;
- :mod:`apex_tpu.obs.contprof` — the always-on continuous profiler
  (bounded sampled capture windows inside the serve/training loops,
  profiled steps excluded from the gated latency histograms) and the
  online :class:`~apex_tpu.obs.contprof.DriftSentinel` (K-consecutive
  out-of-band confirmation against a baseline under the PR-13 band
  rule; incident + flight note + ``serve_profile_drift`` gauge on
  confirmation) — the committed ``PROFILE_DRIFT_r*.json`` artifact
  behind ``apex_tpu/analysis/profile_drift.py``;
- :mod:`apex_tpu.obs.exposition` — the stdlib HTTP scrape target
  (``/metrics`` Prometheus text, ``/fleet`` merged view);
- :mod:`apex_tpu.obs.slo` — declarative SLO objectives over the live
  registry (decode p99, spec acceptance, block utilization) with
  windowed burn-rate evaluation riding the lag-resolved boundary —
  zero new host syncs; consumed by
  :class:`apex_tpu.serve.DisaggRouter` admission (a violating replica
  loses eligibility) and recorded into the SCENARIO / chaos-incident
  artifacts.

See ``docs/source/observability.rst`` for the metric catalog, the
lag-resolution contract, and the span naming convention.
"""

from apex_tpu.obs import contprof, exposition, fleet, slo, stepclass, xplane
from apex_tpu.obs.contprof import (
    ContinuousProfiler,
    ContProfConfig,
    DriftSentinel,
    serve_profiler,
    train_profiler,
)
from apex_tpu.obs.exposition import MetricsServer
from apex_tpu.obs.flight import FlightRecorder
from apex_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    LATENCY_BUCKETS,
    Registry,
    counter,
    gauge,
    get_registry,
    histogram,
    instrument_step,
)
from apex_tpu.obs.reqtrace import EVENT_KINDS, RequestTracer
from apex_tpu.obs.slo import SLObjective, SLOEvaluator, serve_objectives
from apex_tpu.obs.spans import current_path, span, traced_span

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "LATENCY_BUCKETS",
    "counter", "gauge", "histogram", "get_registry", "instrument_step",
    "span", "current_path", "traced_span",
    "EVENT_KINDS", "FlightRecorder", "RequestTracer",
    "SLObjective", "SLOEvaluator", "serve_objectives",
    "fleet", "slo", "xplane",
]
