"""Minimal stdlib HTTP exposition of the metrics registry.

The registry has exported Prometheus text since PR 7
(:meth:`~apex_tpu.obs.metrics.Registry.to_prometheus`) and the fleet
merge since PR 12 (:mod:`apex_tpu.obs.fleet`), but nothing LISTENED —
there was no scrape target a real Prometheus could point at.  This
module is that target, deliberately tiny: ``http.server`` on a
background thread, three endpoints, zero dependencies, zero touch of
the step path (a scrape reads the registry's RESOLVED state under its
own lock — never a device fetch, the same rule the incident snapshot
follows):

- ``/metrics`` — the primary registry's Prometheus text exposition;
- ``/fleet`` — the bucket-union merge of every attached registry
  (:func:`apex_tpu.obs.fleet.merge_registries`: counters sum,
  histograms union, gauges per-replica via ``gauge_table`` appended
  as ``# gauge-table`` comment lines) — what a fleet-level scrape of
  the disaggregated router's replicas reads;
- ``/healthz`` — liveness (``ok``).

``tools/obs_serve.py`` runs it as a command; the smoke test GETs
``http://127.0.0.1:<port>/metrics`` and asserts real instrument names
come back.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Sequence, Tuple

from apex_tpu.obs import fleet
from apex_tpu.obs import metrics as obs_metrics

__all__ = ["MetricsServer"]


class MetricsServer:
    """Serve one registry (and optionally a fleet of them) over HTTP.

    >>> srv = MetricsServer(registry=eng.metrics)
    >>> host, port = srv.start()          # port=0 picks a free one
    >>> ...                               # GET /metrics, /fleet
    >>> srv.stop()
    """

    def __init__(self,
                 registry: Optional[obs_metrics.Registry] = None,
                 fleet_registries: Optional[Dict[str, obs_metrics.Registry]]
                 = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.registry = registry if registry is not None \
            else obs_metrics.DEFAULT
        #: ``{label: registry}`` of the fleet view (``/fleet``); the
        #: primary registry is NOT implicitly included — the router
        #: passes its replicas' registries explicitly
        self.fleet_registries = dict(fleet_registries or {})
        self._host, self._port = host, port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- payloads ------------------------------------------------------

    def metrics_text(self) -> str:
        return self.registry.to_prometheus()

    def fleet_text(self) -> str:
        regs = list(self.fleet_registries.values())
        if not regs:
            return "# no fleet registries attached\n"
        merged = fleet.merge_registries(regs)
        text = merged.to_prometheus()
        table = fleet.gauge_table(regs,
                                  list(self.fleet_registries.keys()))
        lines = [f"# gauge-table {json.dumps({name: vals})}"
                 for name, vals in table.items()]
        return text + "".join(line + "\n" for line in lines)

    # -- the server ----------------------------------------------------

    def start(self) -> Tuple[str, int]:
        """Bind and serve on a daemon thread; returns ``(host,
        port)`` (the OS-assigned port when constructed with 0)."""
        if self._httpd is not None:
            raise RuntimeError("MetricsServer already started")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):                      # noqa: N802
                if self.path.split("?")[0] == "/metrics":
                    body, ctype = outer.metrics_text(), \
                        "text/plain; version=0.0.4"
                elif self.path.split("?")[0] == "/fleet":
                    body, ctype = outer.fleet_text(), \
                        "text/plain; version=0.0.4"
                elif self.path.split("?")[0] == "/healthz":
                    body, ctype = "ok\n", "text/plain"
                else:
                    self.send_error(404)
                    return
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):              # quiet server
                pass

        self._httpd = ThreadingHTTPServer((self._host, self._port),
                                          Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="apex-tpu-metrics-http")
        self._thread.start()
        return self._httpd.server_address[0], \
            self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._thread is not None:
                self._thread.join(timeout=2.0)
                self._thread = None
