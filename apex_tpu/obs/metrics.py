"""Process-local metrics registry: counters, gauges, fixed-bucket
histograms — with **1-step-lagged** resolution of device values.

The design constraint comes from the step path: a serving or training
loop that fetches a metric scalar the step it was produced inserts a
host sync exactly where the paper's speed lives.  The resilience loop
(PR 3) solved this privately — dispatch steps back-to-back, resolve
each step's metrics one step behind, by which point they are already
computed on an accelerator.  This module makes that the *registry's*
contract so every subsystem shares one implementation:

- instruments accept plain host numbers (applied immediately, ~dict-op
  cost) **or concrete ``jax.Array`` values** (appended to a pending
  queue, *no* ``device_get``);
- :meth:`Registry.tick` marks a step boundary; groups older than
  ``lag`` steps (default 1) become resolvable, and are fetched in
  **batches** of ``resolve_every`` groups (default 8) with a single
  ``device_get`` — so a deferred metric is at least ``lag`` and at
  most ``lag + resolve_every - 1`` steps stale, and the step path
  pays one amortized fetch of already-computed values instead of one
  sync point per step (even a lagged per-step ``device_get`` is a
  measurable pipeline serialization on a fast step);
- :meth:`Registry.flush` drains everything (end of run / incident
  snapshot time).

Passing a **tracer** (calling an instrument *inside* a jitted
function) is a hard error: it would leak the tracer and silently
record nothing.  Inside traced code use :mod:`apex_tpu.obs.spans`
(named scopes land in the HLO metadata instead); record metrics on the
step's *outputs*.

Histograms are fixed-bucket (device-friendly: an ``observe`` is a
``searchsorted``, never a growing reservoir) and quantiles are
interpolated from the cumulated bucket counts the way Prometheus's
``histogram_quantile`` does — ``bench.py`` and the serve engine read
p50/p99 through :meth:`Histogram.quantile` so the two can never
disagree on percentile math.

Exports: :meth:`Registry.snapshot` (JSON document — the ``export``
section of the committed ``OBS_r01.json``) and
:meth:`Registry.to_prometheus` (text exposition format).

This module itself imports no jax at module level — jax is touched
lazily, only to classify deferred values and to resolve them.  (The
``apex_tpu.obs`` package init does import jax via :mod:`.spans`, like
every other ``apex_tpu`` subpackage; the lazy imports here keep the
jax dependency confined to the two deferred-value code paths, not a
backend-isolation guarantee.)
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry",
    "DEFAULT", "get_registry", "counter", "gauge", "histogram",
    "instrument_step", "LATENCY_BUCKETS",
]

#: default histogram bucket upper bounds for step/span latencies in
#: SECONDS: geometric ladder 100 us .. ~26 s (factor 2), wide enough
#: for a 2.7 ms chip decode step and a CPU-smoke step alike; the +inf
#: overflow bucket is implicit.
LATENCY_BUCKETS = tuple(1e-4 * 2.0 ** i for i in range(19))


def _classify(value: Any) -> str:
    """``"host"`` | ``"deferred"``; raises on a tracer (recording a
    metric inside a traced function is a bug, not a deferral)."""
    if isinstance(value, (int, float, bool, np.generic, np.ndarray)):
        return "host"
    try:
        import jax
    except ImportError:          # jax-free process: everything is host
        return "host"
    if isinstance(value, jax.core.Tracer):
        raise TypeError(
            "metrics must be recorded on step OUTPUTS (concrete "
            "jax.Array values resolve with 1-step lag), never inside "
            "a traced function — use apex_tpu.obs.spans for named "
            "regions inside jit")
    if isinstance(value, jax.Array):
        return "deferred"
    return "host"


class _Instrument:
    """Base: a named instrument owned by one :class:`Registry`."""

    kind = "untyped"

    def __init__(self, registry: "Registry", name: str, help: str = ""):
        self._registry = registry
        self.name = name
        self.help = help

    def _record(self, value: Any) -> None:
        # fast path: plain host numbers are the per-step hot case (a
        # few of these per serving/training step — they must cost
        # microseconds, not numpy dispatch)
        if type(value) in (int, float, bool):
            with self._registry._lock:
                self._apply_scalar(float(value))
        elif _classify(value) == "deferred":
            self._registry._defer(self, value)
        else:
            with self._registry._lock:
                self._apply(value)

    def _apply_scalar(self, value: float) -> None:
        self._apply(value)

    def _apply(self, value: Any) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


class Counter(_Instrument):
    """Monotonic accumulator.  ``inc(v)`` adds ``v`` (default 1); a
    deferred array adds ``sum(asarray(v))`` once resolved — so
    ``inc(overflow_flag)`` counts a boolean step output and a
    per-scaler tuple stacked into one array counts every firing."""

    kind = "counter"

    def __init__(self, registry, name, help=""):
        super().__init__(registry, name, help)
        self.value = 0.0

    def inc(self, value: Any = 1.0) -> None:
        self._record(value)

    def _apply_scalar(self, value: float) -> None:
        self.value += value

    def _apply(self, value: Any) -> None:
        self.value += float(np.sum(np.asarray(value, dtype=np.float64)))


class Gauge(_Instrument):
    """Last-write-wins scalar.  A deferred array resolves to its mean
    (a scalar stays itself)."""

    kind = "gauge"

    def __init__(self, registry, name, help=""):
        super().__init__(registry, name, help)
        self.value = 0.0

    def set(self, value: Any) -> None:
        self._record(value)

    def _apply_scalar(self, value: float) -> None:
        self.value = value

    def _apply(self, value: Any) -> None:
        self.value = float(np.mean(np.asarray(value, dtype=np.float64)))


class Histogram(_Instrument):
    """Fixed-bucket histogram: ``buckets`` are sorted finite upper
    bounds; an implicit +inf bucket catches the overflow.  ``observe``
    accepts a scalar or an array (every element observed)."""

    kind = "histogram"

    def __init__(self, registry, name, help="",
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        super().__init__(registry, name, help)
        bounds = tuple(float(b) for b in buckets)
        if not bounds or list(bounds) != sorted(set(bounds)) or \
                not all(math.isfinite(b) for b in bounds):
            raise ValueError(
                f"histogram {name!r}: buckets must be strictly "
                f"increasing finite upper bounds, got {buckets!r}")
        self.bounds = bounds
        self.counts = np.zeros(len(bounds) + 1, np.int64)
        self.sum = 0.0
        self.count = 0
        self._max = -math.inf

    def observe(self, value: Any) -> None:
        self._record(value)

    def _apply_scalar(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, value)] += 1
        self.sum += value
        self.count += 1
        if value > self._max:
            self._max = value

    def _apply(self, value: Any) -> None:
        arr = np.asarray(value, dtype=np.float64).ravel()
        if arr.size == 0:
            return
        idx = np.searchsorted(self.bounds, arr, side="left")
        np.add.at(self.counts, idx, 1)
        self.sum += float(arr.sum())
        self.count += arr.size
        self._max = max(self._max, float(arr.max()))

    # -- read side ----------------------------------------------------

    def state(self) -> Tuple[np.ndarray, float, int, float]:
        """Opaque snapshot for windowed reads (``quantile(q,
        since=state)`` — how ``bench.py`` isolates one offered-load
        level on a long-lived engine)."""
        return (self.counts.copy(), self.sum, self.count, self._max)

    def quantile(self, q: float, since=None) -> float:
        """Prometheus-style ``histogram_quantile``: rank-interpolated
        within the owning bucket (lower edge 0 for the first bucket);
        observations in the +inf bucket interpolate toward the largest
        value seen.  ``nan`` when (the window holds) no observations."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        counts, _, total, hi_max = self.counts, self.sum, self.count, \
            self._max
        if since is not None:
            counts = counts - since[0]
            total = self.count - since[2]
            # the window's max is only known when it SET the running
            # max; otherwise a stale pre-window max (e.g. an excluded
            # compile step) must not stretch the overflow bucket —
            # fall back to the last finite bound
            if not self._max > since[3]:
                hi_max = -math.inf
        if total <= 0:
            return math.nan
        rank = q * total
        cum = np.cumsum(counts)
        i = int(np.searchsorted(cum, rank, side="left"))
        i = min(i, len(counts) - 1)
        lo = 0.0 if i == 0 else self.bounds[i - 1]
        hi = self.bounds[i] if i < len(self.bounds) else \
            (hi_max if math.isfinite(hi_max) else lo)
        in_bucket = counts[i]
        if in_bucket <= 0 or hi <= lo:
            return float(hi)
        prev = cum[i - 1] if i else 0
        frac = (rank - prev) / in_bucket
        return float(lo + (hi - lo) * min(max(frac, 0.0), 1.0))


class Registry:
    """A process-local instrument registry with lagged resolution (see
    the module docstring).  ``counter``/``gauge``/``histogram`` are
    get-or-create: asking twice for one name returns the same
    instrument; asking for it as a different kind is an error."""

    def __init__(self, lag: int = 1, resolve_every: int = 8):
        if lag < 0:
            raise ValueError(f"lag={lag}")
        if resolve_every < 1:
            raise ValueError(f"resolve_every={resolve_every}")
        self.lag = lag
        self.resolve_every = resolve_every
        self._lock = threading.RLock()
        self._resolve_lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}
        # sealed groups of (instrument, deferred value), oldest first
        self._pending: Deque[List[Tuple[_Instrument, Any]]] = deque()
        self._current: List[Tuple[_Instrument, Any]] = []

    # -- instrument creation ------------------------------------------

    def _get(self, cls, name: str, help: str, **kwargs) -> _Instrument:
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(self, name, help, **kwargs)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{inst.kind}, not {cls.kind}")
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS
                  ) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    # -- lagged resolution --------------------------------------------

    def _defer(self, instrument: _Instrument, value: Any) -> None:
        with self._lock:
            self._current.append((instrument, value))

    @property
    def pending_groups(self) -> int:
        """Sealed-but-unresolved groups (tests pin the lag contract)."""
        with self._lock:
            return len(self._pending) + (1 if self._current else 0)

    def tick(self) -> None:
        """Step boundary: seal the current deferred group; once
        ``resolve_every`` groups have aged past ``lag``, fetch them
        with one batched ``device_get`` (values at least one step
        behind dispatch are already computed on an accelerator, so
        the amortized fetch never stalls the pipeline)."""
        with self._lock:
            if self._current:
                self._pending.append(self._current)
                self._current = []
        self._drain(keep=self.lag, min_batch=self.resolve_every)

    def flush(self) -> None:
        """Resolve everything pending (end of run, incident capture)."""
        with self._lock:
            if self._current:
                self._pending.append(self._current)
                self._current = []
        self._drain(keep=0, min_batch=1)

    def discard_pending(self) -> None:
        """Drop unresolved deferred values (a rewind re-dispatches the
        steps whose metrics these were — resolving them would count the
        abandoned timeline)."""
        with self._lock:
            self._pending.clear()
            self._current = []

    def _drain(self, keep: int, min_batch: int) -> None:
        """Pop every group past the newest ``keep``, fetch, apply.
        ``_resolve_lock`` is held across pop-and-apply so concurrent
        resolvers (a loop's ``tick`` racing an exporter's ``flush``)
        apply batches in queue order — a stale loss must never
        overwrite a newer one.  The ``device_get`` happens OUTSIDE
        ``_lock`` (a fetch waiting on a wedged device must not block
        :meth:`snapshot` — the watchdog's incident capture reads the
        resolved state through that lock, and only that lock)."""
        with self._resolve_lock:
            with self._lock:
                ripe = len(self._pending) - keep
                if ripe < min_batch:
                    return
                entries = [e for _ in range(ripe)
                           for e in self._pending.popleft()]
            if not entries:
                return
            import jax
            values = jax.device_get([v for _, v in entries])
            with self._lock:
                for (inst, _), host in zip(entries, values):
                    inst._apply(host)

    # -- export --------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-serializable export of every instrument's *resolved*
        state (call :meth:`flush` first to include the lag window)."""
        out = []
        with self._lock:
            for name in sorted(self._instruments):
                inst = self._instruments[name]
                rec: dict = {"name": name, "type": inst.kind,
                             "help": inst.help}
                if isinstance(inst, Histogram):
                    rec["buckets"] = {
                        _fmt_le(b): int(c) for b, c in
                        zip(inst.bounds + (math.inf,),
                            np.cumsum(inst.counts).tolist())}
                    rec["sum"] = round(float(inst.sum), 9)
                    rec["count"] = int(inst.count)
                else:
                    rec["value"] = float(inst.value)
                out.append(rec)
        return {"metrics": out}

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (histograms as cumulative
        ``_bucket{le=...}`` series plus ``_sum``/``_count``)."""
        lines: List[str] = []
        with self._lock:
            for name in sorted(self._instruments):
                inst = self._instruments[name]
                if inst.help:
                    lines.append(f"# HELP {name} {inst.help}")
                lines.append(f"# TYPE {name} {inst.kind}")
                if isinstance(inst, Histogram):
                    cum = np.cumsum(inst.counts)
                    for b, c in zip(inst.bounds + (math.inf,), cum):
                        lines.append(
                            f'{name}_bucket{{le="{_fmt_le(b)}"}} '
                            f"{int(c)}")
                    lines.append(f"{name}_sum {_fmt_val(inst.sum)}")
                    lines.append(f"{name}_count {inst.count}")
                else:
                    lines.append(f"{name} {_fmt_val(inst.value)}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop every instrument and all pending values (tests)."""
        with self._lock:
            self._instruments.clear()
            self._pending.clear()
            self._current = []


def _fmt_le(bound: float) -> str:
    return "+Inf" if math.isinf(bound) else repr(round(bound, 12))


def _fmt_val(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(v)


#: the process-default registry every subsystem shares unless handed a
#: private one (tests isolate by constructing their own)
DEFAULT = Registry(lag=1)


def get_registry() -> Registry:
    return DEFAULT


def counter(name: str, help: str = "") -> Counter:
    return DEFAULT.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return DEFAULT.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
    return DEFAULT.histogram(name, help, buckets=buckets)


def instrument_step(step_fn: Callable, registry: Optional[Registry] = None,
                    name: str = "train") -> Callable:
    """Wrap a jitted ``step_fn(state, *args) -> (state, metrics)`` with
    zero-sync telemetry: per-call dispatch-latency histogram and step
    counter (host numbers, immediate), plus — when the returned
    ``metrics`` dict carries them — ``loss`` (gauge) and ``overflow``
    (counter) recorded as **deferred device values** and resolved with
    the registry's lag at each :meth:`Registry.tick`.

    The wrapper is strictly host-side: the traced program is untouched
    (the graph-lint syncs pass on an instrumented lane proves the
    point), and nothing in it forces a device fetch.
    ``run_resilient`` instruments itself — do not double-wrap a step
    you hand to the resilience loop.
    """
    reg = registry or DEFAULT
    hist = reg.histogram(f"{name}_step_dispatch_seconds",
                         "wall time to dispatch one step (host side; "
                         "not device latency)")
    steps = reg.counter(f"{name}_steps_total", "steps dispatched")
    loss_g = reg.gauge(f"{name}_loss", "last resolved loss (1-step lag)")
    over_c = reg.counter(f"{name}_overflows_total",
                         "loss-scale overflow skips (1-step lag)")
    # O4 fp8 regime telemetry (present only when the step's metrics
    # carry them — make_train_step under an fp8 policy): both are
    # step OUTPUTS recorded as deferred device values at the existing
    # lag-resolved point, so the instrumentation adds zero host syncs
    # (the graph-lint syncs pass on the O4 lane pins the program side)
    fp8_sat = reg.gauge(
        f"{name}_fp8_amax_saturation",
        "fp8 dynamic-range utilization of the worst tensor class "
        "(amax * delayed scale / fp8_max; >1 = clipped, 1-step lag)")
    fp8_resc = reg.counter(
        f"{name}_fp8_rescales_total",
        "fp8 overflow-to-rescale events: tensor classes whose delayed "
        "scale shrank after the step's amax roll (1-step lag)")

    def wrapped(state, *args, **kwargs):
        t0 = time.perf_counter()
        out = step_fn(state, *args, **kwargs)
        hist.observe(time.perf_counter() - t0)
        steps.inc()
        if isinstance(out, tuple) and len(out) == 2 \
                and isinstance(out[1], dict):
            m = out[1]
            if "loss" in m:
                loss_g.set(m["loss"])
            if "overflow" in m:
                over_c.inc(m["overflow"])
            if "fp8_amax_saturation" in m:
                fp8_sat.set(m["fp8_amax_saturation"])
            if "fp8_rescales" in m:
                fp8_resc.inc(m["fp8_rescales"])
        reg.tick()
        return out

    wrapped.__name__ = getattr(step_fn, "__name__", "step")
    wrapped.__wrapped__ = step_fn
    return wrapped
