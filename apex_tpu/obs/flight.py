"""Incident flight recorder: a bounded ring buffer of recent events.

The r02 wedge taught the repo to leave an incident ARTIFACT; PR 7
taught the artifact to embed a resolved metrics snapshot.  What both
still miss is *history*: a watchdog timeout or a divergence rewind
ships the final gauge values, not the sequence of events that led to
the wedge — the overflow storm's firings, the checkpoint that was
skipped, the reroute that overloaded the replica that then hung.  This
module is the black box: a fixed-capacity ring of host-side event
records that subsystems note into as they go, cheap enough to run
always (one dict + deque append per event, microseconds — the
``OBS_r02.json`` tracing lane gates the cost), and bounded so a
month-long run holds exactly the last ``capacity`` events when the
incident fires.

Consumers:

- :func:`apex_tpu.resilience.run_resilient` notes step resolutions,
  overflows, checkpoints, rewinds, watchdog firings and injected
  faults, and every incident it writes embeds the recorder's tail
  under the INCIDENT schema's optional validated ``flight`` field
  (:func:`apex_tpu.resilience.incidents.validate_incident`);
- :meth:`apex_tpu.serve.DisaggRouter.kill_replica` notes the kill and
  every reroute, and dumps the tail into a replica-death incident when
  ``RouterConfig.incident_path`` is set;
- ``tools/chaos_run.py`` asserts the dumped tail actually CONTAINS the
  injected fault's events (a flight recorder that misses the crash it
  flew through is schema-shaped noise).

Like the metrics fast path, ``note()`` takes **host values only** —
it is called at step boundaries where every scalar is already a plain
number; a device value belongs in the registry's lagged path, not
here.  :meth:`FlightRecorder.note_metrics` records a *resolved*
registry snapshot (compacted: counter/gauge values, histogram
count+sum) — never a device fetch, the same
watchdog-must-not-block-on-the-wedged-device rule the incident
``metrics`` field follows.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, Optional

__all__ = ["FlightRecorder"]


class FlightRecorder:
    """Bounded ring of ``{"ts", "kind", ...}`` event records (see the
    module docstring).  ``ts`` is seconds since the recorder's
    construction (monotonic — incident timelines need ordering and
    spacing, not wall-clock epochs)."""

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity={capacity}")
        self.capacity = capacity
        self.dropped = 0
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._events: Deque[Dict[str, Any]] = deque(maxlen=capacity)

    def note(self, kind: str, **data: Any) -> None:
        """Append one event (host values only; a full ring drops the
        oldest and counts it)."""
        if not kind:
            raise ValueError("flight event needs a non-empty kind")
        # per-event hot path (gated in OBS_r02's tracing lane): reuse
        # the **data dict instead of building a second one.  ts is
        # stamped INSIDE the lock — a concurrent noter (the watchdog
        # thread racing the main loop) must not append out of ts
        # order, which the incident schema's validator rejects
        data["kind"] = kind
        with self._lock:
            data["ts"] = round(time.perf_counter() - self._t0, 6)
            if len(self._events) == self.capacity:
                self.dropped += 1
            self._events.append(data)

    def note_metrics(self, registry) -> None:
        """Record a compact snapshot of the registry's RESOLVED state
        (counter/gauge values; histograms as count + sum) — one ring
        event, never a device fetch (call after a ``tick``/``flush``
        if the lag window matters)."""
        compact: Dict[str, Any] = {}
        for row in registry.snapshot()["metrics"]:
            if row["type"] == "histogram":
                compact[row["name"]] = {"count": row["count"],
                                        "sum": row["sum"]}
            else:
                compact[row["name"]] = row["value"]
        self.note("metrics", values=compact)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def dump(self) -> dict:
        """The black box's tail, in the INCIDENT ``flight`` shape:
        ``{"capacity", "dropped", "events": [...]}`` (events oldest
        first — the ring's surviving window)."""
        with self._lock:
            return {"capacity": self.capacity,
                    "dropped": int(self.dropped),
                    "events": [dict(e) for e in self._events]}
