"""Compiled-HLO op classifiers: ONE bucket vocabulary per loop kind.

Every profile consumer in this repo buckets measured op time through a
classifier built from the compiled HLO text — instruction name →
named bucket, shape/metadata markers deciding the bucket.  Until this
module the classifiers were private tool code: the decode shape
classifier lived inside ``tools/profile_decode.py`` and the train
tool (``tools/profile_step.py``) had no op-level vocabulary at all,
only raw ``hlo_category`` tables.  The continuous profiler
(:mod:`apex_tpu.obs.contprof`) runs the SAME bucketing online, inside
the serving and training loops — so the classifiers move here, behind
a library API the offline tools now import (private copies deleted,
behavior pinned by fixture tests — the PR-7 xplane treatment), and
the online profiler and the offline tools can never disagree about
what "kv_read" or "bwd" means.

Three classifiers, two vocabularies:

- :class:`DecodeStepClassifier` — the DECODE_PROFILE seven buckets
  (``param_read / kv_read / kv_write / attention / sampling /
  host_sync / other``) over the monolithic decode program's
  while-body (``tools/profile_decode.py``'s classifier, moved);
- :class:`ServeStepClassifier` — the same seven buckets over the
  serve engine's compiled continuous-batching decode step (whole
  program = one step; paged-pool shape markers, scatter writes);
- :class:`TrainStepClassifier` — the pinned train-step vocabulary
  :data:`TRAIN_BUCKETS` (``fwd / bwd / optimizer / collectives /
  host_gap / other``) from the instructions' ``op_name`` metadata
  scopes: jax AD stamps forward ops ``jvp(...)`` and backward ops
  ``transpose(jvp(...))``; the optimizer/scaler update runs under the
  overflow-skip ``cond`` and the ``amp_unscale`` scope; collectives
  classify by opcode.  ``host_gap`` is never returned by the
  classifier — it is the derived residual (measured step wall minus
  attributed op time) the profiler fills in.

Classifiers are plain callables (``clf(op_name) -> bucket | None``)
with a ``step_ops()`` set, exactly the contract
:func:`apex_tpu.obs.xplane.bucket_op_times` consumes.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Set

__all__ = [
    "TRAIN_BUCKETS", "DECODE_BUCKETS",
    "computations", "closure",
    "DecodeStepClassifier", "ServeStepClassifier",
    "TrainStepClassifier", "StepClassifier",
]

#: the decode bucket vocabulary — MUST equal
#: ``apex_tpu.analysis.decode_profile.BUCKETS`` (pinned by test; the
#: schema module stays stdlib-only and is loaded standalone by
#: gate_hygiene, so the tuple is duplicated, not imported).
DECODE_BUCKETS = ("param_read", "kv_read", "kv_write", "attention",
                  "sampling", "host_sync", "other")

#: the pinned train-step vocabulary — MUST equal
#: ``apex_tpu.analysis.profile_drift.TRAIN_BUCKETS`` (same
#: duplicated-and-pinned arrangement).  ``host_gap`` is the derived
#: wall-minus-ops residual, never a classification result.
TRAIN_BUCKETS = ("fwd", "bwd", "optimizer", "collectives", "host_gap",
                 "other")

_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+) = (.*)$")
_CALLS_RE = re.compile(
    r"(?:calls|body|condition|to_apply|branch_computations)="
    r"[{(]?%?([\w.\-]+)")
_CALLBACKS = ("python_cpu_callback", "python_gpu_callback",
              "python_tpu_callback", "tpu_host_callback", "infeed",
              "outfeed")
_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "collective-permute", "all-to-all",
                   "collective-broadcast")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')

#: ``op_name`` metadata scopes that mark the optimizer/scaler update
#: (the overflow-skip ``cond`` wrapping ``apply_gradients``, the amp
#: unscale, and the named optimizer kernels).
OPTIMIZER_SCOPES = ("cond", "amp_unscale", "adam", "lamb", "sgd",
                    "apply_grad", "optimizer", "larc", "novograd")


def computations(hlo: str) -> dict:
    """``{computation name: [body lines]}`` of an HLO text dump."""
    comps: dict = {}
    cur = None
    for raw in hlo.splitlines():
        s = raw.strip()
        if s.endswith("{") and " = " not in s and "(" in s:
            cur = s.split()[0].lstrip("%").split("(")[0]
            comps[cur] = []
        elif cur is not None:
            comps[cur].append(raw)
            if s == "}":
                cur = None
    return comps


def expand_refs(rest: str, comps: dict) -> str:
    """One instruction's classification text: the def line plus the
    body of every computation it references (``calls=`` fusions,
    ``to_apply=`` calls/reduces, conditional branches) — one level
    deep, which is where the op_name metadata and shape markers of a
    wrapped region live."""
    text = rest
    for m in _CALLS_RE.finditer(rest):
        body = comps.get(m.group(1))
        if body:
            text = text + "\n" + "\n".join(body)
    return text


def closure(comps: dict, roots) -> set:
    """Computation names reachable from ``roots`` through
    calls/body/condition/to_apply references."""
    seen = set()
    work = list(roots)
    while work:
        name = work.pop()
        if name in seen or name not in comps:
            continue
        seen.add(name)
        for raw in comps[name]:
            for m in _CALLS_RE.finditer(raw):
                work.append(m.group(1))
    return seen


class _ShapeBucketer:
    """Shared decode-bucket decision over shape markers (set by the
    concrete classifier): ``cache_full`` (the whole pool's type
    string), ``cache_slices`` (materialized per-request cache reads),
    vocab and context-length marks.  ``_write_ops`` names the write
    opcodes — ``dynamic-update-slice`` for the monolithic in-place
    cache, plus ``scatter`` for the paged pools."""

    cache_full: str = ""
    cache_slices: tuple = ()
    vocab_marks: tuple = ()
    m_marks: tuple = ()
    _write_ops = ("dynamic-update-slice",)

    buckets: Dict[str, Optional[str]]
    slice_copy_ops: Set[str]

    def _classify_comps(self, comps: dict, names) -> None:
        self.buckets = {}
        self.slice_copy_ops = set()
        for cname in names:
            for raw in comps.get(cname, ()):
                m = _DEF_RE.match(raw)
                if not m:
                    continue
                name, rest = m.groups()
                self.buckets[name] = self._bucket(
                    name, rest, expand_refs(rest, comps))

    def _bucket(self, name: str, defline: str, text: str):
        if any(cb in text for cb in _CALLBACKS):
            return "host_sync"
        if self.cache_full in text and \
                any(w in text for w in self._write_ops):
            return "kv_write"
        cacheish = self.cache_full in text or \
            any(cs in text for cs in self.cache_slices)
        dot = re.search(r"\bdot\(", text) is not None
        if cacheish:
            result_type = defline.split(" ")[0]
            if not dot and any(cs in result_type
                               for cs in self.cache_slices):
                # a materialized cache-slice-shaped RESULT with no
                # consuming dot in the same fusion: the slice-copy
                # candidate the decompose residual points at
                self.slice_copy_ops.add(name)
            return "kv_read"
        if dot or "convolution(" in text:
            return "param_read"
        if any(vm in text for vm in self.vocab_marks):
            if "gather(" in text:
                return "param_read"          # embedding-row gather
            return "sampling"
        if any(mm in text for mm in self.m_marks):
            return "attention"
        return None                          # -> "other"

    def step_ops(self) -> set:
        return set(self.buckets)

    def __call__(self, name: str):
        return self.buckets.get(name)


class DecodeStepClassifier(_ShapeBucketer):
    """instruction name -> bucket, for the MONOLITHIC decode
    program's while-body instructions, built from the compiled HLO
    text (moved verbatim from ``tools/profile_decode.py``; behavior
    pinned by the tool's CPU smoke + the fixture test).

    Shape markers (HLO type strings like ``bf16[12,8,2304,4,64]``):
    the full cache pool ``(L,B,M,H,D)``, a cache-slice
    materialization ``(B,M,H,D)`` (the DECODE_DECOMPOSE residual
    candidate — tracked separately as ``slice_copy`` evidence), the
    vocab dimension, and the context length M.  Classification mirrors
    the static walk's conventions: ops reading the cache feed
    ``kv_read``; cache writes ``kv_write``; weight-operand dots and
    the embedding gather ``param_read``; vocab-shaped non-dot ops
    ``sampling``; M-length score-chain tensors ``attention``."""

    def __init__(self, hlo: str, cfg, batch: int, m_ctx: int):
        L, H = cfg.num_layers, cfg.num_heads
        D = cfg.hidden_size // cfg.num_heads
        V = cfg.vocab_size
        self.cache_full = f"[{L},{batch},{m_ctx},{H},{D}]"
        self.cache_slices = (f"[{batch},{m_ctx},{H},{D}]",
                             f"[1,{batch},{m_ctx},{H},{D}]")
        self.vocab_marks = (f",{V}]", f"[{V},")
        self.m_marks = (f",{m_ctx},", f",{m_ctx}]")
        comps = computations(hlo)
        # the decode loop = while bodies whose closure touches the
        # cache pool (prefill has no full-pool operand)
        bodies = []
        for lines in comps.values():
            for raw in lines:
                if " while(" not in raw:
                    continue
                bm = re.search(r"body=%?([\w.\-]+)", raw)
                if bm:
                    bodies.append(bm.group(1))
        step_comps = set()
        for body in bodies:
            cl = closure(comps, [body])
            if any(self.cache_full in raw
                   for c in cl for raw in comps.get(c, [])):
                step_comps |= cl
        if not step_comps:
            raise RuntimeError(
                "no while body touching the KV cache pool "
                f"{self.cache_full} found — the compiled layout "
                "changed; update DecodeStepClassifier")
        self._classify_comps(comps, step_comps)


#: backwards-compatible name ``tools/profile_decode.py`` imported the
#: classifier under before the extraction.
StepClassifier = DecodeStepClassifier


class ServeStepClassifier(_ShapeBucketer):
    """instruction name -> DECODE bucket for the SERVE engine's
    compiled continuous-batching decode step.  The whole program IS
    one step (the engine dispatches it per generated token), so every
    computation is in scope — no while-body selection.  Markers come
    from the paged layout: the ``(L, num_blocks, bs, H, D)`` pools
    (``cache_full``), the page-table-gathered per-slot caches
    ``(S, M, H, D)`` (``cache_slices`` — a materialized gather is the
    paged analog of the monolithic slice copy), vocab and per-slot
    context-length marks.  Cache writes are paged SCATTERS, not
    dynamic-update-slices."""

    _write_ops = ("dynamic-update-slice", "scatter")

    def __init__(self, hlo: str, cfg, serve_cfg):
        L, H = cfg.num_layers, cfg.num_heads
        D = cfg.hidden_size // cfg.num_heads
        V = cfg.vocab_size
        S = serve_cfg.num_slots
        bs = serve_cfg.block_size
        nb = serve_cfg.num_blocks
        m = serve_cfg.max_blocks_per_slot * bs
        self.cache_full = f"[{L},{nb},{bs},{H},{D}]"
        self.cache_slices = (f"[{S},{m},{H},{D}]",
                             f"[1,{S},{m},{H},{D}]",
                             f"[{nb},{bs},{H},{D}]")
        self.vocab_marks = (f",{V}]", f"[{V},")
        self.m_marks = (f",{m},", f",{m}]")
        comps = computations(hlo)
        self._classify_comps(comps, list(comps))


class TrainStepClassifier:
    """instruction name -> TRAIN bucket for a compiled train step,
    from each instruction's ``op_name`` metadata scope (jax stamps
    the Python trace path into the HLO metadata):

    - opcode is a collective (all-reduce / all-gather / reduce-scatter
      / collective-permute / all-to-all) → ``collectives`` (checked
      FIRST: a gradient all-reduce sits inside ``transpose(jvp(``
      scopes but its cost story is the wire, not the backward math);
    - scope contains ``transpose(jvp(`` or ``vjp(`` → ``bwd`` (the AD
      transpose pass);
    - scope hits an optimizer marker (:data:`OPTIMIZER_SCOPES`: the
      overflow-skip ``cond`` wrapping ``apply_gradients``, the
      ``amp_unscale`` pass, named optimizer kernels) → ``optimizer``;
    - scope contains ``jvp(`` → ``fwd``;
    - anything else → ``None`` (→ ``other``).

    Fusions classify by their JOINED text (def line + called fused
    computation), so a fusion mixing forward and backward ops lands in
    ``bwd`` — the precedence is part of the pinned contract (fixture
    test).  ``host_gap`` is never returned: it is the derived
    wall-minus-attributed residual the profiler computes."""

    def __init__(self, hlo: str,
                 optimizer_scopes=OPTIMIZER_SCOPES):
        self._opt_res = [re.compile(r"(?:^|/)[^/]*" + re.escape(s))
                         for s in optimizer_scopes]
        comps = computations(hlo)
        self.buckets: Dict[str, Optional[str]] = {}
        for cname, lines in comps.items():
            for raw in lines:
                m = _DEF_RE.match(raw)
                if not m:
                    continue
                name, rest = m.groups()
                self.buckets[name] = self._bucket(
                    rest, expand_refs(rest, comps))

    def _bucket(self, defline: str, text: str) -> Optional[str]:
        if any(f" {op}(" in text or f" {op}-" in text
               or f"= {op}(" in text for op in _COLLECTIVE_OPS):
            return "collectives"
        scopes = _OPNAME_RE.findall(text)
        joined = "\n".join(scopes)
        if "transpose(jvp" in joined or "vjp(" in joined:
            return "bwd"
        if any(r.search(s) for s in scopes for r in self._opt_res):
            return "optimizer"
        if "jvp(" in joined:
            return "fwd"
        return None

    def step_ops(self) -> set:
        return set(self.buckets)

    def __call__(self, name: str):
        return self.buckets.get(name)
