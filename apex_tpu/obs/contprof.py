"""Always-on continuous profiler + online op-level drift sentinel.

Every profiling surface before this module was OFFLINE:
``tools/profile_decode.py`` / ``tools/profile_step.py`` judge a
capture after the fact, and the PR-13 timeline judges committed
artifacts across rounds.  The live fleet's only online signals were
scalar metrics and SLO burn rates — an op-level regression (a new
materialized copy, a fusion break, a collective gone sync) stayed
invisible until the next offline round.  This module is the runtime
half: bounded sampled captures in the serving/training loop itself,
bucketed through the SAME shared classifiers the offline tools use
(:mod:`apex_tpu.obs.stepclass`), compared online against a baseline
under the PR-13 statistical band rule, raising an incident the moment
a bucket drifts for ``k`` consecutive windows.

Two cooperating pieces:

- :class:`ContinuousProfiler` — every ``capture_every`` steps, wraps
  ``capture_steps`` consecutive step dispatches in one
  ``jax.profiler`` trace, parses the capture through the one shared
  :mod:`apex_tpu.obs.xplane` API (the XLA:CPU ``tf_XLA*`` fallback
  makes the whole pipeline tier-1-testable), buckets the step ops
  with the lane's classifier, and hands the window to the sentinel.
  Integration contract (the serve engine and ``run_resilient`` both
  follow it): the host loop calls :meth:`~ContinuousProfiler.
  step_begin` before a step dispatch and :meth:`~ContinuousProfiler.
  step_end` after — a ``True`` from ``step_begin`` means the step is
  inside a capture window and its latency must be EXCLUDED from the
  gated latency histogram (``serve_decode_step_seconds``), so SLO and
  latency gates never judge a profiled step.  Only ONE window can be
  open per process (``jax.profiler`` is process-global): a second
  profiler's due window is skipped and counted, never queued.  The
  compiled programs are untouched — everything here is host-side
  work at the existing step boundaries, and the window cost is gated
  (≤ :data:`~apex_tpu.analysis.obs.CONTPROF_BUDGET_PCT`% of the
  inter-capture step wall, the OBS_r03 ``contprof`` lane) with an
  auto-throttle that widens ``capture_every`` when a window runs
  over budget;

- :class:`DriftSentinel` — compares each window's bucket fractions
  and step wall against the baseline using the ONE sentinel rule in
  :mod:`apex_tpu.analysis.profile_drift` (band = variance-derived
  width when recorded, else the 0.03 default; out-of-band = a
  fraction moved more than ``band`` absolute, or the wall above
  ``baseline × (1 + band)``).  A drift is CONFIRMED only after ``k``
  consecutive out-of-band windows — never a single noisy one — and
  on confirmation the sentinel notes the flight recorder, writes a
  schema-valid incident naming the drifting bucket and the top
  offending ops, and flips the ``{name}_profile_drift`` gauge the
  SLO evaluator and the router's admission control consume.  The
  rule functions are imported from the stdlib schema module, so the
  live sentinel and the committed artifact's validator can never
  disagree.

Baselines: :func:`baseline_from_profile` builds one from the newest
committed ``DECODE_PROFILE_r*.json`` (the on-chip deployment story —
a stable device makes committed fractions directly comparable);
``baseline=None`` seeds from the session's own first clean window
(recorded as ``"first-window"`` — the CPU thread-summed captures'
cross-host spread makes a foreign-host baseline meaningless, which
``tools/continuous_profile.py`` documents in the artifact).
"""

from __future__ import annotations

import dataclasses
import math
import os
import shutil
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from apex_tpu.analysis.profile_drift import (
    DEFAULT_BAND,
    confirm_bucket,
    out_of_band,
)
from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.obs import xplane
from apex_tpu.obs.stepclass import (
    DECODE_BUCKETS,
    TRAIN_BUCKETS,
    ServeStepClassifier,
    TrainStepClassifier,
)

__all__ = ["ContProfConfig", "ContinuousProfiler", "DriftSentinel",
           "serve_profiler", "train_profiler", "baseline_from_profile",
           "drift_objective"]

#: one ``jax.profiler`` trace per process — a profiler whose window
#: comes due while another holds the capture SKIPS it (counted),
#: never queues behind it.
_capture_lock = threading.Lock()


@dataclasses.dataclass(frozen=True)
class ContProfConfig:
    """Cadence and bounds of the continuous profiler.

    ``capture_every`` steps between window STARTS (the auto-throttle
    can only widen it); ``capture_steps`` dispatches per window;
    ``warmup_steps`` skipped before the cadence counter starts (the
    compile step must never seed a baseline); ``phase`` offsets the
    cadence (per-replica staggering so fleet windows don't collide on
    the process-global tracer); ``max_overhead_pct`` is the
    auto-throttle budget (window cost as a percentage of the
    inter-capture step wall; ``None`` pins the cadence);
    ``max_windows`` stops capturing after N windows (scripted
    sessions/tests)."""

    capture_every: int = 256
    capture_steps: int = 2
    warmup_steps: int = 1
    phase: int = 0
    logdir: Optional[str] = None
    keep_top_ops: int = 5
    max_overhead_pct: Optional[float] = 1.0
    max_windows: Optional[int] = None

    def __post_init__(self):
        if self.capture_steps < 1:
            raise ValueError(f"capture_steps={self.capture_steps}")
        if self.capture_every <= self.capture_steps:
            raise ValueError(
                f"capture_every={self.capture_every} must exceed "
                f"capture_steps={self.capture_steps} — a window may "
                f"not overlap the next window's start")
        if self.phase < 0:
            raise ValueError(f"phase={self.phase}")


class DriftSentinel:
    """Online drift confirmation over profile windows (see the module
    docstring).  The observation machine is EXACTLY
    :func:`apex_tpu.analysis.profile_drift.replay_sentinel` run
    incrementally — the committed artifact's validator replays it
    over the recorded windows and must derive the same verdicts."""

    def __init__(self, baseline: Optional[dict] = None,
                 band: float = DEFAULT_BAND,
                 band_source: str = "default",
                 k: int = 2,
                 name: str = "serve",
                 registry: Optional[obs_metrics.Registry] = None,
                 flight: Optional[Any] = None,
                 incident_path: Optional[str] = None):
        if k < 2:
            raise ValueError(
                f"k={k}: a sentinel confirming on a single window "
                f"alarms on every noisy capture — k >= 2")
        if not 0.0 < band < 1.0:
            raise ValueError(f"band={band} outside (0, 1)")
        self.baseline = baseline
        self.band = float(band)
        self.band_source = band_source
        self.k = k
        self.name = name
        self.flight = flight
        self.incident_path = incident_path
        self.drifts: List[dict] = []
        self.incidents: List[dict] = []
        self._run: List[List[dict]] = []
        self._active = False
        self._gauge = None
        if registry is not None:
            self._gauge = registry.gauge(
                f"{name}_profile_drift",
                "1 = the continuous profiler confirmed an op-level "
                "drift (k consecutive out-of-band windows) that has "
                "not yet recovered; consumed by SLO objectives and "
                "router admission")
            self._gauge.set(0.0)

    @property
    def drifting(self) -> bool:
        """A confirmed drift that has not yet recovered (no fully
        in-band window since) — what router admission de-ranks on."""
        return self._active

    def observe(self, window: dict) -> dict:
        """Judge one window; annotates it with ``out_of_band`` and
        returns it.  On the ``k``-th consecutive out-of-band window,
        confirms the drift (incident + flight note + gauge)."""
        if self.baseline is None:
            # first clean window seeds the baseline: in-band by
            # construction, recorded so the artifact's replay agrees
            self.baseline = {"source": "first-window",
                             "fractions": dict(window["fractions"]),
                             "step_wall_s": window.get("step_wall_s")}
            window["out_of_band"] = []
            return window
        exc = out_of_band(window["fractions"],
                          window.get("step_wall_s"),
                          self.baseline, self.band)
        window["out_of_band"] = exc
        if not exc:
            self._run = []
            if self._active and self._gauge is not None:
                self._gauge.set(0.0)
            self._active = False
            return window
        self._run.append(exc)
        if not self._active and len(self._run) >= self.k:
            self._confirm(window)
        return window

    def _confirm(self, window: dict) -> None:
        bucket = confirm_bucket(self._run[-self.k:])
        top = [op for op in window.get("top_ops", ())
               if op.get("bucket") == bucket] or \
            list(window.get("top_ops", ()))[:3]
        drift = {"window": window["index"], "bucket": bucket,
                 "windows_out": len(self._run),
                 "band": self.band, "top_ops": top}
        self.drifts.append(drift)
        self._active = True
        if self._gauge is not None:
            self._gauge.set(1.0)
        if self.flight is not None:
            self.flight.note("profile_drift", name=self.name,
                             bucket=bucket, window=window["index"],
                             windows_out=len(self._run))
        self._write_incident(drift, window)

    def _write_incident(self, drift: dict, window: dict) -> None:
        # lazy import: resilience.loop imports apex_tpu.obs — a
        # module-level import here would be the cycle back
        from apex_tpu.resilience import incidents as incidents_lib
        summary = (
            f"continuous profiler confirmed an op-level drift on "
            f"{self.name!r}: bucket {drift['bucket']!r} out of band "
            f"({self.band} {self.band_source}) for "
            f"{drift['windows_out']} consecutive window(s)")
        evidence: List[Any] = [
            f"bucket {drift['bucket']} drifted at window "
            f"{drift['window']} (k={self.k})",
            {"excursions": self._run[-1],
             "baseline": self.baseline,
             "top_ops": drift["top_ops"]}]
        extra: Dict[str, Any] = {"drift": drift}
        if self.flight is not None:
            extra["flight"] = self.flight.dump()
        try:
            if self.incident_path:
                rec = incidents_lib.write_incident(
                    self.incident_path, "profile-drift", summary,
                    evidence, **extra)
            else:
                rec = incidents_lib.make_incident(
                    "profile-drift", summary, evidence, **extra)
            self.incidents.append(rec)
        except Exception:   # forensics must not kill the serving loop
            import traceback
            traceback.print_exc()


class ContinuousProfiler:
    """Sampled capture windows around a host loop's step dispatches
    (see the module docstring for the ``step_begin``/``step_end``
    integration contract)."""

    def __init__(self, buckets=DECODE_BUCKETS,
                 classifier_builder: Optional[Callable[[], Any]] = None,
                 config: Optional[ContProfConfig] = None,
                 sentinel: Optional[DriftSentinel] = None,
                 registry: Optional[obs_metrics.Registry] = None,
                 name: str = "serve"):
        self.config = config or ContProfConfig()
        self.buckets = tuple(buckets)
        self.sentinel = sentinel
        self.name = name
        self._builder = classifier_builder
        self._clf = None
        self._clf_error: Optional[str] = None
        self.classifier_build_s = 0.0
        #: clean windows, in capture order (what the sentinel judged)
        self.windows: List[dict] = []
        #: windows discarded before the sentinel (a prefill/admission
        #: dispatch contaminated the capture — its identically-named
        #: ops would misattribute time)
        self.discarded: List[dict] = []
        self.skipped_windows = 0
        self._step = 0
        self._in_window = False
        self._owns_capture = False
        self._win_walls: List[float] = []
        self._win_start_step = 0
        self._open_marker = None
        self._capture_t0 = 0.0
        self._logdir = None
        self.effective_every = self.config.capture_every
        #: the step index the next window may open at, RELATIVE to
        #: the last window start/skip/suppression — never an absolute
        #: cadence grid, so a throttle-widened interval (or a skipped
        #: or suppressed window) always buys the FULL new interval
        #: before the next capture
        self._next_start = self.config.warmup_steps + 1 \
            + self.config.phase
        self._m_windows = None
        self._m_skipped = None
        if registry is not None:
            self._m_windows = registry.counter(
                f"{name}_profile_windows_total",
                "continuous-profiler capture windows parsed")
            self._m_skipped = registry.counter(
                f"{name}_profile_windows_skipped_total",
                "due windows skipped because another profiler held "
                "the process-global capture")

    # -- classifier ----------------------------------------------------

    @property
    def has_classifier_builder(self) -> bool:
        """True when a classifier source exists — a builder still
        pending, a classifier already built, or a build that failed
        and was recorded.  The loop integrations use this to supply a
        builder exactly once (the builder reference is dropped after
        the one build, so its closure never outlives the window that
        consumed it)."""
        return (self._builder is not None or self._clf is not None
                or self._clf_error is not None)

    def set_classifier_builder(self, builder: Callable[[], Any]) -> None:
        self._builder = builder

    def _classifier(self):
        if self._clf is None and self._clf_error is None \
                and self._builder is not None:
            t0 = time.perf_counter()
            try:
                self._clf = self._builder()
            except Exception as e:  # noqa: BLE001 — profiling must
                # degrade, not kill the loop it watches
                self._clf_error = f"{type(e).__name__}: {e}"[:200]
            finally:
                # one build per profiler: drop the closure so
                # anything it captured is released
                self._builder = None
            self.classifier_build_s = round(
                time.perf_counter() - t0, 4)
        return self._clf

    # -- the step hooks ------------------------------------------------

    @property
    def in_window(self) -> bool:
        return self._in_window

    def _window_due(self) -> bool:
        cfg = self.config
        if cfg.max_windows is not None and \
                len(self.windows) + len(self.discarded) >= \
                cfg.max_windows:
            return False
        return self._step >= self._next_start

    def step_begin(self, marker: Any = None) -> bool:
        """Called before a step dispatch; True = this step is inside
        a capture window (EXCLUDE its latency from gated histograms).
        ``marker`` is an opaque contamination cursor (the engine's
        admission-dispatch count): the window is discarded when it
        moved between open and close."""
        self._step += 1
        if self._in_window:
            return True
        if self._step <= self.config.warmup_steps or \
                not self._window_due():
            return False
        if not _capture_lock.acquire(blocking=False):
            self.skipped_windows += 1
            if self._m_skipped is not None:
                self._m_skipped.inc()
            # a full interval before the next attempt — skipped,
            # never queued behind the holder
            self._next_start = self._step + self.effective_every
            return False
        self._owns_capture = True
        if self.config.logdir is not None:
            # a FIXED logdir must be cleared of the previous window's
            # capture before the trace writes the next one
            self._logdir = self.config.logdir
            shutil.rmtree(self._logdir, ignore_errors=True)
        else:
            self._logdir = tempfile.mkdtemp(
                prefix="apex_tpu_contprof_")
        self._capture_t0 = time.perf_counter()
        import jax
        jax.profiler.start_trace(self._logdir)
        self._in_window = True
        self._win_walls = []
        self._win_start_step = self._step
        # ``capture_every`` steps between window STARTS (the throttle
        # pushes this further out when the window runs over budget)
        self._next_start = self._step + self.effective_every
        self._open_marker = marker
        return True

    def step_end(self, wall_s: float, marker: Any = None,
                 block_on: Any = None) -> Optional[dict]:
        """Called after a step dispatch with its wall seconds; closes
        the window (stop trace → parse → bucket → sentinel) on the
        ``capture_steps``-th step and returns the window record."""
        if not self._in_window:
            return None
        self._win_walls.append(float(wall_s))
        if len(self._win_walls) < self.config.capture_steps:
            return None
        return self._close_window(marker, block_on)

    def abort_window(self) -> None:
        """Abort an open capture window without judging it (the loop
        drained or stopped mid-window): stop the process-global
        trace, release ownership, discard the partial capture.  The
        engines' ``run()`` and ``run_resilient``'s exit path call
        this so a half-open window can never leak the tracer into
        the next loop."""
        if not self._in_window:
            return
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        self._release()
        self._in_window = False
        if self._logdir:
            shutil.rmtree(self._logdir, ignore_errors=True)

    def suppress(self) -> None:
        """Abort any open window and restart the cadence from here —
        the rewind path: a loop re-dispatching an abandoned timeline
        must not feed the sentinel a half-rewound capture.  A full
        interval must elapse before the next window opens."""
        self.abort_window()
        self._next_start = self._step + self.effective_every

    def _release(self) -> None:
        if self._owns_capture:
            self._owns_capture = False
            _capture_lock.release()

    def _close_window(self, marker: Any, block_on: Any) -> dict:
        # profiling must degrade, not kill the loop it watches: a
        # failing stop/parse becomes a discarded window — and the
        # process-global lock is ALWAYS released, or every later
        # step would be misrouted into the profiled histogram
        import jax
        stop_err = None
        try:
            if block_on is not None:
                jax.block_until_ready(block_on)
        except Exception as e:  # noqa: BLE001
            stop_err = e
        try:
            # ALWAYS attempted, even after a failed block: a trace
            # left open would poison the process-global tracer
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            stop_err = stop_err or e
        if stop_err is not None:
            self._release()
            self._in_window = False
            if self._logdir and self.config.logdir is None:
                shutil.rmtree(self._logdir, ignore_errors=True)
            window = {"index": len(self.windows) + len(self.discarded),
                      "start_step": self._win_start_step,
                      "steps": len(self._win_walls),
                      "discarded": f"capture stop failed: "
                                   f"{type(stop_err).__name__}: "
                                   f"{stop_err}"[:200]}
            self.discarded.append(window)
            return window
        self._release()
        self._in_window = False
        capture_s = time.perf_counter() - self._capture_t0
        t1 = time.perf_counter()
        try:
            window = self._parse_window()
        except Exception as e:  # noqa: BLE001 — a corrupt/empty
            # capture dir must not propagate into the hot loop
            if self._logdir and self.config.logdir is None:
                shutil.rmtree(self._logdir, ignore_errors=True)
            window = {"index": len(self.windows) + len(self.discarded),
                      "start_step": self._win_start_step,
                      "steps": len(self._win_walls),
                      "discarded": f"capture parse failed: "
                                   f"{type(e).__name__}: {e}"[:200]}
            self.discarded.append(window)
            return window
        window["capture_s"] = round(capture_s, 6)
        parse_s = time.perf_counter() - t1
        window["parse_s"] = round(parse_s, 6)
        if self._logdir and self.config.logdir is None:
            shutil.rmtree(self._logdir, ignore_errors=True)
        clean = marker == self._open_marker
        if not clean:
            window["discarded"] = "admission/prefill dispatch inside " \
                "the capture window (identically-named ops would " \
                "misattribute time)"
            self.discarded.append(window)
        else:
            t2 = time.perf_counter()
            if self.sentinel is not None:
                self.sentinel.observe(window)
            window["sentinel_s"] = round(time.perf_counter() - t2, 6)
            self.windows.append(window)
            if self._m_windows is not None:
                self._m_windows.inc()
        self._throttle(window)
        return window

    def _parse_window(self) -> dict:
        times = xplane.op_times(self._logdir)
        clf = self._classifier()
        walls = self._win_walls
        step_wall = sum(walls) / max(len(walls), 1)
        window: dict = {
            "index": len(self.windows) + len(self.discarded),
            "start_step": self._win_start_step,
            "steps": len(walls),
            "step_wall_s": round(step_wall, 6),
            "total_ps": int(times.total_ps),
            "source": times.source,
        }
        if clf is None:
            # degraded mode (no classifier): everything lands in
            # "other"; the sentinel still watches the step wall
            window["fractions"] = {b: 0.0 for b in self.buckets}
            window["fractions"]["other"] = 1.0 if times.total_ps else 0.0
            window["matched_frac"] = 0.0
            window["top_ops"] = []
            if self._clf_error:
                window["classifier_error"] = self._clf_error
            return window
        step_ops = clf.step_ops()
        step_times = {n: ps for n, ps in times.by_op.items()
                      if n in step_ops}
        step_times = self._seed(step_times, clf)
        named = [b for b in self.buckets if b not in ("other",
                                                      "host_gap")]
        table = xplane.bucket_op_times(step_times, clf, buckets=named)
        bucket_ps = dict(table["bucket_ps"])
        total = table["total_ps"]
        if "host_gap" in self.buckets:
            # the derived residual: measured wall not attributed to
            # any device op (thread-summed CPU captures can exceed
            # wall — clamp at zero)
            gap = max(0, int(sum(walls) * 1e12) - total)
            bucket_ps["host_gap"] = gap
            total += gap
        window["fractions"] = {
            b: round(bucket_ps.get(b, 0) / total, 4) if total else 0.0
            for b in self.buckets}
        window["matched_frac"] = round(
            table["matched_ps"] / max(table["total_ps"], 1), 4)
        top = sorted(step_times.items(), key=lambda kv: -kv[1])
        window["top_ops"] = [
            {"op": n, "ps": int(ps), "bucket": clf(n) or "other"}
            for n, ps in top[:self.config.keep_top_ops]]
        return window

    def _seed(self, step_times: dict, clf) -> dict:
        """Hook for the scripted seeded-regression session
        (``tools/continuous_profile.py`` overrides it to inflate one
        bucket's measured op times); identity in production."""
        return step_times

    def _throttle(self, window: dict) -> None:
        budget = self.config.max_overhead_pct
        if budget is None:
            return
        cost = window.get("capture_s", 0.0) + \
            window.get("parse_s", 0.0) + window.get("sentinel_s", 0.0)
        wall = window.get("step_wall_s") or 0.0
        if wall <= 0 or cost <= 0:
            return
        needed = int(math.ceil(cost / (budget / 100.0 * wall)))
        if needed > self.effective_every:
            self.effective_every = needed
            # re-anchor off the window that just proved the wider
            # interval is needed — the next start must sit the FULL
            # new interval after this window's start, not at the next
            # multiple of an absolute grid
            self._next_start = max(self._next_start,
                                   self._win_start_step + needed)
            window["throttled_to"] = needed


# ---------------------------------------------------------------------------
# integration factories
# ---------------------------------------------------------------------------

def serve_classifier_builder(engine) -> Callable[[], Any]:
    """A lazy :class:`~apex_tpu.obs.stepclass.ServeStepClassifier`
    builder over one engine's OWN compiled step: the jit is lowered
    with the live carry's shapes via the engine's
    ``decode_step_args()`` — same program, same instruction names as
    the executed capture (the lowering never executes, so the donated
    carry is untouched).  A speculative engine classifies against its
    VERIFY program instead (the target model's per-round work — the
    plain decode step is compiled but never dispatched there); draft
    ops land in ``other``."""
    def build():
        args = engine.decode_step_args()
        step = engine._decode_step
        if hasattr(engine, "_verify_step"):
            import jax.numpy as jnp
            proposals = jnp.zeros(
                (engine.scfg.num_slots, engine.spec.k), jnp.int32)
            args = args[:3] + (proposals,) + args[3:]
            step = engine._verify_step
        txt = step.lower(*args).compile().as_text()
        return ServeStepClassifier(txt, engine.cfg, engine.scfg)

    return build


def serve_profiler(engine,
                   config: Optional[ContProfConfig] = None,
                   sentinel: Optional[DriftSentinel] = None,
                   attach: bool = True) -> ContinuousProfiler:
    """A decode-vocabulary profiler for one
    :class:`~apex_tpu.serve.engine.ServeEngine`
    (:func:`serve_classifier_builder` supplies the classifier).
    ``attach=True`` sets ``engine.profiler`` so the engine's
    ``step()`` drives the hooks and excludes profiled steps from
    ``serve_decode_step_seconds``."""
    prof = ContinuousProfiler(
        buckets=DECODE_BUCKETS,
        classifier_builder=serve_classifier_builder(engine),
        config=config, sentinel=sentinel, registry=engine.metrics,
        name="serve")
    if attach:
        engine.profiler = prof
    return prof


def train_profiler(config: Optional[ContProfConfig] = None,
                   sentinel: Optional[DriftSentinel] = None,
                   registry: Optional[obs_metrics.Registry] = None,
                   ) -> ContinuousProfiler:
    """A train-vocabulary profiler for :func:`apex_tpu.resilience.
    run_resilient` (pass it as ``profiler=``): the loop supplies the
    classifier builder from its own jitted step on first dispatch
    (:func:`train_classifier_builder`), captures are suppressed
    across rewinds, and the sentinel (when given) gates on the
    fwd/bwd/optimizer/collectives/host_gap vocabulary."""
    return ContinuousProfiler(
        buckets=TRAIN_BUCKETS, classifier_builder=None, config=config,
        sentinel=sentinel, registry=registry, name="train")


def train_classifier_builder(step_fn, state, batch) -> Callable[[], Any]:
    """A lazy :class:`~apex_tpu.obs.stepclass.TrainStepClassifier`
    builder over a jitted step's compiled HLO.  Only
    ``jax.ShapeDtypeStruct`` avals of the given state/batch are
    captured (``lower()`` needs shapes alone, and the build may run
    hundreds of steps later — closing over the live arrays would pin
    a full copy of params + optimizer state until then).  A step that
    cannot be lowered (not a jit) degrades to the all-``other``
    window."""
    import jax

    def _aval(x):
        if not (hasattr(x, "shape") and hasattr(x, "dtype")):
            import jax.numpy as jnp
            x = jnp.asarray(x)
        return jax.ShapeDtypeStruct(x.shape, x.dtype)

    avals = jax.tree_util.tree_map(_aval, (state, tuple(batch)))

    def build():
        state_av, batch_av = avals
        txt = step_fn.lower(state_av, *batch_av).compile().as_text()
        return TrainStepClassifier(txt)
    return build


def baseline_from_profile(doc: dict) -> dict:
    """A sentinel baseline from a committed ``DECODE_PROFILE_r*.json``
    document: the on-chip story, where a stable device makes the
    committed fractions directly comparable window-to-window.  (On
    CPU the thread-summed fractions spread ~10 percentage points
    ACROSS hosts — ``tools/continuous_profile.py`` self-baselines and
    records the committed document as a cross-reference instead.)"""
    return {"source": "DECODE_PROFILE",
            "fractions": dict(doc.get("device_time_fractions") or {}),
            "step_wall_s": None}


def drift_objective(name: str = "serve"):
    """An :class:`apex_tpu.obs.slo.SLObjective` over the sentinel's
    ``{name}_profile_drift`` gauge — wire it into
    ``RouterConfig.slo`` and a drift-confirmed replica loses
    admission eligibility until its windows recover."""
    from apex_tpu.obs.slo import SLObjective
    return SLObjective(
        name=f"{name}_no_profile_drift", kind="gauge",
        metric=f"{name}_profile_drift", threshold=0.5, op="le",
        window=4, min_count=1)
