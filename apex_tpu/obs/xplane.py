"""xplane / chrome-trace attribution library.

One parser for every profile-reading tool in the repo.  Three tools
(``tools/profile_step.py``, ``tools/conv_attrib.py``,
``tools/fusion_roofline.py``) each carried a copy of the xplane
protobuf walk; this module is that walk extracted behind a library API
so the "profile one step and act on the top hotspot" loop —
and now ``tools/profile_decode.py``'s bucketed decode attribution —
share one implementation whose behavior is pinned by a fixture test.

Sources, in preference order:

1. **xplane protobuf** (``*.xplane.pb`` via the tensorflow/tsl proto):
   complete op-level events.  Device planes (``/device:...`` — TPU,
   GPU) aggregate the ``"XLA Ops"`` line; when a capture has *no*
   device plane (XLA:CPU), the host plane's ``tf_XLA*`` executor lines
   carry the per-HLO-instruction events instead and are harvested with
   the infrastructure events (``Thing::Method`` names) filtered out —
   that CPU path is what makes a tier-1 profile smoke possible at all.
2. **chrome-trace JSON** (``*.trace.json.gz``): lossy fallback when
   the proto is not importable — op-level events can be missing for
   large programs (ADVICE r2); same plane/line filter.

Durations are picoseconds throughout (the xplane unit; the JSON
fallback converts).

API:

- :func:`load_planes` — raw ``XPlane`` protos of a capture;
- :func:`op_times` / :func:`parse_xplane` — device time aggregated by
  op name and by ``hlo_category``;
- :func:`step_markers` — the device ``"Steps"`` line's spans (empty on
  hosts that don't emit step markers, e.g. XLA:CPU);
- :func:`bucket_op_times` — fold an op-time table into named buckets
  through a classifier (the DECODE_PROFILE bucketing).
"""

from __future__ import annotations

import collections
import dataclasses
import glob
import gzip
import json
import sys
from typing import Callable, Counter as TCounter, Dict, List, Optional

__all__ = ["OpTimes", "load_planes", "op_times", "parse_xplane",
           "parse_trace_json", "step_markers", "bucket_op_times"]


@dataclasses.dataclass
class OpTimes:
    """Aggregated device time of one capture (picoseconds)."""

    by_op: TCounter[str]
    by_category: TCounter[str]
    total_ps: int
    source: str                 # xplane-device | xplane-host | trace-json


def _xplane_pb2():
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
        return xplane_pb2
    except ImportError:
        return None


def load_planes(logdir: str) -> List[object]:
    """Every ``XPlane`` proto under ``logdir`` (all ``*.xplane.pb``
    files); ``[]`` when the tsl proto is unavailable."""
    pb2 = _xplane_pb2()
    if pb2 is None:
        return []
    planes = []
    for path in glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True):
        xs = pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        planes.extend(xs.planes)
    return planes


def _short(name: str) -> str:
    """Strip an ``%op = type{layout} ...`` HLO dump down to the op name
    (device-plane event names are full dumps; host-line names are
    already short)."""
    return name.split(" = ")[0].lstrip("%")


def _hlo_category_id(plane):
    """The plane's ``hlo_category`` stat-metadata id, found ONCE per
    plane (scanning per event would be O(events x stat table))."""
    return next((k for k, v in plane.stat_metadata.items()
                 if v.name == "hlo_category"), None)


def _category_of(plane, ev, cat_id) -> str:
    if cat_id is None:
        return "?"
    smeta = plane.stat_metadata
    emeta = plane.event_metadata[ev.metadata_id]
    for st in list(ev.stats) + list(emeta.stats):
        if st.metadata_id != cat_id:
            continue
        which = st.WhichOneof("value")
        val = getattr(st, which)
        return smeta[val].name if which == "ref_value" else str(val)
    return "?"


def _host_xla_event(name: str) -> bool:
    """Keep HLO-instruction events on the host ``tf_XLA*`` lines;
    drop the executor infrastructure (``ThreadpoolListener::...``,
    ``ThunkExecutor::... (…)``)."""
    return "::" not in name and " " not in name and bool(name)


def op_times(logdir: str) -> OpTimes:
    """Aggregate one capture's XLA-op device time by op and category.
    Prefers device planes' ``"XLA Ops"`` lines; falls back to the host
    plane's ``tf_XLA*`` executor lines (XLA:CPU captures), then to the
    lossy chrome-trace JSON (no tsl proto)."""
    planes = load_planes(logdir)
    if not planes:
        if _xplane_pb2() is None:
            # the historical profile_step warning: the JSON export is
            # LOSSY (op events can be missing for large programs) —
            # a silent fallback would print confident tables off an
            # incomplete capture
            print("warning: xplane proto unavailable; falling back to "
                  "the lossy chrome-trace JSON parser (install "
                  "tensorflow for the complete tsl xplane protobuf "
                  "path)", file=sys.stderr)
        by_op, by_cat, total = parse_trace_json(logdir)
        return OpTimes(by_op, by_cat, total, "trace-json")
    by_op: TCounter[str] = collections.Counter()
    by_cat: TCounter[str] = collections.Counter()
    total = 0
    for plane in planes:
        if not plane.name.startswith("/device:"):
            continue
        emeta = plane.event_metadata
        cat_id = _hlo_category_id(plane)
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                d = ev.duration_ps
                by_op[_short(emeta[ev.metadata_id].name)] += d
                by_cat[_category_of(plane, ev, cat_id)] += d
                total += d
    if total:
        return OpTimes(by_op, by_cat, total, "xplane-device")
    # XLA:CPU: no device plane exists — the per-instruction events live
    # on the host plane's executor threadpool lines
    for plane in planes:
        if not plane.name.startswith("/host:"):
            continue
        emeta = plane.event_metadata
        cat_id = _hlo_category_id(plane)
        for line in plane.lines:
            if not line.name.startswith("tf_XLA"):
                continue
            for ev in line.events:
                name = emeta[ev.metadata_id].name
                if not _host_xla_event(name):
                    continue
                d = ev.duration_ps
                by_op[_short(name)] += d
                by_cat[_category_of(plane, ev, cat_id)] += d
                total += d
    return OpTimes(by_op, by_cat, total, "xplane-host")


def parse_xplane(logdir: str):
    """Compatibility shape of :func:`op_times`:
    ``(by_name, by_category, total_ps)`` — the signature the three
    profile tools historically carried as private copies."""
    t = op_times(logdir)
    return t.by_op, t.by_category, t.total_ps


def parse_trace_json(logdir: str):
    """Lossy fallback: aggregate the chrome-trace JSON export
    (op-level events can be missing for large programs — prefer the
    xplane).  Filters to the device planes' ``"XLA Ops"`` line via the
    metadata events, falling back to host ``tf_XLA*`` threads when no
    device thread produced anything, mirroring :func:`op_times`."""
    by_name: TCounter[str] = collections.Counter()
    by_cat: TCounter[str] = collections.Counter()
    total = 0
    host_rows = []
    for path in glob.glob(f"{logdir}/**/*.trace.json.gz", recursive=True):
        with gzip.open(path, "rt") as f:
            trace = json.loads(f.read())
        events = trace.get("traceEvents", [])
        proc: Dict[object, str] = {}
        thread: Dict[tuple, str] = {}
        for ev in events:
            if ev.get("ph") != "M":
                continue
            name = ev.get("args", {}).get("name", "")
            if ev.get("name") == "process_name":
                proc[ev.get("pid")] = name
            elif ev.get("name") == "thread_name":
                thread[(ev.get("pid"), ev.get("tid"))] = name
        for ev in events:
            if ev.get("ph") != "X" or "dur" not in ev:
                continue
            pname = proc.get(ev.get("pid"), "")
            tname = thread.get((ev.get("pid"), ev.get("tid")), "")
            d = int(ev["dur"] * 1e6)            # us -> ps, match xplane
            name = _short(ev.get("name", "?"))
            cat = ev.get("args", {}).get("hlo_category", "?")
            if pname.startswith("/device:") and tname == "XLA Ops":
                by_name[name] += d
                by_cat[cat] += d
                total += d
            elif pname.startswith("/host:") and \
                    tname.startswith("tf_XLA") and _host_xla_event(name):
                host_rows.append((name, cat, d))
    if not total and host_rows:
        for name, cat, d in host_rows:
            by_name[name] += d
            by_cat[cat] += d
            total += d
    return by_name, by_cat, total


def step_markers(logdir: str) -> List[dict]:
    """The device plane's ``"Steps"`` line as
    ``[{"name", "start_ps", "duration_ps"}]`` (step-marker bucketing:
    slice an op-level analysis to one step's window).  Empty when the
    backend emits no step line (XLA:CPU) or no proto is available."""
    out = []
    for plane in load_planes(logdir):
        if not plane.name.startswith("/device:"):
            continue
        emeta = plane.event_metadata
        for line in plane.lines:
            if line.name != "Steps":
                continue
            for ev in line.events:
                out.append({"name": emeta[ev.metadata_id].name,
                            "start_ps": ev.offset_ps,
                            "duration_ps": ev.duration_ps})
    out.sort(key=lambda r: r["start_ps"])
    return out


def bucket_op_times(by_op: Dict[str, int],
                    classify: Callable[[str], Optional[str]],
                    buckets: Optional[List[str]] = None) -> dict:
    """Fold an op→ps table into named buckets: ``classify(op_name)``
    returns a bucket name or ``None`` (→ ``"other"``).  Returns
    ``{"bucket_ps": {...}, "total_ps": n, "matched_ps": n,
    "fractions": {...}}`` with every requested bucket present (zeros
    included) so a schema over the bucket table never sees a partial
    row."""
    bucket_ps: Dict[str, int] = {b: 0 for b in (buckets or [])}
    bucket_ps.setdefault("other", 0)
    total = 0
    matched = 0
    for name, ps in by_op.items():
        b = classify(name)
        total += ps
        if b is None or (buckets is not None and b not in bucket_ps):
            b = "other"
        else:
            matched += ps
        bucket_ps[b] = bucket_ps.get(b, 0) + ps
    fractions = {b: (round(v / total, 4) if total else 0.0)
                 for b, v in bucket_ps.items()}
    return {"bucket_ps": bucket_ps, "total_ps": int(total),
            "matched_ps": int(matched), "fractions": fractions}
