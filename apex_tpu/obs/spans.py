"""Structured trace spans over the profiling shims.

:func:`apex_tpu.utils.profiling.nvtx_range` already names a region in
both worlds — ``jax.named_scope`` (the name rides the HLO op metadata
into compiled programs and captured xplanes) and
``jax.profiler.TraceAnnotation`` (the host-side section shows on the
capture's python line).  This module layers *structure* on that shim:

- spans **nest** and the emitted name is the slash-joined path
  (``serve/step/decode``), so a capture groups by subsystem instead of
  scattering flat labels — :func:`current_path` returns the live path;
- spans are **timed into the metrics registry**: leaving a span
  observes its wall duration in the ``span_seconds__<path>`` histogram
  (dots and slashes sanitized to ``_``), giving every named region
  p50/p99 through the same :class:`~apex_tpu.obs.metrics.Histogram`
  quantile math the serve engine uses;
- under an **active trace** (calling a span inside ``jit`` tracing) the
  timing is suppressed — trace-time wall clock is compile cost, not
  runtime — while the named scope still lands in the HLO metadata.
  That is the whole contract: inside traced code a span contributes
  *metadata only*, so instrumentation can never add a host callback or
  a retrace hazard to the step (the graph-lint syncs pass on the
  instrumented serve/train lanes pins it).

Span naming convention (the catalog in
``docs/source/observability.rst``): ``<subsystem>/<region>`` with
lowercase snake segments — ``serve/decode_step``, ``serve/prefill``,
``train/step``.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, List, Optional

from apex_tpu.obs import metrics as metrics_mod
from apex_tpu.utils.profiling import nvtx_range

__all__ = ["span", "current_path", "traced_span"]

_state = threading.local()


def _stack() -> List[str]:
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


def current_path() -> str:
    """Slash-joined path of the live span stack (``""`` outside any)."""
    return "/".join(_stack())


def _tracing() -> bool:
    """True while jax is tracing (span timings suppressed there)."""
    try:
        import jax
        return not jax.core.trace_state_clean()
    except Exception:  # pragma: no cover - very old/new jax
        return False


def metric_name(path: str) -> str:
    """``serve/decode_step`` -> ``span_seconds__serve_decode_step``."""
    safe = "".join(c if c.isalnum() else "_" for c in path)
    return f"span_seconds__{safe}"


@contextlib.contextmanager
def span(name: str, registry: Optional[metrics_mod.Registry] = None,
         record: bool = True):
    """Named region: HLO metadata + host trace annotation + (outside
    tracing) a wall-duration observation into the registry histogram
    for the span's full path."""
    stack = _stack()
    stack.append(name)
    path = "/".join(stack)
    tracing = _tracing()
    t0 = time.perf_counter()
    try:
        with nvtx_range(path):
            yield
    finally:
        stack.pop()
        if record and not tracing:
            reg = registry or metrics_mod.DEFAULT
            reg.histogram(metric_name(path),
                          f"wall seconds inside span {path!r}"
                          ).observe(time.perf_counter() - t0)


def traced_span(name: Optional[str] = None,
                registry: Optional[metrics_mod.Registry] = None
                ) -> Callable:
    """Decorator form (the :func:`apex_tpu.utils.annotate` shape, with
    span structure and timing)."""
    def deco(fn):
        label = name or fn.__name__

        def wrapped(*args, **kwargs):
            with span(label, registry=registry):
                return fn(*args, **kwargs)

        wrapped.__name__ = fn.__name__
        wrapped.__doc__ = fn.__doc__
        return wrapped
    return deco
