"""Fleet-level registry merging: the ONE implementation of
cross-replica metric aggregation.

The disaggregated fleet (PR 10) runs one registry per engine — the
prefill worker and every decode replica each own their counters,
gauges and ``serve_decode_step_seconds`` histogram.  A fleet-level
answer ("what is the fleet's decode p99?", "how many tokens did the
fleet emit?") is a MERGE of those registries, and until this module
the merge math lived as a private helper inside ``bench.py``
(``_merged_decode_quantile``) that a production scrape could not
import — exactly the private-percentile drift PR 7 killed for the
single-engine case.  This module is that merge as a public API:

- **counters sum** — ``serve_tokens_total`` over a fleet is the sum of
  every replica's counter (each emission increments exactly one
  engine's);
- **histograms union buckets** — same fixed bucket ladder, counts
  added, then ONE :meth:`~apex_tpu.obs.metrics.Histogram.quantile`
  interpolation over the union (:func:`merged_quantile` — never
  per-replica percentiles averaged, which is not a percentile of
  anything);
- **gauges tabulate** — a last-write-wins scalar has no meaningful
  sum, so gauges come back as a per-replica table
  (:func:`gauge_table`), which is also what the router's admission
  control actually wants to look at.

``bench.py``'s disagg config and ``tools/serve_disagg.py``'s artifact
read their fleet percentiles through :func:`merged_quantile`, and
``tools/trace_report.py`` sums its fleet token accounting through
:func:`merge_registries` — bench, the committed artifacts, and a
production scrape can never disagree on the merge math because there
is exactly one copy of it.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

from apex_tpu.obs.metrics import Counter, Gauge, Histogram, Registry

__all__ = ["merge_histograms", "merged_quantile", "merge_registries",
           "gauge_table", "counter_sum"]


def _window(hist: Histogram, mark) -> Tuple:
    """``(counts, sum, count, max)`` of the window since ``mark``
    (``None`` = the histogram's whole history).  The window's max is
    only known when it SET the running max — the same stale-max guard
    :meth:`Histogram.quantile(since=)` applies, or an excluded
    pre-mark compile step would stretch the overflow bucket."""
    if mark is None:
        return hist.counts.copy(), hist.sum, hist.count, hist._max
    counts = hist.counts - mark[0]
    hi_max = hist._max if hist._max > mark[3] else -math.inf
    return counts, hist.sum - mark[1], hist.count - mark[2], hi_max


def merge_histograms(pairs: Sequence[Tuple[Histogram, Optional[Tuple]]],
                     name: str = "_merged") -> Histogram:
    """Bucket-union of histogram windows: ``pairs`` is
    ``[(histogram, mark-or-None), ...]`` where a mark is a
    :meth:`Histogram.state` snapshot bounding the window (``None``
    takes the whole history).  Every histogram must share the same
    bucket bounds — a union across different ladders silently
    misattributes observations, so it is an error instead."""
    if not pairs:
        raise ValueError("merge_histograms: need at least one histogram")
    bounds = pairs[0][0].bounds
    merged = Histogram(Registry(), name, buckets=bounds)
    for hist, mark in pairs:
        if hist.bounds != bounds:
            raise ValueError(
                f"merge_histograms: {hist.name!r} has different bucket "
                f"bounds than {pairs[0][0].name!r} — a bucket union "
                f"across ladders is not a histogram")
        counts, hsum, count, hi_max = _window(hist, mark)
        merged.counts = merged.counts + counts
        merged.sum += hsum
        merged.count += count
        if hi_max > merged._max:
            merged._max = hi_max
    return merged


def merged_quantile(pairs: Sequence[Tuple[Histogram, Optional[Tuple]]],
                    q: float) -> float:
    """Fleet-level quantile: union the replicas' histogram windows
    (same fixed bucket ladder) and interpolate through the SAME
    :meth:`~apex_tpu.obs.metrics.Histogram.quantile` math bench and a
    production scrape use — never a private percentile implementation,
    and never an average of per-replica percentiles."""
    return merge_histograms(pairs).quantile(q)


def counter_sum(registries: Sequence[Registry], name: str) -> float:
    """Sum of one counter across a fleet's registries (a registry
    without the counter contributes 0 — a prefill worker has no
    ``serve_spec_rounds_total``)."""
    total = 0.0
    for reg in registries:
        inst = reg._instruments.get(name)
        if inst is None:
            continue
        if not isinstance(inst, Counter):
            raise TypeError(
                f"counter_sum: {name!r} is a {inst.kind}, not a counter")
        total += inst.value
    return total


def merge_registries(registries: Sequence[Registry]) -> Registry:
    """Merge a fleet's registries into one FRESH registry: counters
    SUM, histograms bucket-union (full history — window one level up
    with :func:`merged_quantile` when marks matter), gauges are
    SKIPPED (a last-write-wins scalar has no meaningful cross-replica
    merge; read them as a table with :func:`gauge_table`).  The
    result is a snapshot, not a sink: a periodic scrape merges into a
    NEW registry each time (merging twice into one would double-count
    — which is why there is no ``into=``).  Pending deferred values
    are NOT resolved here — flush each registry first if the lag
    window matters for the read."""
    out = Registry()
    names: Dict[str, List[Tuple[Registry, object]]] = {}
    for reg in registries:
        with reg._lock:
            for name, inst in reg._instruments.items():
                names.setdefault(name, []).append((reg, inst))
    for name in sorted(names):
        insts = [i for _, i in names[name]]
        kinds = {i.kind for i in insts}
        if len(kinds) != 1:
            raise TypeError(
                f"merge_registries: {name!r} registered as {sorted(kinds)}"
                f" across the fleet — the metric vocabulary must agree")
        first = insts[0]
        if isinstance(first, Counter):
            out.counter(name, first.help)._apply_scalar(
                sum(i.value for i in insts))
        elif isinstance(first, Histogram):
            merged = merge_histograms([(i, None) for i in insts],
                                      name=name)
            tgt = out.histogram(name, first.help, buckets=first.bounds)
            tgt.counts = tgt.counts + merged.counts
            tgt.sum += merged.sum
            tgt.count += merged.count
            if merged._max > tgt._max:
                tgt._max = merged._max
        # gauges: intentionally skipped (see docstring / gauge_table)
    return out


def gauge_table(registries: Sequence[Registry],
                labels: Optional[Sequence[str]] = None
                ) -> Dict[str, Dict[str, float]]:
    """Per-replica gauge values: ``{gauge_name: {label: value}}`` over
    every gauge any registry carries (absent = not listed for that
    replica).  ``labels`` names the columns (default ``"r0"``,
    ``"r1"``, ...) — the disagg tools pass ``["prefill", "replica0",
    ...]``."""
    if labels is None:
        labels = [f"r{i}" for i in range(len(registries))]
    if len(labels) != len(registries):
        raise ValueError(
            f"gauge_table: {len(labels)} labels for "
            f"{len(registries)} registries")
    table: Dict[str, Dict[str, float]] = {}
    for label, reg in zip(labels, registries):
        with reg._lock:
            for name, inst in reg._instruments.items():
                if isinstance(inst, Gauge):
                    table.setdefault(name, {})[label] = float(inst.value)
    return {name: table[name] for name in sorted(table)}
