"""Per-request lifecycle tracing across the serving fleet.

The metrics registry (PR 7) answers "how is the fleet doing"; nothing
answered "what happened to THIS request".  In the disaggregated
topology (PR 10) one request's life spans four engines — enqueue at
the router, chunked prefill on the prefill slice, a KV shipment, decode
steps on a replica, maybe a preemption or a replica death and a
re-prefill somewhere else, retirement — and when an output diverges or
a tail latency spikes, the only forensic record was per-process
counters.  This module is the request-level flight path:

- a **request id is minted at router admission**
  (:meth:`RequestTracer.mint`; a standalone engine mints lazily at its
  own ``submit``), and the SAME uid follows the request through
  preemptions, reroutes and re-prefills — a killed replica's requests
  keep their trace across replicas, which is exactly what the chaos
  drill interrogates;
- **events are host-side records at the existing step boundaries** —
  the PR-7 contract verbatim: every value recorded here is a plain
  host number the loop already holds (the ``(S,)`` sampled tokens it
  must stream anyway, slot indices, byte counts).  Nothing is fetched
  from a device for tracing, nothing runs inside a compiled body, and
  the graph-lint ``syncs`` pass over the instrumented serve lanes
  stays clean because the traced programs are UNCHANGED (device values
  keep riding the registry's lag-resolved path);
- the **event vocabulary is closed** (:data:`EVENT_KINDS`) and
  machine-checked: ``tools/trace_report.py`` exports the committed
  ``TRACE_r*.json`` behind the stdlib-only schema
  ``apex_tpu/analysis/trace.py``, whose contradiction rejection pins
  span-tree nesting, decode-token accounting against the engines' own
  ``serve_tokens_total`` deltas, and reroute events naming a killed
  replica;
- :meth:`RequestTracer.to_chrome_trace` exports the same lifecycles as
  chrome-trace JSON (``ph``/``pid``/``tid``/``ts``/``dur`` — the
  format :func:`apex_tpu.obs.xplane.parse_trace_json` reads), one
  process row per fleet component, one thread per request.

Event vocabulary (``data`` fields in parentheses; every token-emitting
event carries ``tokens`` so accounting is a sum, never an inference):

==================  =====================================================
``enqueue``         request entered a queue (router admission mints the
                    id; an engine-local enqueue is a recompute admission
                    or a standalone engine's submit)
``admit``           installed into a slot + prefill sample drawn
                    (``slot``, ``first_token``, ``prompt_len``,
                    ``tokens=1``)
``prefill_chunk``   one fixed-size prompt chunk dispatched (``start``,
                    ``n_valid``)
``kv_ship``         prefilled KV left the prefill slice (``to_replica``,
                    ``nbytes``)
``kv_install``      shipment scattered into a replica's pools (``slot``)
``decode_step``     one decode-step batch's slot attribution: THIS
                    request's token of the step (``step``, ``token``,
                    ``batch`` = active slots in the dispatch,
                    ``tokens=1``)
``spec_draft``      a speculative draft round proposed for this slot
                    (``step``, ``proposed``)
``spec_verify``     the verify round's per-slot outcome (``step``,
                    ``accepted``, ``tokens`` = emitted incl. the
                    target's own draw)
``preempt``         evicted, recompute-on-resume continuation queued
                    (``slot``)
``reroute``         rebuilt from the streamed-token log after a replica
                    death and re-queued (``from_replica``)
``retire``          finished; blocks freed (``tokens_out`` = full
                    stream length)
==================  =====================================================

Cost: one dict build + list append per event under a lock —
microbenched per-event in ``tools/obs_report.py`` and gated at <= 1%
of the bench-smoke decode step in the committed ``OBS_r02.json``.
``tracer=None`` (the default everywhere) is a no-op: engines guard
every hook with one ``is not None`` check.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

__all__ = ["EVENT_KINDS", "RequestTracer", "spans_of_events"]

#: the closed event vocabulary (see the module docstring's table);
#: ``analysis/trace.py`` pins the committed artifact to the same set.
EVENT_KINDS = (
    "enqueue", "admit", "prefill_chunk", "kv_ship", "kv_install",
    "decode_step", "spec_draft", "spec_verify", "preempt", "reroute",
    "retire",
)

_KIND_SET = frozenset(EVENT_KINDS)

#: event kinds that emit tokens (their ``tokens`` fields sum to the
#: request's — and transitively the fleet's — token accounting)
TOKEN_KINDS = ("admit", "decode_step", "spec_verify")


def spans_of_events(events: List[dict]) -> List[dict]:
    """Fold one request's event list into its span tree: a root
    ``request`` span covering the whole lifecycle, with one child per
    contiguous run of events at the same ``where`` (the residency
    segments — ``router`` -> ``prefill`` -> ``replica0`` -> ``router``
    -> ... for a rerouted request).  Children are nested within the
    root by construction; the TRACE schema re-checks the nesting
    anyway (contradiction rejection beats trust)."""
    if not events:
        return []
    spans = [{"name": "request", "where": "*",
              "t0": events[0]["ts"], "t1": events[-1]["ts"],
              "parent": -1}]
    run_where = events[0]["where"]
    run_t0 = events[0]["ts"]
    last_ts = events[0]["ts"]
    for ev in events[1:]:
        if ev["where"] != run_where:
            spans.append({"name": run_where, "where": run_where,
                          "t0": run_t0, "t1": last_ts, "parent": 0})
            run_where, run_t0 = ev["where"], ev["ts"]
        last_ts = ev["ts"]
    spans.append({"name": run_where, "where": run_where,
                  "t0": run_t0, "t1": last_ts, "parent": 0})
    return spans


class RequestTracer:
    """Fleet-wide per-request event log (see the module docstring).
    One tracer serves a whole fleet: the router hands itself to the
    prefill worker and every replica, each tagged with a ``where``
    label, and all of them record into this one ordered log.

    Retired traces are retained up to ``max_retired`` (oldest dropped
    and counted in :attr:`dropped`), and TOTAL traces are hard-capped
    at ``2 * max_retired`` — a never-retired request (abandoned
    client, a death with nowhere to reroute) must not hold its event
    list forever; when the cap is hit the oldest-minted trace is
    evicted regardless of state.  A serving process lives for months;
    the tracer must not be the leak."""

    def __init__(self, max_retired: int = 4096):
        if max_retired < 1:
            raise ValueError(f"max_retired={max_retired}")
        self.max_retired = max_retired
        self.dropped = 0
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._traces: Dict[str, dict] = {}
        self._retired: Deque[str] = deque()
        self._seq = 0
        self._minted = 0

    # -- recording ----------------------------------------------------

    def mint(self, uid: str) -> str:
        """Begin a trace for ``uid`` (router admission — the id's
        birthplace); returns the trace id.  Re-minting an existing uid
        returns the existing trace id (a continuation is the SAME
        request)."""
        with self._lock:
            return self._begin(uid)["trace_id"]

    def _begin(self, uid: str) -> dict:
        tr = self._traces.get(uid)
        if tr is None:
            self._minted += 1
            tr = {"trace_id": f"t{self._minted:05d}", "events": []}
            self._traces[uid] = tr
            # the hard total cap: evict the oldest-minted trace
            # (dict order = mint order) — retired or not — so
            # never-retired requests cannot leak unboundedly
            while len(self._traces) > 2 * self.max_retired:
                old = next(iter(self._traces))
                del self._traces[old]
                try:
                    self._retired.remove(old)
                except ValueError:
                    pass
                self.dropped += 1
        return tr

    def record(self, kind: str, uid: str, where: str,
               **data: Any) -> None:
        """Append one host-side event (the per-event cost the
        ``OBS_r02.json`` tracing lane gates).  Unknown kinds raise —
        the vocabulary is the contract every consumer (schema, docs,
        chrome export) shares, and a typo'd kind silently dropped from
        analysis is worse than a loud error."""
        if kind not in _KIND_SET:
            raise ValueError(
                f"unknown trace event kind {kind!r}; the vocabulary is "
                f"{EVENT_KINDS}")
        # the per-event hot path (gated in OBS_r02's tracing lane):
        # reuse the **data dict instead of building a second one.  ts
        # is stamped INSIDE the lock, with seq — concurrent recorders
        # must not produce seq-increasing events whose ts go backwards
        # (the schema rejects both orders disagreeing)
        data["kind"] = kind
        data["where"] = where
        with self._lock:
            tr = self._traces.get(uid)
            if tr is None:
                tr = self._begin(uid)
            self._seq += 1
            data["ts"] = round(time.perf_counter() - self._t0, 6)
            data["seq"] = self._seq
            tr["events"].append(data)
            if kind == "retire":
                self._retired.append(uid)
                while len(self._retired) > self.max_retired:
                    old = self._retired.popleft()
                    if old in self._traces:
                        del self._traces[old]
                        self.dropped += 1

    # -- reading ------------------------------------------------------

    def events(self, uid: str) -> List[dict]:
        """A copy of one request's event list (``[]`` when unknown or
        already dropped)."""
        with self._lock:
            tr = self._traces.get(uid)
            return [dict(e) for e in tr["events"]] if tr else []

    def uids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def tokens_of(self, uid: str) -> int:
        """Token-emitting events' ``tokens`` summed — the request's
        generated-token count as the TRACE accounts it."""
        return sum(int(e.get("tokens", 0)) for e in self.events(uid))

    def to_doc_requests(self) -> Dict[str, dict]:
        """The ``requests`` section of a TRACE document: per uid the
        trace id, events, derived span tree and token total (the
        schema re-derives the latter two — recorded AND re-checked)."""
        out: Dict[str, dict] = {}
        with self._lock:
            items = [(uid, tr["trace_id"], [dict(e) for e in
                                            tr["events"]])
                     for uid, tr in self._traces.items()]
        for uid, tid, events in items:
            out[uid] = {
                "trace_id": tid,
                "events": events,
                "spans": spans_of_events(events),
                "tokens": sum(int(e.get("tokens", 0)) for e in events),
            }
        return out

    # -- chrome-trace export ------------------------------------------

    def to_chrome_trace(self) -> dict:
        """The lifecycles as chrome-trace JSON (``chrome://tracing`` /
        Perfetto): one process row per ``where`` component, one thread
        per request; residency spans as ``ph: "X"`` duration events,
        point events (preempt/reroute/ship) as ``ph: "i"`` instants.
        Timestamps are microseconds since the tracer's epoch — the
        unit :func:`apex_tpu.obs.xplane.parse_trace_json` converts
        from."""
        doc = self.to_doc_requests()
        wheres: List[str] = []
        events: List[dict] = []
        tid_of: Dict[str, int] = {}
        for tid, uid in enumerate(sorted(doc), start=1):
            tid_of[uid] = tid
            for ev in doc[uid]["events"]:
                if ev["where"] not in wheres:
                    wheres.append(ev["where"])
        pid_of = {w: i + 1 for i, w in enumerate(wheres)}
        for w, pid in pid_of.items():
            events.append({"ph": "M", "name": "process_name",
                           "pid": pid,
                           "args": {"name": f"/fleet:{w}"}})
        for uid, tid in tid_of.items():
            for pid in pid_of.values():
                events.append({"ph": "M", "name": "thread_name",
                               "pid": pid, "tid": tid,
                               "args": {"name": uid}})
        for uid, rec in doc.items():
            tid = tid_of[uid]
            for sp in rec["spans"]:
                if sp["parent"] == -1:
                    continue        # the root is implied by the row
                events.append({
                    "ph": "X", "name": f"{uid}:{sp['name']}",
                    "pid": pid_of[sp["where"]], "tid": tid,
                    "ts": round(sp["t0"] * 1e6, 3),
                    "dur": round(max(sp["t1"] - sp["t0"], 1e-6) * 1e6,
                                 3),
                    "args": {"trace_id": rec["trace_id"]}})
            for ev in rec["events"]:
                if ev["kind"] not in ("preempt", "reroute", "kv_ship",
                                      "kv_install", "retire"):
                    continue
                events.append({
                    "ph": "i", "s": "t", "name": ev["kind"],
                    "pid": pid_of[ev["where"]], "tid": tid,
                    "ts": round(ev["ts"] * 1e6, 3),
                    "args": {k: v for k, v in ev.items()
                             if k not in ("ts", "kind", "where")}})
        events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0.0)))
        return {"traceEvents": events,
                "displayTimeUnit": "ms"}
