"""Declarative SLOs over the live metrics registry, with windowed
burn-rate evaluation — zero new host syncs.

An SLO here is an objective over instruments the registry already
resolves (:mod:`apex_tpu.obs.metrics`): the serve decode-step p99, the
speculative-decoding acceptance rate, block utilization, queue depth.
The evaluator rides the **existing lag-resolved boundary**: it reads
ONLY the registry's resolved host-side state (numpy bucket counts,
gauge/counter floats) at the same step boundaries where
``Registry.tick()`` already runs, so an SLO-instrumented loop adds no
``device_get`` anywhere — the graph-lint syncs pass on an
SLO-instrumented serve lane stays clean, machine-checked
(``tests/l0/test_slo.py``).  Tracers cannot reach an objective at all:
the registry rejects them at record time, and the evaluator never
touches a jax value.

Objective kinds (:class:`SLObjective`):

- ``"quantile"`` — over a histogram: ``p_q(metric) <= threshold``
  within the window.  The **burn rate** is the textbook SRE form: the
  objective "p99 <= T" allows ``1 − q`` of observations over T (the
  error budget); ``burn_rate = bad_frac / (1 − q)`` where ``bad_frac``
  is the windowed fraction of observations exceeding T.  Burn > 1
  means the budget burns faster than it accrues → ``violated``.
  ``threshold`` is snapped DOWN to the histogram's nearest bucket
  bound at/below it (the conservative direction: every observation
  truly over the threshold is over the snapped bound too, so a
  violation can never hide between bounds — borderline observations
  over-count as bad, judging the objective tighter than declared,
  never looser; the snapped value is recorded).
- ``"gauge"`` — the windowed MEAN of a gauge vs the threshold
  (``op="le"`` or ``"ge"``); burn = value/threshold (le) or
  threshold/value (ge) — budget utilization, >1 = violated.
- ``"ratio"`` — windowed counter delta ratio (``ratio_num`` /
  ``ratio_den``), e.g. spec acceptance = accepted/proposed, vs the
  threshold with ``op``; burn as for gauges.

Every objective answers one of three statuses per evaluation:
``"met"``, ``"violated"``, or ``"insufficient_window"`` (fewer than
``min_count`` observations / boundaries in the window — an SLO that
judges on no data is the armed-gate-asserts-nothing class).

Consumers: :class:`apex_tpu.serve.DisaggRouter` de-ranks an
SLO-violating replica out of admission eligibility
(``RouterConfig.slo`` — the gauge-ranking hook, now driven by
objectives instead of raw ranking only), and
``tools/serve_scenarios.py`` / ``tools/chaos_run.py`` record SLO
verdicts into their committed artifacts.
"""

from __future__ import annotations

import bisect
import dataclasses
import math
from collections import deque
from typing import Dict, Optional, Sequence, Tuple

from apex_tpu.obs import metrics as obs_metrics

__all__ = ["SLObjective", "SLOEvaluator", "STATUS_MET",
           "STATUS_VIOLATED", "STATUS_INSUFFICIENT",
           "serve_objectives"]

STATUS_MET = "met"
STATUS_VIOLATED = "violated"
STATUS_INSUFFICIENT = "insufficient_window"

#: the closed status vocabulary (schemas validate against it)
STATUSES = (STATUS_MET, STATUS_VIOLATED, STATUS_INSUFFICIENT)


@dataclasses.dataclass(frozen=True)
class SLObjective:
    """One declarative objective over a registry instrument.

    ``kind="quantile"``: ``metric`` names a histogram; good means
    ``p_q <= threshold`` (op fixed to ``le`` — latency quantiles).
    ``kind="gauge"``: ``metric`` names a gauge; good means the
    windowed mean ``op`` threshold.  ``kind="ratio"``: good means
    ``delta(ratio_num)/delta(ratio_den)`` ``op`` threshold.  ``window``
    counts EVALUATION BOUNDARIES (one per ``evaluate()`` call — the
    fleet/engine step boundary); ``window=0`` means SINCE-START (the
    evaluator's first boundary is the permanent base — a run-scoped
    objective, quantile/ratio only, that costs one held snapshot
    instead of an unbounded ring).  ``min_count`` is the observations
    (or denominator events, or boundaries for gauges) the window must
    hold before the objective judges at all."""

    name: str
    kind: str
    threshold: float
    metric: str = ""
    op: str = "le"
    q: float = 0.99
    ratio_num: str = ""
    ratio_den: str = ""
    window: int = 32
    min_count: int = 8

    def __post_init__(self):
        if self.kind not in ("quantile", "gauge", "ratio"):
            raise ValueError(f"kind={self.kind!r}: pick 'quantile', "
                             f"'gauge' or 'ratio'")
        if self.op not in ("le", "ge"):
            raise ValueError(f"op={self.op!r}: pick 'le' or 'ge'")
        if self.kind == "quantile" and not 0.0 < self.q < 1.0:
            raise ValueError(f"q={self.q} outside (0, 1)")
        if self.kind == "ratio" and not (self.ratio_num
                                         and self.ratio_den):
            raise ValueError("ratio objectives need ratio_num and "
                             "ratio_den counter names")
        if self.kind in ("quantile", "gauge") and not self.metric:
            raise ValueError(f"{self.kind} objective needs a metric "
                             f"name")
        if self.window < 0 or self.min_count < 1:
            raise ValueError("window must be >= 0 (0 = since-start) "
                             "and min_count >= 1")
        if self.window == 0 and self.kind == "gauge":
            raise ValueError("window=0 (since-start) needs delta/"
                             "bucket semantics — quantile or ratio "
                             "objectives only; give gauges a finite "
                             "window")


def serve_objectives(decode_p99_s: float = 0.5,
                     max_block_util: float = 0.97,
                     min_acceptance: Optional[float] = None,
                     window: int = 32,
                     min_count: int = 8) -> Tuple[SLObjective, ...]:
    """The serving vocabulary: decode-step p99, block-utilization
    headroom, and (for spec engines) the acceptance-rate floor —
    objectives over exactly the instruments the engines already
    export."""
    objs = [
        SLObjective(name="decode_p99", kind="quantile",
                    metric="serve_decode_step_seconds", q=0.99,
                    threshold=decode_p99_s, window=window,
                    min_count=min_count),
        SLObjective(name="block_util", kind="gauge",
                    metric="serve_block_utilization", op="le",
                    threshold=max_block_util, window=window,
                    min_count=min_count),
    ]
    if min_acceptance is not None:
        objs.append(SLObjective(
            name="spec_acceptance", kind="ratio",
            ratio_num="serve_spec_accepted_total",
            ratio_den="serve_spec_proposed_total", op="ge",
            threshold=min_acceptance, window=window,
            min_count=min_count))
    return tuple(objs)


def _snap_threshold(bounds: Sequence[float],
                    threshold: float) -> "Tuple[int, float]":
    """``(bucket_index, bound)`` of the LARGEST bucket bound <=
    threshold — the conservative countable bar: every observation
    truly over the threshold is over the snapped bound too, so a
    violation can never hide between bounds (observations in
    ``(snapped, threshold]`` over-count as bad — tighter, never
    looser).  Index −1 when the threshold sits under the whole
    ladder: nothing is provably under it, so every observation
    counts as exceeding."""
    i = bisect.bisect_right(bounds, threshold) - 1
    return (i, bounds[i]) if i >= 0 else (-1, threshold)


class SLOEvaluator:
    """Evaluate a set of objectives against ONE registry's resolved
    state, once per step boundary.

    Call :meth:`evaluate` right after the boundary's
    ``Registry.tick()`` — every read is host-side resolved state (the
    lag contract means the values are at least one step old, which is
    exactly the point: no fetch, no sync).  Keeps a bounded ring of
    per-boundary snapshots (histogram states, counter values) so each
    objective is judged over its trailing ``window`` boundaries."""

    def __init__(self, registry: obs_metrics.Registry,
                 objectives: Sequence[SLObjective]):
        self.registry = registry
        self.objectives = tuple(objectives)
        if not self.objectives:
            raise ValueError("no objectives — an empty SLO set judges "
                             "nothing")
        # per-boundary snapshot ring for FINITE windows (bounded at
        # the largest one); since-start objectives (window=0) pin the
        # first boundary's snapshot instead — one held copy, however
        # long the run
        finite = [o.window for o in self.objectives if o.window > 0]
        self._snaps: deque = deque(maxlen=(max(finite) if finite
                                           else 0) + 1)
        self._first: "dict | None" = None
        self.last: Dict[str, dict] = {}

    # -- snapshotting --------------------------------------------------

    def _instrument(self, name: str):
        return self.registry._instruments.get(name)

    def _take_snapshot(self) -> dict:
        snap: dict = {}
        for o in self.objectives:
            if o.kind == "quantile":
                inst = self._instrument(o.metric)
                if isinstance(inst, obs_metrics.Histogram):
                    snap[o.metric] = inst.state()
            elif o.kind == "gauge":
                inst = self._instrument(o.metric)
                if isinstance(inst, obs_metrics.Gauge):
                    snap[o.metric] = float(inst.value)
            else:
                for cname in (o.ratio_num, o.ratio_den):
                    inst = self._instrument(cname)
                    if isinstance(inst, obs_metrics.Counter):
                        snap[cname] = float(inst.value)
        return snap

    def _window_base(self, objective: SLObjective) -> "dict | None":
        """The snapshot ``window`` boundaries ago (or the oldest held
        one while the ring is still priming); for a since-start
        objective the FIRST boundary's snapshot; ``None`` before any
        boundary."""
        if objective.window == 0:
            return self._first
        if not self._snaps:
            return None
        idx = max(0, len(self._snaps) - objective.window)
        return self._snaps[idx]

    # -- evaluation ----------------------------------------------------

    def _eval_quantile(self, o: SLObjective, base) -> dict:
        inst = self._instrument(o.metric)
        rec = {"objective": o.name, "kind": o.kind, "metric": o.metric,
               "q": o.q, "threshold": o.threshold, "window": o.window}
        if not isinstance(inst, obs_metrics.Histogram) or base is None \
                or o.metric not in base:
            rec.update(status=STATUS_INSUFFICIENT, observations=0)
            return rec
        since = base[o.metric]
        counts = inst.counts - since[0]
        total = int(inst.count - since[2])
        rec["observations"] = total
        if total < o.min_count:
            rec["status"] = STATUS_INSUFFICIENT
            return rec
        # exceed count: observations strictly above the bound the
        # threshold snapped DOWN to (buckets are upper-inclusive:
        # value <= bound lands at/under its bucket index).  Snapping
        # down means every true violation is counted and borderline
        # observations in (snapped, threshold] over-count as bad —
        # the objective can only be judged TIGHTER than declared,
        # never looser (the never-fail-open direction); a threshold
        # under the whole ladder counts everything as exceeding.
        i, snapped = _snap_threshold(inst.bounds, o.threshold)
        bad = int(total - counts[:i + 1].sum()) if i >= 0 else total
        bad_frac = bad / total
        budget = 1.0 - o.q
        burn = bad_frac / budget
        rec.update(
            value=round(float(inst.quantile(o.q, since=since)), 9),
            snapped_threshold=snapped,
            bad_frac=round(bad_frac, 6), burn_rate=round(burn, 4),
            status=STATUS_VIOLATED if burn > 1.0 else STATUS_MET)
        return rec

    def _eval_gauge(self, o: SLObjective, base) -> dict:
        rec = {"objective": o.name, "kind": o.kind, "metric": o.metric,
               "op": o.op, "threshold": o.threshold,
               "window": o.window}
        inst = self._instrument(o.metric)
        if not isinstance(inst, obs_metrics.Gauge):
            rec.update(status=STATUS_INSUFFICIENT, observations=0)
            return rec
        # windowed mean over the held per-boundary reads + the live one
        idx = max(0, len(self._snaps) - o.window)
        vals = [s[o.metric] for s in list(self._snaps)[idx:]
                if o.metric in s]
        vals.append(float(inst.value))
        rec["observations"] = len(vals)
        if len(vals) < o.min_count:
            rec["status"] = STATUS_INSUFFICIENT
            return rec
        value = sum(vals) / len(vals)
        rec["value"] = round(value, 9)
        good, burn = _judge(value, o.threshold, o.op)
        rec.update(burn_rate=burn,
                   status=STATUS_MET if good else STATUS_VIOLATED)
        return rec

    def _eval_ratio(self, o: SLObjective, base) -> dict:
        rec = {"objective": o.name, "kind": o.kind, "op": o.op,
               "num": o.ratio_num, "den": o.ratio_den,
               "threshold": o.threshold, "window": o.window}
        num = self._instrument(o.ratio_num)
        den = self._instrument(o.ratio_den)
        if not isinstance(num, obs_metrics.Counter) or \
                not isinstance(den, obs_metrics.Counter) or base is None:
            rec.update(status=STATUS_INSUFFICIENT, observations=0)
            return rec
        dnum = float(num.value) - base.get(o.ratio_num, 0.0)
        dden = float(den.value) - base.get(o.ratio_den, 0.0)
        rec["observations"] = int(dden)
        if dden < o.min_count:
            rec["status"] = STATUS_INSUFFICIENT
            return rec
        value = dnum / dden
        rec["value"] = round(value, 6)
        good, burn = _judge(value, o.threshold, o.op)
        rec.update(burn_rate=burn,
                   status=STATUS_MET if good else STATUS_VIOLATED)
        return rec

    def evaluate(self) -> Dict[str, dict]:
        """One boundary: judge every objective over its trailing
        window of RESOLVED registry state, then append this boundary's
        snapshot to the ring.  Returns (and stores in :attr:`last`)
        ``{objective_name: record}`` with the closed status
        vocabulary."""
        out: Dict[str, dict] = {}
        for o in self.objectives:
            base = self._window_base(o)
            if o.kind == "quantile":
                out[o.name] = self._eval_quantile(o, base)
            elif o.kind == "gauge":
                out[o.name] = self._eval_gauge(o, base)
            else:
                out[o.name] = self._eval_ratio(o, base)
        snap = self._take_snapshot()
        if self._first is None:
            self._first = snap
        self._snaps.append(snap)
        self.last = out
        return out

    def violated(self) -> bool:
        """Any objective in the LAST evaluation violated (insufficient
        windows never count as violations — an SLO without data must
        not de-rank a fresh replica)."""
        return any(r.get("status") == STATUS_VIOLATED
                   for r in self.last.values())

    def summary(self) -> dict:
        """JSON-ready verdict block for artifacts: per-objective
        records + an ``ok`` that is true exactly when nothing is
        violated (insufficient windows are named, not passed off as
        met)."""
        return {"objectives": dict(self.last),
                "ok": not self.violated()}


def _judge(value: float, threshold: float, op: str):
    """``(good, burn_rate)`` for direct-comparison objectives: burn is
    budget utilization — value/threshold for an upper bound,
    threshold/value for a lower one; > 1 means over budget."""
    if op == "le":
        good = value <= threshold
        burn = value / threshold if threshold > 0 else math.inf
    else:
        good = value >= threshold
        burn = threshold / value if value > 0 else math.inf
    return good, round(burn, 4) if math.isfinite(burn) else burn
