"""apex_tpu.attention — sequence/context-parallel attention.

Long-context support the reference lacks (SURVEY.md §5.7): ring attention
(K/V rotation with online softmax) and Ulysses-style all-to-all head/
sequence resharding, both exact and mesh-axis native.
"""

from apex_tpu.attention.ring import (
    attention,
    ring_attention,
    ulysses_attention,
)
from apex_tpu.ops.pallas.flash_attention import flash_attention

__all__ = ["attention", "ring_attention", "ulysses_attention",
           "flash_attention"]
