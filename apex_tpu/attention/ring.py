"""Sequence/context-parallel attention over a mesh axis.

Long-context scaling has no counterpart in the reference (SURVEY.md §5.7 —
apex predates it); these are the TPU-native mechanisms that make sequence
length a shardable dimension, designed around ICI collectives:

- :func:`ring_attention` — blockwise attention with online softmax: K/V
  shards rotate around the ring axis via ``lax.ppermute`` while each device
  keeps its query shard resident; peak memory per device is O(L·L/W) for
  the running block only, and the per-step ppermute overlaps with the
  block matmuls (Liu et al., "Ring Attention with Blockwise Transformers",
  2023 — pattern, not code).
- :func:`ulysses_attention` — all-to-all sequence parallelism: swap the
  sequence sharding for a head sharding with ``lax.all_to_all``, run full
  -sequence attention on 1/W of the heads per device, swap back
  (Jacobs et al., "DeepSpeed Ulysses", 2023 — pattern, not code).

Both compute softmax statistics in fp32 regardless of input dtype (the amp
blacklist rule for softmax, reference ``functional_overrides.py:29-65``)
and are exact: outputs match single-device full attention to float
tolerance (asserted in ``tests/distributed/test_ring_attention.py``).

Shapes follow the ``(batch, seq, heads, head_dim)`` convention with the
sequence dimension sharded over ``axis_name``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.utils.jax_compat import axis_size as _axis_size
from apex_tpu.utils.jax_compat import pvary as _pvary

NEG_INF = -1e30


def _use_pallas_blocks() -> bool:
    from apex_tpu.ops import use_pallas
    return use_pallas()


def _vary_like(reference_array, axis_name):
    """``pvary`` tagger matching the full varying-axes set of an operand:
    under a multi-dim mesh the inputs may vary over more axes than the
    ring axis (e.g. a batch axis), and loop carries / switch branches must
    type-match them exactly."""
    try:
        vma = tuple(set(jax.typeof(reference_array).vma) | {axis_name})
    except Exception:
        vma = (axis_name,)
    return lambda t: _pvary(t, vma)


def _block_scores(q, k, scale, q_off, k_off, causal, kv_mask):
    """fp32 attention scores for one (local-q, rotating-k) block pair."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, NEG_INF)
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        qpos = q_off + jnp.arange(lq)
        kpos = k_off + jnp.arange(lk)
        s = jnp.where(qpos[None, None, :, None] >= kpos[None, None, None, :],
                      s, NEG_INF)
    return s


def _ring_attention_flash(q, k, v, axis_name, causal, kv_mask, scale):
    """Ring attention with the Pallas flash kernel as the per-step block
    engine: each hop computes an exact local attention (out, lse) pair and
    merges it into the carry by logsumexp weighting — no ``(L/W, L/W)``
    score tensor ever hits HBM.  The merge is differentiable because
    :func:`flash_attention` exposes a differentiable ``lse``."""
    from apex_tpu.ops.pallas.flash_attention import NEG_INF as FLASH_NEG
    from apex_tpu.ops.pallas.flash_attention import flash_attention

    world = _axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    b, l_local, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    perm = [(i, (i + 1) % world) for i in range(world)]

    vary = _vary_like(q, axis_name)
    o = vary(jnp.zeros((b, l_local, h, d), jnp.float32))
    lse = vary(jnp.full((b, l_local, h), FLASH_NEG, jnp.float32))
    mask_c = (vary(jnp.ones((b, l_local), bool))
              if kv_mask is None else kv_mask)

    def step(t, carry):
        k_t, v_t, mask_t, o, lse = carry
        src = (rank - t) % world

        def full_block(_):
            ot, lt = flash_attention(q, k_t, v_t, causal=False,
                                     kv_mask=mask_t, scale=scale,
                                     return_lse=True)
            return ot.astype(jnp.float32), lt

        def diag_block(_):
            ot, lt = flash_attention(q, k_t, v_t, causal=True,
                                     kv_mask=mask_t, scale=scale,
                                     return_lse=True)
            return ot.astype(jnp.float32), lt

        def skip_block(_):
            # literal zeros must be tagged device-varying to type-match the
            # other switch branches under VMA checking
            return (vary(jnp.zeros((b, l_local, h, d), jnp.float32)),
                    vary(jnp.full((b, l_local, h), FLASH_NEG, jnp.float32)))

        if causal:
            # src < rank: fully visible; src == rank: local causal;
            # src > rank: entirely in the future.
            branch = jnp.where(src == rank, 1,
                               jnp.where(src < rank, 0, 2))
            o_t, lse_t = lax.switch(branch,
                                    [full_block, diag_block, skip_block],
                                    None)
        else:
            o_t, lse_t = full_block(None)

        # logsumexp-weighted merge of two normalized partial results.
        m = jnp.maximum(lse, lse_t)
        w1 = jnp.exp(lse - m)
        w2 = jnp.exp(lse_t - m)
        tot = w1 + w2
        o_new = (o * w1[:, :, :, None]
                 + o_t * w2[:, :, :, None]) / tot[:, :, :, None]
        lse_new = m + jnp.log(tot)

        k_n = lax.ppermute(k_t, axis_name, perm)
        v_n = lax.ppermute(v_t, axis_name, perm)
        mask_n = lax.ppermute(mask_t, axis_name, perm)
        return k_n, v_n, mask_n, o_new, lse_new

    _, _, _, o, lse = lax.fori_loop(0, world, step,
                                    (k, v, mask_c, o, lse))
    return o.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    kv_mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    impl: Optional[str] = None,
) -> jax.Array:
    """Exact attention with the sequence dimension sharded over
    ``axis_name``; call inside ``shard_map``.

    q, k, v: ``(B, L/W, H, D)`` local shards (contiguous blocks in ring
    order).  ``kv_mask``: optional ``(B, L/W)`` bool key mask, sharded like
    k/v (True = attend).  Online-softmax state (running max ``m``, running
    normalizer ``l``, fp32 accumulator) is carried across the W ring steps;
    K/V (and the mask) advance one hop per step with ``ppermute``.

    On TPU the per-step block attention runs the Pallas flash kernel
    (``impl="flash"`` forces it, ``impl="jnp"`` forces the materializing
    path).
    """
    if impl not in (None, "flash", "jnp"):
        raise ValueError(f"unknown ring impl {impl!r}")
    if impl == "flash" or (impl is None and _use_pallas_blocks()):
        return _ring_attention_flash(q, k, v, axis_name, causal, kv_mask,
                                     scale)
    world = _axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    b, l_local, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    perm = [(i, (i + 1) % world) for i in range(world)]

    # literal-initialized carries must be tagged device-varying so the loop
    # carry type matches the (varying) step outputs under shard_map's VMA
    # checking
    vary = _vary_like(q, axis_name)
    m = vary(jnp.full((b, h, l_local), NEG_INF, jnp.float32))
    l = vary(jnp.zeros((b, h, l_local), jnp.float32))
    acc = vary(jnp.zeros((b, l_local, h, d), jnp.float32))
    if kv_mask is None:
        kv_mask_c = vary(jnp.ones((b, l_local), bool))
    else:
        kv_mask_c = kv_mask

    def step(t, carry):
        k_t, v_t, mask_t, m, l, acc = carry
        # device `rank` holds K/V block (rank - t) mod world at step t
        src = (rank - t) % world
        s = _block_scores(q, k_t, scale, rank * l_local, src * l_local,
                          causal, mask_t)                  # (b, h, lq, lk)
        m_new = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])                  # (b, h, lq, lk)
        l = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_t.astype(jnp.float32))
        acc = acc * corr.transpose(0, 2, 1)[..., None] + pv
        k_n = lax.ppermute(k_t, axis_name, perm)
        v_n = lax.ppermute(v_t, axis_name, perm)
        mask_n = lax.ppermute(mask_t, axis_name, perm)
        return k_n, v_n, mask_n, m_new, l, acc

    _, _, _, m, l, acc = lax.fori_loop(
        0, world, step, (k, v, kv_mask_c, m, l, acc))

    # rows with no attendable key (fully masked) produce l = 0; emit zeros
    # rather than NaN, matching masked-softmax conventions.
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = acc / safe_l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    kv_mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    impl: Optional[str] = None,
) -> jax.Array:
    """All-to-all sequence parallelism: trade the sequence sharding for a
    head sharding, attend over the full sequence locally, trade back.
    ``impl="flash"``/``"jnp"`` forces the local attention engine (auto:
    flash on TPU).

    Requires ``heads % world == 0``.  One fused all-to-all each way on ICI;
    preferable to the ring when heads are plentiful and the sequence fits
    once per device.
    """
    world = _axis_size(axis_name)
    b, l_local, h, d = q.shape
    if h % world != 0:
        raise ValueError(f"heads ({h}) must divide by the axis size "
                         f"({world}) for ulysses_attention")
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    def to_full_seq(t):
        # (B, L/W, H, D) -> (B, L, H/W, D): split heads, concat sequence
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qf, kf, vf = to_full_seq(q), to_full_seq(k), to_full_seq(v)
    mask_f = (lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
              if kv_mask is not None else None)

    if impl not in (None, "flash", "jnp"):
        raise ValueError(f"unknown ulysses impl {impl!r}")
    if impl == "flash" or (impl is None and _use_pallas_blocks()):
        from apex_tpu.ops.pallas.flash_attention import flash_attention
        out = flash_attention(qf, kf, vf, causal=causal, kv_mask=mask_f,
                              scale=scale)
    else:
        s = _block_scores(qf, kf, scale, 0, 0, causal, mask_f)
        m = s.max(axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = p.sum(axis=-1, keepdims=True)
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out = jnp.einsum("bhqk,bkhd->bqhd", p / safe_l,
                         vf.astype(jnp.float32)).astype(q.dtype)

    # (B, L, H/W, D) -> (B, L/W, H, D)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: Optional[str] = None,
    impl: str = "ring",
    **kwargs,
) -> jax.Array:
    """Dispatcher: full local attention when ``axis_name`` is None (the
    Pallas flash kernel on TPU, the jnp path elsewhere; force one with
    ``impl="flash"`` / ``impl="jnp"``), else the selected sequence-parallel
    implementation (``impl="flash"``/``"jnp"`` with an ``axis_name`` select
    the ring path's block engine)."""
    if impl not in ("ring", "ulysses", "flash", "jnp"):
        raise ValueError(f"unknown attention impl {impl!r}")
    layout = kwargs.pop("layout", "blhd")
    if layout == "bhld" and axis_name is not None:
        # Head-major fast path (see flash_attention): local only — the
        # sequence-parallel engines speak (B, L, H, D).
        raise ValueError("layout='bhld' requires axis_name=None")
    rope = kwargs.pop("rope", None)
    if rope is not None and axis_name is not None:
        # The sequence-parallel engines take pre-rotated q/k (positions
        # are global, each rank rotates its shard before the collective).
        raise ValueError("rope=(cos, sin) requires axis_name=None; "
                         "rotate q/k with apply_rope before a "
                         "sequence-parallel call")
    if axis_name is None:
        if impl == "flash" or (impl != "jnp" and _use_pallas_blocks()):
            from apex_tpu.ops.pallas.flash_attention import flash_attention
            return flash_attention(q, k, v, layout=layout,
                                   causal=kwargs.get("causal", False),
                                   kv_mask=kwargs.get("kv_mask"),
                                   scale=kwargs.get("scale"),
                                   block_q=kwargs.get("block_q"),
                                   block_k=kwargs.get("block_k"),
                                   return_lse=kwargs.get("return_lse",
                                                         False),
                                   rope=rope)
        if rope is not None:
            from apex_tpu.ops.rope import apply_rope_tables
            q, k = apply_rope_tables(q, k, rope, layout)
        if layout == "bhld":
            # jnp fallback speaks (B, L, H, D)
            out = attention(jnp.moveaxis(q, 1, 2), jnp.moveaxis(k, 1, 2),
                            jnp.moveaxis(v, 1, 2), axis_name=None,
                            impl=impl, **kwargs)
            if kwargs.get("return_lse", False):
                return jnp.moveaxis(out[0], 1, 2), out[1]
            return jnp.moveaxis(out, 1, 2)
        s = _block_scores(q, k, kwargs.get("scale") or 1.0 / (q.shape[-1] ** 0.5),
                          0, 0, kwargs.get("causal", False),
                          kwargs.get("kv_mask"))
        m = s.max(axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = p.sum(axis=-1, keepdims=True)
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out = jnp.einsum("bhqk,bkhd->bqhd", p / safe_l,
                         v.astype(jnp.float32)).astype(q.dtype)
        if kwargs.get("return_lse", False):
            # (B, L, H) fp32, NEG_INF for fully-masked rows — the flash
            # branch's convention, so the two backends interchange.
            lse = jnp.where(l[..., 0] == 0.0, NEG_INF,
                            m[..., 0] + jnp.log(safe_l[..., 0]))
            return out, jnp.moveaxis(lse, 1, 2)
        return out
    if impl == "ulysses":
        return ulysses_attention(q, k, v, axis_name, **kwargs)
    if impl in ("flash", "jnp"):
        return ring_attention(q, k, v, axis_name, impl=impl, **kwargs)
    return ring_attention(q, k, v, axis_name, **kwargs)
