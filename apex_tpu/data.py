"""Host→device input pipeline: overlapped prefetch + on-device transform.

The reference's ``data_prefetcher`` (``examples/imagenet/main_amp.py:
256-290``) overlaps the next batch's H2D copy and normalization with the
current step's compute on a side CUDA stream.  The TPU-native analog
needs no explicit stream: ``jax.device_put`` returns immediately with
the transfer in flight on the DMA engines, and a jitted transform
dispatched on the in-flight arrays queues behind the copy — so a small
lookahead queue is the whole machine.  While the chip executes step N,
the host thread is already inside Python generating/putting batch N+1
(the step call itself is async too; only the periodic metrics fetch
joins).

Two entry points:

- :func:`prefetch_to_device` — generator adapter: wraps any host batch
  iterator (numpy arrays, pytrees of them), keeps ``lookahead`` batches
  in flight, optionally applies a jitted on-device ``transform``
  (e.g. uint8→float normalize, the reference prefetcher's side-stream
  work) to each.
- :class:`DataPrefetcher` — the reference-shaped object API
  (``.next()`` returning ``None`` at exhaustion, like
  ``main_amp.py:283-290``) for loops ported from the reference.

Streaming uint8 and normalizing on device is the intended pattern: it
cuts H2D bytes 4x vs fp32 and matches the reference (whose prefetcher
also receives uint8 and normalizes device-side).
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import jax.numpy as jnp

__all__ = ["prefetch_to_device", "DataPrefetcher", "IMAGENET_MEAN",
           "IMAGENET_STD", "normalize_uint8", "host_synthetic_loader"]

#: the reference prefetcher's normalization constants
#: (``examples/imagenet/main_amp.py:259-265``), RGB mean/std * 255.
IMAGENET_MEAN = (0.485 * 255, 0.456 * 255, 0.406 * 255)
IMAGENET_STD = (0.229 * 255, 0.224 * 255, 0.225 * 255)


def normalize_uint8(batch):
    """On-device uint8→fp32 ImageNet normalize of an ``(x, y)`` batch —
    the work the reference prefetcher does on its side stream
    (``main_amp.py:276-280``).  Pass as ``transform=``; streaming uint8
    and normalizing device-side cuts H2D bytes 4x vs fp32."""
    x, y = batch
    x = x.astype(jnp.float32)
    x = (x - jnp.asarray(IMAGENET_MEAN)) / jnp.asarray(IMAGENET_STD)
    return x, y


def host_synthetic_loader(steps: int, batch: int, size: int, seed: int):
    """uint8 HOST image batches (numpy) — models a real loader's
    output.  A small pre-generated pool is cycled so per-step host cost
    is the realistic memcpy/collate, not RNG."""
    import numpy as np
    rng = np.random.RandomState(seed)
    pool = [(rng.randint(0, 256, (batch, size, size, 3), np.uint8),
             rng.randint(0, 1000, (batch,), np.int64).astype(np.int32))
            for _ in range(4)]
    for i in range(steps):
        yield pool[i % len(pool)]


def _put(batch: Any, sharding) -> Any:
    if sharding is None:
        return jax.tree.map(jax.device_put, batch)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def prefetch_to_device(
    iterator: Iterable[Any],
    lookahead: int = 2,
    sharding=None,
    transform: Optional[Callable[[Any], Any]] = None,
) -> Iterator[Any]:
    """Yield batches from ``iterator`` with ``lookahead`` batches'
    H2D transfers (and ``transform`` dispatches) already in flight.

    ``lookahead=2`` double-buffers: while the consumer runs a step on
    batch N, batch N+1 is transferring and N+2 is being produced.
    ``sharding`` (a ``jax.sharding.Sharding``) places each leaf for
    multi-device data parallelism — pass the data axis's sharding and
    the queue feeds a ``shard_map``'d step directly.  ``transform`` is
    jitted once and dispatched per batch on the device-side arrays
    (normalize, augment, unpack) — it executes on the accelerator,
    overlapped like any other dispatched work."""
    if lookahead < 1:
        raise ValueError(f"lookahead must be >= 1, got {lookahead}")
    # an already-jitted transform is reused as-is so its trace/compile
    # cache survives across generators (re-wrapping would re-trace per
    # generator — a benchmarking hazard)
    if transform is None:
        jitted = None
    elif isinstance(transform, jax.stages.Wrapped):
        jitted = transform
    else:
        jitted = jax.jit(transform)

    def produce(batch):
        dev = _put(batch, sharding)
        return jitted(dev) if jitted is not None else dev

    queue: collections.deque = collections.deque()
    it = iter(iterator)
    try:
        while len(queue) < lookahead:
            queue.append(produce(next(it)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(produce(next(it)))
        except StopIteration:
            pass
        yield out


class DataPrefetcher:
    """Reference-shaped prefetcher (``main_amp.py:256-290``): construct
    over a host iterator, call :meth:`next` per step; returns ``None``
    when the iterator is exhausted (the reference's sentinel protocol).

    >>> pf = DataPrefetcher(loader, transform=normalize)
    >>> batch = pf.next()
    >>> while batch is not None:
    ...     state = step(state, *batch)
    ...     batch = pf.next()
    """

    def __init__(self, iterator: Iterable[Any], lookahead: int = 2,
                 sharding=None,
                 transform: Optional[Callable[[Any], Any]] = None):
        self._gen = prefetch_to_device(iterator, lookahead=lookahead,
                                       sharding=sharding,
                                       transform=transform)

    def next(self) -> Any:
        return next(self._gen, None)

    def __iter__(self):
        return self._gen
