// apex_tpu_C — native host-side runtime helpers.
//
// TPU-native counterpart of the reference's host/C++ layer:
//  - flatten/unflatten: csrc/flatten_unflatten.cpp:15-18 (apex_C). On GPU
//    those call torch's tensor coalescing; here they are multithreaded
//    memcpy gather/scatter over host buffers (checkpoint packing, host-side
//    param staging before device put).
//  - plan_buckets: the greedy message-size bucket assignment apex DDP builds
//    on its first backward (apex/parallel/distributed.py:339-362): walk
//    tensors in hook-firing order, close a bucket once the cumulative numel
//    reaches message_numel or a trigger tensor is seen.
//  - fingerprint64: FNV-1a over raw bytes — the digest primitive for the
//    L1 conformance harness (the reference compared loss digests between
//    ext and no-ext installs, tests/L1/common/compare.py:36-63).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this
// environment).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

namespace {

// Run fn(i) for i in [0, n) over up to n_threads workers, partitioning the
// index space by contiguous blocks weighted by nbytes so each worker copies
// a similar byte volume.
template <typename Fn>
void parallel_over_tensors(const int64_t* nbytes, int64_t n, int n_threads,
                           Fn fn) {
  if (n <= 0) return;
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) total += nbytes[i];
  int workers = std::max(1, std::min<int>(n_threads, (int)n));
  if (workers == 1 || total < (1 << 20)) {  // small payloads: not worth threads
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(workers);
  int64_t per = (total + workers - 1) / workers;
  int64_t start = 0;
  for (int w = 0; w < workers && start < n; ++w) {
    int64_t end = start, acc = 0;
    while (end < n && (acc < per || end == start)) acc += nbytes[end++];
    if (w == workers - 1) end = n;
    pool.emplace_back([start, end, &fn]() {
      for (int64_t i = start; i < end; ++i) fn(i);
    });
    start = end;
  }
  for (auto& t : pool) t.join();
}

}  // namespace

extern "C" {

// Gather n source buffers into dst at byte offsets[i]; nbytes[i] per buffer.
void apex_flatten(const void** srcs, const int64_t* nbytes,
                  const int64_t* offsets, int64_t n, char* dst,
                  int n_threads) {
  parallel_over_tensors(nbytes, n, n_threads, [&](int64_t i) {
    std::memcpy(dst + offsets[i], srcs[i], (size_t)nbytes[i]);
  });
}

// Scatter a flat buffer back into n destination buffers.
void apex_unflatten(const char* src, const int64_t* nbytes,
                    const int64_t* offsets, int64_t n, void** dsts,
                    int n_threads) {
  parallel_over_tensors(nbytes, n, n_threads, [&](int64_t i) {
    std::memcpy(dsts[i], src + offsets[i], (size_t)nbytes[i]);
  });
}

// Greedy bucket planning (apex/parallel/distributed.py:339-362 semantics):
// tensors are taken in order; the running bucket closes once its cumulative
// numel reaches message_numel, or immediately after a trigger tensor.
// Writes bucket_ids[i] for every tensor and returns the bucket count.
int64_t apex_plan_buckets(const int64_t* numels, const uint8_t* is_trigger,
                          int64_t n, int64_t message_numel,
                          int64_t* bucket_ids) {
  int64_t bucket = 0, acc = 0;
  bool open = false;
  for (int64_t i = 0; i < n; ++i) {
    bucket_ids[i] = bucket;
    open = true;
    acc += numels[i];
    bool trigger = is_trigger != nullptr && is_trigger[i];
    if (acc >= message_numel || trigger) {
      ++bucket;
      acc = 0;
      open = false;
    }
  }
  return bucket + (open ? 1 : 0);
}

// 64-bit FNV-1a over a byte buffer.
uint64_t apex_fingerprint64(const void* data, int64_t nbytes, uint64_t seed) {
  const unsigned char* p = (const unsigned char*)data;
  uint64_t h = seed ? seed : 0xCBF29CE484222325ULL;  // FNV offset basis
  for (int64_t i = 0; i < nbytes; ++i) {
    h ^= p[i];
    h *= 0x100000001B3ULL;  // FNV prime
  }
  return h;
}

int apex_native_abi_version(void) { return 1; }

}  // extern "C"
