"""BASELINE config 1: MNIST-scale MLP with amp O1.

Port of the reference's ``examples/simple`` role: the smallest end-to-end
amp workload.  Accepts the amp flags as argparse strings exactly like the
reference examples (``frontend.py:74-92`` parses "dynamic"/"True" directly).

Run (any backend):
    python examples/mnist_amp.py --opt-level O1 --steps 200
"""

# Make the repo root importable when run as "python examples/<name>.py"
# without an install (the environment forbids pip install).
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu import amp
from apex_tpu.models.mlp import MLP, cross_entropy_loss


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--opt-level", default="O1")
    p.add_argument("--loss-scale", default=None)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--print-freq", type=int, default=50)
    p.add_argument("--deterministic", action="store_true")
    return p.parse_args()


def synthetic_mnist(key, n, batch):
    """Deterministic synthetic MNIST-shaped data (class-dependent means so
    the model has something to learn)."""
    ks = jax.random.split(key, 2)
    y = jax.random.randint(ks[0], (n, batch), 0, 10)
    centers = jax.random.normal(ks[1], (10, 784)) * 0.5
    x = centers[y] + 0.3 * jax.random.normal(ks[0], (n, batch, 784))
    return x, y


def main():
    args = parse_args()
    model = MLP(features=(256, 256))
    key = jax.random.PRNGKey(0 if args.deterministic else int(time.time()))
    params = model.init(key, jnp.zeros((1, 784)))["params"]

    a = amp.initialize(optimizer=optax.sgd(args.lr),
                       opt_level=args.opt_level, loss_scale=args.loss_scale)
    state = a.init(params)
    step = jax.jit(amp.make_train_step(
        a, lambda p, x, y: cross_entropy_loss(
            model.apply({"params": p}, x), y)))

    xs, ys = synthetic_mnist(jax.random.PRNGKey(1), args.steps,
                             args.batch_size)
    t0 = time.time()
    for i in range(args.steps):
        state, m = step(state, xs[i], ys[i])
        if i % args.print_freq == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(m['loss']):.4f}  "
                  f"scale {float(m['loss_scale']):.0f}  "
                  f"overflow {bool(m['overflow'])}")
    dt = time.time() - t0
    print(f"done: {args.steps} steps, "
          f"{args.steps * args.batch_size / dt:.0f} samples/s")


if __name__ == "__main__":
    main()
