"""Pipeline- and expert-parallel training demo.

Capabilities the reference lacks entirely (2019-era apex has only data
parallelism — SURVEY.md §2 "NOT present"): this example trains with

- ``--mode pp``: a GPipe-style pipeline — each mesh rank owns one stage's
  params (and Adam moments), microbatch activations flow over ICI via
  ``ppermute`` inside one ``lax.scan`` schedule, and the backward pipeline
  falls out of autodiff;
- ``--mode ep``: a switch top-1 MoE FFN — experts sharded over the mesh,
  tokens routed through capacity-bounded dispatch/combine einsums around a
  pair of ``all_to_all`` exchanges, with the load-balancing aux loss.

Both run under amp O2 (bf16 compute, fp32 masters, dynamic loss scaling)
with ``finite_axes`` keeping the overflow-skip decision globally
consistent across the sharded ranks.

Run anywhere (virtual device mesh on CPU):
    python examples/pipeline_moe.py --mode pp --steps 20
    python examples/pipeline_moe.py --mode ep --steps 20
On a real TPU slice the mesh spans the chips; drop --force-cpu.
"""

# Make the repo root importable when run as "python examples/<name>.py"
# without an install (the environment forbids pip install).
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--mode", choices=["pp", "ep"], default="pp")
    p.add_argument("--devices", type=int, default=4)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--dim", type=int, default=32)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--print-freq", type=int, default=5)
    p.add_argument("--force-cpu", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="run on a virtual CPU mesh (default; use "
                        "--no-force-cpu on a real multi-chip slice)")
    return p.parse_args()


def main():
    args = parse_args()
    if args.force_cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
    import jax
    from apex_tpu.utils.jax_compat import shard_map
    if args.force_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu.optimizers import FusedAdam

    n = min(args.devices, len(jax.devices()))
    devices = np.array(jax.devices()[:n])
    d, batch = args.dim, args.batch
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))
    target = jnp.tanh(x @ jax.random.normal(jax.random.PRNGKey(2), (d, d)))

    a = amp.initialize(optimizer=FusedAdam(lr=args.lr),
                       opt_level=args.opt_level, verbosity=0)

    if args.mode == "pp":
        from apex_tpu.parallel import pipeline_apply, stack_stage_params
        mesh = Mesh(devices, ("pipe",))
        keys = jax.random.split(rng, n)
        params = stack_stage_params(
            [{"w": jax.random.normal(k, (d, d)) * 0.4} for k in keys])
        axis = "pipe"

        def loss_fn(p, xb):
            y = pipeline_apply(lambda sp, h: jnp.tanh(h @ sp["w"]), p, xb,
                               "pipe")
            return jnp.mean(jnp.square((y - target).astype(jnp.float32)))

        def match(path, leaf):
            return getattr(leaf, "ndim", 0) >= 1   # all params stage-stacked
        data_spec = P()
    else:
        from apex_tpu.parallel import moe_apply
        mesh = Mesh(devices, ("expert",))
        e_local, hidden = 2, 4 * d
        E = n * e_local
        k = jax.random.split(rng, 3)
        params = {
            "experts": {
                "wi": jax.random.normal(k[0], (E, d, hidden)) * 0.3,
                "wo": jax.random.normal(k[1], (E, hidden, d)) * 0.3,
            },
            "router": jax.random.normal(k[2], (d, E)),
        }
        axis = "expert"

        def loss_fn(p, xb):
            def ffn(ep, h):
                return jax.nn.gelu(h @ ep["wi"]) @ ep["wo"]
            y, aux = moe_apply(ffn, p["experts"], p["router"], xb, "expert")
            y = xb + y
            # target shard for this rank's tokens
            i = jax.lax.axis_index("expert")
            tgt = jax.lax.dynamic_slice_in_dim(target, i * xb.shape[0],
                                               xb.shape[0])
            return (jnp.mean(jnp.square((y - tgt).astype(jnp.float32)))
                    + 0.01 * aux.astype(jnp.float32))

        def match(path, leaf):
            # router stays replicated; scalar leaves (per-leaf optimizer
            # step counters) always replicate
            return "experts" in path and getattr(leaf, "ndim", 0) >= 1
        data_spec = P("expert")

    state = a.init(params)
    if args.mode == "ep":
        # the replicated router's grads are cross-rank reduced
        # EXPLICITLY (axis_name pvary's the params — identity on legacy
        # jax — so no jax version's SPMD autodiff auto-psums them, and
        # reduce_fn pmean's only the router; expert grads are per-rank
        # shards and stay local)
        def reduce_grads(g):
            return {"experts": g["experts"],
                    "router": jax.lax.pmean(g["router"], axis)}
        train = amp.make_train_step(a, loss_fn, axis_name=axis,
                                    reduce_fn=reduce_grads,
                                    finite_axes=(axis,))
    else:
        train = amp.make_train_step(a, loss_fn, finite_axes=(axis,))

    def train_step(state, xb):
        new_state, metrics = train(state, xb)
        return new_state, jax.lax.pmean(metrics["loss"], axis)

    import jax.tree_util as jtu
    state_specs = jtu.tree_map_with_path(
        lambda path, leaf: P(axis) if match(jtu.keystr(path), leaf) else P(),
        state)
    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(state_specs, data_spec),
        out_specs=(state_specs, P())))

    for i in range(args.steps):
        state, loss = step(state, x)
        if i % args.print_freq == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    print(f"done: {args.mode} over {n} devices "
          f"({jax.devices()[0].platform})")


if __name__ == "__main__":
    main()
