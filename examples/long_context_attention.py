"""Long-context attention demo: sequence parallelism over a device mesh.

Capability the reference lacks entirely (SURVEY.md §5.7 — it predates
long-context training): a sequence too long for one device's attention is
sharded over the mesh's sequence axis and attended exactly with

- ``ring``: K/V shards rotate by ``ppermute`` while each device keeps its
  query shard; per-hop blocks run the Pallas flash kernel and merge by
  logsumexp weighting, and
- ``ulysses``: one fused ``all_to_all`` each way trades the sequence
  sharding for a head sharding.

Run anywhere (virtual 8-device CPU mesh):
    python examples/long_context_attention.py --seq-len 8192 --impl ring
On real multi-chip TPU, drop --force-cpu and the mesh spans the slice.
"""

# Make the repo root importable when run as "python examples/<name>.py"
# without an install (the environment forbids pip install).
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from apex_tpu.utils.jax_compat import shard_map


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=8192)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--impl", choices=["ring", "ulysses"], default="ring")
    p.add_argument("--causal", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--world-size", type=int, default=0)
    p.add_argument("--force-cpu", action="store_true",
                   help="virtual CPU mesh (for laptops/CI)")
    p.add_argument("--iters", type=int, default=3)
    return p.parse_args()


def main():
    args = parse_args()
    # The CPU-mesh decision must happen BEFORE any jax.devices() call:
    # device enumeration initializes the backend, after which neither
    # xla_force_host_platform_device_count nor jax_platforms can take
    # effect.  Hence an explicit flag rather than auto-detection.
    if args.force_cpu:
        n = args.world_size or 8
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
        # Make CPU the *default* platform, not just the mesh devices: the
        # kernel layer keys interpret-vs-Mosaic off the default backend.
        jax.config.update("jax_platforms", "cpu")
        devices = jax.devices("cpu")
    else:
        devices = jax.devices()
    world = min(len(devices), args.world_size or len(devices))
    if world < 2:
        print("NOTE: only one device visible — running a degenerate "
              "1-way mesh; pass --force-cpu for a virtual 8-device demo")
    mesh = Mesh(np.array(devices[:world]), ("seq",))

    from apex_tpu.attention import ring_attention, ulysses_attention

    B, L, H, D = args.batch, args.seq_len, args.heads, args.head_dim
    assert L % world == 0, "seq-len must divide the mesh"
    print(f"{args.impl} attention: B={B} L={L} H={H} D={D} over "
          f"{world}x {devices[0].platform} (L/W = {L // world})")

    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(kk, (B, L, H, D), jnp.bfloat16)
               for kk in jax.random.split(key, 3))

    fn = {"ring": ring_attention, "ulysses": ulysses_attention}[args.impl]
    step = jax.jit(shard_map(
        lambda q, k, v: fn(q, k, v, "seq", causal=args.causal),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq")))

    out = step(q, k, v)
    checksum = float(jnp.sum(out.astype(jnp.float32)))   # full sync
    t0 = time.perf_counter()
    for _ in range(args.iters):
        out = step(q, k, v)
    checksum = float(jnp.sum(out.astype(jnp.float32)))
    dt = (time.perf_counter() - t0) / args.iters
    toks = B * L / dt
    print(f"{dt * 1e3:.1f} ms/attention  ({toks / 1e3:.0f}K tokens/s)  "
          f"checksum {checksum:.3f}")


if __name__ == "__main__":
    main()
